"""BatchSolver: the TPU solve plugged into the admission path.

Integration contract (mirrors how the reference's AdmissionCheck
controllers plug in, per BASELINE.json's north star): the Scheduler hands
the cycle's validated heads + snapshot to the solver; the solver returns
full fit-mode admissions (flavor assignments + usage) computed on device;
entries it could not admit fall through to the CPU path (preemption,
partial admission, detailed status messages).

Equivalence class vs the reference: for cycles where every nominated
entry is fit-mode, the solver's result is identical to the sequential
scheduler (same ordering, same intra-cycle accounting — differentially
tested in tests/test_solver.py). In mixed cycles, ALL nomination (fit on
device, preempt-mode on CPU, preemption targets on device) happens
against the pre-cycle snapshot exactly like the reference's nominate
phase — but the admit loop is split: every device fit-mode admission is
accounted before preempt-mode entries run, instead of interleaving by
the global borrow->share->priority->FIFO order. Consequence (pinned by
tests/test_solver.py::TestMixedCycleEquivalenceClass): a fit-mode entry
can consume capacity the reference would have reserved for a BLOCKED
higher-priority preemptor (scheduler.go:245-253); the blocked preemptor
retries next cycle. Entries with preemption targets still re-check fits
against post-admission usage, so no over-admission is possible. The CPU
path (solver=None) remains the strict-conformance mode.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from kueue_tpu import features
from kueue_tpu.cache.snapshot import Snapshot
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.core.resources import FlavorResource
from kueue_tpu.resilience import faultinject
from kueue_tpu.resilience.faultinject import DeviceFault
from kueue_tpu.resilience.supervisor import SupervisedWorker
from kueue_tpu.resilience.watchdog import DispatchTimeout
from kueue_tpu.scheduler import flavorassigner as fa
from kueue_tpu.solver import encode
from kueue_tpu.utils import vlog
import jax

from kueue_tpu.solver.arena import WorkloadArena
from kueue_tpu.solver.kernel import (
    DECISION_KEYS,
    MAX_COMPACT_FLAVORS,
    max_rank_bound,
    solve_cycle_fused,
    solve_cycle_resident,
    solve_cycle_resident_arena,
    solve_cycle_with_preempt,
    solve_phase_a,
    topo_to_device,
)

# the staged (dense) decision fetch keys — the compact wire format
# (kernel.DECISION_KEYS) replaces exactly these on the fetch
DENSE_DECISION_KEYS = ("admitted", "fit", "chosen", "borrows",
                       "chosen_borrow")


def unpack_decisions(fetched: dict, num_podsets: int,
                     num_resources: int) -> dict:
    """Host-side inverse of kernel.pack_decisions_impl: expand the
    compact wire format back into the exact dense decision arrays the
    validation + decode paths consume. Bit-identical to the staged
    fetch by construction (tests/test_transport.py pins it). Dicts
    without the packed keys (dense fetch, mesh path) pass through."""
    if "dec_pr" not in fetched:
        return fetched
    pr = np.asarray(fetched["dec_pr"])
    bits = np.asarray(fetched["dec_bits"])
    W = pr.shape[0]
    planes = np.unpackbits(bits, axis=1,
                           bitorder="little")[:, :W].astype(bool)
    out = {k: v for k, v in fetched.items() if k not in DECISION_KEYS}
    out["fit"], out["admitted"], out["borrows"] = planes
    chosen = (pr & 0x7F).astype(np.int32) - 1
    out["chosen"] = chosen.reshape(W, num_podsets, num_resources)
    out["chosen_borrow"] = (pr >> 7).astype(bool).reshape(
        W, num_podsets, num_resources)
    return out


def _topo_np(topo) -> dict:
    """The kernel's topology dict as plain numpy (for the local CPU
    router); same field list as kernel.topo_to_device."""
    from kueue_tpu.solver.kernel import TOPO_FIELDS
    return {name: getattr(topo, name) for name in TOPO_FIELDS}


# batched_partial_admission marker: this entry's probes weren't
# encodable — run the sequential CPU reducer for it instead
CPU_FALLBACK = object()


# --- warmed-program registry (compile-storm visibility; COMPILE.md) ---
#
# XLA's jit cache is process-global, so the registry of program
# variants known compiled — warmed by the governor, or already
# dispatched once — is module-level too. A dispatch or route whose
# variant key has never been seen carries a (potential) compile on the
# hot path: counted as counters["mid_traffic_compiles"], the number
# the north-star rangespec pins at zero after warmup. A persistent-
# cache hit still costs a trace + deserialize stall on the scheduler
# path, so first-dispatch counts regardless of where the executable
# comes from (the conservative reading).
_SEEN_PROGRAMS: set = set()
_SEEN_LOCK = threading.Lock()


def note_program(key: tuple) -> bool:
    """Record a program variant as compiled; True when it was new."""
    with _SEEN_LOCK:
        if key in _SEEN_PROGRAMS:
            return False
        _SEEN_PROGRAMS.add(key)
        return True


def reset_seen_programs() -> None:
    """Forget every recorded variant — pairs with jax.clear_caches()
    when a bench/test simulates a process restart."""
    with _SEEN_LOCK:
        _SEEN_PROGRAMS.clear()


def _warm_deltas(L: int, dlt):
    """Placeholder delta-prologue arrays for one changed-row bucket
    (None = no prologue). The one copy of the prologue layout for
    every warm helper — it must stay in lockstep with the dispatch
    side's delta assembly or warm keys silently desynchronize."""
    if dlt is None:
        return None
    return (np.full(dlt, -1, np.int32),
            np.zeros(dlt, np.int32),
            np.zeros(dlt, np.int32),
            np.zeros(dlt, np.int64),
            np.full((L, dlt, 3), -1, np.int32),
            np.full((L, dlt), -1, np.int32))


class WarmContext:
    """Host/device zero-state shared by every bucket warm: built once
    by ``BatchSolver.warm_setup`` (the only solver-state-mutating
    step), after which each ``warm_router``/``warm_bucket``/
    ``warm_scatter`` call is read-only w.r.t. the solver — safe on the
    governor's worker thread while live cycles dispatch already-warmed
    buckets (solver/COMPILE.md)."""

    __slots__ = ("topo", "topo_dev", "usage", "cohort_usage",
                 "arena_dev", "arena_cap", "cluster")


def _scramble_fetched(fetched: dict) -> dict:
    """The collect site's CORRUPT action: garbage decision arrays, as a
    bit-flipped fetch would produce. Deliberately invariant-violating
    (admitted rows without the fit bit) — the containment contract is
    that detectable garbage is caught by _validate_fetched; see
    RESILIENCE.md for why undetectable corruption is out of the fault
    model. Handles both wire formats: the compact decision fetch
    scrambles the packed bit planes (fit row zeroed, admitted row
    all-ones), the staged fetch the dense bool arrays."""
    out = dict(fetched)
    if "dec_bits" in fetched:
        bits = np.array(np.asarray(fetched["dec_bits"]))
        bits[0, :] = 0     # fit plane
        bits[1, :] = 0xFF  # admitted plane
        out["dec_bits"] = bits
        return out
    out["admitted"] = np.ones_like(np.asarray(fetched["admitted"]))
    out["fit"] = np.zeros_like(np.asarray(fetched["fit"]))
    return out


# The specific phrasings jax uses for a MISSING backend/platform (the
# legitimate probe-failure shapes). Deliberately narrow: a generic
# "device"/"backend" substring would also match genuine runtime device
# failures ("failed to sync device stream"), re-swallowing exactly the
# faults the narrowed probes exist to surface.
_EXPECTED_BACKEND_MSGS = (
    "unknown backend",                  # jax.devices("nope")
    "backend 'cpu' failed to initialize",
    "unable to initialize backend",
    "no visible",                       # "no visible TPU devices"
    "not found in the list of known platforms",
)


def _expected_backend_error(exc: BaseException) -> bool:
    """Backend probes (local XLA-CPU router, calibration dispatch)
    legitimately fail on platforms without that backend — jax surfaces
    those as ImportError or a RuntimeError with a known missing-backend
    message. Anything else is a real fault that must not be silently
    swallowed (ISSUE 3 satellite: the blanket ``except Exception``
    probes hid genuine device failures)."""
    if isinstance(exc, ImportError):
        return True
    if isinstance(exc, RuntimeError):
        msg = str(exc).lower()
        return any(w in msg for w in _EXPECTED_BACKEND_MSGS)
    return False


class Plan:
    """One cycle's encoded inputs + the host-side routing decision."""

    def __init__(self, topo, topo_dev, state, batch, start_rank, fit_pred):
        self.topo = topo
        self.topo_dev = topo_dev
        self.state = state
        self.batch = batch
        self.start_rank = start_rank
        # fit_pred[i]: the router's exact Phase A fit bit for entry i —
        # entries predicted non-fit are CPU-nominated (preempt-mode
        # discovery) BEFORE the device sync so fit + preemption solve in
        # one execute. In pipelined cycles the prediction runs against a
        # mirror that is stale by one in-flight cycle (advisory only).
        self.fit_pred = fit_pred
        self.deltas = None        # encoded device_backlog corrections
        self.backlog_gen = -1     # residency generation the deltas cover
        self.resident = False     # dispatch through the resident kernel
        self.rs = None            # the ResidentState this plan was built on
        self.slots = None         # arena slots for batch.infos (arena path)
        # Per-slot arena generations captured AT ENCODE TIME (not at
        # dispatch): the speculation token must witness the state the
        # rows were gathered from, so a delta landing between encode
        # and stamp is seen as the staleness it is (stages.py).
        self.slot_gens = None
        # MultiKueue remote-cluster capacity columns (ISSUE 13): encoded
        # from Snapshot.remote_clusters when the snapshot carries any
        # and a CQ routes through a multikueue check; scored inside the
        # fused solve (kernel.score_cluster_columns_impl) and decoded
        # into BatchSolver.last_placements.
        self.cluster = None   # encode.ClusterColumns or None


class InFlight:
    """A dispatched, un-fetched cycle (pipelined dispatch)."""

    def __init__(self, plan, result, keys, preempt_batch):
        self.plan = plan
        self.result = result          # device array dict (not fetched)
        self.keys = keys
        self.preempt_batch = preempt_batch
        self.fair_batch = None
        self.future = None            # background fetch, when started
        self.t_dispatch = None
        self.deadline_s = None        # watchdog bound on the round trip


class ResidentState:
    """Device-resident usage/cohort_usage across cycles + the host-side
    bookkeeping that keeps them honest (VERDICT r3 missing #2):

    - usage_dev/cohort_dev: the kernel's own post-cycle outputs, fed back
      as next cycle's inputs — no per-cycle state upload.
    - mirror_usage/mirror_cohort: numpy twin (drives the CPU-backend fit
      router and stays bit-identical to the device by applying the same
      delta program host-side).
    - pending: device-applied admissions awaiting their cache-journal
      confirmation (the assume write); confirmed entries cancel, entries
      the scheduler failed to assume are reverted.
    - device_backlog: net corrections (evictions, finishes, CPU-path
      admissions) the device has not seen yet; shipped as a sparse delta
      prologue in the next dispatch.
    """

    def __init__(self, token):
        self.token = token
        self.usage_dev = None
        self.cohort_dev = None
        self.mirror_usage = None
        self.mirror_cohort = None
        self.pending: dict = {}        # key -> (cq_name, usage dict, age)
        self.device_backlog: dict = {}  # (cq_name, fr) -> net delta
        self.backlog_gen = 0


class BatchSolver:
    def __init__(self, max_podsets: int = 4, ordering: Optional[wlpkg.Ordering] = None,
                 mesh=None, backend: str = "jit"):
        """backend: "jit" (XLA on the configured platform — the TPU path)
        or "native" (the C++ solve in kueue_tpu.native — the accelerator-
        free runtime; falls back to jit when the library is unavailable)."""
        self.max_podsets = max_podsets
        self.ordering = ordering or wlpkg.Ordering()
        self.mesh = mesh  # optional jax.sharding.Mesh for multi-chip solve
        self.backend = backend
        self._topo_cache = None
        self._topo_key = None
        self._cpu_device = None  # lazy: local XLA-CPU device for routing
        self._sync_samples: list = []  # recent device sync costs (ms)
        self._cache = None  # bound Cache (usage journal source)
        self._resident: Optional[ResidentState] = None
        self._fetch_pool = None  # lazy: background-fetch executor
        # Supervised dispatch (resilience/supervisor.py): with a
        # deadline, the dispatch body (trace/compile/transfer) runs on
        # a persistent worker thread and a hang is abandoned instead of
        # freezing the scheduler — the collect-side watchdog's twin.
        self.supervise_dispatch = True
        self._supervisor = SupervisedWorker("solver-dispatch")
        # Bumped on every abandonment: an orphaned dispatch that later
        # wakes up checks it before mutating shared host state (the
        # arena twin) and bails instead of racing the live cycle. The
        # lock serializes the arena upload section itself — at most one
        # dispatch (live or orphaned) is ever inside prepare_device, so
        # a wedge INSIDE the upload blocks the next dispatch on the
        # lock (which the supervisor then times out and the breaker
        # contains) instead of corrupting the twin.
        self._dispatch_epoch = 0
        self._arena_lock = threading.Lock()
        # Workload encode arena (solver/arena.py): persistent per-workload
        # encoded rows, maintained by the queue manager's delta feed.
        # Engaged only once a Manager is bound (bind_queues) — without
        # the feed there is no invalidation source for in-place object
        # updates, so unbound callers keep the from-scratch encode.
        self._arena = WorkloadArena(max_podsets)
        self._queues = None
        # Per-cycle encode-phase latency samples (perf: encode_ms p50/p99).
        self.encode_samples: list = []
        # Per-cycle host<->device payload accounting (bench visibility).
        self.last_upload_bytes = 0
        self.last_fetch_bytes = 0
        # Device-made MultiKueue placements from the last decode:
        # workload key -> cluster name (ISSUE 13 batched columns). The
        # scheduler forwards them through its on_placement hook.
        self.last_placements: dict = {}
        # Decision-only fetch (kernel.pack_decisions_impl): None = auto
        # (compact whenever the topology's flavor count fits the wire
        # format), False = force the staged dense fetch (the
        # differential oracle the compact path is pinned against).
        self.compact_fetch: Optional[bool] = None
        # Cumulative per-phase wall time + engagement counters, reported
        # by the perf harness (VERDICT r4 missing #4: the artifacts must
        # show whether residency/pipelining engaged and where the cycle
        # time goes: encode, route, dispatch, fetch, decode). Every
        # increment also lands as a span in the flight recorder's open
        # cycle trace when one is bound (_phase).
        # Dotted keys are sub-spans nested inside their prefix phase
        # (dispatch.scatter rides inside dispatch), mirroring the
        # flight recorder's span-tree convention exactly so the perf
        # artifact's phase breakdown and /debug/cycles agree by
        # construction (obs/recorder.CycleTrace.phase_sums).
        self.phase_s = {"encode": 0.0, "route": 0.0, "dispatch": 0.0,
                        "dispatch.scatter": 0.0, "fetch": 0.0,
                        "decode": 0.0}
        self._recorder = None  # bound FlightRecorder (obs/recorder.py)
        self.counters = {"prepares": 0, "dispatches": 0, "collects": 0,
                         "resident_cycles": 0, "establishes": 0,
                         "upload_bytes": 0, "fetch_bytes": 0,
                         "dispatch_timeouts": 0, "backend_probe_faults": 0,
                         "validation_faults": 0, "supervised_timeouts": 0,
                         "mid_traffic_compiles": 0}
        self.log = vlog.logger("solver")

    def bind_cache(self, cache) -> None:
        """Attach the scheduler's Cache: enables the usage journal that
        keeps device-resident state reconciled across cycles. Mesh/native
        backends never consume the journal, so don't make the cache feed
        one nobody drains."""
        self._cache = cache
        if self.mesh is None and self.backend == "jit":
            cache.enable_usage_journal()

    def bind_recorder(self, recorder) -> None:
        """Attach the scheduler's FlightRecorder: phase bookkeeping
        emits spans into the open cycle trace (no-op per span while no
        trace is open or the recorder is disabled)."""
        self._recorder = recorder

    def _phase(self, name: str, t0: float, t1: float) -> None:
        """One phase interval: accumulate the cumulative total (perf
        artifacts) AND emit a flight-recorder span."""
        self.phase_s[name] += t1 - t0
        rec = self._recorder
        if rec is not None:
            rec.span(name, t0, t1 - t0)

    def bind_queues(self, queues) -> None:
        """Attach the queue Manager's workload delta feed: the encode
        arena's rows are invalidated/freed by deltas instead of being
        rebuilt per cycle. Idempotent."""
        if self._queues is queues:
            return
        self._queues = queues
        queues.add_workload_listener(self._arena.note)

    def detach(self) -> None:
        """Forget everything bound to a (dead) control plane: device
        residency, the encode arena (host rows AND device twin), the
        topology cache, and the cache/queue bindings. Crash-restart
        recovery (resilience/recovery.py) reuses the solver object —
        its jit caches and the persistent XLA compilation cache are the
        "restart is cheap" carry-over — while ALL state derived from
        the old manager is rebuilt from the new one: the next
        Scheduler.__init__ rebinds cache/queues/recorder and the first
        prepare() re-establishes residency from a fresh snapshot,
        re-warming lazily through the compile governor."""
        self.invalidate_resident()
        self._arena = WorkloadArena(self.max_podsets)
        self._topo_cache = None
        self._topo_key = None
        self._cache = None
        self._queues = None

    def release_workload(self, key: str) -> None:
        """Scheduler hook: the workload was admitted (it holds quota and
        leaves the pending set without a queue-manager delete), so its
        arena slot can be recycled."""
        self._arena.release(key)

    def slot_generations(self, slots):
        """Per-slot encode-arena generations for a dispatched batch's
        slots — the speculative pipeline's staleness witness
        (scheduler/stages.SpeculationToken): stamped at dispatch,
        re-read at apply-validation; any mid-flight upsert/delete of a
        dispatched workload bumps its slot's generation and the
        speculation aborts. None when no arena feed is bound (no
        invalidation source -> no per-slot protocol)."""
        if slots is None or self._queues is None:
            return None
        return self._arena.slot_generations(slots)

    @property
    def resident_capable(self) -> bool:
        return (self._cache is not None and self.mesh is None
                and self.backend == "jit")

    def estimated_sync_ms(self, default: float = 120.0) -> float:
        """The device dispatch+sync floor: calibrated once with a trivial
        dispatch (so the first estimate isn't a compile-inflated real
        cycle), then refined as the MIN of observed cycle syncs — robust
        to compile-time outliers, and a floor is a lower bound by
        definition. Feeds the scheduler's work gates: device work must
        save more than this to dispatch."""
        if not self._sync_samples:
            try:
                self._sync_samples.append(self._calibrate_floor())
            except Exception as exc:  # noqa: BLE001 — classified below
                self._note_backend_error("calibrate_floor", exc)
                return default
        return min(self._sync_samples)

    def _note_backend_error(self, where: str, exc: BaseException) -> None:
        """Classify a backend-probe failure: a missing backend is an
        expected environment shape (V4 note only), anything else is a
        real fault — counted and surfaced instead of silently swallowed
        (the probe still falls back; the scheduler hot path must not
        crash on it)."""
        if _expected_backend_error(exc):
            self.log.v(4, "solver.backendUnavailable", where=where,
                       error=repr(exc))
            return
        self.counters["backend_probe_faults"] += 1
        self.log.error("solver.backendProbeFault", where=where,
                       error=repr(exc))

    @staticmethod
    def _calibrate_floor() -> float:
        """Measure the dispatch+sync floor with a REPRESENTATIVE program:
        a small solve_cycle_fused (not `a+1` — over a tunneled TPU a real
        cycle's upload/fetch measurably exceeds a trivial op's, and an
        underestimate biases the work gates toward the device)."""
        import time
        import jax.numpy as jnp
        from kueue_tpu.solver.kernel import solve_cycle_fused
        from kueue_tpu.solver.synth import synth_solver_inputs
        topo, usage, cohort_usage, wl = synth_solver_inputs(
            num_cqs=8, num_cohorts=2, num_flavors=2, num_resources=2,
            num_workloads=8, seed=7)
        topo_dev = {k: jnp.asarray(v) for k, v in topo.items()}

        def run():
            out = solve_cycle_fused(
                topo_dev, usage, cohort_usage, wl["requests"],
                wl["podset_active"], wl["wl_cq"], wl["priority"],
                wl["timestamp"], wl["eligible"], wl["solvable"],
                num_podsets=1, max_rank=8)
            return np.asarray(out["admitted"])

        run()  # compile
        t0 = time.perf_counter()
        run()
        return (time.perf_counter() - t0) * 1e3

    def _observe_sync(self, ms: float) -> None:
        self._sync_samples.append(ms)
        if len(self._sync_samples) > 16:
            self._sync_samples.pop(0)

    # --- shape-bucket warmup (compile governor seam; solver/COMPILE.md) ---

    def _topo_dims(self, topo) -> tuple:
        """The shape signature compilation actually keys on: every
        kernel argument's dims derive from these plus the per-call
        batch/rank/delta buckets (the warmed-program registry keys)."""
        return (topo.nominal.shape, topo.cohort_subtree.shape[0],
                topo.cq_chain.shape[1])

    def _compact_flag(self, topo) -> bool:
        """Whether this topology's cycles dispatch the compact
        decision-fetch program variants (kernel.pack_decisions_impl):
        on whenever the flavor count fits the wire format, unless the
        staged dense fetch is forced (compact_fetch=False — the
        differential oracle). A deterministic function of (knob, topo),
        so the warm helpers and the dispatch sites compute the same
        program keys."""
        return (self.compact_fetch is not False
                and topo.nominal.shape[1] <= MAX_COMPACT_FLAVORS)

    def warm_setup(self, snapshot: Snapshot,
                   expected_pending: Optional[int] = None):
        """Build the zeroed shape context (WarmContext) every bucket
        warm runs against: compilation keys on shapes + static args
        only, so zero batches at the run's REAL topology warm the real
        programs. This is the only warm step that mutates solver state
        (the topology cache and, with ``expected_pending``, the encode
        arena pre-size — growth mid-run would drop the device twin and
        mint a fresh gather shape), so the governor calls it while
        every bucket is still un-warmed and the route gate holds
        cycles on the CPU path. None for mesh/native backends (their
        dispatch paths cache separately)."""
        if self.mesh is not None or self.backend != "jit":
            return None
        import jax.numpy as jnp
        from kueue_tpu.solver.arena import ARENA_FIELDS
        topo, topo_dev = self._topology(snapshot)
        Q, F, R = topo.nominal.shape
        # The BUCKETED cohort dim (what encode_state allocates) — the
        # raw cohort count warmed wrong-shape programs that a real
        # cycle never hit, a silent miss until the narrowed backend
        # probes surfaced the shape error (ISSUE 3 satellite).
        C = topo.cohort_subtree.shape[0]
        ctx = WarmContext()
        ctx.topo, ctx.topo_dev = topo, topo_dev
        ctx.usage = jnp.zeros((Q, F, R), jnp.int64)
        ctx.cohort_usage = jnp.zeros((max(C, 1), F, R), jnp.int64)
        ctx.arena_dev = None
        ctx.arena_cap = 0
        # MultiKueue deployments (snapshot carries capacity columns):
        # live dispatches key on kdim = the bucketed column shape, so
        # every variant is warmed BOTH ways — without columns and at
        # the deployment's column shape (warm_bucket/_cluster_variants;
        # a K-bucket change from adding clusters later self-heals with
        # one counted compile).
        ctx.cluster = encode.encode_cluster_columns(snapshot, topo)
        if expected_pending is not None:
            # Pre-size the arena so the run never pays mid-run growth,
            # and warm the arena-resident kernel at that shape.
            self._arena.reserve(expected_pending, topo)
        elif self._queues is not None:
            # Arena-capable solver warming pre-traffic (cap may still
            # be 0): live dispatch engages the arena at its floor
            # capacity on the first workload, so warm the arena-gather
            # variant at that floor — the plain resident variant is
            # never dispatched once queues are bound. (No-op when the
            # arena already holds workloads.)
            self._arena.reserve(1, topo)
        if self._arena.cap and (expected_pending is not None
                                or self._queues is not None):
            # With the arena engaged, the plain resident kernel is
            # never dispatched — the gather variant is the one to warm.
            ctx.arena_dev = {
                name: jnp.zeros(getattr(self._arena, name).shape,
                                getattr(self._arena, name).dtype)
                for name in ARENA_FIELDS}
            ctx.arena_cap = self._arena.cap
        return ctx

    @staticmethod
    def _warm_batch_arrays(topo, width: int, max_podsets: int):
        from kueue_tpu.solver.encode import _bucket
        _, F, R = topo.nominal.shape
        W = _bucket(max(1, width))
        P = max_podsets
        return (W, np.zeros((W, P, R), np.int64), np.zeros((W, P), bool),
                np.zeros(W, np.int32), np.zeros(W, np.int64),
                np.zeros(W, np.float64), np.zeros((W, P, F), bool),
                np.zeros(W, bool), np.zeros((W, P, R), np.int32))

    def warm_router(self, ctx: WarmContext, width: int) -> int:
        """Warm the local-CPU Phase A router (with and without the
        flavor-resume variant) at one width bucket."""
        topo = ctx.topo
        Q, F, R = topo.nominal.shape
        C = topo.cohort_subtree.shape[0]
        (W, requests, podset_active, wl_cq, priority, timestamp,
         eligible, solvable, start_rank) = self._warm_batch_arrays(
            topo, width, self.max_podsets)
        try:
            from kueue_tpu.solver.encode import WorkloadBatch
            b = WorkloadBatch(infos=[], n=0)
            (b.requests, b.podset_active, b.wl_cq, b.priority,
             b.timestamp, b.eligible, b.solvable, b.start_rank) = (
                requests, podset_active, wl_cq, priority, timestamp,
                eligible, solvable, start_rank)
            state = encode.State(usage=np.zeros((Q, F, R), np.int64),
                                 cohort_usage=np.zeros(
                                     (max(C, 1), F, R), np.int64))
            self._route(topo, state, b, None, count_compiles=False)
            self._route(topo, state, b, start_rank, count_compiles=False)
            return 2
        except Exception as exc:  # noqa: BLE001 — classified below
            self._note_backend_error("warm_route", exc)
            return 0

    def warm_bucket(self, ctx: WarmContext, width: int,
                    max_ranks=(8, 32, 128, 512), deltas_buckets=(8,),
                    fair_sharing: bool = False) -> int:
        """Warm every single-chip solve variant for one batch-width
        bucket: the fused sync kernel plus the resident kernel (the
        arena-gather variant when the arena is engaged), with and
        without a delta prologue and flavor-resume ranks, per
        conflict-domain rank bucket. Registers each program in the
        warmed-program registry so a later live dispatch of the same
        variant is not counted as a mid-traffic compile. Read-only
        w.r.t. solver state (see warm_setup)."""
        topo, topo_dev = ctx.topo, ctx.topo_dev
        usage, cohort_usage = ctx.usage, ctx.cohort_usage
        dims = self._topo_dims(topo)
        (W, requests, podset_active, wl_cq, priority, timestamp,
         eligible, solvable, start_rank) = self._warm_batch_arrays(
            topo, width, self.max_podsets)
        P = self.max_podsets
        args = (requests, podset_active, wl_cq, priority, timestamp,
                eligible, solvable)
        L = topo.cq_chain.shape[1]
        # Warm the exact program the dispatch sites will run: the
        # compact decision-fetch variant whenever the topology is
        # compact-capable (the dense twin is never dispatched then).
        compact = self._compact_flag(topo)
        ready_key = "dec_bits" if compact else "admitted"
        warmed = 0
        for max_rank in max_ranks:
            for sr in (None, start_rank):
                for cargs_w, kdim_w in self._cluster_variants(ctx):
                    out = solve_cycle_fused(
                        topo_dev, usage, cohort_usage, *args,
                        num_podsets=P, max_rank=max_rank,
                        fair_sharing=fair_sharing, start_rank=sr,
                        compact=compact, cluster_args=cargs_w)
                    out[ready_key].block_until_ready()
                    note_program(("fused", dims, W, P, max_rank,
                                  fair_sharing, sr is not None, (), (), (),
                                  compact, kdim_w))
                    warmed += 1
                    for dlt in (None,) + tuple(deltas_buckets):
                        deltas = _warm_deltas(L, dlt)
                        if ctx.arena_dev is None:
                            out = solve_cycle_resident(
                                topo_dev, usage, cohort_usage, deltas,
                                *args, num_podsets=P, max_rank=max_rank,
                                fair_sharing=fair_sharing, start_rank=sr,
                                compact=compact, cluster_args=cargs_w)
                            key = ("resident", dims, W, P, max_rank,
                                   fair_sharing, sr is not None, dlt,
                                   (), (), (), compact, kdim_w)
                        else:
                            slots_w = np.full(W, -1, np.int32)
                            out = solve_cycle_resident_arena(
                                topo_dev, usage, cohort_usage, deltas,
                                ctx.arena_dev, slots_w,
                                num_podsets=P, max_rank=max_rank,
                                fair_sharing=fair_sharing, start_rank=sr,
                                compact=compact, cluster_args=cargs_w)
                            key = ("arena", dims, ctx.arena_cap, W, P,
                                   max_rank, fair_sharing, sr is not None,
                                   dlt, (), (), (), compact, kdim_w)
                        out[ready_key].block_until_ready()
                        note_program(key)
                        warmed += 1
        return warmed

    @staticmethod
    def _cluster_variants(ctx: WarmContext) -> list:
        """(cluster_args, kdim) pairs every solve variant warms: the
        column-less program always, plus the deployment's bucketed
        cluster-column shape when the warm snapshot carried capacity
        columns (ISSUE 13) — live dispatch keys on exactly these kdims,
        so a MultiKueue deployment's cluster-carrying cycles hit warm
        programs instead of compiling on the admission thread."""
        variants = [(None, None)]
        cluster = getattr(ctx, "cluster", None)
        if cluster is not None:
            variants.append((encode.cluster_args_device(cluster),
                             cluster.ccap.shape))
        return variants

    def warm_scatter(self, ctx: WarmContext) -> int:
        """Warm the changed-row arena scatter programs: one compile per
        row bucket at this arena capacity (shape-independent of the
        solve variants by design). Warms the DONATED executable — the
        one prepare_device actually dispatches — against a throwaway
        zero twin per bucket (donation deletes its input, so the shared
        ctx.arena_dev must never be the donated operand)."""
        if ctx.arena_dev is None:
            return 0
        import jax.numpy as jnp
        from kueue_tpu.solver.arena import _UPD_BUCKETS
        from kueue_tpu.solver.kernel import scatter_arena_rows_donated
        warmed = 0
        for D in _UPD_BUCKETS:
            upd_slots = np.full(D, ctx.arena_cap, np.int32)
            upd_rows = {name: np.zeros((D,) + a.shape[1:], a.dtype)
                        for name, a in ctx.arena_dev.items()}
            burn = {name: jnp.zeros_like(a)
                    for name, a in ctx.arena_dev.items()}
            out = scatter_arena_rows_donated(burn, upd_slots, upd_rows)
            out["solvable"].block_until_ready()
            note_program(("scatter", ctx.arena_cap, self.max_podsets,
                          self._topo_dims(ctx.topo), D))
            warmed += 1
        return warmed

    def warm_preempt_bucket(self, ctx: WarmContext, width: int,
                            pshapes, max_ranks=(8,),
                            deltas_buckets=(8,),
                            fair_sharing: bool = False,
                            fs_flags: tuple = (),
                            start_rank: bool = False) -> int:
        """Warm the mixed admission+preemption program variants for one
        batch width across the preemption shape ladder: the sync fused
        kernel (solve_cycle_with_preempt) plus the resident/arena
        variant the production scheduler actually dispatches, with and
        without a delta prologue. ``pshapes`` is the ladder of bucketed
        preemption dims {B, K, QL, CL, RF, U} (encode_problems buckets
        every one of them, so the ladder can enumerate them from
        topology alone); ``max_ranks`` is the full rank-rung ladder —
        dispatch prices max_rank from the batch's conflict domains
        (kernel.max_rank_bound), so warming only the top rung would
        miss every cycle whose domains sit below it.

        Without fair sharing every shape warms the minimal-preemptions
        program. With ``fair_sharing`` the dispatch key splits by how
        build_fair_problems partitions the cycle's entries: all-same-
        queue entries build a MINIMAL-only batch (QL bucket 1,
        fshapes=(), fs_strategies normalized to ()), cohort-candidate
        entries a FAIR-only batch (pshapes=()), and a mixed cycle pairs
        a within-CQ minimal batch with a cohort-wide fair batch — each
        variant is warmed explicitly, because the homogeneous
        (pargs, fargs) pairing over one geometry matches no production
        dispatch. ``start_rank`` warms the flavor-resume twin of every
        program (requeued heads after an eviction carry resume state,
        so mid-storm preempt cycles routinely dispatch sr=True).
        Registers every program so the first preemption-heavy cycle
        after startup is not a mid-traffic compile
        (solver/COMPILE.md)."""
        from kueue_tpu.solver import fairpreempt
        from kueue_tpu.solver import preempt as devpreempt
        topo, topo_dev = ctx.topo, ctx.topo_dev
        dims = self._topo_dims(topo)
        DC = topo.cq_chain.shape[1]
        if isinstance(pshapes, dict):
            pshapes = (pshapes,)

        def build_pb(shape):
            B, K = shape["B"], shape["K"]
            QL, CL = shape["QL"], shape["CL"]
            RF, U = shape["RF"], shape["U"]
            pb = devpreempt.PreemptionBatch()
            pb.gq = np.full((B, QL), -1, np.int32)
            pb.gf = np.full((B, RF), -1, np.int32)
            pb.gr = np.zeros((B, RF), np.int32)
            pb.gc = np.full((B, CL), -1, np.int32)
            pb.chain_local = np.full((B, QL, DC), -1, np.int32)
            pb.requests = np.zeros((B, RF), np.int64)
            pb.frs_np = np.zeros((B, RF), bool)
            pb.cand_idx = np.zeros((B, K), np.int32)
            pb.cand_ql = np.full((B, K), -1, np.int16)
            pb.cand_usage = np.zeros((U, RF), np.int64)
            pb.cand_prio = np.zeros(U, np.int32)
            pb.allow_borrowing = np.zeros(B, bool)
            pb.threshold_active = np.zeros(B, bool)
            pb.threshold = np.zeros(B, np.int64)
            pb.has_cohort = np.zeros(B, bool)
            pargs = devpreempt.preempt_args(pb)
            return pb, pargs, tuple(np.asarray(a).shape for a in pargs)

        def build_fb(pb, shape):
            B, K = shape["B"], shape["K"]
            QL, RF = shape["QL"], shape["RF"]
            fb = fairpreempt.FairBatch(
                **{f: getattr(pb, f) for f in (
                    "gq", "gf", "gr", "gc", "chain_local", "requests",
                    "frs_np", "cand_idx", "cand_ql", "cand_usage",
                    "cand_prio", "allow_borrowing", "threshold_active",
                    "threshold", "has_cohort")})
            fb.cand_rank = np.full((B, K), -1, np.int32)
            fb.cq_count = np.zeros((B, QL), np.int32)
            fb.cq_order = np.full((B, QL), 2**30, np.int32)
            fb.base_other = np.zeros((B, QL, RF), np.int64)
            fb.floor_ratio = np.full((B, QL), -1, np.int64)
            fb.floor_any = np.zeros((B, QL), bool)
            fb.weight = np.full((B, QL), 1000, np.int64)
            fb.lendable = np.zeros((B, RF), np.int64)
            fargs = fairpreempt.fair_args(fb)
            return fargs, tuple(np.asarray(a).shape for a in fargs)

        flags = tuple(fs_flags)
        built = [(shape,) + build_pb(shape) for shape in pshapes]
        # (pargs, pshapes_key, fargs, fshapes_key, fs_strategies)
        variants = []
        for shape, pb, pargs, psh in built:
            if not fair_sharing or shape["QL"] == 1:
                # fs off: any geometry dispatches as one minimal batch;
                # fs on: minimal problems are all-same-queue (QL 1)
                variants.append((pargs, psh, None, (), ()))
        if fair_sharing:
            fair_by_b = {}
            for shape, pb, pargs, psh in built:
                if shape["QL"] > 1:
                    fargs, fsh = build_fb(pb, shape)
                    variants.append((None, (), fargs, fsh, flags))
                    fair_by_b.setdefault(shape["B"], (fargs, fsh))
            # mixed cycles pair a within-CQ minimal batch with a
            # cohort-wide fair batch; pair equal B rungs (a lopsided
            # split pays one counted compile)
            for shape, pb, pargs, psh in built:
                if shape["QL"] == 1 and shape["B"] in fair_by_b:
                    fargs, fsh = fair_by_b[shape["B"]]
                    variants.append((pargs, psh, fargs, fsh, flags))

        (W, requests, podset_active, wl_cq, priority, timestamp,
         eligible, solvable, sr_arr) = self._warm_batch_arrays(
            topo, width, self.max_podsets)
        P = self.max_podsets
        args = (requests, podset_active, wl_cq, priority, timestamp,
                eligible, solvable)
        L = topo.cq_chain.shape[1]
        sr = sr_arr if start_rank else None
        sr_flag = sr is not None
        compact = self._compact_flag(topo)
        ready_key = "dec_bits" if compact else "admitted"
        warmed = 0
        for max_rank in dict.fromkeys(max_ranks):
            for pargs, psh, fargs, fsh, fflags in variants:
                for cargs_w, kdim_w in self._cluster_variants(ctx):
                    out = solve_cycle_with_preempt(
                        ctx.topo_dev, ctx.usage, ctx.cohort_usage, *args,
                        pargs, num_podsets=P, max_rank=max_rank,
                        fair_sharing=fair_sharing, start_rank=sr,
                        fair_preempt_args=fargs, fs_strategies=fflags,
                        compact=compact, cluster_args=cargs_w)
                    out[ready_key].block_until_ready()
                    note_program(("preempt", dims, W, P, max_rank,
                                  fair_sharing, sr_flag, psh, fsh, fflags,
                                  compact, kdim_w))
                    warmed += 1
                    for dlt in (None,) + tuple(deltas_buckets):
                        deltas = _warm_deltas(L, dlt)
                        if ctx.arena_dev is None:
                            out = solve_cycle_resident(
                                topo_dev, ctx.usage, ctx.cohort_usage,
                                deltas, *args, num_podsets=P,
                                max_rank=max_rank,
                                fair_sharing=fair_sharing, start_rank=sr,
                                preempt_args=pargs, fair_preempt_args=fargs,
                                fs_strategies=fflags, compact=compact,
                                cluster_args=cargs_w)
                            key = ("resident", dims, W, P, max_rank,
                                   fair_sharing, sr_flag, dlt, psh, fsh,
                                   fflags, compact, kdim_w)
                        else:
                            slots_w = np.full(W, -1, np.int32)
                            out = solve_cycle_resident_arena(
                                topo_dev, ctx.usage, ctx.cohort_usage,
                                deltas, ctx.arena_dev, slots_w,
                                num_podsets=P, max_rank=max_rank,
                                fair_sharing=fair_sharing, start_rank=sr,
                                preempt_args=pargs, fair_preempt_args=fargs,
                                fs_strategies=fflags, compact=compact,
                                cluster_args=cargs_w)
                            key = ("arena", dims, ctx.arena_cap, W, P,
                                   max_rank, fair_sharing, sr_flag, dlt,
                                   psh, fsh, fflags, compact, kdim_w)
                        out[ready_key].block_until_ready()
                        note_program(key)
                        warmed += 1
        return warmed

    def warm(self, snapshot: Snapshot, widths=(2048,),
             max_ranks=(8, 32, 128, 512), deltas_buckets=(8,),
             fair_sharing: bool = False,
             expected_pending: Optional[int] = None) -> int:
        """Precompile (or load from the persistent cache) the fit-path
        kernel variants for the shape buckets a run will hit, BEFORE the
        measured clock starts (VERDICT r4 weak #7 / ask #3: un-amortized
        jit compiles landed inside measured cycles and poisoned both the
        router's early samples and the cycle p99). One blocking call
        over the whole ladder; the compile governor
        (solver/warmgov.py) drives the same per-bucket helpers
        incrementally, supervised and fault-contained. Returns the
        number of programs warmed; 0 for mesh/native backends."""
        ctx = self.warm_setup(snapshot, expected_pending)
        if ctx is None:
            return 0
        warmed = 0
        for width in widths:
            warmed += self.warm_router(ctx, width)
            warmed += self.warm_bucket(ctx, width, max_ranks=max_ranks,
                                       deltas_buckets=deltas_buckets,
                                       fair_sharing=fair_sharing)
        warmed += self.warm_scatter(ctx)
        return warmed

    # --- encoding with topology caching across cycles ---

    def _topology(self, snapshot: Snapshot):
        # topology_epoch bumps on every spec-level change that alters the
        # encoded tensors (CQ set/quotas, cohort tree, flavors, activity)
        # but NOT on workload churn — per-CQ allocatable generations bump
        # on every deletion purely for flavor-resume invalidation, and
        # keying on them rebuilt the topology every cycle under load.
        key = snapshot.topology_epoch
        if key != self._topo_key or self._topo_cache is None:
            own_snap = None
            try:
                if getattr(snapshot, "light", False) \
                        and self._cache is not None:
                    # topology encode iterates whole resource trees —
                    # never off a light snapshot's shared live
                    # structures; take a full (frozen) one for the
                    # rebuild
                    snapshot = own_snap = self._cache.snapshot()
                    key = snapshot.topology_epoch
                topo = encode.encode_topology(snapshot)
                self._topo_cache = (topo, topo_to_device(topo))
                # Key stamped only AFTER the cache tuple is built: a
                # contained encode/upload fault must leave the old
                # (key, cache) pair consistent, or the next cycle at
                # this epoch would silently serve the stale topology.
                self._topo_key = key
            finally:
                if own_snap is not None:
                    # internal handout, fully consumed by the encode —
                    # released on the fault paths too, or a contained
                    # backend error would leak it forever
                    self._cache.release_snapshot(own_snap)
        return self._topo_cache

    def prepare(self, snapshot: Snapshot, entries: list) -> Optional[Plan]:
        """Encode the cycle and route it: the exact Phase A fit bit is
        computed on the LOCAL XLA-CPU backend (~1 ms at the north-star
        shape) so the scheduler knows, before any device sync, which
        entries need CPU preempt-mode nomination. Their preemption
        problems then ship in the same execute as the fit solve
        (kernel.solve_cycle_with_preempt): one device sync per cycle.

        With a bound cache, usage state is device-resident: the journal
        reconciles it with sparse corrections instead of a per-cycle
        re-encode + re-upload."""
        if not entries:
            return None
        import time as _t
        t0 = _t.perf_counter()
        self.counters["prepares"] += 1
        topo, topo_dev = self._topology(snapshot)
        cycle_snapshot = snapshot
        state, deltas, resident, snapshot = self._state_for_cycle(snapshot,
                                                                  topo)
        # The establishing path may have swapped a light snapshot for a
        # fresh full handout of its own — released on EVERY exit, fault
        # paths included (the encoded batch/state copy everything they
        # need; an un-released handout on a contained device fault
        # would leak forever).
        own_snap = snapshot if snapshot is not cycle_snapshot else None
        try:
            if resident:
                self.counters["resident_cycles"] += 1
            slots = None
            if self._queues is not None:
                # Arena path: O(changed) row encodes + a vectorized
                # gather instead of the per-head reassembly loop.
                self._arena.begin_cycle(topo)
                batch, slots = self._arena.assemble(entries, snapshot,
                                                    topo, self.ordering,
                                                    self.max_podsets)
                slot_gens = self._arena.gen[
                    np.asarray(slots, np.int64)].copy()
                self.counters["arena_rows_encoded"] = \
                    self._arena.encoded_rows
                self.counters["arena_gathers"] = self._arena.gathers
            else:
                batch = encode.encode_workloads(
                    entries, snapshot, topo, ordering=self.ordering,
                    max_podsets=self.max_podsets)
            t1 = _t.perf_counter()
            self._phase("encode", t0, t1)
            if len(self.encode_samples) >= (1 << 20):
                del self.encode_samples[: 1 << 19]
            self.encode_samples.append(t1 - t0)
            if not batch.solvable.any():
                return None
            start_rank = batch.start_rank if batch.start_rank.any() \
                else None
            fit_pred = self._route(topo, state, batch, start_rank)
            self._phase("route", t1, _t.perf_counter())
        finally:
            if own_snap is not None:
                self._cache.release_snapshot(own_snap)
        plan = Plan(topo, topo_dev, state, batch, start_rank, fit_pred)
        plan.cluster = encode.encode_cluster_columns(cycle_snapshot, topo)
        plan.slots = slots
        if slots is not None:
            plan.slot_gens = slot_gens
        plan.deltas = deltas
        plan.resident = resident
        if resident:
            plan.rs = self._resident  # identity-pinned: a residency reset
            plan.backlog_gen = self._resident.backlog_gen
        return plan

    # --- device-resident state management ---

    def _state_for_cycle(self, snapshot: Snapshot, topo):
        """Returns (state-with-mirror-arrays, encoded deltas or None,
        resident?, the snapshot the cycle should encode against — the
        establishing path replaces a light one with a fresh full one so
        batch generations match the encoded usage). Establishes residency
        on the first cycle (full encode + upload), reconciles via the
        journal afterwards."""
        if not self.resident_capable:
            return encode.encode_state(snapshot, topo), None, False, snapshot
        rs = self._resident
        if rs is not None and rs.token == topo.token \
                and self._reconcile(snapshot, topo):
            state = encode.State(usage=rs.mirror_usage,
                                 cohort_usage=rs.mirror_cohort)
            if len(rs.device_backlog) > 512:
                # A huge correction set (mass completions) would mint a
                # fresh delta-shape bucket — and each new bucket is a
                # multi-second remote compile. The mirror IS device state
                # + backlog, so re-upload it wholesale instead (fixed
                # shape, ~1MB at the north-star size).
                rs.usage_dev = None
                rs.cohort_dev = None
                rs.device_backlog = {}
            if rs.usage_dev is None:
                # Not dispatched yet: the establishing upload ships the
                # (already-corrected) mirror itself — shipping the backlog
                # as a delta prologue too would double-count it.
                rs.device_backlog = {}
                deltas = None
            else:
                deltas = (encode.encode_deltas(rs.device_backlog, topo)
                          if rs.device_backlog else None)
                if deltas is None:
                    rs.device_backlog = {}
            return state, deltas, True, snapshot
        # (re)establish: the snapshot is the full truth — drop any journal
        # history up to it, encode once, upload once. A LIGHT snapshot's
        # usage is live (not frozen at its journal_seq), so take a fresh
        # full snapshot for the establishing encode; if the topology
        # moved in between (a CQ added/activated concurrently), bail out
        # — the scheduler falls back to the CPU path this cycle and the
        # next prepare() re-encodes against the new epoch.
        if getattr(snapshot, "light", False):
            snapshot = self._cache.snapshot()
            if snapshot.topology_epoch != self._topo_key:
                self._cache.release_snapshot(snapshot)
                raise RuntimeError("topology moved during establish")
        self._cache.drain_usage_journal(snapshot.journal_seq)
        state = encode.encode_state(snapshot, topo)
        rs = ResidentState(topo.token)
        rs.mirror_usage = state.usage
        rs.mirror_cohort = state.cohort_usage
        self._resident = rs
        return state, None, True, snapshot

    def _reconcile(self, snapshot: Snapshot, topo) -> bool:
        """Drain the cache journal up to the snapshot: device admissions
        confirmed by their assume write cancel; everything else (CPU-path
        admissions, evictions, finishes, reverts of failed assumes)
        becomes a sparse correction applied to the mirror now and shipped
        to the device at the next dispatch. False = residency must be
        dropped (journal overflow)."""
        # Injection site: a replay fault propagates out of prepare();
        # the scheduler drops residency and the cycle re-establishes
        # from a fresh full snapshot (host truth) — by construction no
        # partial replay can linger in the mirror, because the mirror is
        # only mutated after the whole drain below succeeds.
        faultinject.site(faultinject.SITE_REPLAY)
        rs = self._resident
        entries, overflow = self._cache.drain_usage_journal(
            snapshot.journal_seq)
        if overflow:
            self._resident = None
            return False
        corr: dict = {}
        for entry in entries:
            kind, cq_name, key, usage = entry[1], entry[2], entry[3], entry[4]
            if kind == "add":
                p = rs.pending.pop(key, None)
                if p is not None:
                    pcq, pusage, _age = p
                    if pcq == cq_name and pusage == usage:
                        continue  # confirmed exactly — device already has it
                    # divergent confirmation: revert the device's version,
                    # then apply the journal's
                    for fr, v in pusage.items():
                        k = (pcq, fr)
                        corr[k] = corr.get(k, 0) - v
                sign = 1
            elif kind == "del":
                sign = -1
            else:
                # snapshot-replay-only records ('cq' scalar refresh,
                # 'ready' flips): no usage movement, nothing to mirror
                continue
            for fr, v in usage.items():
                k = (cq_name, fr)
                corr[k] = corr.get(k, 0) + sign * v
        # age out device admissions never confirmed (aborted cycles);
        # note_unapplied() covers the common failure synchronously.
        expired = [k for k, (_cq, _u, age) in rs.pending.items() if age >= 3]
        for key in expired:
            pcq, pusage, _age = rs.pending.pop(key)
            for fr, v in pusage.items():
                k = (pcq, fr)
                corr[k] = corr.get(k, 0) - v
        for key, (pcq, pusage, age) in rs.pending.items():
            rs.pending[key] = (pcq, pusage, age + 1)
        if corr:
            self._apply_corrections(rs, topo, corr)
        return True

    @staticmethod
    def _apply_corrections(rs: ResidentState, topo, corr: dict) -> None:
        """Fold net corrections into the mirror NOW and the device backlog
        (shipped as the next dispatch's delta prologue)."""
        deltas = encode.encode_deltas(corr, topo)
        if deltas is not None:
            encode.apply_deltas_np(topo, rs.mirror_usage,
                                   rs.mirror_cohort, deltas)
        for k, v in corr.items():
            nv = rs.device_backlog.get(k, 0) + v
            if nv:
                rs.device_backlog[k] = nv
            else:
                rs.device_backlog.pop(k, None)

    def note_unapplied(self, key: str) -> None:
        """The scheduler failed to assume a device-admitted workload:
        revert it from the mirror and queue the device correction."""
        rs = self._resident
        if rs is None:
            return
        p = rs.pending.pop(key, None)
        if p is None:
            return
        pcq, pusage, _age = p
        corr = {(pcq, fr): -v for fr, v in pusage.items()}
        topo = self._topo_cache[0] if self._topo_cache else None
        if topo is not None:
            self._apply_corrections(rs, topo, corr)

    def invalidate_resident(self) -> None:
        self._resident = None
        # The arena twin may hold rows from an aborted dispatch whose
        # dirty-set was already cleared: force a full re-upload.
        self._arena.drop_device()

    def _note_mid_traffic_compile(self, kind: str, width: int) -> None:
        """A program variant never warmed (or dispatched) in this
        process is about to execute on the hot path — a potential
        compile stall inside a measured cycle. Counted for the perf
        artifacts (RunResult.mid_traffic_compiles; the north-star
        rangespec pins it at 0 — solver/COMPILE.md), logged, and
        annotated onto the open cycle trace."""
        self.counters["mid_traffic_compiles"] += 1
        self.log.v(2, "solver.midTrafficCompile", kind=kind, width=width)
        rec = self._recorder
        if rec is not None:
            rec.annotate("compile",
                         f"unwarmed {kind} program at width {width} "
                         f"compiled mid-traffic", program=kind, width=width)

    def _route(self, topo, state, batch, start_rank,
               count_compiles: bool = True):
        """Exact host-side replica of the device Phase A (same jitted
        program, local CPU backend): integer math, so the fit bits are
        identical to the device's. Returns [n] bool, or None when no
        local CPU backend exists (the scheduler then nominates
        device-rejected entries after the sync instead).
        ``count_compiles=False`` suppresses the mid-traffic compile
        accounting (warm paths register programs without counting)."""
        if self._cpu_device is None:
            try:
                self._cpu_device = jax.devices("cpu")[0]
            except Exception as exc:  # noqa: BLE001 — classified below
                self._note_backend_error("route_cpu_device", exc)
                self._cpu_device = False
        if self._cpu_device is False:
            return None
        cached = getattr(self, "_topo_cpu", None)
        if cached is None or cached[0] != topo.token:
            cached = (topo.token,
                      jax.device_put(_topo_np(topo), self._cpu_device))
            self._topo_cpu = cached
        if note_program(("route", self._topo_dims(topo),
                         batch.requests.shape[0], self.max_podsets,
                         start_rank is not None)) and count_compiles:
            self._note_mid_traffic_compile("route",
                                           batch.requests.shape[0])
        with jax.default_device(self._cpu_device):
            out = solve_phase_a(cached[1], state.usage, state.cohort_usage,
                                batch.requests, batch.podset_active,
                                batch.wl_cq, batch.eligible, batch.solvable,
                                num_podsets=self.max_podsets,
                                fair_sharing=False, start_rank=start_rank)
            fit = np.asarray(out[0])
        return fit[:batch.n]

    def solve_prepared(self, plan: Plan, snapshot: Snapshot,
                       preempt_batch=None, fair_sharing: bool = False,
                       fair_batch=None, fs_flags: tuple = (),
                       deadline_s: Optional[float] = None,
                       supervise_deadline_s: Optional[float] = None):
        """Dispatch the cycle (fit solve, plus the preemption batches when
        present, as ONE device program), sync once, decode. Returns
        (decisions dict, aux) where aux is None or
        {"preempt": (targets, feasible), "fair": (targets, feasible,
        reasons)}. deadline_s bounds the device round trip (watchdog):
        a collect past it raises DispatchTimeout instead of blocking."""
        topo, topo_dev, state, batch = (plan.topo, plan.topo_dev,
                                        plan.state, plan.batch)
        start_rank = plan.start_rank
        entries = batch.infos

        # The native ABI encodes the flat (single-level) cohort forest and
        # no fair-share sort key, flavor-resume state, or per-resource
        # borrow flags (needed for TryNextFlavor resume decode); those go
        # through the jit path.
        if (self.backend == "native" and self.mesh is None
                and preempt_batch is None
                and topo.cq_chain.shape[1] == 1 and not fair_sharing
                and start_rank is None and not topo.prefer_no_borrow.any()):
            from kueue_tpu import native
            result = native.solve_cycle_native(
                topo, state.usage, state.cohort_usage, batch.requests,
                batch.podset_active, batch.wl_cq, batch.priority,
                batch.timestamp, batch.eligible, batch.solvable)
            return (self._decode_batch(entries, snapshot, topo, batch,
                                       result), None)

        if self.mesh is not None:
            from kueue_tpu.parallel.mesh import solve_cycle_sharded
            from kueue_tpu.solver import preempt as devpreempt
            pargs = (devpreempt.preempt_args(preempt_batch)
                     if preempt_batch is not None else None)
            cargs = (encode.cluster_args_device(plan.cluster)
                     if plan.cluster is not None else None)
            # Preemption is FUSED into the sharded execute (sharded over
            # the planner-assigned problem axis while Phase A shards over
            # workloads): one dispatch, one sync (VERDICT r3 weak #6).
            # Fair-sharing preemption stays on the CPU path under a mesh
            # (the scheduler routes it there). Remote-cluster capacity
            # columns score replicated inside the same program.
            result = solve_cycle_sharded(self.mesh, topo_dev, state, batch,
                                         self.max_podsets,
                                         fair_sharing=fair_sharing,
                                         start_rank=start_rank,
                                         preempt_args=pargs, topo_np=topo,
                                         cluster_args=cargs)
            keys = ["admitted", "fit", "chosen", "borrows", "chosen_borrow"]
            if preempt_batch is not None:
                keys += ["preempt_targets", "preempt_feasible"]
            if cargs is not None:
                keys.append("mk_cluster")
            fetched = jax.device_get({k: result[k] for k in keys
                                      if k in result})
            aux = None
            if preempt_batch is not None:
                aux = {"preempt": (np.asarray(fetched["preempt_targets"]),
                                   np.asarray(fetched["preempt_feasible"]))}
            return (self._decode_batch(entries, snapshot, topo, batch,
                                       fetched,
                                       cluster_names=(plan.cluster.names
                                                      if plan.cluster
                                                      else None)), aux)

        inflight = self.dispatch(plan, preempt_batch=preempt_batch,
                                 fair_sharing=fair_sharing,
                                 fair_batch=fair_batch, fs_flags=fs_flags,
                                 deadline_s=deadline_s,
                                 supervise_deadline_s=supervise_deadline_s)
        return self.collect(inflight, snapshot)

    def dispatch(self, plan: Plan, preempt_batch=None,
                 fair_sharing: bool = False, fair_batch=None,
                 fs_flags: tuple = (),
                 deadline_s: Optional[float] = None,
                 supervise_deadline_s: Optional[float] = None) -> InFlight:
        """Dispatch the single-chip cycle WITHOUT fetching. The returned
        InFlight's outputs are device references; collect() (or a
        background fetch via start_fetch()) brings the decisions home.
        With residency, the post-cycle usage/cohort_usage stay on device
        as next cycle's inputs — the upload is the workload batch plus
        sparse corrections only.

        ``deadline_s`` is the regime-keyed watchdog deadline the COLLECT
        is bounded by (stamped on the InFlight). With
        ``supervise_deadline_s``, the dispatch body itself runs
        SUPERVISED on the persistent solver-worker thread
        (resilience/supervisor.py): tracing/compile/transfer that wedges
        past it raises DispatchTimeout here instead of freezing the
        scheduler — the worker is orphaned, and the epoch guard keeps
        the orphan from mutating live arena state if it ever wakes up.
        The scheduler passes the watchdog's COLD clamp (max_deadline_s)
        here, not the warm regime deadline: a dispatch legitimately
        carries jit compiles (a fresh shape bucket mid-run, a cold
        start) whose cost is not regime-priced, so only the clamp — the
        operator's compile-absorbing bound — may abandon it."""
        if supervise_deadline_s is None or not self.supervise_dispatch:
            return self._dispatch_impl(plan, preempt_batch, fair_sharing,
                                       fair_batch, fs_flags, deadline_s)
        epoch = self._dispatch_epoch
        try:
            return self._supervisor.run(
                self._dispatch_impl, plan, preempt_batch, fair_sharing,
                fair_batch, fs_flags, deadline_s, epoch,
                deadline_s=supervise_deadline_s)
        except DispatchTimeout:
            self._dispatch_epoch = epoch + 1
            self.counters["supervised_timeouts"] += 1
            raise

    def _check_epoch(self, epoch: Optional[int]) -> None:
        """An orphaned dispatch waking after abandonment must not touch
        shared host state the live cycle owns (the arena twin): bail
        with a DeviceFault nobody will see (the request was abandoned —
        the exception only parks on the orphaned hand-off)."""
        if epoch is not None and epoch != self._dispatch_epoch:
            raise DeviceFault("dispatch abandoned by supervisor")

    def _dispatch_impl(self, plan: Plan, preempt_batch=None,
                       fair_sharing: bool = False, fair_batch=None,
                       fs_flags: tuple = (),
                       deadline_s: Optional[float] = None,
                       epoch: Optional[int] = None) -> InFlight:
        import time
        t0 = time.perf_counter()
        # Injection site: a raise here is exactly a dead-tunnel dispatch
        # error — the scheduler's device-failure handler owns it (and a
        # DELAY here is the `hang` action the supervised deadline
        # bounds: before this PR it froze the scheduler forever).
        faultinject.site(faultinject.SITE_DISPATCH)
        self._check_epoch(epoch)
        topo, topo_dev, state, batch = (plan.topo, plan.topo_dev,
                                        plan.state, plan.batch)
        start_rank = plan.start_rank
        max_rank = max_rank_bound(batch.wl_cq, topo.cq_cohort,
                                  topo.cohort_root)
        pargs = None
        if preempt_batch is not None:
            from kueue_tpu.solver import preempt as devpreempt
            pargs = devpreempt.preempt_args(preempt_batch)
        fargs = None
        if fair_batch is not None:
            from kueue_tpu.solver import fairpreempt
            fargs = fairpreempt.fair_args(fair_batch)
        if fargs is None:
            # fs_strategies is a STATIC jit arg that only parameterizes
            # the fair-preemption program: with no fair batch this cycle
            # it is dead, but a non-empty tuple would still mint a
            # distinct (computationally identical) executable — and the
            # scheduler's sync path always passes the configured flags.
            # Normalize so the warmed variants are reused.
            fs_flags = ()

        # Mid-traffic compile accounting (solver/COMPILE.md): the
        # variant keys mirror the warm helpers' registry keys exactly,
        # so a dispatch of a warmed bucket never counts and a dispatch
        # of an unwarmed one always does.
        dims = self._topo_dims(topo)
        W = batch.requests.shape[0]
        D = plan.deltas[0].shape[0] if plan.deltas is not None else None
        pshapes = (tuple(np.asarray(a).shape for a in pargs)
                   if pargs is not None else ())
        fshapes = (tuple(np.asarray(a).shape for a in fargs)
                   if fargs is not None else ())
        sr_flag = start_rank is not None
        # Decision-only fetch: compact-capable topologies dispatch the
        # packed-output program variants; the fetch then ships the
        # compact decisions buffer instead of the dense [W,...] arrays.
        compact = self._compact_flag(topo)
        # MultiKueue capacity columns ride the SAME execute (scored by
        # kernel.score_cluster_columns_impl); their bucketed [K,F,R]
        # shape keys the program variant like the other batch dims.
        cargs = (encode.cluster_args_device(plan.cluster)
                 if plan.cluster is not None else None)
        kdim = plan.cluster.ccap.shape if plan.cluster is not None else None

        # Identity check: the plan must have been built on the CURRENT
        # ResidentState — after an invalidate + re-establish, a stale
        # plan's decisions must not chain into the fresh device arrays.
        rs = self._resident
        if plan.resident and plan.rs is not rs:
            plan.resident = False
        establishing = rs is None or rs.usage_dev is None
        arena_bytes = None
        if plan.resident and rs is not None and rs.token == topo.token:
            usage_in = (rs.usage_dev if rs.usage_dev is not None
                        else state.usage)
            cohort_in = (rs.cohort_dev if rs.cohort_dev is not None
                         else state.cohort_usage)
            if plan.slots is not None:
                # Arena-resident dispatch: the batch rows already live on
                # device — ship only the head slot indices plus a sparse
                # scatter of the rows that changed since the last
                # dispatch (applied to the twin by prepare_device), and
                # gather on device.
                t_sc = time.perf_counter()
                # Bounded acquire: healthy dispatches never contend
                # (one dispatcher at a time), so failing to take the
                # lock means a previous dispatch is WEDGED inside the
                # upload. Fail fast with a DeviceFault instead of
                # blocking out the whole supervise deadline — otherwise
                # every breaker probe for the outage's duration would
                # park another orphaned thread (plus its Plan arrays)
                # behind the dead call.
                if not self._arena_lock.acquire(timeout=1.0):
                    raise DeviceFault(
                        "arena upload busy: a previous dispatch is "
                        "wedged in the device upload")
                try:
                    # Entry check AND mutual exclusion: an orphan that
                    # was abandoned before reaching here bails; one that
                    # is already wedged inside holds the lock, so later
                    # dispatches fail fast above — never two threads in
                    # the upload.
                    self._check_epoch(epoch)
                    arena_dev, up_nbytes = self._arena.prepare_device()
                    if epoch is not None and epoch != self._dispatch_epoch:
                        # Abandoned WHILE inside the upload: the publish
                        # (arena.dev, cleared dirty set) is stale — drop
                        # the twin (the next live dispatch re-uploads
                        # wholesale from the host arrays, which faults
                        # never touch) before any later dispatch can
                        # read it, then bail. An abandonment landing
                        # after this check is the live cycle's own —
                        # its upload was consistent, and the scheduler's
                        # fault path drops the twin right after.
                        self._arena.drop_device()
                        raise DeviceFault(
                            "dispatch abandoned by supervisor")
                finally:
                    self._arena_lock.release()
                t_sc_end = time.perf_counter()
                # Same accumulation the recorder span gets: the perf
                # artifact's phase breakdown carries the scatter
                # sub-split exactly as /debug/cycles nests it.
                self.phase_s["dispatch.scatter"] += t_sc_end - t_sc
                if self._recorder is not None:
                    # Nested under dispatch (dotted name: excluded from
                    # per-phase sums — it's already inside dispatch).
                    self._recorder.span("dispatch.scatter", t_sc,
                                        t_sc_end - t_sc)
                slots_w = np.full(W, -1, np.int32)
                slots_w[:batch.n] = plan.slots
                arena_bytes = up_nbytes + slots_w.nbytes
                if note_program(("arena", dims, self._arena.cap, W,
                                 self.max_podsets, max_rank, fair_sharing,
                                 sr_flag, D, pshapes, fshapes,
                                 tuple(fs_flags), compact, kdim)):
                    self._note_mid_traffic_compile("arena", W)
                result = solve_cycle_resident_arena(
                    topo_dev, usage_in, cohort_in, plan.deltas,
                    arena_dev, slots_w,
                    num_podsets=self.max_podsets, max_rank=max_rank,
                    fair_sharing=fair_sharing, start_rank=start_rank,
                    preempt_args=pargs, fair_preempt_args=fargs,
                    fs_strategies=fs_flags, compact=compact,
                    cluster_args=cargs)
            else:
                if note_program(("resident", dims, W, self.max_podsets,
                                 max_rank, fair_sharing, sr_flag, D,
                                 pshapes, fshapes, tuple(fs_flags),
                                 compact, kdim)):
                    self._note_mid_traffic_compile("resident", W)
                result = solve_cycle_resident(
                    topo_dev, usage_in, cohort_in, plan.deltas,
                    batch.requests, batch.podset_active, batch.wl_cq,
                    batch.priority, batch.timestamp, batch.eligible,
                    batch.solvable, num_podsets=self.max_podsets,
                    max_rank=max_rank, fair_sharing=fair_sharing,
                    start_rank=start_rank, preempt_args=pargs,
                    fair_preempt_args=fargs, fs_strategies=fs_flags,
                    compact=compact, cluster_args=cargs)
            rs.usage_dev = result["usage"]
            rs.cohort_dev = result["cohort_usage"]
            if plan.deltas is not None and plan.backlog_gen == rs.backlog_gen:
                rs.device_backlog = {}
                rs.backlog_gen += 1
        else:
            plan.resident = False
            if pargs is None and fargs is None:
                if note_program(("fused", dims, W, self.max_podsets,
                                 max_rank, fair_sharing, sr_flag,
                                 (), (), (), compact, kdim)):
                    self._note_mid_traffic_compile("fused", W)
                result = solve_cycle_fused(
                    topo_dev, state.usage, state.cohort_usage,
                    batch.requests, batch.podset_active, batch.wl_cq,
                    batch.priority, batch.timestamp, batch.eligible,
                    batch.solvable, num_podsets=self.max_podsets,
                    max_rank=max_rank, fair_sharing=fair_sharing,
                    start_rank=start_rank, compact=compact,
                    cluster_args=cargs)
            else:
                if note_program(("preempt", dims, W, self.max_podsets,
                                 max_rank, fair_sharing, sr_flag,
                                 pshapes, fshapes, tuple(fs_flags),
                                 compact, kdim)):
                    self._note_mid_traffic_compile("preempt", W)
                result = solve_cycle_with_preempt(
                    topo_dev, state.usage, state.cohort_usage,
                    batch.requests, batch.podset_active, batch.wl_cq,
                    batch.priority, batch.timestamp, batch.eligible,
                    batch.solvable, pargs,
                    num_podsets=self.max_podsets, max_rank=max_rank,
                    fair_sharing=fair_sharing, start_rank=start_rank,
                    fair_preempt_args=fargs, fs_strategies=fs_flags,
                    compact=compact, cluster_args=cargs)

        # An orphan whose wedged solve call finally returned must not
        # run the bookkeeping below: counters would double-count, and
        # _phase would append its (multi-second) span into whatever
        # cycle trace is CURRENTLY open — polluting the live cycle's
        # /debug/cycles view and the cycle_phase_seconds histograms.
        self._check_epoch(epoch)
        # The decision-only fetch (compact) ships the packed decisions
        # buffer; the staged fetch the five dense arrays. Either way
        # the residency chain (usage/cohort_usage) stays on device.
        keys = (list(DECISION_KEYS) if compact
                else list(DENSE_DECISION_KEYS))
        if plan.cluster is not None:
            keys.append("mk_cluster")
        if preempt_batch is not None:
            keys += ["preempt_targets", "preempt_feasible", "preempt_stats"]
        if fair_batch is not None:
            keys += ["fair_targets", "fair_feasible", "fair_reasons",
                     "fair_stats"]
        if arena_bytes is not None:
            # Arena dispatch: the batch never shipped — only the slot
            # index array and the changed-row scatter did.
            up = arena_bytes
        else:
            batch_np = (batch.requests, batch.podset_active, batch.wl_cq,
                        batch.priority, batch.timestamp, batch.eligible,
                        batch.solvable)
            up = sum(a.nbytes for a in batch_np if isinstance(a, np.ndarray))
        if start_rank is not None:
            up += start_rank.nbytes
        if plan.resident:
            if establishing:  # one-time upload when residency (re)forms
                up += state.usage.nbytes + state.cohort_usage.nbytes
            if plan.deltas is not None:
                up += sum(np.asarray(a).nbytes for a in plan.deltas)
        else:
            up += state.usage.nbytes + state.cohort_usage.nbytes
        if pargs is not None:
            up += sum(np.asarray(a).nbytes for a in pargs)
        if fargs is not None:
            up += sum(np.asarray(a).nbytes for a in fargs)
        self.last_upload_bytes = up
        self.counters["dispatches"] += 1
        self.counters["upload_bytes"] += up
        if plan.resident and establishing:
            self.counters["establishes"] += 1
        inflight = InFlight(plan, result, keys, preempt_batch)
        inflight.fair_batch = fair_batch
        inflight.deadline_s = deadline_s
        inflight.t_dispatch = time.perf_counter()
        self._phase("dispatch", t0, inflight.t_dispatch)
        return inflight

    def start_fetch(self, inflight: InFlight) -> None:
        """Begin fetching the cycle's outputs on a background thread so
        the tunnel round trip overlaps host work (pipelined dispatch)."""
        d = {k: inflight.result[k] for k in inflight.keys
             if k in inflight.result}
        inflight.future = self._fetch_pool_submit(jax.device_get, d)

    def _validate_fetched(self, plan: Plan, fetched: dict) -> None:
        """Cheap output-invariant check on the fetched decision arrays
        (a few [W]-bool ops): a solve whose results violate them is
        corrupt — raise DeviceFault so the scheduler invalidates the
        (possibly poisoned) device-resident state and the heads retry
        on fresh state instead of turning garbage into admissions.
        Corruption that only DENIES (fit bits flipped off) is safe
        without detection: denied entries fall through to the CPU
        nomination path, which is the conformance oracle. See
        RESILIENCE.md §corruption containment."""
        n = plan.batch.n
        fit = fetched.get("fit")
        admitted = fetched.get("admitted")
        if fit is None or admitted is None \
                or np.asarray(fit).shape[0] < n \
                or np.asarray(admitted).shape[0] < n:
            self.counters["validation_faults"] += 1
            raise DeviceFault("solve output missing/short decision arrays")
        fit = np.asarray(fit)[:n].astype(bool)
        admitted = np.asarray(admitted)[:n].astype(bool)
        if bool(np.any(admitted & ~fit)):
            self.counters["validation_faults"] += 1
            raise DeviceFault("solve output corrupt: admitted without fit")
        if bool(np.any(fit & ~plan.batch.solvable[:n])):
            self.counters["validation_faults"] += 1
            raise DeviceFault("solve output corrupt: fit on unsolvable row")

    def _fetch_pool_submit(self, fn, *args):
        if self._fetch_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._fetch_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="solver-fetch")
        return self._fetch_pool.submit(fn, *args)

    def _abandon_fetch(self) -> None:
        """A fetch missed its deadline: orphan the worker (Python cannot
        cancel a blocked device call — only stop waiting for it) and
        mint a fresh pool so the next fetch isn't queued behind the
        wedged one."""
        pool, self._fetch_pool = self._fetch_pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def collect(self, inflight: InFlight, snapshot: Snapshot):
        """Fetch (or join the background fetch), decode, and update the
        residency bookkeeping. Returns (decisions, preemption or None).

        With a deadline (inflight.deadline_s, stamped at dispatch), the
        fetch is BOUNDED: it runs on the background pool and a result
        that hasn't landed deadline seconds after dispatch raises
        DispatchTimeout — the in-flight device arrays are abandoned and
        the caller invalidates residency (host mirrors are the truth;
        the device twin is a rebuildable cache), instead of the cycle
        blocking forever on a wedged tunnel."""
        import time
        from concurrent.futures import TimeoutError as FetchTimeout
        plan = inflight.plan
        t0 = time.perf_counter()
        deadline = inflight.deadline_s
        future, sync_fetch = inflight.future, inflight.future is None
        if sync_fetch and deadline is not None:
            # Synchronous cycle under a deadline: route the device_get
            # through the pool so the wait is interruptible.
            d = {k: inflight.result[k] for k in inflight.keys
                 if k in inflight.result}
            future = self._fetch_pool_submit(jax.device_get, d)
        if future is not None:
            if deadline is not None:
                remaining = deadline - (time.perf_counter()
                                        - inflight.t_dispatch)
                try:
                    fetched = future.result(timeout=max(0.0, remaining))
                except FetchTimeout:
                    self._abandon_fetch()
                    self.counters["dispatch_timeouts"] += 1
                    waited = time.perf_counter() - inflight.t_dispatch
                    raise DispatchTimeout(deadline, waited) from None
            else:
                fetched = future.result()
        else:
            fetched = jax.device_get({k: inflight.result[k]
                                      for k in inflight.keys
                                      if k in inflight.result})
        if sync_fetch:
            # The wait IS the sync floor only on a synchronous cycle (a
            # background fetch's round trip overlapped host work).
            self._observe_sync((time.perf_counter() - t0) * 1e3)
        # Injection site: CORRUPT scrambles the fetched decision arrays
        # (caught by the invariant validation below), DELAY models a
        # fetch that landed only after the deadline.
        fetched = faultinject.site(faultinject.SITE_COLLECT, fetched,
                                   corrupt=_scramble_fetched)
        if deadline is not None:
            # Bounds collect-INTERNAL time only (the fetch wait + any
            # injected delay). Deliberately not time-since-dispatch: in
            # a pipelined cycle, legitimate host work between dispatch
            # and collect must not turn a completed fetch into a
            # spurious timeout — the genuine-hang bound is the
            # result(timeout=remaining) above, whose budget does count
            # from dispatch because the background fetch ran that
            # whole time.
            waited = time.perf_counter() - t0
            if waited > deadline:
                self.counters["dispatch_timeouts"] += 1
                raise DispatchTimeout(deadline, waited)
        # Wire payload accounting BEFORE the host-side unpack: the
        # compact decision fetch is the transferred bytes, not the
        # dense arrays it expands into.
        wire_nbytes = sum(np.asarray(v).nbytes for v in fetched.values())
        fetched = unpack_decisions(fetched, self.max_podsets,
                                   plan.topo.nominal.shape[2])
        self._validate_fetched(plan, fetched)
        t_fetch = time.perf_counter()
        self._phase("fetch", t0, t_fetch)
        self.counters["collects"] += 1
        self.last_fetch_bytes = wire_nbytes
        self.counters["fetch_bytes"] += self.last_fetch_bytes
        aux = None
        if inflight.preempt_batch is not None:
            aux = {"preempt": (np.asarray(fetched["preempt_targets"]),
                               np.asarray(fetched["preempt_feasible"]))}
            if "preempt_stats" in fetched:
                aux["preempt_stats"] = np.asarray(fetched["preempt_stats"])
        if getattr(inflight, "fair_batch", None) is not None:
            aux = aux or {}
            aux["fair"] = (np.asarray(fetched["fair_targets"]),
                           np.asarray(fetched["fair_feasible"]),
                           np.asarray(fetched["fair_reasons"]))
            if "fair_stats" in fetched:
                aux["fair_stats"] = np.asarray(fetched["fair_stats"])
        # Mirror/pending updates only apply when the plan's ResidentState
        # is still the live one (not invalidated+re-established since).
        resident_ok = plan.resident and plan.rs is self._resident
        decisions = self._decode_batch(plan.batch.infos, snapshot, plan.topo,
                                       plan.batch, fetched,
                                       resident=resident_ok,
                                       cluster_names=(plan.cluster.names
                                                      if plan.cluster
                                                      else None))
        self._phase("decode", t_fetch, time.perf_counter())
        return decisions, aux

    def batched_partial_admission(self, plan: Plan, snapshot: Snapshot,
                                  infos: list):
        """Partial admission for many entries at once (VERDICT r3 ask #9;
        reference: podset_reducer.go:29-86 run per entry per probe).

        All entries' binary searches advance in LOCKSTEP: each round,
        every active entry's probe (its PodSets scaled to the candidate
        counts) becomes one row of a single Phase A batch evaluated on
        the LOCAL XLA-CPU backend — exact fit bits, no tunnel — so
        log2(delta) batched evaluations replace per-entry per-probe full
        assigner runs. Only valid for entries whose probes cannot pass
        via preemption (the caller restricts to Never/Never CQs, where
        the CPU reducer's predicate degenerates to pure fit).

        Returns {entry index: reduced counts list | None}, or None when
        no local CPU backend exists (caller falls back to the CPU
        reducer)."""
        topo, state = plan.topo, plan.state

        def shadow(info, counts):
            s = wlpkg.Info.__new__(wlpkg.Info)
            s.obj = info.obj
            s.cluster_queue = info.cluster_queue
            s.last_assignment = None
            s._fru_cache = None
            s._fr_keys_cache = None
            s.total_requests = [
                psr if psr.count == c else psr.scaled_to(c)
                for psr, c in zip(info.total_requests, counts)]
            return s

        from kueue_tpu.scheduler.podset_reducer import (
            counts_for_index, reduction_space)

        class _Search:
            __slots__ = ("full", "deltas", "total", "lo", "hi", "good")

            def __init__(self, pod_sets):
                # shared interpolation with the CPU PodSetReducer — the
                # feature's contract is bit-for-bit equality with it
                self.full, self.deltas, self.total = reduction_space(pod_sets)
                self.lo, self.hi = 0, self.total + 1
                self.good = None

            def counts(self, i):
                return counts_for_index(self.full, self.deltas,
                                        self.total, i)

        searches = {i: _Search(info.obj.spec.pod_sets)
                    for i, info in enumerate(infos)}
        out = {i: None for i in range(len(infos))}
        for _round in range(40):  # log2(total_delta) rounds in practice
            active = [i for i, s in searches.items()
                      if s.total > 0 and s.lo < s.hi]
            if not active:
                break
            mids = {i: (searches[i].lo + searches[i].hi) // 2
                    for i in active}
            shadows = [shadow(infos[i], searches[i].counts(mids[i]))
                       for i in active]
            batch = encode.encode_workloads(shadows, snapshot, topo,
                                            ordering=self.ordering,
                                            max_podsets=self.max_podsets)
            fit = self._route(topo, state, batch, None)
            if fit is None:
                return None  # no local backend — CPU reducer fallback
            solvable = batch.solvable
            for k, i in enumerate(active):
                s = searches[i]
                if not solvable[k]:
                    # unencodable probe: hand the entry to the CPU reducer
                    s.lo = s.hi = 0
                    s.good = None
                    out[i] = CPU_FALLBACK
                    continue
                if fit[k]:
                    s.good = mids[i]
                    s.hi = mids[i]
                else:
                    s.lo = mids[i] + 1
        for i, s in searches.items():
            if out[i] is CPU_FALLBACK:
                continue
            if s.good is not None and s.lo == s.good:
                out[i] = s.counts(s.good)
        return out

    def solve(self, snapshot: Snapshot, entries: list,
              fair_sharing: bool = False) -> dict:
        """entries: list of workload Info. Returns
        {entry index -> (fa.Assignment, admitted)} for every entry the
        solver could fully assign (fit mode). admitted=False means the
        assignment no longer fit after intra-cycle accounting — the
        scheduler skips it exactly like the reference's sequential
        re-check (scheduler.go:266-273) instead of re-assigning flavors
        against post-cycle usage."""
        plan = self.prepare(snapshot, entries)
        if plan is None:
            return {}
        decisions, _ = self.solve_prepared(plan, snapshot,
                                           fair_sharing=fair_sharing)
        return decisions

    def _decode_batch(self, entries: list, snapshot: Snapshot,
                      topo: encode.Topology, batch, fetched: dict,
                      resident: bool = False,
                      cluster_names: Optional[tuple] = None) -> dict:
        """Decode device output into the scheduler's Assignment form,
        including the LastTriedFlavorIdx resume state exactly as the CPU
        assigner stores it (reference: flavorassigner.go:289-324): the
        rank where the search ended, -1 when the list was exhausted
        (chosen == last flavor, or a TryNextFlavor CQ settling for a
        borrowing fit after scanning the whole list).

        All numeric work (rank, group exhaustion, borrow flags) runs as
        one vectorized numpy pass over the admitted rows; the per-entry
        loop only assembles the Assignment objects from Python lists."""
        from kueue_tpu.api.corev1 import RESOURCE_PODS
        n = batch.n
        # MultiKueue placements decoded this cycle (ISSUE 13): reset
        # unconditionally so a column-less cycle never serves a stale
        # map to the scheduler's placement flush.
        self.last_placements = {}
        fit = np.asarray(fetched["fit"])[:n]
        idx = np.flatnonzero(fit)
        if idx.size == 0:
            return {}
        mkc = fetched.get("mk_cluster")
        mk_l = (np.asarray(mkc)[:n][idx].tolist()
                if mkc is not None and cluster_names else None)
        admitted = np.asarray(fetched["admitted"])[:n][idx]     # [M]
        chosen = np.asarray(fetched["chosen"])[:n][idx]          # [M,P,R]
        borrows = np.asarray(fetched["borrows"])[:n][idx]        # [M]
        cb = fetched.get("chosen_borrow")
        chosen_borrow = (np.asarray(cb)[:n][idx] if cb is not None
                         else np.zeros_like(chosen, dtype=bool))  # [M,P,R]
        qi_arr = batch.wl_cq[idx]                                 # [M]

        # With FlavorFungibility off the CPU assigner never writes the
        # tried index (stays at the dataclass default 0).
        fungibility_on = features.enabled(features.FLAVOR_FUNGIBILITY)
        fi_safe = np.maximum(chosen, 0)
        rank = topo.flavor_rank[qi_arr[:, None, None], fi_safe]   # [M,P,R]
        gi = topo.group_id[qi_arr]                                # [M,R]
        gsize = topo.group_size[qi_arr[:, None], np.maximum(gi, 0)]  # [M,R]
        exhausted = rank == gsize[:, None, :] - 1
        prefer_nb = topo.prefer_no_borrow[qi_arr]                 # [M]
        # TryNextFlavor CQs scanned the whole list looking for a no-borrow
        # fit before settling for this borrowing one.
        exhausted |= prefer_nb[:, None, None] & chosen_borrow
        if fungibility_on:
            tried = np.where(exhausted | (chosen < 0), -1, rank)
        else:
            tried = np.zeros_like(rank)

        # Flavor names resolved for the whole batch in one fancy-indexed
        # gather (the per-row Python lookups dominated decode time).
        fname_grid = np.asarray(topo.flavors, dtype=object)[fi_safe]  # [M,P,R]
        fname_l = fname_grid.tolist()
        tried_l = tried.tolist()
        chosen_neg = (chosen < 0).tolist()
        borrows_l = borrows.tolist()
        admitted_l = admitted.tolist()
        resource_index = topo.resource_index
        FlavorAssignmentC = fa.FlavorAssignment
        PodSetResultC = fa.PodSetAssignmentResult
        AssignmentC = fa.Assignment
        StateC = wlpkg.AssignmentClusterQueueState

        # last_state generations per CQ, read fresh per cycle: the cohort
        # generation is the cache's global capacity version, which moves
        # on events (e.g. workload removal) that never rebuild the
        # topology, so caching it across cycles would hand out stale
        # resume state.
        gen_cache: dict = {}
        rs = self._resident if resident else None
        mirror_corr: dict = {}
        out = {}
        for row, wi in enumerate(idx.tolist()):
            info = entries[wi]
            gens = gen_cache.get(info.cluster_queue)
            if gens is None:
                cq = snapshot.cluster_queues[info.cluster_queue]
                gens = (cq.allocatable_resource_generation,
                        cq.cohort.allocatable_resource_generation
                        if cq.cohort else 0)
                gen_cache[info.cluster_queue] = gens
            assignment = AssignmentC(borrowing=bool(borrows_l[row]))
            assignment.last_state = StateC(
                cluster_queue_generation=gens[0], cohort_generation=gens[1])
            covers_pods = topo.covers_pods[batch.wl_cq[wi]]
            usage = assignment.usage
            for pi, psr in enumerate(info.total_requests):
                reqs = dict(psr.requests)
                if covers_pods:
                    reqs[RESOURCE_PODS] = psr.count
                fname_p = fname_l[row][pi]
                neg_p = chosen_neg[row][pi]
                tried_p = tried_l[row][pi]
                flavors = {}
                flavor_idx = {}
                for r, v in reqs.items():
                    ri = resource_index[r]
                    if v > 0 and neg_p[ri]:
                        raise AssertionError(
                            "solver admitted workload without flavor")
                    fname = fname_p[ri]
                    t = tried_p[ri]
                    flavors[r] = FlavorAssignmentC(name=fname, mode=fa.FIT,
                                                   tried_flavor_idx=t)
                    flavor_idx[r] = t
                    fr = FlavorResource(fname, r)
                    usage[fr] = usage.get(fr, 0) + v
                assignment.pod_sets.append(PodSetResultC(
                    name=psr.name, flavors=flavors, requests=reqs,
                    count=psr.count))
                assignment.last_state.last_tried_flavor_idx.append(flavor_idx)
            was_admitted = bool(admitted_l[row])
            if mk_l is not None and was_admitted:
                ki = mk_l[row]
                if 0 <= ki < len(cluster_names):
                    # device-made placement: the multikueue controller
                    # executes it (scheduler forwards via on_placement)
                    self.last_placements[info.key] = cluster_names[ki]
            if rs is not None and was_admitted:
                # Device Phase B applied this usage; track it until the
                # assume write confirms it through the journal, and bring
                # the host mirror up to the device state.
                rs.pending[info.key] = (info.cluster_queue, dict(usage), 0)
                cq_name = info.cluster_queue
                for fr, v in usage.items():
                    k = (cq_name, fr)
                    mirror_corr[k] = mirror_corr.get(k, 0) + v
            out[wi] = (assignment, was_admitted)
        if rs is not None and mirror_corr:
            deltas = encode.encode_deltas(mirror_corr, topo)
            if deltas is not None:
                encode.apply_deltas_np(topo, rs.mirror_usage,
                                       rs.mirror_cohort, deltas)
        return out
