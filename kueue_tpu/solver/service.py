"""BatchSolver: the TPU solve plugged into the admission path.

Integration contract (mirrors how the reference's AdmissionCheck
controllers plug in, per BASELINE.json's north star): the Scheduler hands
the cycle's validated heads + snapshot to the solver; the solver returns
full fit-mode admissions (flavor assignments + usage) computed on device;
entries it could not admit fall through to the CPU path (preemption,
partial admission, detailed status messages).

Equivalence class vs the reference: for cycles where every nominated
entry is fit-mode, the solver's result is identical to the sequential
scheduler (same ordering, same intra-cycle accounting — differentially
tested in tests/test_solver.py). In mixed cycles, ALL nomination (fit on
device, preempt-mode on CPU, preemption targets on device) happens
against the pre-cycle snapshot exactly like the reference's nominate
phase — but the admit loop is split: every device fit-mode admission is
accounted before preempt-mode entries run, instead of interleaving by
the global borrow->share->priority->FIFO order. Consequence (pinned by
tests/test_solver.py::TestMixedCycleEquivalenceClass): a fit-mode entry
can consume capacity the reference would have reserved for a BLOCKED
higher-priority preemptor (scheduler.go:245-253); the blocked preemptor
retries next cycle. Entries with preemption targets still re-check fits
against post-admission usage, so no over-admission is possible. The CPU
path (solver=None) remains the strict-conformance mode.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from kueue_tpu import features
from kueue_tpu.cache.snapshot import Snapshot
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.core.resources import FlavorResource
from kueue_tpu.scheduler import flavorassigner as fa
from kueue_tpu.solver import encode
import jax

from kueue_tpu.solver.kernel import (
    max_rank_bound,
    solve_cycle_fused,
    solve_cycle_with_preempt,
    solve_phase_a,
    topo_to_device,
)


def _topo_np(topo) -> dict:
    """The kernel's topology dict as plain numpy (for the local CPU
    router); same field list as kernel.topo_to_device."""
    from kueue_tpu.solver.kernel import TOPO_FIELDS
    return {name: getattr(topo, name) for name in TOPO_FIELDS}


class Plan:
    """One cycle's encoded inputs + the host-side routing decision."""

    def __init__(self, topo, topo_dev, state, batch, start_rank, fit_pred):
        self.topo = topo
        self.topo_dev = topo_dev
        self.state = state
        self.batch = batch
        self.start_rank = start_rank
        # fit_pred[i]: the router's exact Phase A fit bit for entry i —
        # entries predicted non-fit are CPU-nominated (preempt-mode
        # discovery) BEFORE the device sync so fit + preemption solve in
        # one execute.
        self.fit_pred = fit_pred


class BatchSolver:
    def __init__(self, max_podsets: int = 4, ordering: Optional[wlpkg.Ordering] = None,
                 mesh=None, backend: str = "jit"):
        """backend: "jit" (XLA on the configured platform — the TPU path)
        or "native" (the C++ solve in kueue_tpu.native — the accelerator-
        free runtime; falls back to jit when the library is unavailable)."""
        self.max_podsets = max_podsets
        self.ordering = ordering or wlpkg.Ordering()
        self.mesh = mesh  # optional jax.sharding.Mesh for multi-chip solve
        self.backend = backend
        self._topo_cache = None
        self._topo_key = None
        self._cpu_device = None  # lazy: local XLA-CPU device for routing
        self._sync_samples: list = []  # recent device sync costs (ms)

    def estimated_sync_ms(self, default: float = 120.0) -> float:
        """The device dispatch+sync floor: calibrated once with a trivial
        dispatch (so the first estimate isn't a compile-inflated real
        cycle), then refined as the MIN of observed cycle syncs — robust
        to compile-time outliers, and a floor is a lower bound by
        definition. Feeds the scheduler's work gates: device work must
        save more than this to dispatch."""
        if not self._sync_samples:
            try:
                self._sync_samples.append(self._calibrate_floor())
            except Exception:  # noqa: BLE001 — backend unavailable
                return default
        return min(self._sync_samples)

    @staticmethod
    def _calibrate_floor() -> float:
        import time
        import jax.numpy as jnp
        triv = jax.jit(lambda a: a + 1)
        np.asarray(triv(jnp.zeros(8, jnp.int32)))  # compile
        t0 = time.perf_counter()
        np.asarray(triv(jnp.zeros(8, jnp.int32)))
        return (time.perf_counter() - t0) * 1e3

    def _observe_sync(self, ms: float) -> None:
        self._sync_samples.append(ms)
        if len(self._sync_samples) > 16:
            self._sync_samples.pop(0)

    # --- encoding with topology caching across cycles ---

    def _topology(self, snapshot: Snapshot):
        # cohort_epoch: cohort re-parents / quota edits don't bump any
        # CQ's generation but change the encoded tree. flavor_spec_epoch:
        # ResourceFlavor taint/label edits change eligibility rows without
        # bumping any CQ generation.
        key = (snapshot.cohort_epoch, snapshot.flavor_spec_epoch) + tuple(sorted(
            (name, cq.allocatable_resource_generation)
            for name, cq in snapshot.cluster_queues.items()))
        if key != self._topo_key:
            self._topo_key = key
            topo = encode.encode_topology(snapshot)
            self._topo_cache = (topo, topo_to_device(topo))
        return self._topo_cache

    def prepare(self, snapshot: Snapshot, entries: list) -> Optional[Plan]:
        """Encode the cycle and route it: the exact Phase A fit bit is
        computed on the LOCAL XLA-CPU backend (~1 ms at the north-star
        shape) so the scheduler knows, before any device sync, which
        entries need CPU preempt-mode nomination. Their preemption
        problems then ship in the same execute as the fit solve
        (kernel.solve_cycle_with_preempt): one device sync per cycle."""
        if not entries:
            return None
        topo, topo_dev = self._topology(snapshot)
        state = encode.encode_state(snapshot, topo)
        batch = encode.encode_workloads(entries, snapshot, topo,
                                        ordering=self.ordering,
                                        max_podsets=self.max_podsets)
        if not batch.solvable.any():
            return None
        start_rank = batch.start_rank if batch.start_rank.any() else None
        fit_pred = self._route(topo, state, batch, start_rank)
        return Plan(topo, topo_dev, state, batch, start_rank, fit_pred)

    def _route(self, topo, state, batch, start_rank):
        """Exact host-side replica of the device Phase A (same jitted
        program, local CPU backend): integer math, so the fit bits are
        identical to the device's. Returns [n] bool, or None when no
        local CPU backend exists (the scheduler then nominates
        device-rejected entries after the sync instead)."""
        if self._cpu_device is None:
            try:
                self._cpu_device = jax.devices("cpu")[0]
            except Exception:  # noqa: BLE001 — platform without CPU backend
                self._cpu_device = False
        if self._cpu_device is False:
            return None
        cached = getattr(self, "_topo_cpu", None)
        if cached is None or cached[0] != topo.token:
            cached = (topo.token,
                      jax.device_put(_topo_np(topo), self._cpu_device))
            self._topo_cpu = cached
        with jax.default_device(self._cpu_device):
            out = solve_phase_a(cached[1], state.usage, state.cohort_usage,
                                batch.requests, batch.podset_active,
                                batch.wl_cq, batch.eligible, batch.solvable,
                                num_podsets=self.max_podsets,
                                fair_sharing=False, start_rank=start_rank)
            fit = np.asarray(out[0])
        return fit[:batch.n]

    def solve_prepared(self, plan: Plan, snapshot: Snapshot,
                       preempt_batch=None, fair_sharing: bool = False):
        """Dispatch the cycle (fit solve, plus the preemption batch when
        present, as ONE device program), sync once, decode. Returns
        (decisions dict, (targets_mask, feasible) or None)."""
        topo, topo_dev, state, batch = (plan.topo, plan.topo_dev,
                                        plan.state, plan.batch)
        start_rank = plan.start_rank
        entries = batch.infos

        # The native ABI encodes the flat (single-level) cohort forest and
        # no fair-share sort key, flavor-resume state, or per-resource
        # borrow flags (needed for TryNextFlavor resume decode); those go
        # through the jit path.
        if (self.backend == "native" and self.mesh is None
                and preempt_batch is None
                and topo.cq_chain.shape[1] == 1 and not fair_sharing
                and start_rank is None and not topo.prefer_no_borrow.any()):
            from kueue_tpu import native
            result = native.solve_cycle_native(
                topo, state.usage, state.cohort_usage, batch.requests,
                batch.podset_active, batch.wl_cq, batch.priority,
                batch.timestamp, batch.eligible, batch.solvable)
            return (self._decode_batch(entries, snapshot, topo, batch,
                                       result), None)

        pre = None
        if self.mesh is not None:
            from kueue_tpu.parallel.mesh import solve_cycle_sharded
            result = solve_cycle_sharded(self.mesh, topo_dev, state, batch,
                                         self.max_podsets,
                                         fair_sharing=fair_sharing,
                                         start_rank=start_rank)
            if preempt_batch is not None:
                # The sharded fit solve doesn't fuse the preemption
                # program; pay a second dispatch (single-host mesh only).
                from kueue_tpu.solver import preempt as devpreempt
                pre = devpreempt.solve_preemption_batch(
                    topo_dev, state.usage, state.cohort_usage, preempt_batch)
            fetched = jax.device_get({k: result[k] for k in
                                      ("admitted", "fit", "chosen", "borrows",
                                       "chosen_borrow") if k in result})
            return (self._decode_batch(entries, snapshot, topo, batch,
                                       fetched), pre)

        max_rank = max_rank_bound(batch.wl_cq, topo.cq_cohort,
                                  topo.cohort_root)
        if preempt_batch is None:
            # fused cohort-parallel cycle: Phase A + device-built order
            # grid + row-parallel Phase B in ONE dispatch
            result = solve_cycle_fused(
                topo_dev, state.usage, state.cohort_usage,
                batch.requests, batch.podset_active, batch.wl_cq,
                batch.priority, batch.timestamp, batch.eligible,
                batch.solvable, num_podsets=self.max_podsets,
                max_rank=max_rank, fair_sharing=fair_sharing,
                start_rank=start_rank)
            keys = ("admitted", "fit", "chosen", "borrows", "chosen_borrow")
        else:
            from kueue_tpu.solver import preempt as devpreempt
            result = solve_cycle_with_preempt(
                topo_dev, state.usage, state.cohort_usage,
                batch.requests, batch.podset_active, batch.wl_cq,
                batch.priority, batch.timestamp, batch.eligible,
                batch.solvable,
                devpreempt.preempt_args(preempt_batch),
                num_podsets=self.max_podsets, max_rank=max_rank,
                fair_sharing=fair_sharing, start_rank=start_rank)
            keys = ("admitted", "fit", "chosen", "borrows", "chosen_borrow",
                    "preempt_targets", "preempt_feasible")

        # One execute, one sync: all outputs come from the same device
        # program, so the first fetch pays the tunnel round trip and the
        # rest are free.
        import time
        t0 = time.perf_counter()
        fetched = jax.device_get({k: result[k] for k in keys if k in result})
        self._observe_sync((time.perf_counter() - t0) * 1e3)
        if preempt_batch is not None:
            pre = (np.asarray(fetched["preempt_targets"]),
                   np.asarray(fetched["preempt_feasible"]))
        return (self._decode_batch(entries, snapshot, topo, batch, fetched),
                pre)

    def solve(self, snapshot: Snapshot, entries: list,
              fair_sharing: bool = False) -> dict:
        """entries: list of workload Info. Returns
        {entry index -> (fa.Assignment, admitted)} for every entry the
        solver could fully assign (fit mode). admitted=False means the
        assignment no longer fit after intra-cycle accounting — the
        scheduler skips it exactly like the reference's sequential
        re-check (scheduler.go:266-273) instead of re-assigning flavors
        against post-cycle usage."""
        plan = self.prepare(snapshot, entries)
        if plan is None:
            return {}
        decisions, _ = self.solve_prepared(plan, snapshot,
                                           fair_sharing=fair_sharing)
        return decisions

    def _decode_batch(self, entries: list, snapshot: Snapshot,
                      topo: encode.Topology, batch, fetched: dict) -> dict:
        """Decode device output into the scheduler's Assignment form,
        including the LastTriedFlavorIdx resume state exactly as the CPU
        assigner stores it (reference: flavorassigner.go:289-324): the
        rank where the search ended, -1 when the list was exhausted
        (chosen == last flavor, or a TryNextFlavor CQ settling for a
        borrowing fit after scanning the whole list).

        All numeric work (rank, group exhaustion, borrow flags) runs as
        one vectorized numpy pass over the admitted rows; the per-entry
        loop only assembles the Assignment objects from Python lists."""
        from kueue_tpu.api.corev1 import RESOURCE_PODS
        n = batch.n
        fit = np.asarray(fetched["fit"])[:n]
        idx = np.flatnonzero(fit)
        if idx.size == 0:
            return {}
        admitted = np.asarray(fetched["admitted"])[:n][idx]     # [M]
        chosen = np.asarray(fetched["chosen"])[:n][idx]          # [M,P,R]
        borrows = np.asarray(fetched["borrows"])[:n][idx]        # [M]
        cb = fetched.get("chosen_borrow")
        chosen_borrow = (np.asarray(cb)[:n][idx] if cb is not None
                         else np.zeros_like(chosen, dtype=bool))  # [M,P,R]
        qi_arr = batch.wl_cq[idx]                                 # [M]

        # With FlavorFungibility off the CPU assigner never writes the
        # tried index (stays at the dataclass default 0).
        fungibility_on = features.enabled(features.FLAVOR_FUNGIBILITY)
        fi_safe = np.maximum(chosen, 0)
        rank = topo.flavor_rank[qi_arr[:, None, None], fi_safe]   # [M,P,R]
        gi = topo.group_id[qi_arr]                                # [M,R]
        gsize = topo.group_size[qi_arr[:, None], np.maximum(gi, 0)]  # [M,R]
        exhausted = rank == gsize[:, None, :] - 1
        prefer_nb = topo.prefer_no_borrow[qi_arr]                 # [M]
        # TryNextFlavor CQs scanned the whole list looking for a no-borrow
        # fit before settling for this borrowing one.
        exhausted |= prefer_nb[:, None, None] & chosen_borrow
        if fungibility_on:
            tried = np.where(exhausted | (chosen < 0), -1, rank)
        else:
            tried = np.zeros_like(rank)

        chosen_l = chosen.tolist()
        tried_l = tried.tolist()
        borrows_l = borrows.tolist()
        admitted_l = admitted.tolist()
        flavor_names = topo.flavors
        resource_index = topo.resource_index

        # last_state generations per CQ, read fresh per cycle: the cohort
        # generation is the cache's global capacity version, which moves
        # on events (e.g. workload removal) that never rebuild the
        # topology, so caching it across cycles would hand out stale
        # resume state.
        gen_cache: dict = {}
        out = {}
        for row, wi in enumerate(idx.tolist()):
            info = entries[wi]
            gens = gen_cache.get(info.cluster_queue)
            if gens is None:
                cq = snapshot.cluster_queues[info.cluster_queue]
                gens = (cq.allocatable_resource_generation,
                        cq.cohort.allocatable_resource_generation
                        if cq.cohort else 0)
                gen_cache[info.cluster_queue] = gens
            assignment = fa.Assignment(borrowing=bool(borrows_l[row]))
            assignment.last_state = wlpkg.AssignmentClusterQueueState(
                cluster_queue_generation=gens[0], cohort_generation=gens[1])
            covers_pods = topo.covers_pods[batch.wl_cq[wi]]
            usage = assignment.usage
            for pi, psr in enumerate(info.total_requests):
                reqs = dict(psr.requests)
                if covers_pods:
                    reqs[RESOURCE_PODS] = psr.count
                chosen_p = chosen_l[row][pi]
                tried_p = tried_l[row][pi]
                flavors = {}
                flavor_idx = {}
                for r, v in reqs.items():
                    ri = resource_index[r]
                    fi = chosen_p[ri]
                    if v > 0 and fi < 0:
                        raise AssertionError(
                            "solver admitted workload without flavor")
                    fname = flavor_names[fi] if fi >= 0 else flavor_names[0]
                    t = tried_p[ri]
                    flavors[r] = fa.FlavorAssignment(name=fname, mode=fa.FIT,
                                                     tried_flavor_idx=t)
                    flavor_idx[r] = t
                    fr = FlavorResource(fname, r)
                    usage[fr] = usage.get(fr, 0) + v
                assignment.pod_sets.append(fa.PodSetAssignmentResult(
                    name=psr.name, flavors=flavors, requests=reqs,
                    count=psr.count))
                assignment.last_state.last_tried_flavor_idx.append(flavor_idx)
            out[wi] = (assignment, bool(admitted_l[row]))
        return out
