"""BatchSolver: the TPU solve plugged into the admission path.

Integration contract (mirrors how the reference's AdmissionCheck
controllers plug in, per BASELINE.json's north star): the Scheduler hands
the cycle's validated heads + snapshot to the solver; the solver returns
full fit-mode admissions (flavor assignments + usage) computed on device;
entries it could not admit fall through to the CPU path (preemption,
partial admission, detailed status messages).

Equivalence class vs the reference: for cycles where every nominated
entry is fit-mode, the solver's result is identical to the sequential
scheduler (same ordering, same intra-cycle accounting — differentially
tested in tests/test_solver.py). When preemption is involved, fit-mode
entries are accounted before preempt-mode entries instead of interleaved
by the global order; preemptors then run against the post-admission
snapshot. The CPU path (solver=None) remains the strict-conformance mode.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from kueue_tpu import features
from kueue_tpu.cache.snapshot import Snapshot
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.core.resources import FlavorResource
from kueue_tpu.scheduler import flavorassigner as fa
from kueue_tpu.solver import encode
import jax

from kueue_tpu.solver.kernel import (
    max_rank_bound,
    solve_cycle_fused,
    topo_to_device,
)


class BatchSolver:
    def __init__(self, max_podsets: int = 4, ordering: Optional[wlpkg.Ordering] = None,
                 mesh=None, backend: str = "jit"):
        """backend: "jit" (XLA on the configured platform — the TPU path)
        or "native" (the C++ solve in kueue_tpu.native — the accelerator-
        free runtime; falls back to jit when the library is unavailable)."""
        self.max_podsets = max_podsets
        self.ordering = ordering or wlpkg.Ordering()
        self.mesh = mesh  # optional jax.sharding.Mesh for multi-chip solve
        self.backend = backend
        self._topo_cache = None
        self._topo_key = None
        self._decode_cache: dict = {}  # qi -> (group_size, prefer_nb)

    # --- encoding with topology caching across cycles ---

    def _topology(self, snapshot: Snapshot):
        # cohort_epoch: cohort re-parents / quota edits don't bump any
        # CQ's generation but change the encoded tree.
        key = (snapshot.cohort_epoch,) + tuple(sorted(
            (name, cq.allocatable_resource_generation)
            for name, cq in snapshot.cluster_queues.items()))
        if key != self._topo_key:
            self._topo_key = key
            topo = encode.encode_topology(snapshot)
            self._topo_cache = (topo, topo_to_device(topo))
            self._decode_cache = {}
        return self._topo_cache

    def solve(self, snapshot: Snapshot, entries: list,
              fair_sharing: bool = False) -> dict:
        """entries: list of workload Info. Returns
        {entry index -> (fa.Assignment, admitted)} for every entry the
        solver could fully assign (fit mode). admitted=False means the
        assignment no longer fit after intra-cycle accounting — the
        scheduler skips it exactly like the reference's sequential
        re-check (scheduler.go:266-273) instead of re-assigning flavors
        against post-cycle usage."""
        if not entries:
            return {}
        topo, topo_dev = self._topology(snapshot)
        state = encode.encode_state(snapshot, topo)
        batch = encode.encode_workloads(entries, snapshot, topo,
                                        ordering=self.ordering,
                                        max_podsets=self.max_podsets)
        if not batch.solvable.any():
            return {}

        result = None
        start_rank = batch.start_rank if batch.start_rank.any() else None
        # The native ABI encodes the flat (single-level) cohort forest and
        # no fair-share sort key, flavor-resume state, or per-resource
        # borrow flags (needed for TryNextFlavor resume decode); those go
        # through the jit path.
        if (self.backend == "native" and self.mesh is None
                and topo.cq_chain.shape[1] == 1 and not fair_sharing
                and start_rank is None and not topo.prefer_no_borrow.any()):
            from kueue_tpu import native
            result = native.solve_cycle_native(
                topo, state.usage, state.cohort_usage, batch.requests,
                batch.podset_active, batch.wl_cq, batch.priority,
                batch.timestamp, batch.eligible, batch.solvable)
        if result is None:
            if self.mesh is not None:
                from kueue_tpu.parallel.mesh import solve_cycle_sharded
                result = solve_cycle_sharded(self.mesh, topo_dev, state, batch,
                                             self.max_podsets,
                                             fair_sharing=fair_sharing,
                                             start_rank=start_rank)
            else:
                # fused cohort-parallel cycle: Phase A + device-built
                # order grid + row-parallel Phase B in ONE dispatch; scan
                # length = max workloads per conflict domain instead of
                # the whole batch
                result = solve_cycle_fused(
                    topo_dev, state.usage, state.cohort_usage,
                    batch.requests, batch.podset_active, batch.wl_cq,
                    batch.priority, batch.timestamp, batch.eligible,
                    batch.solvable, num_podsets=self.max_podsets,
                    max_rank=max_rank_bound(batch.wl_cq, topo.cq_cohort,
                                            topo.cohort_root),
                    fair_sharing=fair_sharing, start_rank=start_rank)

        # One batched fetch: per-array transfers each pay a full device
        # round-trip (severe over a tunneled TPU).
        fetched = jax.device_get({k: result[k] for k in
                                  ("admitted", "fit", "chosen", "borrows",
                                   "chosen_borrow") if k in result})
        admitted = np.asarray(fetched["admitted"])
        fit = np.asarray(fetched["fit"])
        chosen = np.asarray(fetched["chosen"])
        borrows = np.asarray(fetched["borrows"])
        cb = fetched.get("chosen_borrow")
        chosen_borrow = np.asarray(cb) if cb is not None else np.zeros(0)

        out = {}
        for wi in range(batch.n):
            if not fit[wi]:
                continue  # CPU path: preemption / partial admission / status
            out[wi] = (self._build_assignment(
                entries[wi], snapshot, topo, chosen[wi], bool(borrows[wi]),
                chosen_borrow[wi] if chosen_borrow.ndim == 3 else None),
                bool(admitted[wi]))
        return out

    def _build_assignment(self, info: wlpkg.Info, snapshot: Snapshot,
                          topo: encode.Topology, chosen_w: np.ndarray,
                          borrows: bool,
                          chosen_borrow_w=None) -> fa.Assignment:
        """Decode device output into the scheduler's Assignment form,
        including the LastTriedFlavorIdx resume state exactly as the CPU
        assigner stores it (reference: flavorassigner.go:289-324): the
        rank where the search ended, -1 when the list was exhausted
        (chosen == last flavor, or a TryNextFlavor CQ settling for a
        borrowing fit after scanning the whole list)."""
        from kueue_tpu.api.corev1 import RESOURCE_PODS
        assignment = fa.Assignment(borrowing=borrows)
        cq = snapshot.cluster_queues[info.cluster_queue]
        assignment.last_state = wlpkg.AssignmentClusterQueueState(
            cluster_queue_generation=cq.allocatable_resource_generation,
            cohort_generation=(cq.cohort.allocatable_resource_generation
                               if cq.cohort else 0))
        qi = topo.cq_index[info.cluster_queue]
        cached = self._decode_cache.get(qi)
        if cached is None:
            group_size = {}
            for gi in topo.flavor_group[qi]:
                if gi >= 0:
                    group_size[int(gi)] = group_size.get(int(gi), 0) + 1
            cached = (group_size, bool(topo.prefer_no_borrow[qi]))
            self._decode_cache[qi] = cached
        group_size, prefer_nb = cached
        # With FlavorFungibility off the CPU assigner never writes the
        # tried index (stays at the dataclass default 0).
        fungibility_on = features.enabled(features.FLAVOR_FUNGIBILITY)
        for pi, psr in enumerate(info.total_requests):
            reqs = dict(psr.requests)
            if topo.covers_pods[qi]:
                reqs[RESOURCE_PODS] = psr.count
            flavors = {}
            for r, v in reqs.items():
                ri = topo.resource_index[r]
                fi = int(chosen_w[pi, ri])
                if v > 0 and fi < 0:
                    raise AssertionError("solver admitted workload without flavor")
                fname = topo.flavors[fi] if fi >= 0 else topo.flavors[0]
                tried = -1 if fungibility_on else 0
                if fi >= 0 and fungibility_on:
                    rank = int(topo.flavor_rank[qi, fi])
                    gi = int(topo.group_id[qi, ri])
                    exhausted = rank == group_size.get(gi, 1) - 1
                    if prefer_nb and chosen_borrow_w is not None \
                            and bool(chosen_borrow_w[pi, ri]):
                        exhausted = True  # scanned past it looking for no-borrow
                    tried = -1 if exhausted else rank
                flavors[r] = fa.FlavorAssignment(name=fname, mode=fa.FIT,
                                                 tried_flavor_idx=tried)
            ps = fa.PodSetAssignmentResult(name=psr.name, flavors=flavors,
                                           requests=reqs, count=psr.count)
            assignment.pod_sets.append(ps)
            flavor_idx = {}
            for r, fassign in flavors.items():
                fr = FlavorResource(fassign.name, r)
                assignment.usage[fr] = assignment.usage.get(fr, 0) + reqs[r]
                flavor_idx[r] = fassign.tried_flavor_idx
            assignment.last_state.last_tried_flavor_idx.append(flavor_idx)
        return assignment
