"""Array-native preemption candidate discovery for the solver path.

The reference discovers and orders preemption candidates per preemptor
(findCandidates + candidatesOrdering, preemption.go:488-614): an
O(cohort workloads) scan and an O(K log K) sort per entry. At the
north-star shape (thousands of preempt-mode heads sharing cohorts) that
is hundreds of thousands of per-candidate Python operations per cycle —
the dominant host cost of the batched device preemptor.

This module builds, once per cycle per conflict domain (root cohort or
standalone CQ), numpy columns over the domain's admitted workloads and a
single global pre-sort. Per-preemptor candidate sets then come out as
vectorized boolean masks + slices:

- candidatesOrdering's key is (not_evicted, in_own_cq, priority,
  -reserved_at, uid); only in_own_cq is preemptor-specific, so the
  domain-wide order sorted by (not_evicted, priority, -reserved_at, uid)
  is partitioned into four stable groups per preemptor — a pure
  boolean-mask operation.
- workload-uses-resources and cq-is-borrowing filters are cached per
  FlavorResource-set signature.
- the device encode consumes deduplicated per-domain usage-row tables,
  so shipping a problem to the TPU touches no per-candidate Python.

This module is the ENCODE stage of the batched preemption pipeline
(encode -> solve -> decode; solver/PREEMPT.md): its pools feed
preempt.encode_problems / fairpreempt.encode_fair_problems, whose
bucketed problem tensors the parallel prefix/auction solve consumes.

The CPU preemptor (scheduler/preemption.py) keeps its independent
sequential discovery as the conformance oracle; the differential suites
(tests/test_preempt_solver.py, tests/test_preempt_batched.py)
cross-validate the two.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import find_condition
from kueue_tpu.core import priority as prioritypkg
from kueue_tpu.core import workload as wlpkg


@dataclass
class RowsView:
    """Per-(domain, request-FlavorResource-set) usage projection with a
    deduplicated (priority, usage row) table for the device upload."""

    slots: list                      # canonical (sorted) FlavorResource order
    row_of: np.ndarray = None        # [N] int32 index into table
    table_usage: np.ndarray = None   # [U,RF] int64
    table_prio: np.ndarray = None    # [U] int32


class DomainCandidates:
    """All admitted workloads of one conflict domain (a root cohort's
    subtree, or a standalone CQ), with the preemptor-independent part of
    candidatesOrdering precomputed."""

    def __init__(self, cq_snaps: list, ordering, now: float):
        self.cq_names = [c.name for c in cq_snaps]
        self.cq_index = {n: i for i, n in enumerate(self.cq_names)}
        self.cq_snaps = cq_snaps
        infos, cq_of, prio, ts, evicted, reserved, uids = \
            [], [], [], [], [], [], []
        for qi, cq in enumerate(cq_snaps):
            for info in cq.workloads.values():
                infos.append(info)
                cq_of.append(qi)
                prio.append(prioritypkg.priority(info.obj))
                ts.append(ordering.queue_order_timestamp(info.obj))
                cond = find_condition(info.obj.status.conditions,
                                      api.WORKLOAD_QUOTA_RESERVED)
                reserved.append(cond.last_transition_time
                                if cond is not None and cond.status == "True"
                                else now)
                evicted.append(wlpkg.is_evicted(info.obj))
                uids.append(info.obj.metadata.uid)
        n = len(infos)
        self.n = n
        self.infos = infos
        self.cq_of = np.asarray(cq_of, np.int32) if n else np.zeros(0, np.int32)
        self.prio = np.asarray(prio, np.int64) if n else np.zeros(0, np.int64)
        self.ts = np.asarray(ts, np.float64) if n else np.zeros(0)
        self.evicted = np.asarray(evicted, bool) if n else np.zeros(0, bool)
        self.reserved = np.asarray(reserved, np.float64) if n else np.zeros(0)
        if n:
            _, uid_codes = np.unique(np.asarray(uids, object),
                                     return_inverse=True)
            # preemptor-independent part of candidatesOrdering
            # (preemption.go:587-614): ascending (not_evicted, prio,
            # -reserved_at, uid)
            self.order = np.lexsort((uid_codes, -self.reserved, self.prio,
                                     ~self.evicted))
        else:
            self.order = np.zeros(0, np.int64)
        self._rows_views: dict = {}
        self._uses_masks: dict = {}
        self._borrowing_masks: dict = {}
        self._all_frs: Optional[frozenset] = None
        self._share_views: dict = {}

    def all_frs(self) -> frozenset:
        """Union of FlavorResources any domain candidate occupies — the
        fair-share kernel's slot extension (removals must move every fr
        that feeds dominantResourceShare)."""
        if self._all_frs is None:
            out: set = set()
            for info in self.infos:
                out.update(info.flavor_resource_keys())
            self._all_frs = frozenset(out)
        return self._all_frs

    def share_view(self, slots: tuple) -> dict:
        """Per-CQ DRF-share constants for the given slot order
        (clusterqueue.go:503-564 decomposition): borrowing on
        FlavorResources outside the slots is invariant during a fair
        scan, so it ships as per-(CQ, slot-resource) constants plus a
        ratio floor for resources with no slot at all."""
        view = self._share_views.get(slots)
        if view is not None:
            return view
        Qd = len(self.cq_snaps)
        RF = max(1, len(slots))
        base = np.zeros((Qd, RF), np.int64)
        floor_ratio = np.full(Qd, -1, np.int64)
        floor_any = np.zeros(Qd, bool)
        weight = np.asarray([cq.fair_weight for cq in self.cq_snaps],
                            np.int64)
        root = (self.cq_snaps[0].cohort.root()
                if self.cq_snaps and self.cq_snaps[0].cohort is not None
                else None)
        lendable_map = (root.resource_node.calculate_lendable()
                        if root is not None else {})
        lendable = np.asarray(
            [lendable_map.get(fr.resource, 0) for fr in slots] or [0],
            np.int64)
        slot_set = set(slots)
        slot_resources = {fr.resource for fr in slots}
        for qi, cq in enumerate(self.cq_snaps):
            extra: dict = {}
            for fr, used in cq.resource_node.usage.items():
                b = used - cq.quota_for(fr).nominal
                if b <= 0 or fr in slot_set:
                    continue
                extra[fr.resource] = extra.get(fr.resource, 0) + b
            for r, b in extra.items():
                if r in slot_resources:
                    for i, fr in enumerate(slots):
                        if fr.resource == r:
                            base[qi, i] = b
                else:
                    floor_any[qi] = True
                    lr = lendable_map.get(r, 0)
                    if lr > 0:
                        floor_ratio[qi] = max(floor_ratio[qi],
                                              b * 1000 // lr)
        view = {"base_other": base, "floor_ratio": floor_ratio,
                "floor_any": floor_any, "weight": weight,
                "lendable": lendable}
        self._share_views[slots] = view
        return view

    def uses_mask(self, frs: frozenset) -> np.ndarray:
        """[N] bool — workloadUsesResources per candidate."""
        mask = self._uses_masks.get(frs)
        if mask is None:
            mask = np.fromiter(
                (not frs.isdisjoint(i.flavor_resource_keys())
                 for i in self.infos), bool, self.n)
            self._uses_masks[frs] = mask
        return mask

    def borrowing_mask(self, frs: frozenset) -> np.ndarray:
        """[Q] bool — cqIsBorrowing per local CQ."""
        mask = self._borrowing_masks.get(frs)
        if mask is None:
            mask = np.asarray(
                [cq.cohort is not None and any(cq.borrowing(fr) for fr in frs)
                 for cq in self.cq_snaps], bool)
            self._borrowing_masks[frs] = mask
        return mask

    def rows_view(self, req_frs: frozenset) -> RowsView:
        view = self._rows_views.get(req_frs)
        if view is not None:
            return view
        slots = sorted(req_frs)
        n = self.n
        RF = max(1, len(slots))
        slot_of = {fr: i for i, fr in enumerate(slots)}
        rows = np.zeros((n, RF), np.int64)
        for i, info in enumerate(self.infos):
            for fr, v in info.flavor_resource_usage().items():
                si = slot_of.get(fr)
                if si is not None:
                    rows[i, si] = v
        view = RowsView(slots=slots)
        if n:
            combo = np.concatenate([self.prio[:, None], rows], axis=1)
            uniq, inv = np.unique(combo, axis=0, return_inverse=True)
            view.row_of = inv.astype(np.int32)
            view.table_prio = uniq[:, 0].astype(np.int32)
            view.table_usage = uniq[:, 1:].astype(np.int64)
        else:
            view.row_of = np.zeros(0, np.int32)
            view.table_prio = np.zeros(0, np.int32)
            view.table_usage = np.zeros((0, RF), np.int64)
        self._rows_views[req_frs] = view
        return view

    def select(self, cq_name: str, wl_prio: int, preemptor_ts: float,
               frs: frozenset, within_policy: str, consider_same_prio: bool,
               reclaim_policy: str, only_lower: bool) -> np.ndarray:
        """findCandidates + candidatesOrdering (preemption.go:488-614) as
        mask algebra. Returns ordered candidate indices."""
        uses = self.uses_mask(frs)
        qi = self.cq_index[cq_name]
        in_cq = self.cq_of == qi

        mask = np.zeros(self.n, bool)
        if within_policy != api.PREEMPTION_NEVER:
            own = in_cq & uses & (
                (self.prio < wl_prio)
                | ((self.prio == wl_prio) & consider_same_prio
                   & (preemptor_ts < self.ts)))
            mask |= own
        if len(self.cq_snaps) > 1 and reclaim_policy != api.PREEMPTION_NEVER:
            other = (~in_cq) & uses & self.borrowing_mask(frs)[self.cq_of]
            if only_lower:
                other &= self.prio < wl_prio
            mask |= other

        om = self.order[mask[self.order]]
        if om.size == 0:
            return om
        # interleave the preemptor-specific in_cq key: four stable
        # partitions of the global order
        ev = self.evicted[om]
        own = in_cq[om]
        return np.concatenate([om[ev & ~own], om[ev & own],
                               om[~ev & ~own], om[~ev & own]])


class CandidateIndex:
    """Lazy per-snapshot index: conflict domain -> DomainCandidates."""

    def __init__(self, snapshot, ordering, now: float):
        self.snapshot = snapshot
        self.ordering = ordering
        self.now = now
        self._domains: dict = {}

    def domain_for(self, cq_snap) -> DomainCandidates:
        if cq_snap.cohort is not None:
            root = cq_snap.cohort.root()
            key = ("cohort", root.name)
            if key not in self._domains:
                self._domains[key] = DomainCandidates(
                    sorted(root.subtree_cqs(), key=lambda c: c.name),
                    self.ordering, self.now)
        else:
            key = ("cq", cq_snap.name)
            if key not in self._domains:
                self._domains[key] = DomainCandidates(
                    [cq_snap], self.ordering, self.now)
        return self._domains[key]


def candidate_index(snapshot, ordering, now: float) -> CandidateIndex:
    """The cycle's CandidateIndex, cached on the snapshot."""
    idx = getattr(snapshot, "_candidate_index", None)
    if idx is None:
        idx = CandidateIndex(snapshot, ordering, now)
        snapshot._candidate_index = idx
    return idx
