"""Device-side preemption target selection (TPU solver v2/v3).

Replaces the per-entry sequential simulation of the reference's
minimalPreemptions (remove candidates in order until the preemptor fits,
then fill back in reverse — pkg/scheduler/preemption/preemption.go:237-310)
with one batched program staged as encode (candidate-pool tensors, this
module + solver/candidates.py) / solve (the parallel prefix + fill-back
auction below) / decode (victim sets, decode_targets). The solve stage
evaluates EVERY candidate prefix of every problem in one shot — the
greedy loop's state at any prefix is a closed-form clamp-telescoped
function of per-CQ prefix sums — and resolves fill-back with a handful
of parallel auction rounds instead of a K-step scan. See
solver/PREEMPT.md for the derivation and the equivalence argument vs
the Go greedy.

Host side (cheap, O(entries x candidates) filters):
- candidate discovery + ordering (findCandidates / candidatesOrdering,
  preemption.go:488-614) — static per entry, no simulation state
- the get_targets_internal policy dispatch (preemption.go:116-171),
  encoded as up to two device "problems" per entry (the under-nominal
  reclaim attempt falls back to same-queue-only)

Device side (the hot loop):
- the problem tensors carry only GLOBAL indices (CQ, flavor, resource,
  cohort); quotas, usage and cohort chains are gathered on device from
  the topology/state tensors already resident for the fit solve — the
  round-2 host-side per-problem projection (nested B x QL x RF Python
  loops + an O(CQs x depth) cohort search) is gone
- per problem: a K-step scan that removes candidates (with the dynamic
  cq-is-borrowing skip and the borrowWithinCohort priority-threshold
  borrowing flip), checks fit after each removal, then a reverse
  fill-back scan
- the whole thing composes with the fit solve into ONE jitted execute
  (kernel.solve_cycle_with_preempt), so a mixed admission+preemption
  cycle pays a single device sync — the dominant cost over a tunneled
  TPU link.

Fair-sharing preemption (fairPreemptions' DRF-heap loop,
preemption.go:312-437) runs on device too — solver/fairpreempt.py builds
on this module's problem encoding and simulation toolkit
(make_problem_sim) and composes into the same single execute.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from kueue_tpu.api import kueue as api
from kueue_tpu.core import priority as prioritypkg
from kueue_tpu.scheduler import preemption as cpu_preempt
from kueue_tpu.solver.encode import _bucket  # shared shape-bucketing policy

BIG = np.int64(2**61)


@dataclass
class PreemptionProblem:
    """One minimal_preemptions run, as an ordered index selection into a
    DomainCandidates (solver/candidates.py) — no per-candidate Python
    objects are materialized until decode."""

    entry_idx: int = -1
    domain: object = None            # candidates.DomainCandidates
    sel: np.ndarray = None           # ordered candidate indices into domain
    allow_borrowing: bool = True
    threshold_active: bool = False
    threshold: int = 0

    @property
    def num_candidates(self) -> int:
        return int(self.sel.size) if self.sel is not None else 0


@dataclass
class PreemptionBatch:
    problems: list = field(default_factory=list)
    # device tensors, leading axis = problem; all indices GLOBAL
    gq: np.ndarray = None             # [B,QL] int32 global CQ idx (-1 pad);
                                      #   row 0 = the preemptor's CQ
    gf: np.ndarray = None             # [B,RF] int32 global flavor idx (-1 pad)
    gr: np.ndarray = None             # [B,RF] int32 global resource idx
    gc: np.ndarray = None             # [B,CL] int32 global cohort idx (-1 pad)
                                      #   — the union of the problem CQs'
                                      #   chains, so per-lane cohort state
                                      #   is CL-wide, not C-wide
    chain_local: np.ndarray = None    # [B,QL,DC] int32 local cohort ids
    requests: np.ndarray = None       # [B,RF] int64
    frs_np: np.ndarray = None         # [B,RF] bool — needs-preemption frs
    # Candidates are deduplicated into a row table: identical pod shapes
    # dominate real queues, and the tunnel to the TPU is bandwidth-bound —
    # uploading [B,K] int32 indices + a small [U] table beats uploading
    # dense [B,K,RF] usage planes by ~10x.
    cand_idx: np.ndarray = None       # [B,K] int32 index into the table
                                      #   (index 0 = the padding row)
    cand_ql: np.ndarray = None        # [B,K] int16 LOCAL ql slot (-1 pad)
    cand_usage: np.ndarray = None     # [U,RF] int64 table
    cand_prio: np.ndarray = None      # [U] int32 table
    allow_borrowing: np.ndarray = None   # [B] bool
    threshold_active: np.ndarray = None  # [B] bool
    threshold: np.ndarray = None         # [B] int64
    has_cohort: np.ndarray = None        # [B] bool


def build_problems(entry_idx: int, wl, requests: dict, frs_need_preemption: set,
                   snapshot, preemptor: "cpu_preempt.Preemptor",
                   cand_index) -> list:
    """get_targets_internal's policy dispatch (preemption.go:116-171) as a
    list of 1-2 PreemptionProblems (first non-empty result wins).
    Candidate discovery + ordering run as mask algebra over the cycle's
    CandidateIndex (solver/candidates.py) instead of the per-entry scan +
    sort of the CPU oracle."""
    cq = snapshot.cluster_queues[wl.cluster_queue]
    domain = cand_index.domain_for(cq)
    preemption = cq.preemption
    wl_prio = prioritypkg.priority(wl.obj)
    frs = frozenset(frs_need_preemption)
    sel = domain.select(
        cq.name, wl_prio,
        preemptor.ordering.queue_order_timestamp(wl.obj), frs,
        within_policy=preemption.within_cluster_queue,
        consider_same_prio=(preemption.within_cluster_queue
                            == api.PREEMPTION_LOWER_OR_NEWER_EQUAL_PRIORITY),
        reclaim_policy=preemption.reclaim_within_cohort,
        only_lower=(preemption.reclaim_within_cohort != api.PREEMPTION_ANY))
    if sel.size == 0:
        return []
    qi = domain.cq_index[cq.name]
    in_cq = domain.cq_of[sel] == qi

    if bool(in_cq.all()):
        return [PreemptionProblem(entry_idx, domain, sel,
                                  allow_borrowing=True)]

    borrow_within, threshold = cpu_preempt.can_borrow_within_cohort(cq, wl.obj)
    if borrow_within:
        s = sel
        if not cpu_preempt.queue_under_nominal(frs_need_preemption, cq):
            s = sel[in_cq | (domain.prio[sel] < threshold)]
        return [PreemptionProblem(entry_idx, domain, s, allow_borrowing=True,
                                  threshold_active=True, threshold=threshold)]

    problems = []
    if cpu_preempt.queue_under_nominal(frs_need_preemption, cq):
        problems.append(PreemptionProblem(entry_idx, domain, sel,
                                          allow_borrowing=False))
    problems.append(PreemptionProblem(entry_idx, domain, sel[in_cq],
                                      allow_borrowing=True))
    return problems


def encode_problems(problems: list, snapshot, topo, requests_by_entry: dict,
                    wl_cq_by_entry: dict,
                    frs_np_by_entry: dict) -> PreemptionBatch:
    """Problems -> global-index tensors, with NO per-candidate Python:
    candidate usage rows come from the per-domain deduplicated row tables
    (candidates.RowsView), per-problem candidate columns are vectorized
    gathers over the problem's index selection, and the batch-level table
    is a concatenation of the domain tables with offsets."""
    B = _bucket(max(1, len(problems)), 1)
    # Fair problems extend their slots past the request's FlavorResources
    # (extra_frs: the frs candidate removals move — share math needs them)
    RF = _bucket(max(max(
        (len(frozenset(requests_by_entry[p.entry_idx])
             | getattr(p, "extra_frs", frozenset())) for p in problems),
        default=1), 1))
    K = _bucket(max(max((p.num_candidates for p in problems), default=1), 1))

    batch = PreemptionBatch(problems=list(problems))
    batch.gf = np.full((B, RF), -1, np.int32)
    batch.gr = np.full((B, RF), 0, np.int32)
    batch.requests = np.zeros((B, RF), np.int64)
    batch.frs_np = np.zeros((B, RF), bool)
    batch.cand_idx = np.zeros((B, K), np.int32)
    batch.cand_ql = np.full((B, K), -1, np.int16)
    batch.allow_borrowing = np.zeros(B, bool)
    batch.threshold_active = np.zeros(B, bool)
    batch.threshold = np.zeros(B, np.int64)
    batch.has_cohort = np.zeros(B, bool)

    cq_index = topo.cq_index
    flavor_index = topo.flavor_index
    resource_index = topo.resource_index

    # batch-level candidate row table: concat of per-(domain, req-frs)
    # tables; row 0 is the padding row
    table_usage = [np.zeros((1, RF), np.int64)]
    table_prio = [np.zeros(1, np.int32)]
    offsets: dict = {}
    next_off = 1

    gq_rows = []
    max_ql = 1
    for bi, p in enumerate(problems):
        ei = p.entry_idx
        requests = requests_by_entry[ei]
        frs_np = frs_np_by_entry[ei]
        preemptor_cq = wl_cq_by_entry[ei]
        domain = p.domain
        req_frs = frozenset(requests) | getattr(p, "extra_frs", frozenset())
        rows = domain.rows_view(req_frs)

        for i, fr in enumerate(rows.slots):
            batch.gf[bi, i] = flavor_index.get(fr.flavor, -1)
            batch.gr[bi, i] = resource_index.get(fr.resource, 0)
            batch.requests[bi, i] = requests.get(fr, 0)
            batch.frs_np[bi, i] = fr in frs_np

        okey = (id(domain), req_frs)
        off = offsets.get(okey)
        if off is None:
            off = next_off
            offsets[okey] = off
            u = rows.table_usage
            if u.shape[1] < RF:
                u = np.pad(u, ((0, 0), (0, RF - u.shape[1])))
            table_usage.append(u)
            table_prio.append(rows.table_prio)
            next_off += len(rows.table_prio)

        sel = p.sel
        k = sel.size
        pre_qi = domain.cq_index[preemptor_cq]
        if k:
            batch.cand_idx[bi, :k] = off + rows.row_of[sel]
            # problem-local CQ slots: preemptor first, then first-appearance
            cqs = domain.cq_of[sel]
            if cqs[0] == pre_qi and (cqs == pre_qi).all():
                # within-CQ problem (the common case): all slot 0
                batch.cand_ql[bi, :k] = 0
                local_list = [pre_qi]
            else:
                uniq, first = np.unique(cqs, return_index=True)
                occ = uniq[np.argsort(first)]
                local_list = [pre_qi] + [int(c) for c in occ if c != pre_qi]
                lut = np.full(len(domain.cq_names), -1, np.int16)
                lut[local_list] = np.arange(len(local_list), dtype=np.int16)
                batch.cand_ql[bi, :k] = lut[cqs]
                max_ql = max(max_ql, len(local_list))
        else:
            local_list = [pre_qi]
        gq_rows.append([cq_index[domain.cq_names[c]] for c in local_list])

        batch.allow_borrowing[bi] = p.allow_borrowing
        batch.threshold_active[bi] = p.threshold_active
        batch.threshold[bi] = p.threshold if p.threshold_active else 0
        batch.has_cohort[bi] = \
            snapshot.cluster_queues[preemptor_cq].cohort is not None

    QL = _bucket(max_ql, 1)
    batch.gq = np.full((B, QL), -1, np.int32)
    for bi, row in enumerate(gq_rows):
        batch.gq[bi, :len(row)] = row
    # The dedup table's row count is BUCKETED like every other batch dim:
    # un-padded it tracked the per-cycle distinct-row count exactly, so
    # every preemption-heavy cycle with a new dedup count minted a fresh
    # program shape — unwarmable by construction and a compile-storm
    # hazard (solver/COMPILE.md). Padding rows are all-zero and index 0
    # is already reserved, so no cand_idx ever points at the padding.
    U = _bucket(next_off, 1)
    pad = U - next_off
    if pad:
        table_usage.append(np.zeros((pad, RF), np.int64))
        table_prio.append(np.zeros(pad, np.int32))
    batch.cand_usage = np.concatenate(table_usage, axis=0)
    batch.cand_prio = np.concatenate(table_prio)
    _localize_cohorts(batch, topo)
    return batch


def _localize_cohorts(batch: PreemptionBatch, topo) -> None:
    """Per problem, project the global cohort chains of its CQs onto a
    small local id space (the union of those chains), fully vectorized:
    the simulation state each lane carries is then [CL,RF] instead of the
    whole [C,RF] cohort plane."""
    B, QL = batch.gq.shape
    DC = topo.cq_chain.shape[1]
    q_safe = np.maximum(batch.gq, 0)
    chains = topo.cq_chain[q_safe]                      # [B,QL,DC]
    chains = np.where((batch.gq >= 0)[:, :, None], chains, -1)
    SENT = np.int32(2**30)
    flat = chains.reshape(B, QL * DC).astype(np.int32)
    flat_s = np.where(flat < 0, SENT, flat)
    srt = np.sort(flat_s, axis=1)                       # valid asc, SENT last
    first = np.ones_like(srt, bool)
    first[:, 1:] = srt[:, 1:] != srt[:, :-1]
    first &= srt != SENT
    counts = first.sum(axis=1)
    CL = _bucket(max(1, int(counts.max())) if B else 1)
    loc_sorted = np.cumsum(first, axis=1) - 1           # [B,QL*DC]
    gc = np.full((B, CL), -1, np.int32)
    rows = np.nonzero(first)[0]
    gc[rows, loc_sorted[first]] = srt[first]
    # local id of each chain entry: count of distinct valid ids < value
    gc_cmp = np.where(gc >= 0, gc, SENT)                # [B,CL]
    local = (gc_cmp[:, None, :] < flat_s[:, :, None]).sum(axis=2)
    batch.chain_local = np.where(flat >= 0, local,
                                 -1).reshape(B, QL, DC).astype(np.int32)
    batch.gc = gc


# --------------------------------------------------------------------------
# Device kernel (global index space; composes with the fit solve)
# --------------------------------------------------------------------------

def make_problem_sim(topo, usage, cohort_usage, gq_b, gf_b, gr_b, gc_b,
                     chain_local_b, req_b, has_cohort_b):
    """Per-problem simulation toolkit shared by the minimal and fair
    preemption kernels: quota-plane gathers projected onto the problem's
    (CQ, FlavorResource) slots, plus fits / remove_usage / add_usage
    closures implementing the reference's resource_node math
    (resource_node.go:89-143) with dense one-hot arithmetic (dynamic
    scatters under vmap x scan lower catastrophically on TPU)."""
    import jax.numpy as jnp

    NOLIM = 2**61
    QL = gq_b.shape[0]
    RF = gf_b.shape[0]
    CL = gc_b.shape[0]
    valid_fr = gf_b >= 0
    gf_s = jnp.maximum(gf_b, 0)
    q_s = jnp.maximum(gq_b, 0)                       # [QL]

    def plane(t):
        return jnp.where(valid_fr[None, :], t[q_s][:, gf_s, gr_b], 0)

    nominal = plane(topo["nominal"])
    guaranteed = plane(topo["guaranteed"])
    borrow_limit = jnp.where(valid_fr[None, :],
                             topo["borrow_limit"][q_s][:, gf_s, gr_b],
                             NOLIM)
    u0 = plane(usage)
    chain = chain_local_b                            # [QL,DC] local ids
    DC = chain.shape[1]
    chain_oh = (chain[:, :, None] == jnp.arange(CL)[None, None, :]) \
        & (chain >= 0)[:, :, None]                   # [QL,DC,CL]

    gc_s = jnp.maximum(gc_b, 0)
    valid_c = (gc_b >= 0)[:, None] & valid_fr[None, :]

    def cplane(t, fill=0):
        return jnp.where(valid_c, t[gc_s][:, gf_s, gr_b], fill)

    c_subtree = cplane(topo["cohort_subtree"])
    c_guar = cplane(topo["cohort_guaranteed"])
    c_bl = cplane(topo["cohort_borrow_limit"], NOLIM)
    cu0 = cplane(cohort_usage)

    def oh_rows(oh, t):
        """oh [C] bool one-hot, t [C,RF] -> t[c] as [RF] dense."""
        return jnp.sum(jnp.where(oh[:, None], t, 0), axis=0)

    # The availability chain walk exists ONCE: _avail_cq0_prefix (the
    # K-vectorized form the prefix/auction solver uses). The scalar
    # case below is its K=1 instance, so a future semantic fix to the
    # resource_node.go math cannot silently diverge the device victim
    # sets from the CPU oracle between the minimal/fair kernels and
    # the fill-back oracle (ROADMAP carried thread; the randomized
    # differentials in tests/test_preempt_batched.py pin bit-identity).
    sim_view = {"chain_oh": chain_oh, "c_subtree": c_subtree,
                "c_guar": c_guar, "c_bl": c_bl, "nominal": nominal,
                "guaranteed": guaranteed, "borrow_limit": borrow_limit}

    def avail_cq0(u, cu):
        """available() for local CQ 0 (the preemptor's), walking its
        cohort chain (reference: resource_node.go:89-104) — the K=1
        instance of the vectorized walk."""
        return _avail_cq0_prefix(sim_view, has_cohort_b, u[0][None, :],
                                 cu[:, None, :])[0]

    def fits(u, cu, ab):
        """workload_fits (reference: preemption.go:576-585)."""
        has_req = req_b > 0
        avail = avail_cq0(u, cu)
        borrow_ok = ab | jnp.all(~has_req | (u[0] + req_b <= nominal[0]))
        return borrow_ok & jnp.all(~has_req | (req_b <= avail))

    def remove_usage(u, cu, q_oh, q_chain_oh, val):
        """removeUsage bubbling (reference: resource_node.go:133-143),
        dense: q_oh [QL] one-hot CQ row, q_chain_oh [DC,C] its chain."""
        guar_q = jnp.sum(jnp.where(q_oh[:, None], guaranteed, 0), axis=0)
        u_q = jnp.sum(jnp.where(q_oh[:, None], u, 0), axis=0)
        stored = u_q - guar_q                        # pre-removal
        u = u - jnp.where(q_oh[:, None], val[None, :], 0)
        delta = jnp.minimum(val, jnp.maximum(0, stored))
        for d in range(DC):
            oh = q_chain_oh[d]                       # [C]
            ok = jnp.any(oh) & jnp.any(delta > 0)
            stored_c = oh_rows(oh, cu) - oh_rows(oh, c_guar)
            dd = jnp.where(ok, delta, 0)
            cu = cu - jnp.where(oh[:, None], dd[None, :], 0)
            delta = jnp.minimum(dd, jnp.maximum(0, stored_c))
        return u, cu

    def add_usage(u, cu, q_oh, q_chain_oh, val):
        """addUsage bubbling (reference: resource_node.go:121-131)."""
        guar_q = jnp.sum(jnp.where(q_oh[:, None], guaranteed, 0), axis=0)
        u_q = jnp.sum(jnp.where(q_oh[:, None], u, 0), axis=0)
        local_avail = jnp.maximum(0, guar_q - u_q)
        u = u + jnp.where(q_oh[:, None], val[None, :], 0)
        delta = jnp.maximum(0, val - local_avail)
        for d in range(DC):
            oh = q_chain_oh[d]
            ok = jnp.any(oh)
            local_c = jnp.maximum(0, oh_rows(oh, c_guar) - oh_rows(oh, cu))
            dd = jnp.where(ok, delta, 0)
            cu = cu + jnp.where(oh[:, None], dd[None, :], 0)
            delta = jnp.where(ok, jnp.maximum(0, dd - local_c), delta)
        return u, cu

    return {
        "QL": QL, "RF": RF, "CL": CL, "DC": DC,
        "nominal": nominal, "guaranteed": guaranteed,
        "borrow_limit": borrow_limit, "u0": u0, "cu0": cu0,
        "chain_oh": chain_oh, "oh_rows": oh_rows, "avail_cq0": avail_cq0,
        "fits": fits, "remove_usage": remove_usage, "add_usage": add_usage,
        # cohort constant planes, exported for the prefix/auction solver
        # (solve_preempt_impl) which evaluates every candidate prefix in
        # parallel instead of scanning
        "c_subtree": c_subtree, "c_guar": c_guar, "c_bl": c_bl,
    }


def _avail_cq0_prefix(sim, has_cohort_b, u0row_k, cu_k):
    """``avail_cq0`` vectorized over a leading K axis: availability of
    the preemptor's CQ (local row 0) for EVERY candidate prefix at once.
    u0row_k [K,RF] is CQ 0's usage row per prefix; cu_k [CL,K,RF] the
    problem-local cohort usage planes per prefix. Same chain walk as
    make_problem_sim's avail_cq0 (resource_node.go:89-104)."""
    import jax.numpy as jnp

    NOLIM = 2**61
    chain_oh = sim["chain_oh"]
    c_subtree, c_guar, c_bl = sim["c_subtree"], sim["c_guar"], sim["c_bl"]
    nominal, guaranteed = sim["nominal"], sim["guaranteed"]
    borrow_limit = sim["borrow_limit"]
    DC = chain_oh.shape[1]
    K, RF = u0row_k.shape
    parent = jnp.zeros((K, RF), jnp.int64)
    started = jnp.zeros((), bool)
    for d in range(DC - 1, -1, -1):
        oh = chain_oh[0, d]                                   # [CL]
        ok = jnp.any(oh)

        def rows(t, oh=oh):
            return jnp.sum(jnp.where(oh[:, None], t, 0), axis=0)

        cuc = jnp.sum(jnp.where(oh[:, None, None], cu_k, 0), axis=0)
        sub, gua, bl = rows(c_subtree), rows(c_guar), rows(c_bl)
        root_avail = sub[None, :] - cuc
        local = jnp.maximum(0, gua[None, :] - cuc)
        cap = (sub - gua)[None, :] - jnp.maximum(0, cuc - gua[None, :]) \
            + jnp.minimum(bl, NOLIM // 4)[None, :]
        child = local + jnp.minimum(parent, cap)
        new = jnp.where(started, child, root_avail)
        parent = jnp.where(ok, new, parent)
        started = started | ok
    local0 = jnp.maximum(0, guaranteed[0][None, :] - u0row_k)
    cap0 = (nominal[0] - guaranteed[0])[None, :] \
        - jnp.maximum(0, u0row_k - guaranteed[0][None, :]) \
        + jnp.minimum(borrow_limit[0], NOLIM // 4)[None, :]
    with_cohort = local0 + jnp.minimum(parent, cap0)
    return jnp.where(has_cohort_b, with_cohort,
                     nominal[0][None, :] - u0row_k)


def _fits_prefix(sim, has_cohort_b, req_b, u0row_k, cu_k, ab_k):
    """workload_fits for every prefix/hypothesis at once. ab_k: [K] (or
    a scalar broadcast)."""
    import jax.numpy as jnp

    nominal = sim["nominal"]
    has_req = (req_b > 0)[None, :]
    avail = _avail_cq0_prefix(sim, has_cohort_b, u0row_k, cu_k)
    borrow_ok = ab_k | jnp.all(
        ~has_req | (u0row_k + req_b[None, :] <= nominal[0][None, :]), axis=1)
    return borrow_ok & jnp.all(~has_req | (req_b[None, :] <= avail), axis=1)


def _own_cq_cumsum(cand_q_b, vals, QL, reverse=False):
    """Per-candidate EXCLUSIVE same-CQ running sum of ``vals`` [K,RF]
    (reverse=True: suffix sums). A static python loop over the QL local
    CQ rows keeps peak memory at [K,RF] instead of a [QL,K,RF] cumsum
    blow-up; QL is a small bucketed dim."""
    import jax.numpy as jnp

    out = jnp.zeros_like(vals)
    for q in range(QL):
        m = cand_q_b == q
        vm = jnp.where(m[:, None], vals, 0)
        if reverse:
            cs = jnp.cumsum(vm[::-1], axis=0)[::-1]
        else:
            cs = jnp.cumsum(vm, axis=0)
        out = jnp.where(m[:, None], cs - vm, out)
    return out


def _chain_flows_fwd(sim, cand_chain, dep_of_local, ed, delta0):
    """Route each candidate's removal marginal up the cohort tree and
    return IN[c,k]: total arrivals at local cohort c over candidates
    0..k (every prefix at once).

    Exactness rests on the clamp-telescoping identity
    ``min(d, max(0, s)) = max(0, s) - max(0, s - d)``: a node's total
    pass-up is a function of its total arrivals only, so per-candidate
    MARGINALS (each clamped against the node's running prefix state)
    reproduce the sequential remove_usage bubbling bit-for-bit. Nodes
    are processed by tree depth (deepest first) so a node shared by CQs
    at different chain positions receives all its arrivals in one step."""
    import jax.numpy as jnp

    CL = sim["CL"]
    DC = cand_chain.shape[1]
    K, RF = delta0.shape
    s0 = sim["cu0"] - sim["c_guar"]                       # [CL,RF]
    IN = jnp.zeros((CL, K, RF), jnp.int64)
    flow = delta0
    arange_cl = jnp.arange(CL)
    for dlt in range(DC - 1, -1, -1):
        pos = ed - dlt                                    # [K]
        act = (pos >= 0) & (ed >= 0)
        node = jnp.take_along_axis(
            cand_chain, jnp.clip(pos, 0, DC - 1)[:, None], axis=1)[:, 0]
        act = act & (node >= 0)
        noh = (node[None, :] == arange_cl[:, None]) & act[None, :]  # [CL,K]
        inm = jnp.where(noh[:, :, None], flow[None, :, :], 0)
        cs = jnp.cumsum(inm, axis=1)                      # [CL,K,RF]
        excl = cs - inm
        out = jnp.minimum(inm, jnp.maximum(0, s0[:, None, :] - excl))
        IN = jnp.where((dep_of_local == dlt)[:, None, None], cs, IN)
        flow = jnp.where(act[:, None],
                         jnp.sum(jnp.where(noh[:, :, None], out, 0), axis=0),
                         flow)
    return IN


def _fillback_ok(sim, cand_chain, dep_of_local, ed, elig, members, v,
                 cand_q_b, q_safe, u_fwd, cu_fwd, guar_k, req_b,
                 has_cohort_b, ab_fb, QL):
    """One fill-back auction round: for every eligible candidate j,
    would the reverse-greedy accept it back given that exactly
    ``members`` (the candidates with higher index) came back before it?
    Returns ok[K] bool. Evaluating every hypothesis against the SAME
    member set is what makes the round a parallel map; the caller
    iterates rounds to the exact greedy fixpoint (see solve docstring)."""
    import jax.numpy as jnp

    CL = sim["CL"]
    DC = cand_chain.shape[1]
    K, RF = v.shape
    c_guar, cu0 = sim["c_guar"], sim["cu0"]
    arange_cl = jnp.arange(CL)
    mv = jnp.where(members[:, None], v, 0)

    # CQ-level add marginal per candidate, against the member-suffix
    # state of its own CQ (addUsage: pass-up = clamp difference)
    rev_own = _own_cq_cumsum(cand_q_b, mv, QL, reverse=True)
    t_pre = u_fwd[q_safe] + rev_own - guar_k              # [K,RF]
    delta_add = jnp.maximum(0, t_pre + v) - jnp.maximum(0, t_pre)

    RIN = jnp.zeros((CL, K, RF), jnp.int64)   # member suffix arrivals
    OWN = jnp.zeros((CL, K, RF), jnp.int64)   # own hypothetical arrivals
    flow = delta_add
    for dlt in range(DC - 1, -1, -1):
        pos = ed - dlt
        act = (pos >= 0) & (ed >= 0) & elig
        node = jnp.take_along_axis(
            cand_chain, jnp.clip(pos, 0, DC - 1)[:, None], axis=1)[:, 0]
        act = act & (node >= 0)
        noh = (node[None, :] == arange_cl[:, None]) & act[None, :]
        inm = jnp.where((noh & members[None, :])[:, :, None],
                        flow[None, :, :], 0)
        rcs = jnp.cumsum(inm[:, ::-1], axis=1)[:, ::-1]
        rexcl = rcs - inm                                 # strictly-after j
        RIN = jnp.where((dep_of_local == dlt)[:, None, None], rexcl, RIN)
        OWN = jnp.where(noh[:, :, None], flow[None, :, :], OWN)
        # clamp this candidate's marginal through the node state it
        # would see (cu after fwd + members above it)
        cu_pre = jnp.sum(jnp.where(noh[:, :, None],
                                   cu_fwd[:, None, :] + rexcl, 0), axis=0)
        gguar = jnp.sum(jnp.where(noh[:, :, None],
                                  c_guar[:, None, :], 0), axis=0)
        local_c = jnp.maximum(0, gguar - cu_pre)
        flow = jnp.where(act[:, None], jnp.maximum(0, flow - local_c), flow)

    cu_hyp = cu_fwd[:, None, :] + RIN + OWN               # [CL,K,RF]
    r0 = jnp.where((members & (cand_q_b == 0))[:, None], v, 0)
    r0cs = jnp.cumsum(r0[::-1], axis=0)[::-1]
    r0_excl = r0cs - r0
    u0row_hyp = u_fwd[0][None, :] + r0_excl \
        + jnp.where((cand_q_b == 0)[:, None], v, 0)
    ok = _fits_prefix(sim, has_cohort_b, req_b, u0row_hyp, cu_hyp, ab_fb)
    return elig & ok


def solve_preempt_impl(topo, usage, cohort_usage, gq, gf, gr, gc, chain_local,
                       requests, frs_np, cand_idx, cand_ql,
                       cand_usage_table, cand_prio_table,
                       allow_borrowing, threshold_active, threshold,
                       has_cohort):
    """Batched minimalPreemptions as a PARALLEL PREFIX program — no
    per-candidate scan. All quota tensors are gathered on device from
    the fit solve's topology/state:

    - usage[Q,F,R], cohort_usage[C,F,R]: pre-cycle state (preemption
      targets are selected in nominate, against the cycle snapshot —
      reference scheduler.go:404-441)
    - per problem b, FlavorResource slot i = (gf[b,i], gr[b,i]); local CQ
      row ql maps to global CQ gq[b,ql]; its cohort chain is
      chain_local[b,ql] in the problem's local cohort space gc[b]

    The greedy remove-until-fit loop is reformulated (solver/PREEMPT.md):

    1. The dynamic cq-stopped-borrowing skip only depends on a CQ's OWN
       earlier candidates (removals never raise usage), so the do-mask
       is a closed-form per-CQ exclusive prefix sum — no iteration.
    2. remove_usage's cohort bubbling telescopes: each node's total
       pass-up is a clamp difference of its total arrivals, so the
       simulation state after ANY candidate prefix is a closed-form
       function of per-CQ prefix sums (_chain_flows_fwd) and the fit
       check runs for every prefix in parallel; the answer is the first
       fitting prefix (the auction's single clearing reduction).
    3. Fill-back runs as bounded auction rounds: each round evaluates
       every "would it come back" hypothesis in parallel against lower/
       upper bounds of the accepted set; the bounds squeeze monotonically
       onto the exact reverse-greedy fixpoint (the topmost unresolved
       candidate resolves every round), so results stay bit-identical to
       fillBackWorkloads while typical rounds ~2-3.

    Returns (targets [B,K] bool, feasible [B] bool, stats [B,4] int32 —
    (candidate pool, prefix scanned, fill-back rounds, filled back))."""
    import jax
    import jax.numpy as jnp

    def one(gq_b, gf_b, gr_b, gc_b, chain_local_b, req_b, frs_np_b,
            cand_q_b, cand_usage_b, cand_prio_b, ab0, th_act, th,
            has_cohort_b):
        sim = make_problem_sim(topo, usage, cohort_usage, gq_b, gf_b, gr_b,
                               gc_b, chain_local_b, req_b, has_cohort_b)
        QL = sim["QL"]
        nominal, guaranteed = sim["nominal"], sim["guaranteed"]
        u0, cu0 = sim["u0"], sim["cu0"]

        K = cand_q_b.shape[0]
        arange_k = jnp.arange(K)
        valid = cand_q_b >= 0
        q_safe = jnp.maximum(cand_q_b, 0)
        in_cq = cand_q_b == 0
        v = jnp.where(valid[:, None], cand_usage_b, 0)    # [K,RF]
        u0_k = u0[q_safe]                                  # [K,RF]
        nom_k = nominal[q_safe]
        guar_k = guaranteed[q_safe]

        # --- do-mask: the dynamic skip, closed form ---
        # candidate k's CQ is still borrowing at its turn iff it borrows
        # after subtracting ALL earlier same-CQ candidates (monotone:
        # skipped ones only over-subtract an already-false condition)
        own_all_excl = _own_cq_cumsum(cand_q_b, v, QL)
        borrowing_before = jnp.any(
            frs_np_b[None, :] & (u0_k - own_all_excl > nom_k), axis=1)
        do = valid & (in_cq | borrowing_before)

        # borrowWithinCohort threshold flip (preemption.go:252-270),
        # cumulative — inclusive of the candidate's own flip
        at_or_above = th_act & (~in_cq) & (cand_prio_b >= th)
        ab_k = ab0 & ~(jnp.cumsum((do & at_or_above).astype(jnp.int32))
                       > 0)                               # [K]

        # --- prefix states: CQ0 row + cohort planes per prefix ---
        own_rm_excl = _own_cq_cumsum(cand_q_b, jnp.where(do[:, None], v, 0),
                                     QL)
        delta0 = jnp.where(
            do[:, None],
            jnp.minimum(v, jnp.maximum(0, u0_k - guar_k - own_rm_excl)), 0)

        cand_chain = chain_local_b[q_safe]                # [K,DC]
        dep_g = topo["cohort_depth"][jnp.maximum(gc_b, 0)]
        dep_of_local = jnp.where(gc_b >= 0, dep_g, -1)    # [CL]
        first = cand_chain[:, 0]
        ed = jnp.where((first >= 0) & do,
                       dep_of_local[jnp.maximum(first, 0)], -1)
        IN = _chain_flows_fwd(sim, cand_chain, dep_of_local, ed, delta0)
        cu_k = cu0[:, None, :] - IN                       # [CL,K,RF]
        v0 = jnp.where((do & in_cq)[:, None], v, 0)
        u0row_k = u0[0][None, :] - jnp.cumsum(v0, axis=0)  # [K,RF]

        fit_k = _fits_prefix(sim, has_cohort_b, req_b, u0row_k, cu_k, ab_k)
        cond = do & fit_k
        done = jnp.any(cond)
        k_star = jnp.argmax(cond)                         # first fitting
        targets_fwd = do & (arange_k <= k_star) & done
        ab_fb = jnp.where(done, ab_k[k_star], ab0)

        # --- fill-back auction rounds (fillBackWorkloads) ---
        elig = targets_fwd & (arange_k != k_star)
        removed_k = do & (arange_k <= k_star)
        u_fwd = u0 - jnp.stack([
            jnp.sum(jnp.where(((cand_q_b == q) & removed_k)[:, None], v, 0),
                    axis=0)
            for q in range(QL)])                          # [QL,RF]
        cu_fwd = jnp.where(done, cu0 - IN[:, k_star, :], cu0)
        u_fwd = jnp.where(done, u_fwd, u0)

        def ok_fn(members):
            return _fillback_ok(sim, cand_chain, dep_of_local,
                                jnp.where(elig, ed, -1), elig, members, v,
                                cand_q_b, q_safe, u_fwd, cu_fwd, guar_k,
                                req_b, has_cohort_b, ab_fb, QL)

        def fb_cond(carry):
            lo, hi, it = carry
            return jnp.any(lo != hi) & (it < K + 2)

        def fb_body(carry):
            lo, hi, it = carry
            hi2 = ok_fn(lo)     # over-approx accepted set
            lo2 = ok_fn(hi2)    # under-approx accepted set
            return lo2, hi2, it + 1

        hi0 = elig
        lo0 = ok_fn(hi0)
        lo_f, hi_f, fb_rounds = jax.lax.while_loop(
            fb_cond, fb_body, (lo0, hi0, jnp.int32(1)))
        came_back = lo_f
        targets = targets_fwd & ~came_back

        stats = jnp.stack([
            jnp.sum(valid).astype(jnp.int32),
            jnp.where(done, k_star + 1, 0).astype(jnp.int32),
            fb_rounds,
            jnp.sum(came_back).astype(jnp.int32)])
        return targets, done, stats

    # expand the deduplicated candidate table on device (one gather each,
    # outside the vmap — the upload ships only indices + the table)
    cand_q = cand_ql.astype(jnp.int32)        # [B,K]
    cand_usage = cand_usage_table[cand_idx]   # [B,K,RF]
    cand_prio = cand_prio_table[cand_idx]     # [B,K]
    return jax.vmap(one)(gq, gf, gr, gc, chain_local, requests, frs_np,
                         cand_q, cand_usage, cand_prio, allow_borrowing,
                         threshold_active, threshold, has_cohort)


_SOLVE_JIT = None


def solve_preemption_batch(topo_dev, usage, cohort_usage,
                           batch: PreemptionBatch, with_stats: bool = False):
    """Standalone dispatch (tests / CPU-free preempt cycles). Production
    mixed cycles go through kernel.solve_cycle_with_preempt instead so
    fit + preemption share one execute."""
    global _SOLVE_JIT
    import jax
    import jax.numpy as jnp
    if _SOLVE_JIT is None:
        _SOLVE_JIT = jax.jit(solve_preempt_impl)
    targets, feasible, stats = jax.device_get(_SOLVE_JIT(
        topo_dev, jnp.asarray(usage), jnp.asarray(cohort_usage),
        *preempt_args(batch)))
    if with_stats:
        return np.asarray(targets), np.asarray(feasible), np.asarray(stats)
    return np.asarray(targets), np.asarray(feasible)


def preempt_args(batch: PreemptionBatch) -> tuple:
    return (batch.gq, batch.gf, batch.gr, batch.gc, batch.chain_local,
            batch.requests, batch.frs_np, batch.cand_idx, batch.cand_ql,
            batch.cand_usage, batch.cand_prio, batch.allow_borrowing,
            batch.threshold_active, batch.threshold, batch.has_cohort)


# Slots of preempt_args WITHOUT a leading problem axis (the deduplicated
# cand_usage/cand_prio row tables) — the mesh path replicates these and
# shards every other slot over problems; keep in lockstep with the tuple
# above.
PREEMPT_ARGS_REPLICATED_SLOTS = (9, 10)


def decode_targets(batch: PreemptionBatch, targets_mask: np.ndarray,
                   feasible: np.ndarray, snapshot,
                   wl_cq_by_entry: dict) -> dict:
    """entry_idx -> list[Target]; the first feasible problem per entry
    wins (matching get_targets_internal's fallthrough order)."""
    out: dict = {}
    for bi, p in enumerate(batch.problems):
        ei = p.entry_idx
        if ei in out and out[ei]:
            continue
        if not feasible[bi]:
            out.setdefault(ei, [])
            continue
        preemptor_cq = wl_cq_by_entry[ei]
        domain = p.domain
        targets = []
        k = p.num_candidates
        hit = np.flatnonzero(targets_mask[bi, :k])
        for ki in hit.tolist():
            cand = domain.infos[p.sel[ki]]
            if cand.cluster_queue == preemptor_cq:
                reason = api.IN_CLUSTER_QUEUE_REASON
            elif p.threshold_active and \
                    int(domain.prio[p.sel[ki]]) < p.threshold:
                reason = api.IN_COHORT_RECLAIM_WHILE_BORROWING_REASON
            else:
                reason = api.IN_COHORT_RECLAMATION_REASON
            targets.append(cpu_preempt.Target(cand, reason))
        out[ei] = targets
    return out
