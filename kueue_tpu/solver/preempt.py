"""Device-side preemption target selection (TPU solver v2).

Replaces the per-entry sequential simulation of the reference's
minimalPreemptions (remove candidates in order until the preemptor fits,
then fill back in reverse — pkg/scheduler/preemption/preemption.go:237-310)
with one batched program: every preempt-mode entry's simulation runs as an
independent lane of a vmapped lax.scan over a padded candidate axis.

Host side (cheap, O(entries x candidates) filters):
- candidate discovery + ordering (findCandidates / candidatesOrdering,
  preemption.go:488-614) — static per entry, no simulation state
- the get_targets_internal policy dispatch (preemption.go:116-171),
  encoded as up to two device "problems" per entry (the under-nominal
  reclaim attempt falls back to same-queue-only)

Device side (the hot loop):
- per problem: a local sub-snapshot of the entry's cohort tree
  (CQs/cohorts re-indexed into small padded spaces, quotas/usage projected
  onto the entry's requested FlavorResources), then a K-step scan that
  removes candidates (with the dynamic cq-is-borrowing skip and the
  borrowWithinCohort priority-threshold borrowing flip), checks fit after
  each removal, and a reverse fill-back scan.

Fair-sharing preemption (fairPreemptions' DRF heap) stays on the CPU
path; the scheduler gates this solver off when fair sharing is enabled.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from kueue_tpu.api import kueue as api
from kueue_tpu.core import priority as prioritypkg
from kueue_tpu.scheduler import preemption as cpu_preempt

BIG = np.int64(2**61)


def _bucket(n: int, minimum: int = 4) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


@dataclass
class PreemptionProblem:
    """One minimal_preemptions run in local index space."""

    entry_idx: int = -1
    candidates: list = field(default_factory=list)  # workload Infos, ordered
    allow_borrowing: bool = True
    threshold_active: bool = False
    threshold: int = 0


@dataclass
class PreemptionBatch:
    problems: list = field(default_factory=list)
    # device tensors, leading axis = problem
    requests: np.ndarray = None       # [B,RF] int64
    frs_np: np.ndarray = None         # [B,RF] bool — needs-preemption frs
    nominal: np.ndarray = None        # [B,QL,RF]
    borrow_limit: np.ndarray = None   # [B,QL,RF]
    guaranteed: np.ndarray = None     # [B,QL,RF]
    usage: np.ndarray = None          # [B,QL,RF]
    cq_chain: np.ndarray = None       # [B,QL,DC] local cohort ids
    c_subtree: np.ndarray = None      # [B,CL,RF]
    c_guaranteed: np.ndarray = None   # [B,CL,RF]
    c_borrow_limit: np.ndarray = None  # [B,CL,RF]
    c_usage: np.ndarray = None        # [B,CL,RF]
    cand_q: np.ndarray = None         # [B,K] local cq (-1 pad)
    cand_usage: np.ndarray = None     # [B,K,RF]
    cand_prio: np.ndarray = None      # [B,K]
    allow_borrowing: np.ndarray = None   # [B] bool
    threshold_active: np.ndarray = None  # [B] bool
    threshold: np.ndarray = None         # [B] int64
    has_cohort: np.ndarray = None        # [B] bool


def build_problems(entry_idx: int, wl, requests: dict, frs_need_preemption: set,
                   snapshot, preemptor: "cpu_preempt.Preemptor") -> list:
    """get_targets_internal's policy dispatch (preemption.go:116-171) as a
    list of 1-2 PreemptionProblems (first non-empty result wins)."""
    cq = snapshot.cluster_queues[wl.cluster_queue]
    candidates = preemptor.find_candidates(wl.obj, cq, frs_need_preemption)
    if not candidates:
        return []
    # candidatesOrdering — reuse the CPU oracle's key so the two paths
    # can never diverge on ordering (preemption.go:587-614).
    candidates.sort(key=preemptor._candidate_sort_key(cq.name))
    same_queue = [c for c in candidates if c.cluster_queue == cq.name]

    if len(same_queue) == len(candidates):
        return [PreemptionProblem(entry_idx, candidates, allow_borrowing=True)]

    borrow_within, threshold = cpu_preempt.can_borrow_within_cohort(cq, wl.obj)
    if borrow_within:
        cands = candidates
        if not cpu_preempt.queue_under_nominal(frs_need_preemption, cq):
            cands = [c for c in candidates
                     if c.cluster_queue == cq.name
                     or prioritypkg.priority(c.obj) < threshold]
        return [PreemptionProblem(entry_idx, cands, allow_borrowing=True,
                                  threshold_active=True, threshold=threshold)]

    problems = []
    if cpu_preempt.queue_under_nominal(frs_need_preemption, cq):
        problems.append(PreemptionProblem(entry_idx, candidates,
                                          allow_borrowing=False))
    problems.append(PreemptionProblem(entry_idx, same_queue,
                                      allow_borrowing=True))
    return problems


def encode_problems(problems: list, snapshot, requests_by_entry: dict,
                    frs_np_by_entry: dict, wl_cq_by_entry: dict) -> PreemptionBatch:
    """Project each problem's cohort tree onto local padded index spaces."""
    B = _bucket(max(1, len(problems)), 1)
    RF = _bucket(max(max((len(requests_by_entry[p.entry_idx]) for p in problems),
                         default=1), 1))
    QL = _bucket(max(max((1 + len({c.cluster_queue for c in p.candidates
                                   if c.cluster_queue != wl_cq_by_entry[p.entry_idx]})
                          for p in problems), default=1), 1))
    K = _bucket(max(max((len(p.candidates) for p in problems), default=1), 1))

    # local cohort space: union of chains of all local CQs
    def chain_of(cq_snap):
        out = []
        node = cq_snap.cohort
        while node is not None:
            out.append(node)
            node = node.parent
        return out

    CL, DC = 1, 1
    for p in problems:
        cq_names = {wl_cq_by_entry[p.entry_idx]} | {
            c.cluster_queue for c in p.candidates}
        cohorts = {}
        for name in cq_names:
            ch = chain_of(snapshot.cluster_queues[name])
            DC = max(DC, len(ch))
            for c in ch:
                cohorts[c.name] = c
        CL = max(CL, len(cohorts))
    CL = _bucket(CL)

    batch = PreemptionBatch(problems=list(problems))
    batch.requests = np.zeros((B, RF), np.int64)
    batch.frs_np = np.zeros((B, RF), bool)
    batch.nominal = np.zeros((B, QL, RF), np.int64)
    batch.borrow_limit = np.full((B, QL, RF), BIG, np.int64)
    batch.guaranteed = np.zeros((B, QL, RF), np.int64)
    batch.usage = np.zeros((B, QL, RF), np.int64)
    batch.cq_chain = np.full((B, QL, DC), -1, np.int32)
    batch.c_subtree = np.zeros((B, CL, RF), np.int64)
    batch.c_guaranteed = np.zeros((B, CL, RF), np.int64)
    batch.c_borrow_limit = np.full((B, CL, RF), BIG, np.int64)
    batch.c_usage = np.zeros((B, CL, RF), np.int64)
    batch.cand_q = np.full((B, K), -1, np.int32)
    batch.cand_usage = np.zeros((B, K, RF), np.int64)
    batch.cand_prio = np.zeros((B, K), np.int64)
    batch.allow_borrowing = np.zeros(B, bool)
    batch.threshold_active = np.zeros(B, bool)
    batch.threshold = np.zeros(B, np.int64)
    batch.has_cohort = np.zeros(B, bool)

    for bi, p in enumerate(problems):
        ei = p.entry_idx
        requests = requests_by_entry[ei]
        frs = list(requests)
        fr_index = {fr: i for i, fr in enumerate(frs)}
        preemptor_cq = wl_cq_by_entry[ei]

        local_cqs = [preemptor_cq]
        for c in p.candidates:
            if c.cluster_queue not in local_cqs:
                local_cqs.append(c.cluster_queue)
        cq_index = {n: i for i, n in enumerate(local_cqs)}
        cohort_index: dict = {}

        for qn, qi in cq_index.items():
            cq_snap = snapshot.cluster_queues[qn]
            for ci, cobj in enumerate(chain_of(cq_snap)):
                li = cohort_index.setdefault(cobj.name, len(cohort_index))
                batch.cq_chain[bi, qi, ci] = li
            for fr, i in fr_index.items():
                quota = cq_snap.quota_for(fr)
                batch.nominal[bi, qi, i] = quota.nominal
                if quota.borrowing_limit is not None:
                    batch.borrow_limit[bi, qi, i] = quota.borrowing_limit
                batch.guaranteed[bi, qi, i] = \
                    cq_snap.resource_node.guaranteed_quota(fr)
                batch.usage[bi, qi, i] = cq_snap.usage_for(fr)
        for cname, li in cohort_index.items():
            # find the cohort snapshot object via any chain
            cobj = None
            for qn in local_cqs:
                for c in chain_of(snapshot.cluster_queues[qn]):
                    if c.name == cname:
                        cobj = c
                        break
                if cobj is not None:
                    break
            rn = cobj.resource_node
            for fr, i in fr_index.items():
                batch.c_subtree[bi, li, i] = rn.subtree_quota.get(fr, 0)
                batch.c_guaranteed[bi, li, i] = rn.guaranteed_quota(fr)
                quota = rn.quotas.get(fr)
                if quota is not None and quota.borrowing_limit is not None:
                    batch.c_borrow_limit[bi, li, i] = quota.borrowing_limit
                batch.c_usage[bi, li, i] = rn.usage.get(fr, 0)

        for i, fr in enumerate(frs):
            batch.requests[bi, i] = requests[fr]
            batch.frs_np[bi, i] = fr in frs_np_by_entry[ei]
        for ki, cand in enumerate(p.candidates):
            batch.cand_q[bi, ki] = cq_index[cand.cluster_queue]
            batch.cand_prio[bi, ki] = prioritypkg.priority(cand.obj)
            for fr, v in cand.flavor_resource_usage().items():
                i = fr_index.get(fr)
                if i is not None:
                    batch.cand_usage[bi, ki, i] = v
        batch.allow_borrowing[bi] = p.allow_borrowing
        batch.threshold_active[bi] = p.threshold_active
        batch.threshold[bi] = p.threshold if p.threshold_active else 0
        batch.has_cohort[bi] = \
            snapshot.cluster_queues[preemptor_cq].cohort is not None
    return batch


# --------------------------------------------------------------------------
# Device kernel
# --------------------------------------------------------------------------

def _make_kernel():
    import jax
    import jax.numpy as jnp

    NOLIM = 2**61

    def avail_cq0(nominal, borrow_limit, guaranteed, usage, cq_chain,
                  c_subtree, c_guar, c_bl, c_usage, has_cohort):
        """available() for local CQ 0 (the preemptor's), walking its
        cohort chain (reference: resource_node.go:89-104)."""
        chain = cq_chain[0]                       # [DC]
        DC = chain.shape[0]
        RF = nominal.shape[1]
        parent = jnp.zeros(RF, jnp.int64)
        started = jnp.zeros((), bool)
        for d in range(DC - 1, -1, -1):
            c = chain[d]
            valid = c >= 0
            c_ = jnp.maximum(c, 0)
            cu = c_usage[c_]
            root_avail = c_subtree[c_] - cu
            local = jnp.maximum(0, c_guar[c_] - cu)
            cap = (c_subtree[c_] - c_guar[c_]) - jnp.maximum(0, cu - c_guar[c_]) \
                + jnp.minimum(c_bl[c_], NOLIM // 4)
            child = local + jnp.minimum(parent, cap)
            new = jnp.where(started, child, root_avail)
            parent = jnp.where(valid, new, parent)
            started = started | valid
        local0 = jnp.maximum(0, guaranteed[0] - usage[0])
        cap0 = (nominal[0] - guaranteed[0]) - jnp.maximum(0, usage[0] - guaranteed[0]) \
            + jnp.minimum(borrow_limit[0], NOLIM // 4)
        with_cohort = local0 + jnp.minimum(parent, cap0)
        return jnp.where(has_cohort, with_cohort, nominal[0] - usage[0])

    def fits(requests, nominal, borrow_limit, guaranteed, usage, cq_chain,
             c_subtree, c_guar, c_bl, c_usage, has_cohort, allow_borrowing):
        """workload_fits (reference: preemption.go:576-585)."""
        has_req = requests > 0
        avail = avail_cq0(nominal, borrow_limit, guaranteed, usage, cq_chain,
                          c_subtree, c_guar, c_bl, c_usage, has_cohort)
        borrow_ok = allow_borrowing | \
            jnp.all(~has_req | (usage[0] + requests <= nominal[0]))
        return borrow_ok & jnp.all(~has_req | (requests <= avail))

    def remove_usage(usage, c_usage, cq_chain, guaranteed, c_guar, q, val):
        """removeUsage bubbling (reference: resource_node.go:133-143)."""
        stored = usage[q] - guaranteed[q]          # pre-removal
        usage = usage.at[q].add(-val)
        delta = jnp.minimum(val, jnp.maximum(0, stored))
        chain = cq_chain[q]
        DC = chain.shape[0]
        for d in range(DC):
            c = chain[d]
            valid = (c >= 0) & jnp.any(delta > 0)
            c_ = jnp.maximum(c, 0)
            stored_c = c_usage[c_] - c_guar[c_]
            dd = jnp.where(valid, delta, 0)
            c_usage = c_usage.at[c_].add(-dd)
            delta = jnp.minimum(dd, jnp.maximum(0, stored_c))
        return usage, c_usage

    def add_usage(usage, c_usage, cq_chain, guaranteed, c_guar, q, val):
        """addUsage bubbling (reference: resource_node.go:121-131)."""
        local_avail = jnp.maximum(0, guaranteed[q] - usage[q])
        usage = usage.at[q].add(val)
        delta = jnp.maximum(0, val - local_avail)
        chain = cq_chain[q]
        DC = chain.shape[0]
        for d in range(DC):
            c = chain[d]
            valid = c >= 0
            c_ = jnp.maximum(c, 0)
            local_c = jnp.maximum(0, c_guar[c_] - c_usage[c_])
            dd = jnp.where(valid, delta, 0)
            c_usage = c_usage.at[c_].add(dd)
            delta = jnp.where(valid, jnp.maximum(0, dd - local_c), delta)
        return usage, c_usage

    def solve_one(requests, frs_np, nominal, borrow_limit, guaranteed, usage,
                  cq_chain, c_subtree, c_guar, c_bl, c_usage, cand_q,
                  cand_usage, cand_prio, allow_borrowing0, threshold_active,
                  threshold, has_cohort):
        K = cand_q.shape[0]

        def fits_now(u, cu, ab):
            return fits(requests, nominal, borrow_limit, guaranteed, u,
                        cq_chain, c_subtree, c_guar, c_bl, cu, has_cohort, ab)

        # --- forward: remove until fit (minimalPreemptions) ---
        def fwd(carry, k):
            u, cu, ab, done, targets = carry
            valid = (cand_q[k] >= 0) & ~done
            q = jnp.maximum(cand_q[k], 0)
            in_cq = q == 0
            # dynamic skip: other-CQ candidate whose CQ stopped borrowing
            borrowing_cq = jnp.any(frs_np & (u[q] > nominal[q]))
            skip = (~in_cq) & ~borrowing_cq
            # borrowWithinCohort threshold: candidate at/above threshold
            # forbids borrowing for the remainder (preemption.go:252-270)
            at_or_above = threshold_active & (~in_cq) & \
                (cand_prio[k] >= threshold)
            ab = ab & ~(valid & ~skip & at_or_above)
            do = valid & ~skip
            val = jnp.where(do, cand_usage[k], 0)
            u2, cu2 = remove_usage(u, cu, cq_chain, guaranteed, c_guar, q, val)
            u = jnp.where(do, u2, u)
            cu = jnp.where(do, cu2, cu)
            targets = targets.at[k].set(do)
            done = done | (do & fits_now(u, cu, ab))
            return (u, cu, ab, done, targets), None

        init = (usage, c_usage, allow_borrowing0, jnp.zeros((), bool),
                jnp.zeros(K, bool))
        (u, cu, ab, done, targets), _ = jax.lax.scan(
            fwd, init, jnp.arange(K))

        # no fit => no targets (preemption.go:300-303)
        targets = targets & done

        # --- reverse: fill back (fillBackWorkloads) — skip the last-added
        # target (the one that achieved the fit) ---
        last_idx = jnp.where(done,
                             (K - 1) - jnp.argmax(targets[::-1], axis=0), -1)

        def back(carry, k_rev):
            u, cu, targets = carry
            k = K - 1 - k_rev
            consider = targets[k] & (k != last_idx)
            q = jnp.maximum(cand_q[k], 0)
            val = jnp.where(consider, cand_usage[k], 0)
            u2, cu2 = add_usage(u, cu, cq_chain, guaranteed, c_guar, q, val)
            still = fits_now(u2, cu2, ab)
            keep_back = consider & still     # workload comes back
            u = jnp.where(keep_back, u2, u)
            cu = jnp.where(keep_back, cu2, cu)
            targets = targets.at[k].set(targets[k] & ~keep_back)
            return (u, cu, targets), None

        (_, _, targets), _ = jax.lax.scan(back, (u, cu, targets),
                                          jnp.arange(K))
        return targets, done

    solve = jax.jit(jax.vmap(solve_one))
    return solve


_KERNEL = None


def solve_preemption_batch(batch: PreemptionBatch):
    """Returns (targets_mask [B,K] bool, feasible [B] bool)."""
    global _KERNEL
    import jax.numpy as jnp
    if _KERNEL is None:
        _KERNEL = _make_kernel()
    args = (batch.requests, batch.frs_np, batch.nominal, batch.borrow_limit,
            batch.guaranteed, batch.usage, batch.cq_chain, batch.c_subtree,
            batch.c_guaranteed, batch.c_borrow_limit, batch.c_usage,
            batch.cand_q, batch.cand_usage, batch.cand_prio,
            batch.allow_borrowing, batch.threshold_active, batch.threshold,
            batch.has_cohort)
    import jax
    targets, feasible = jax.device_get(
        _KERNEL(*tuple(jnp.asarray(a) for a in args)))
    return np.asarray(targets), np.asarray(feasible)


def decode_targets(batch: PreemptionBatch, targets_mask: np.ndarray,
                   feasible: np.ndarray, snapshot,
                   wl_cq_by_entry: dict) -> dict:
    """entry_idx -> list[Target]; the first feasible problem per entry
    wins (matching get_targets_internal's fallthrough order)."""
    out: dict = {}
    for bi, p in enumerate(batch.problems):
        ei = p.entry_idx
        if ei in out and out[ei]:
            continue
        if not feasible[bi]:
            out.setdefault(ei, [])
            continue
        preemptor_cq = wl_cq_by_entry[ei]
        targets = []
        for ki, cand in enumerate(p.candidates):
            if not targets_mask[bi, ki]:
                continue
            if cand.cluster_queue == preemptor_cq:
                reason = api.IN_CLUSTER_QUEUE_REASON
            elif p.threshold_active and \
                    prioritypkg.priority(cand.obj) < p.threshold:
                reason = api.IN_COHORT_RECLAIM_WHILE_BORROWING_REASON
            else:
                reason = api.IN_COHORT_RECLAMATION_REASON
            targets.append(cpu_preempt.Target(cand, reason))
        out[ei] = targets
    return out
