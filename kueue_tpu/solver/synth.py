"""Synthetic scenario generation for benchmarks and the graft entry.

Builds solver input tensors for a parameterized cluster shape without
going through the Python object model (the object path is exercised by
tests; this measures the device program at scale).
"""

from __future__ import annotations

import numpy as np


def synth_solver_inputs(num_cqs: int = 256, num_cohorts: int = 32,
                        num_flavors: int = 8, num_resources: int = 2,
                        num_workloads: int = 256, num_podsets: int = 1,
                        seed: int = 0):
    """Returns (topo dict of np arrays, usage, cohort_usage, workload arrays)
    shaped like encode.py's output: one resource group per CQ covering all
    resources with all flavors in order."""
    rng = np.random.default_rng(seed)
    Q, F, R, C, W, P = (num_cqs, num_flavors, num_resources, num_cohorts,
                        num_workloads, num_podsets)

    nominal_units = rng.integers(10, 50, size=(Q, F, R)).astype(np.int64) * 1000
    topo = {
        "cq_cohort": (np.arange(Q) % C).astype(np.int32),
        "nominal": nominal_units,
        "borrow_limit": np.full((Q, F, R), 2**62, np.int64),
        "guaranteed": np.zeros((Q, F, R), np.int64),
        "offered": np.ones((Q, F, R), bool),
        "group_id": np.zeros((Q, R), np.int32),
        "flavor_group": np.zeros((Q, F), np.int32),
        "flavor_rank": np.tile(np.arange(F, dtype=np.int32), (Q, 1)),
        "prefer_no_borrow": np.zeros(Q, bool),
        "cohort_subtree": np.zeros((C, F, R), np.int64),
        # flat (single-level) cohort forest
        "cohort_parent": np.full(C, -1, np.int32),
        "cohort_depth": np.zeros(C, np.int32),
        "cohort_root": np.arange(C, dtype=np.int32),
        "cohort_guaranteed": np.zeros((C, F, R), np.int64),
        "cohort_borrow_limit": np.full((C, F, R), 2**62, np.int64),
        "cq_chain": (np.arange(Q) % C).astype(np.int32).reshape(Q, 1),
        "fair_weight": np.full(Q, 1000, np.int64),
        "cohort_lendable": np.zeros((C, R), np.int64),
    }
    for c in range(C):
        members = topo["cq_cohort"] == c
        topo["cohort_subtree"][c] = nominal_units[members].sum(axis=0)
        topo["cohort_lendable"][c] = topo["cohort_subtree"][c].sum(axis=0)

    usage = (nominal_units * rng.uniform(0, 0.5, size=(Q, F, R))).astype(np.int64)
    cohort_usage = np.zeros((C, F, R), np.int64)
    for c in range(C):
        members = topo["cq_cohort"] == c
        cohort_usage[c] = np.maximum(0, usage[members] - topo["guaranteed"][members]).sum(axis=0)

    wl = {
        "requests": np.zeros((W, P, R), np.int64),
        "podset_active": np.zeros((W, P), bool),
        "wl_cq": rng.integers(0, Q, size=W).astype(np.int32),
        "priority": rng.integers(0, 100, size=W).astype(np.int64),
        "timestamp": rng.uniform(0, 1e6, size=W),
        "eligible": np.ones((W, P, F), bool),
        "solvable": np.ones(W, bool),
    }
    for p in range(P):
        active = rng.uniform(size=W) < (1.0 if p == 0 else 0.3)
        wl["podset_active"][:, p] = active
        wl["requests"][:, p, :] = np.where(
            active[:, None],
            rng.integers(1, 20, size=(W, R)) * 1000, 0)
    # Randomly restrict some eligibility (taints/affinity analogue).
    wl["eligible"] &= rng.uniform(size=(W, P, F)) < 0.9
    return topo, usage, cohort_usage, wl
