"""Snapshot -> tensor encoding for the batched admission solver.

Dimensions (padded to bucket sizes to avoid jit recompilation storms):
- W: head-of-queue workloads this cycle
- P: pod sets per workload
- R: distinct resource names across all ClusterQueues
- F: distinct flavor names
- Q: ClusterQueues
- C: cohorts

The hierarchical quota tree (reference: pkg/cache/resource_node.go) is
flattened into [Q,F,R] / [C,F,R] integer tensors; taint/affinity
eligibility (string matching) is computed host-side into a [W,P,F] mask
so the device program is pure integer arithmetic.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from kueue_tpu.api import kueue as api
from kueue_tpu.api.corev1 import RESOURCE_PODS
from kueue_tpu.cache.snapshot import Snapshot
from kueue_tpu.core import priority as prioritypkg
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.core.resources import FlavorResource
from kueue_tpu.scheduler.flavorassigner import flavor_selector_matches
from kueue_tpu.api.corev1 import find_untolerated_taint

BIG = np.int64(2**62)  # "no limit" encoding

# Eligibility-cache bound: at the cap, the OLDEST half (insertion order)
# is evicted instead of clearing wholesale — a churn-heavy cycle then
# re-primes only cold rows rather than stampeding a full recompute of
# every hot row at once.
ELIG_CACHE_CAP = 65536


def _bucket(n: int, minimum: int = 8, factor: int = 4) -> int:
    """Round up to the next power of `factor` (jit-compilation bucketing).

    The default factor 4 is for PER-CYCLE batch dims (W, the preemption
    problem dims): coarse buckets trade padding for far fewer distinct
    compiled shapes — over a remote-compile tunnel each new shape costs
    seconds, which dominated the north-star run's p99 cycles. TOPOLOGY
    dims (Q, F, R, C) use factor 2: they change only on spec edits (a
    full topology rebuild anyway), and tight buckets keep the per-cycle
    usage upload small on the bandwidth-bound tunnel."""
    b = minimum
    while b < n:
        b *= factor
    return b


@dataclass
class Topology:
    """Cycle-stable cluster topology tensors + name<->index maps."""

    resources: list = field(default_factory=list)   # index -> resource name
    flavors: list = field(default_factory=list)     # index -> flavor name
    cq_names: list = field(default_factory=list)    # index -> cq name
    cohort_names: list = field(default_factory=list)

    cq_cohort: np.ndarray = None          # [Q] int32, -1 = no cohort
    nominal: np.ndarray = None            # [Q,F,R] int64
    borrow_limit: np.ndarray = None       # [Q,F,R] int64 (BIG = unlimited)
    guaranteed: np.ndarray = None         # [Q,F,R] int64 (subtree - lending cap)
    offered: np.ndarray = None            # [Q,F,R] bool — (flavor,resource) in CQ
    group_id: np.ndarray = None           # [Q,R] int32, -1 = resource not covered
    flavor_group: np.ndarray = None       # [Q,F] int32, -1 = flavor not in CQ
    flavor_rank: np.ndarray = None        # [Q,F] int32 — order within its group
    covers_pods: np.ndarray = None        # [Q] bool — CQ has a "pods" resource group
    prefer_no_borrow: np.ndarray = None   # [Q] bool — whenCanBorrow == TryNextFlavor
    cohort_subtree: np.ndarray = None     # [C,F,R] int64
    # Hierarchical cohorts (reference: resource_node.go:89-146; the alpha
    # Cohort CRD forms arbitrary-depth trees, cohort_types.go:26-100):
    cohort_parent: np.ndarray = None      # [C] int32, -1 = root
    cohort_depth: np.ndarray = None       # [C] int32, root = 0
    cohort_root: np.ndarray = None        # [C] int32 — root cohort index
    cohort_guaranteed: np.ndarray = None  # [C,F,R] int64 (subtree - lending cap)
    cohort_borrow_limit: np.ndarray = None  # [C,F,R] int64 (BIG = unlimited)
    cq_chain: np.ndarray = None           # [Q,DC] int32 — cohort ancestor chain
                                          #   (direct cohort first; -1 padding)
    # Fair sharing (reference: clusterqueue.go:503-564):
    fair_weight: np.ndarray = None        # [Q] int64 milli-weight
    cohort_lendable: np.ndarray = None    # [C,R] int64 — root tree's lendable
    group_size: np.ndarray = None         # [Q,G] int32 — flavors per group
    cq_index: dict = field(default_factory=dict)
    flavor_index: dict = field(default_factory=dict)
    resource_index: dict = field(default_factory=dict)
    # Monotonic identity for cache invalidation: per-Info encoded rows and
    # the eligibility cache are keyed by this token, so a topology rebuild
    # (new generations / cohort epoch) drops every derived row at once.
    token: int = 0
    elig_cache: dict = field(default_factory=dict)


_TOPO_TOKEN = itertools.count(1)


@dataclass
class State:
    """Per-cycle mutable usage."""

    usage: np.ndarray = None         # [Q,F,R] int64
    cohort_usage: np.ndarray = None  # [C,F,R] int64


@dataclass
class WorkloadBatch:
    infos: list = field(default_factory=list)  # original Info objects (host side)
    n: int = 0                         # real workload count (<= W)
    requests: np.ndarray = None        # [W,P,R] int64
    podset_active: np.ndarray = None   # [W,P] bool
    wl_cq: np.ndarray = None           # [W] int32
    priority: np.ndarray = None        # [W] int64
    timestamp: np.ndarray = None       # [W] float64
    eligible: np.ndarray = None        # [W,P,F] bool (taints/affinity, host-computed)
    solvable: np.ndarray = None        # [W] bool — encodable by the solver
    start_rank: np.ndarray = None      # [W,P,R] int32 — flavor-resume position
                                       #   (LastTriedFlavorIdx + 1; 0 = from start)


def iter_cohorts(snapshot: Snapshot) -> dict:
    """name -> CohortSnapshot for every cohort reachable from any CQ
    (whole trees, including quota-only intermediate nodes)."""
    out: dict = {}

    def visit(c):
        if c.name in out:
            return
        out[c.name] = c
        if c.parent is not None:
            visit(c.parent)
        for child in c.child_cohorts:
            visit(child)

    for cq in snapshot.cluster_queues.values():
        if cq.cohort is not None:
            visit(cq.cohort)
    return out


def encode_topology(snapshot: Snapshot) -> Topology:
    topo = Topology()
    topo.token = next(_TOPO_TOKEN)
    res_set, flavor_set = set(), set()
    for cq in snapshot.cluster_queues.values():
        for rg in cq.resource_groups:
            res_set.update(rg.covered_resources)
            flavor_set.update(rg.flavors)
    topo.resources = sorted(res_set)
    topo.flavors = sorted(flavor_set)
    topo.cq_names = sorted(snapshot.cluster_queues)
    cohort_objs = iter_cohorts(snapshot)
    topo.cohort_names = sorted(cohort_objs)
    topo.resource_index = {r: i for i, r in enumerate(topo.resources)}
    topo.flavor_index = {f: i for i, f in enumerate(topo.flavors)}
    topo.cq_index = {c: i for i, c in enumerate(topo.cq_names)}
    cohort_index = {c: i for i, c in enumerate(topo.cohort_names)}

    Q = _bucket(max(1, len(topo.cq_names)), 1, factor=2)
    F = _bucket(max(1, len(topo.flavors)), 1, factor=2)
    R = _bucket(max(1, len(topo.resources)), 1, factor=2)
    C = _bucket(max(1, len(topo.cohort_names)), 1, factor=2)

    topo.cq_cohort = np.full(Q, -1, np.int32)
    topo.nominal = np.zeros((Q, F, R), np.int64)
    topo.borrow_limit = np.full((Q, F, R), BIG, np.int64)
    topo.guaranteed = np.zeros((Q, F, R), np.int64)
    topo.offered = np.zeros((Q, F, R), bool)
    topo.group_id = np.full((Q, R), -1, np.int32)
    topo.flavor_group = np.full((Q, F), -1, np.int32)
    topo.flavor_rank = np.full((Q, F), 10**6, np.int32)
    topo.covers_pods = np.zeros(Q, bool)
    topo.prefer_no_borrow = np.zeros(Q, bool)
    topo.cohort_subtree = np.zeros((C, F, R), np.int64)
    topo.cohort_parent = np.full(C, -1, np.int32)
    topo.cohort_depth = np.zeros(C, np.int32)
    topo.cohort_root = np.arange(C, dtype=np.int32)
    topo.cohort_guaranteed = np.zeros((C, F, R), np.int64)
    topo.cohort_borrow_limit = np.full((C, F, R), BIG, np.int64)
    topo.fair_weight = np.full(Q, 1000, np.int64)
    topo.cohort_lendable = np.zeros((C, R), np.int64)

    for cname, cobj in cohort_objs.items():
        ci = cohort_index[cname]
        if cobj.parent is not None:
            topo.cohort_parent[ci] = cohort_index[cobj.parent.name]
        rn = cobj.resource_node
        for fr, q in rn.subtree_quota.items():
            fi = topo.flavor_index.get(fr.flavor)
            ri = topo.resource_index.get(fr.resource)
            if fi is not None and ri is not None:
                topo.cohort_subtree[ci, fi, ri] = q
                topo.cohort_guaranteed[ci, fi, ri] = rn.guaranteed_quota(fr)
        for fr, quota in rn.quotas.items():
            fi = topo.flavor_index.get(fr.flavor)
            ri = topo.resource_index.get(fr.resource)
            if fi is not None and ri is not None and quota.borrowing_limit is not None:
                topo.cohort_borrow_limit[ci, fi, ri] = quota.borrowing_limit
    # depth + root by chasing parents (trees are cycle-checked upstream)
    lendable_by_root: dict = {}
    for cname in topo.cohort_names:
        ci = cohort_index[cname]
        depth, node = 0, cohort_objs[cname]
        while node.parent is not None:
            depth += 1
            node = node.parent
        topo.cohort_depth[ci] = depth
        topo.cohort_root[ci] = cohort_index[node.name]
        # DRF denominator: the root tree's lendable capacity per resource
        # (host-computed so flavors outside this topology still count;
        # only root rows are read by the kernel).
        if node.name not in lendable_by_root:
            lendable_by_root[node.name] = node.resource_node.calculate_lendable()
        if cname == node.name:
            for rname, q in lendable_by_root[node.name].items():
                ri = topo.resource_index.get(rname)
                if ri is not None:
                    topo.cohort_lendable[ci, ri] = q
    # per-CQ ancestor chain, direct cohort first (static max depth)
    max_chain = 1
    for cq in snapshot.cluster_queues.values():
        if cq.cohort is not None:
            max_chain = max(max_chain,
                            int(topo.cohort_depth[cohort_index[cq.cohort.name]]) + 1)
    topo.cq_chain = np.full((Q, max_chain), -1, np.int32)

    for qname, cq in snapshot.cluster_queues.items():
        qi = topo.cq_index[qname]
        if cq.cohort is not None:
            topo.cq_cohort[qi] = cohort_index[cq.cohort.name]
            node, d = cq.cohort, 0
            while node is not None:
                topo.cq_chain[qi, d] = cohort_index[node.name]
                node, d = node.parent, d + 1
        topo.prefer_no_borrow[qi] = (cq.flavor_fungibility.when_can_borrow
                                     == api.TRY_NEXT_FLAVOR)
        topo.fair_weight[qi] = cq.fair_weight
        for gi, rg in enumerate(cq.resource_groups):
            for r in rg.covered_resources:
                if r == RESOURCE_PODS:
                    topo.covers_pods[qi] = True
                topo.group_id[qi, topo.resource_index[r]] = gi
            for rank, fname in enumerate(rg.flavors):
                fi = topo.flavor_index[fname]
                topo.flavor_group[qi, fi] = gi
                topo.flavor_rank[qi, fi] = rank
                for r in rg.covered_resources:
                    ri = topo.resource_index[r]
                    fr = FlavorResource(fname, r)
                    quota = cq.quota_for(fr)
                    topo.offered[qi, fi, ri] = True
                    topo.nominal[qi, fi, ri] = quota.nominal
                    if quota.borrowing_limit is not None:
                        topo.borrow_limit[qi, fi, ri] = quota.borrowing_limit
                    topo.guaranteed[qi, fi, ri] = cq.resource_node.guaranteed_quota(fr)
    # flavors per resource group (decode needs it for LastTriedFlavorIdx
    # exhaustion; vectorized over all admitted rows)
    max_groups = max((len(cq.resource_groups)
                      for cq in snapshot.cluster_queues.values()), default=1)
    topo.group_size = np.zeros((Q, max(1, max_groups)), np.int32)
    for qname, cq in snapshot.cluster_queues.items():
        qi = topo.cq_index[qname]
        for gi, rg in enumerate(cq.resource_groups):
            topo.group_size[qi, gi] = len(rg.flavors)
    return topo


def encode_state(snapshot: Snapshot, topo: Topology) -> State:
    Q, F, R = topo.nominal.shape
    C = topo.cohort_subtree.shape[0]
    state = State(usage=np.zeros((Q, F, R), np.int64),
                  cohort_usage=np.zeros((C, F, R), np.int64))
    cohort_index = {c: i for i, c in enumerate(topo.cohort_names)}
    for qname, cq in snapshot.cluster_queues.items():
        qi = topo.cq_index[qname]
        for fr, used in cq.resource_node.usage.items():
            fi = topo.flavor_index.get(fr.flavor)
            ri = topo.resource_index.get(fr.resource)
            if fi is not None and ri is not None:
                state.usage[qi, fi, ri] = used
    for cname, cobj in iter_cohorts(snapshot).items():
        ci = cohort_index.get(cname)
        if ci is None:
            continue
        for fr, used in cobj.resource_node.usage.items():
            fi = topo.flavor_index.get(fr.flavor)
            ri = topo.resource_index.get(fr.resource)
            if fi is not None and ri is not None:
                state.cohort_usage[ci, fi, ri] = used
    return state


def _encode_one(info, snapshot: Snapshot, topo: Topology, P: int):
    """Encode one workload's cycle-stable rows. Returns
    (qi, requests [P,R], active [P], eligible [P,F], solvable) — or
    qi == -1 when the CQ is unknown. Cached on the Info keyed by
    topo.token (Info.total_requests is fixed at Info construction; the
    queue manager builds a fresh Info on workload updates)."""
    cq = snapshot.cluster_queues.get(info.cluster_queue)
    if cq is None:
        return -1, None, None, None, False
    qi = topo.cq_index[info.cluster_queue]
    _, F, R = topo.nominal.shape
    requests = np.zeros((P, R), np.int64)
    active = np.zeros(P, bool)
    eligible = np.zeros((P, F), bool)
    if len(info.total_requests) > P:
        return qi, requests, active, eligible, False  # CPU fallback
    resource_index = topo.resource_index
    covers_pods = topo.covers_pods[qi]
    for pi, psr in enumerate(info.total_requests):
        reqs = dict(psr.requests)
        if covers_pods:
            reqs[RESOURCE_PODS] = psr.count
        for r, v in reqs.items():
            ri = resource_index.get(r)
            if ri is None or topo.group_id[qi, ri] < 0:
                return qi, requests, active, eligible, False
            requests[pi, ri] = v
        active[pi] = True
        eligible[pi] = eligibility_row(info, pi, qi, cq, snapshot, topo)
    return qi, requests, active, eligible, True


def eligibility_row(info, pi: int, qi: int, cq, snapshot: Snapshot,
                    topo: Topology) -> np.ndarray:
    """Host-side taints/affinity per flavor for one podset, memoized by
    pod-spec signature: identical pod shapes (the common case at scale)
    share one eligibility row instead of re-running the string-matching
    loop per workload. Shared by the oracle and the encode arena."""
    pod_spec = info.obj.spec.pod_sets[pi].template.spec
    key = (qi, _eligibility_key(pod_spec))
    row = topo.elig_cache.get(key)
    if row is not None:
        # Move-to-end on hit: the oldest-half eviction then drops the
        # LEAST-RECENTLY-USED half, so a permanently-hot shared row
        # (the dominant pod shape) survives every cap trip. Row encodes
        # are already O(changed), so the two dict ops are noise.
        del topo.elig_cache[key]
        topo.elig_cache[key] = row
        return row
    if len(topo.elig_cache) >= ELIG_CACHE_CAP:
        _evict_oldest_half(topo.elig_cache)
    F = topo.nominal.shape[1]
    row = np.zeros(F, bool)
    for rg in cq.resource_groups:
        for fname in rg.flavors:
            flavor = snapshot.resource_flavors.get(fname)
            if flavor is None:
                continue
            if find_untolerated_taint(flavor.spec.node_taints,
                                      pod_spec.tolerations) is not None:
                continue
            if not flavor_selector_matches(pod_spec, rg.label_keys,
                                           flavor.spec.node_labels):
                continue
            row[topo.flavor_index[fname]] = True
    topo.elig_cache[key] = row
    return row


def _evict_oldest_half(cache: dict) -> None:
    """Bound growth under per-workload-unique pod shapes. dicts preserve
    insertion order and eligibility_row moves entries to the end on
    every hit, so the first half is the least recently used."""
    for k in list(itertools.islice(cache, len(cache) // 2)):
        del cache[k]


def fill_start_ranks(start_rank: np.ndarray, entries: list, solvable,
                     snapshot: Snapshot, topo: Topology, P: int) -> None:
    """Flavor-fungibility resume positions for the batch (reference:
    flavorassigner.go:289-296) — the one genuinely per-cycle encode
    input (capacity generations move between cycles). Shared by the
    from-scratch oracle and the arena assembler.

    Writes only the stored (podset, resource) entries instead of the old
    per-workload P x R double loop: absent resources and podsets resolve
    to next_flavor_to_try == 0, the array default, so the output is
    bit-identical. The outdated-generation check clears
    info.last_assignment exactly like the sequential assigner."""
    import operator
    gen_cache: dict = {}
    resource_index = topo.resource_index
    cqs = snapshot.cluster_queues
    # C-level attribute walk: most heads have no resume state, and the
    # per-entry getattr loop was measurable at 2048 heads.
    las = map(operator.attrgetter("last_assignment"), entries)
    for wi, la in enumerate(las):
        if la is None or not solvable[wi]:
            continue
        info = entries[wi]
        gens = gen_cache.get(info.cluster_queue)
        if gens is None:
            cq = cqs[info.cluster_queue]
            gens = (cq.allocatable_resource_generation,
                    cq.cohort.allocatable_resource_generation
                    if cq.cohort is not None else None)
            gen_cache[info.cluster_queue] = gens
        if gens[0] > la.cluster_queue_generation \
                or (gens[1] is not None and gens[1] > la.cohort_generation):
            info.last_assignment = None  # capacity moved: restart from 0
            continue
        n_ps = min(len(info.total_requests), P)
        for pi, tried in enumerate(la.last_tried_flavor_idx[:n_ps]):
            for r, idx in tried.items():
                ri = resource_index.get(r)
                if ri is not None and idx >= 0:
                    start_rank[wi, pi, ri] = idx + 1


def encode_workloads(entries: list, snapshot: Snapshot, topo: Topology,
                     ordering: Optional[wlpkg.Ordering] = None,
                     max_podsets: int = 4) -> WorkloadBatch:
    """entries: list of workload Info heads."""
    ordering = ordering or wlpkg.Ordering()
    W = _bucket(max(1, len(entries)))
    P = max_podsets
    _, F, R = topo.nominal.shape

    batch = WorkloadBatch(infos=list(entries), n=len(entries))
    batch.requests = np.zeros((W, P, R), np.int64)
    batch.podset_active = np.zeros((W, P), bool)
    batch.wl_cq = np.zeros(W, np.int32)
    batch.priority = np.zeros(W, np.int64)
    batch.timestamp = np.zeros(W, np.float64)
    batch.eligible = np.zeros((W, P, F), bool)
    batch.solvable = np.zeros(W, bool)
    batch.start_rank = np.zeros((W, P, R), np.int32)

    token = topo.token
    priorities, timestamps = batch.priority, batch.timestamp
    for wi, info in enumerate(entries):
        # Keyed by (topology token, resourceVersion): a workload update
        # that rebuilds requests without a fresh Info (e.g. reclaimable
        # pods) must invalidate the cached rows too.
        key = (token, info.obj.metadata.resource_version)
        enc = getattr(info, "_solver_enc", None)
        if enc is None or enc[0] != key:
            enc = (key,) + _encode_one(info, snapshot, topo, P)
            info._solver_enc = enc
        _, qi, requests, active, eligible, ok = enc
        if qi < 0:
            continue
        batch.wl_cq[wi] = qi
        priorities[wi] = prioritypkg.priority(info.obj)
        timestamps[wi] = ordering.queue_order_timestamp(info.obj)
        if not ok:
            continue
        batch.requests[wi] = requests
        batch.podset_active[wi] = active
        batch.eligible[wi] = eligible
        batch.solvable[wi] = True
    # Flavor-fungibility resume: both the outdated check and the resume
    # apply regardless of the FlavorFungibility gate, mirroring the CPU
    # assigner.
    fill_start_ranks(batch.start_rank, entries, batch.solvable, snapshot,
                     topo, P)
    return batch


# ---------------------------------------------------------------------------
# MultiKueue remote clusters as flavor-capacity columns (ISSUE 13)
# ---------------------------------------------------------------------------
#
# Snapshot.remote_clusters carries each worker cluster's available
# capacity as {(flavor, resource): quantity}; the encoder folds them
# into [K,F,R] tensors in the LOCAL topology's flavor/resource index
# space (capacity on flavors/resources unknown locally is unscorable
# and ignored — workers in a MultiKueue fleet share the flavor
# vocabulary, SURVEY.md §2.7). kernel.score_cluster_columns_impl scores
# the columns inside the fused solve; place_remote_dicts is the
# sequential host oracle with the IDENTICAL placement rule — the
# scheduler uses it on CPU-routed cycles, and the differential tests
# pin device == oracle bit-for-bit.


@dataclass
class ClusterColumns:
    """Encoded remote-cluster capacity columns for one cycle."""

    names: tuple = ()                 # K_real cluster names, column order
    ccap: np.ndarray = None           # [K,F,R] int64 available capacity
    coffer: np.ndarray = None         # [K,F,R] bool — (f,r) offered
    cactive: np.ndarray = None        # [K] bool — reachable
    mk_cq: np.ndarray = None          # [Q] bool — CQ has a mk check


def encode_cluster_columns(snapshot: Snapshot,
                           topo: Topology) -> Optional[ClusterColumns]:
    """Snapshot remote-cluster capacities -> column tensors, or None
    when the snapshot carries no remote clusters. K is bucketed
    (factor 2, like the other topology dims); padding columns are
    inactive and offer nothing."""
    remotes = getattr(snapshot, "remote_clusters", ())
    if not remotes:
        return None
    _, F, R = topo.nominal.shape
    Q = topo.nominal.shape[0]
    K = _bucket(len(remotes), 1, factor=2)
    cols = ClusterColumns(names=tuple(name for name, _, _ in remotes))
    cols.ccap = np.zeros((K, F, R), np.int64)
    cols.coffer = np.zeros((K, F, R), bool)
    cols.cactive = np.zeros(K, bool)
    for ki, (_name, caps, active) in enumerate(remotes):
        cols.cactive[ki] = bool(active)
        for (fname, rname), avail in caps.items():
            fi = topo.flavor_index.get(fname)
            ri = topo.resource_index.get(rname)
            if fi is None or ri is None:
                continue
            cols.coffer[ki, fi, ri] = True
            cols.ccap[ki, fi, ri] = max(int(avail), 0)
    mk_checks = getattr(snapshot, "mk_check_names", frozenset())
    cols.mk_cq = np.zeros(Q, bool)
    if mk_checks:
        for qname, cq in snapshot.cluster_queues.items():
            if not mk_checks.isdisjoint(cq.admission_checks):
                cols.mk_cq[topo.cq_index[qname]] = True
    if not cols.mk_cq.any():
        return None  # no CQ routes through the columns this cycle
    return cols


def cluster_args_device(cols: ClusterColumns) -> tuple:
    """The kernel-facing (ccap, coffer, cactive, mk_cq) tuple."""
    return (cols.ccap, cols.coffer, cols.cactive, cols.mk_cq)


def consume_remote_dicts(remote_clusters: tuple, requests: list,
                         pinned: list) -> tuple:
    """Debit already-decided (pinned) placements from the capacity
    columns and return the REMAINING columns tuple — the controller
    uses it to price in-flight planned-but-not-yet-reserved workloads
    so consecutive cycles don't re-place onto capacity the remote
    hasn't materialized yet (the remote usage read lags by however
    long the worker takes to reserve)."""
    remaining = [dict(caps) for _, caps, _ in remote_clusters]
    by_name = {c[0]: i for i, c in enumerate(remote_clusters)}
    for req, cluster in zip(requests, pinned):
        ki = by_name.get(cluster)
        if ki is None:
            continue
        caps = remaining[ki]
        flavors: dict = {}
        for (fname, rname), avail in caps.items():
            flavors.setdefault(fname, {})[rname] = avail
        req = {r: v for r, v in req.items() if v > 0}
        for fname in sorted(flavors):
            rem = flavors[fname]
            if all(r in rem and rem[r] >= v for r, v in req.items()) \
                    and any(r in rem for r in req):
                for r, v in req.items():
                    caps[(fname, r)] -= v
                break
    return tuple((name, remaining[i], active)
                 for i, (name, _caps, active) in enumerate(remote_clusters))


def place_remote_dicts(remote_clusters: tuple, requests: list,
                       pinned: Optional[list] = None) -> list:
    """The sequential placement oracle in name space: for each
    per-workload request dict {resource: quantity} (in admission
    order), pick the FIRST active cluster (column order) with ONE
    flavor whose remaining capacity covers every requested resource;
    consume it. ``pinned[i]`` (a cluster name) forces workload i's
    choice — the scheduler pins device-decided rows so the host
    continuation accounts from the same remaining capacity. Returns a
    cluster name or None per workload. This is the one definition of
    the placement rule; kernel.score_cluster_columns_impl is its
    batched twin (differentially pinned in tests/test_clusters.py)."""
    remaining = []
    for name, caps, active in remote_clusters:
        flavors: dict = {}
        for (fname, rname), avail in caps.items():
            flavors.setdefault(fname, {})[rname] = max(int(avail), 0)
        remaining.append((name, flavors, bool(active)))
    out: list = []

    def fit_flavor(flavors: dict, req: dict) -> Optional[str]:
        # sorted: the device twin scans flavors in topology index
        # order, which encode_topology builds from sorted names — the
        # oracle must consume the same flavor or later placements
        # would diverge on remaining capacity.
        for fname in sorted(flavors):
            rem = flavors[fname]
            if all(r in rem and rem[r] >= v for r, v in req.items() if v > 0):
                if any(r in rem for r, v in req.items() if v > 0):
                    return fname
        return None

    for i, req in enumerate(requests):
        req = {r: v for r, v in req.items() if v > 0}
        chosen = None
        want = pinned[i] if pinned is not None else None
        for name, flavors, active in remaining:
            if not active or not req:
                continue
            if want is not None and name != want:
                continue
            f = fit_flavor(flavors, req)
            if f is not None:
                chosen = name
                for r, v in req.items():
                    flavors[f][r] -= v
                break
            if want is not None:
                # Pinned but no longer fits host-side: honor the pin
                # anyway (the device already consumed this capacity in
                # its own accounting) without decrementing twice.
                chosen = name
                break
        if chosen is None and want is not None:
            chosen = want  # pinned to a cluster outside the column set
        out.append(chosen)
    return out


# ---------------------------------------------------------------------------
# Device-resident state: sparse correction encoding + the host mirror
# ---------------------------------------------------------------------------

def encode_deltas(corrections: dict, topo: Topology):
    """corrections: {(cq_name, FlavorResource) -> net delta}. Returns the
    (dq, df, dr, dv, lvl_c, lvl_seg) tuple for kernel.apply_state_deltas,
    or None when nothing maps onto the topology. Coords are unique per
    level by construction (aggregation dict + np.unique)."""
    coords = []
    for (cq_name, fr), dv in corrections.items():
        if dv == 0:
            continue
        qi = topo.cq_index.get(cq_name)
        fi = topo.flavor_index.get(fr.flavor)
        ri = topo.resource_index.get(fr.resource)
        if qi is None or fi is None or ri is None:
            continue
        coords.append((qi, fi, ri, dv))
    if not coords:
        return None
    D = _bucket(len(coords), 8)
    dq = np.full(D, -1, np.int32)
    df = np.zeros(D, np.int32)
    dr = np.zeros(D, np.int32)
    dv = np.zeros(D, np.int64)
    arr = np.asarray(coords, np.int64)
    n = len(coords)
    dq[:n] = arr[:, 0]
    df[:n] = arr[:, 1]
    dr[:n] = arr[:, 2]
    dv[:n] = arr[:, 3]

    L = topo.cq_chain.shape[1]
    lvl_c = np.full((L, D, 3), -1, np.int32)
    lvl_seg = np.full((L, D), -1, np.int32)
    # level 0 parents: the delta coords' direct cohorts; level l parents:
    # level l-1's cohort coords' parents. Dedup per level with np.unique.
    prev_c = np.where(dq >= 0, topo.cq_chain[np.maximum(dq, 0), 0], -1)  # [D]
    prev_f, prev_r = df, dr
    for lvl in range(L):
        valid = prev_c >= 0
        if not valid.any():
            break
        key = (prev_c.astype(np.int64) << 32) | \
              (prev_f.astype(np.int64) << 16) | prev_r.astype(np.int64)
        key = np.where(valid, key, np.int64(-1))
        uniq, inv = np.unique(key, return_inverse=True)
        off = 1 if uniq[0] == -1 else 0  # drop the invalid bucket
        m = len(uniq) - off
        lvl_c[lvl, :m, 0] = (uniq[off:] >> 32).astype(np.int32)
        lvl_c[lvl, :m, 1] = ((uniq[off:] >> 16) & 0xFFFF).astype(np.int32)
        lvl_c[lvl, :m, 2] = (uniq[off:] & 0xFFFF).astype(np.int32)
        lvl_seg[lvl] = np.where(valid, inv - off, -1).astype(np.int32)
        # next level: parents of this level's unique cohorts
        prev_c = np.full(D, -1, np.int32)
        prev_c[:m] = topo.cohort_parent[lvl_c[lvl, :m, 0]]
        prev_f = np.maximum(lvl_c[lvl, :, 1], 0)
        prev_r = np.maximum(lvl_c[lvl, :, 2], 0)
    return dq, df, dr, dv, lvl_c, lvl_seg


def apply_deltas_np(topo: Topology, usage: np.ndarray,
                    cohort_usage: np.ndarray, deltas) -> None:
    """In-place numpy twin of kernel.apply_state_deltas — keeps the host
    mirror bit-identical to the device-resident state (the mirror feeds
    the CPU-backend fit router and the decode path)."""
    dq, df, dr, dv, lvl_c, lvl_seg = deltas
    valid = dq >= 0
    dqs = np.maximum(dq, 0)
    dvm = np.where(valid, dv, 0)
    old = usage[dqs, df, dr].copy()
    np.add.at(usage, (dqs, df, dr), dvm)
    g = topo.guaranteed[dqs, df, dr]
    dover = np.maximum(0, old + dvm - g) - np.maximum(0, old - g)
    D = len(dq)
    for lvl in range(lvl_c.shape[0]):
        seg = lvl_seg[lvl]
        delta_l = np.zeros(D, np.int64)
        np.add.at(delta_l, np.maximum(seg, 0), np.where(seg >= 0, dover, 0))
        c = lvl_c[lvl, :, 0]
        cvalid = c >= 0
        if not cvalid.any():
            break
        cs = np.maximum(c, 0)
        fs = np.maximum(lvl_c[lvl, :, 1], 0)
        rs = np.maximum(lvl_c[lvl, :, 2], 0)
        delta_l = np.where(cvalid, delta_l, 0)
        oldc = cohort_usage[cs, fs, rs].copy()
        np.add.at(cohort_usage, (cs, fs, rs), delta_l)
        gc = topo.cohort_guaranteed[cs, fs, rs]
        dover = np.maximum(0, oldc + delta_l - gc) - np.maximum(0, oldc - gc)


def _eligibility_key(pod_spec) -> tuple:
    """Hashable signature of the pod-spec fields that feed flavor
    eligibility (tolerations, node selector, node affinity)."""
    tols = tuple((t.key, t.operator, t.value, t.effect)
                 for t in pod_spec.tolerations)
    sel = tuple(sorted(pod_spec.node_selector.items()))
    aff = ()
    if pod_spec.affinity is not None and pod_spec.affinity.node_affinity is not None:
        req = pod_spec.affinity.node_affinity.required
        if req is not None:
            aff = tuple(
                tuple((e.key, e.operator, tuple(e.values))
                      for e in term.match_expressions)
                for term in req.node_selector_terms)
    return tols, sel, aff
