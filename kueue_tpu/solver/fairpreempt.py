"""Device-side fairPreemptions (TPU solver v2, final stage).

Replaces the CPU DRF-heap loop (reference:
pkg/scheduler/preemption/preemption.go:312-437 — pop the max-dominant-
share ClusterQueue, test the configured strategy against the preemptor's
and preemptee's shares, remove, re-heap; then the optional second-
strategy retry pass; then fill-back) with a batched program: every
fair-preemption entry runs as an independent vmapped lane whose heap
loop carries INCREMENTAL per-CQ shares and early-exits once the
preemptor fits (solve_fair_impl; design notes in solver/PREEMPT.md §3),
composing with the fit solve into the cycle's single device execute.

Share decomposition (the design pinned in solver/preempt.py round 3):
dominantResourceShare (clusterqueue.go:503-564) for a CQ is

    max over resources r of (borrowed[r] * 1000 // lendable[r])
        * 1000 // fair_weight

where borrowed[r] sums max(0, usage[fr] - nominal[fr]) over that CQ's
FlavorResources of resource r. The problem's RF slots carry the
FlavorResources of the preemptor's request PLUS every FlavorResource any
domain candidate occupies (DomainCandidates.all_frs), so removals only
move the slot-carried terms; borrowing on FlavorResources outside the
slots is constant during the scan and ships as host-encoded per-CQ
constants:

- base_other[QL, RF]: extra borrowed quantity on slot i's RESOURCE from
  non-slot FlavorResources (same value on every slot of that resource),
- floor_ratio[QL] / floor_any[QL]: the share ratio contribution (and
  borrowing-exists bit) of resources with no slot at all.

Heap-tie determinization: the reference pops equal-share CQs in an
unspecified binary-heap order; both paths here break ties by the CQ's
first candidate's position in candidatesOrdering (the CPU heap's
less_func gets the same tie-break), so decisions are bit-comparable.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from kueue_tpu.api import kueue as api
from kueue_tpu.core import priority as prioritypkg
from kueue_tpu.scheduler import preemption as cpu_preempt
from kueue_tpu.solver.encode import _bucket
from kueue_tpu.solver.preempt import (
    PreemptionBatch,
    PreemptionProblem,
    make_problem_sim,
)

MAXSHARE = np.int64(2**62)

# device reason codes -> API reasons (decode)
_REASONS = (api.IN_CLUSTER_QUEUE_REASON,
            api.IN_COHORT_FAIR_SHARING_REASON,
            api.IN_COHORT_RECLAIM_WHILE_BORROWING_REASON)


@dataclass
class FairProblem(PreemptionProblem):
    """One fairPreemptions run. Slots extend to the domain candidates'
    FlavorResource union (share math needs every fr a removal touches)."""

    extra_frs: frozenset = frozenset()


@dataclass
class FairBatch(PreemptionBatch):
    """PreemptionBatch plus the DRF-share machinery."""

    cand_rank: np.ndarray = None     # [B,K] int32 rank within its CQ
    cq_count: np.ndarray = None      # [B,QL] int32 candidates per CQ
    cq_order: np.ndarray = None      # [B,QL] int32 first-candidate position
                                     #   in candidatesOrdering (tie-break)
    base_other: np.ndarray = None    # [B,QL,RF] int64 non-slot borrowing
                                     #   on the slot's resource
    floor_ratio: np.ndarray = None   # [B,QL] int64 ratio of no-slot
                                     #   resources (-1 = none)
    floor_any: np.ndarray = None     # [B,QL] bool borrowing exists there
    weight: np.ndarray = None        # [B,QL] int64 fair weight (milli)
    lendable: np.ndarray = None      # [B,RF] int64 root lendable per slot


def build_fair_problems(entry_idx: int, wl, requests: dict,
                        frs_need_preemption: set, snapshot,
                        preemptor, cand_index) -> tuple:
    """get_targets_internal's dispatch under fair sharing
    (preemption.go:131-172 with enableFairSharing): all-same-queue
    entries still run minimalPreemptions; entries with cohort candidates
    run fairPreemptions. Returns (minimal problems, fair problems)."""
    cq = snapshot.cluster_queues[wl.cluster_queue]
    domain = cand_index.domain_for(cq)
    preemption = cq.preemption
    wl_prio = prioritypkg.priority(wl.obj)
    frs = frozenset(frs_need_preemption)
    sel = domain.select(
        cq.name, wl_prio,
        preemptor.ordering.queue_order_timestamp(wl.obj), frs,
        within_policy=preemption.within_cluster_queue,
        consider_same_prio=(preemption.within_cluster_queue
                            == api.PREEMPTION_LOWER_OR_NEWER_EQUAL_PRIORITY),
        reclaim_policy=preemption.reclaim_within_cohort,
        only_lower=(preemption.reclaim_within_cohort != api.PREEMPTION_ANY))
    if sel.size == 0:
        return [], []
    qi = domain.cq_index[cq.name]
    in_cq = domain.cq_of[sel] == qi
    if bool(in_cq.all()):
        return [PreemptionProblem(entry_idx, domain, sel,
                                  allow_borrowing=True)], []
    borrow_within, threshold = cpu_preempt.can_borrow_within_cohort(cq, wl.obj)
    fp = FairProblem(entry_idx, domain, sel, allow_borrowing=True,
                     threshold_active=borrow_within,
                     threshold=threshold if borrow_within else 0,
                     extra_frs=domain.all_frs())
    return [], [fp]


def encode_fair_problems(problems: list, snapshot, topo,
                         requests_by_entry: dict, wl_cq_by_entry: dict,
                         frs_np_by_entry: dict) -> FairBatch:
    """Fair problems -> tensors: the PreemptionBatch layout (slots
    extended by extra_frs) plus per-CQ share constants."""
    from kueue_tpu.solver.preempt import encode_problems
    base = encode_problems(problems, snapshot, topo, requests_by_entry,
                           wl_cq_by_entry, frs_np_by_entry)
    batch = FairBatch(**{f: getattr(base, f) for f in (
        "problems", "gq", "gf", "gr", "gc", "chain_local", "requests",
        "frs_np", "cand_idx", "cand_ql", "cand_usage", "cand_prio",
        "allow_borrowing", "threshold_active", "threshold", "has_cohort")})
    B, K = batch.cand_ql.shape
    QL = batch.gq.shape[1]
    RF = batch.gf.shape[1]
    batch.cand_rank = np.full((B, K), -1, np.int32)
    batch.cq_count = np.zeros((B, QL), np.int32)
    batch.cq_order = np.full((B, QL), 2**30, np.int32)
    batch.base_other = np.zeros((B, QL, RF), np.int64)
    batch.floor_ratio = np.full((B, QL), -1, np.int64)
    batch.floor_any = np.zeros((B, QL), bool)
    batch.weight = np.full((B, QL), 1000, np.int64)
    batch.lendable = np.zeros((B, RF), np.int64)

    for bi, p in enumerate(problems):
        ql = batch.cand_ql[bi]
        k = p.num_candidates
        if k:
            # rank within CQ + first-appearance order, vectorized
            q = ql[:k].astype(np.int64)
            perm = np.argsort(q, kind="stable")
            sq = q[perm]
            pos = np.arange(k)
            first = np.r_[True, sq[1:] != sq[:-1]]
            seg_start = np.maximum.accumulate(np.where(first, pos, 0))
            rank = np.empty(k, np.int32)
            rank[perm] = (pos - seg_start).astype(np.int32)
            batch.cand_rank[bi, :k] = rank
            counts = np.bincount(q, minlength=QL)[:QL]
            batch.cq_count[bi] = counts.astype(np.int32)
            firsts = np.full(QL, 2**30, np.int64)
            np.minimum.at(firsts, q, pos)
            batch.cq_order[bi] = firsts.astype(np.int32)

        domain = p.domain
        req_frs = frozenset(requests_by_entry[p.entry_idx]) | p.extra_frs
        slots = domain.rows_view(req_frs).slots
        sv = domain.share_view(tuple(slots))
        # local CQ slot ql -> domain CQ index: reconstruct from gq (the
        # global CQ index), slot 0 = preemptor's CQ, then first appearance
        name_by_global = {topo.cq_index[n]: n for n in domain.cq_names
                          if n in topo.cq_index}
        for lq in range(QL):
            g = int(batch.gq[bi, lq])
            if g < 0:
                continue
            name = name_by_global.get(g)
            if name is None:
                continue
            di = domain.cq_index[name]
            nslots = min(RF, sv["base_other"].shape[1])
            batch.base_other[bi, lq, :nslots] = sv["base_other"][di, :nslots]
            batch.floor_ratio[bi, lq] = sv["floor_ratio"][di]
            batch.floor_any[bi, lq] = sv["floor_any"][di]
            batch.weight[bi, lq] = sv["weight"][di]
        nslots = min(RF, len(sv["lendable"]))
        batch.lendable[bi, :nslots] = sv["lendable"][:nslots]
    return batch


def fair_args(batch: FairBatch) -> tuple:
    return (batch.gq, batch.gf, batch.gr, batch.gc, batch.chain_local,
            batch.requests, batch.frs_np, batch.cand_idx, batch.cand_ql,
            batch.cand_usage, batch.cand_prio, batch.threshold_active,
            batch.threshold, batch.has_cohort, batch.cand_rank,
            batch.cq_count, batch.cq_order, batch.base_other,
            batch.floor_ratio, batch.floor_any, batch.weight,
            batch.lendable)


def strategy_flags(fs_strategies: list) -> tuple:
    """Static (strat0_is_s2a, has_retry, strat1_is_s2a) for the jit."""
    s0 = fs_strategies[0] is cpu_preempt._strategy_s2a
    has_retry = len(fs_strategies) > 1
    s1 = has_retry and fs_strategies[1] is cpu_preempt._strategy_s2a
    return (bool(s0), bool(has_retry), bool(s1))


def solve_fair_impl(topo, usage, cohort_usage, gq, gf, gr, gc, chain_local,
                    requests, frs_np, cand_idx, cand_ql, cand_usage_table,
                    cand_prio_table, threshold_active, threshold, has_cohort,
                    cand_rank, cq_count, cq_order, base_other, floor_ratio,
                    floor_any, weight, lendable, strat: tuple):
    """Batched fairPreemptions. Returns (targets [B,K] bool,
    feasible [B] bool, reasons [B,K] int8, stats [B,4] int32 —
    (candidate pool, heap pops, fill-back iterations, filled back)).

    The DRF-heap loop runs as a while_loop with the per-CQ share vector
    maintained INCREMENTALLY (one masked max-ratio row reduction per
    pop — SURVEY.md §7's "trivially vectorizable" observation — instead
    of a full [QL,RF,RF] shares() recompute per candidate) and exits as
    soon as the preemptor fits or the heap drains, so a fair cycle pays
    for the pops it performs, not the padded candidate axis."""
    import jax
    import jax.numpy as jnp

    strat0_s2a, has_retry, strat1_s2a = strat

    def one(gq_b, gf_b, gr_b, gc_b, chain_local_b, req_b, frs_np_b,
            cand_q_b, cand_usage_b, cand_prio_b, th_act, th, has_cohort_b,
            rank_b, count_b, order_b, base_b, floor_b, floor_any_b,
            weight_b, lendable_b):
        sim = make_problem_sim(topo, usage, cohort_usage, gq_b, gf_b, gr_b,
                               gc_b, chain_local_b, req_b, has_cohort_b)
        QL, RF = sim["QL"], sim["RF"]
        nominal = sim["nominal"]
        u0, cu0 = sim["u0"], sim["cu0"]
        chain_oh = sim["chain_oh"]
        fits = sim["fits"]
        remove_usage = sim["remove_usage"]
        add_usage = sim["add_usage"]

        valid_fr = gf_b >= 0
        # same-resource incidence between slots (for per-resource sums)
        same_res = (gr_b[:, None] == gr_b[None, :]) \
            & valid_fr[:, None] & valid_fr[None, :]       # [RF,RF]
        arange_ql = jnp.arange(QL)
        valid_q = gq_b >= 0

        def share_of_rows(u_rows, nom_rows, base_rows, floor_rows,
                          floor_any_rows, weight_rows):
            """dominantResourceShare over a leading rows axis
            (clusterqueue.go:503-564): the masked max-ratio reduction
            per [RF] usage row. THE one copy of the share math — the
            full-vector ``shares`` and the single-row ``share_of_row``
            below are its [QL] and K=1 instances, so the two can never
            diverge (ROADMAP carried thread; per-resource sums stay
            masked reductions, NOT a matmul — XLA's x64 rewrite can't
            lower an s64 dot_general on TPU)."""
            borrow_fr = jnp.where(valid_fr[None, :],
                                  jnp.maximum(0, u_rows - nom_rows), 0)
            borrow_res = jnp.sum(
                jnp.where(same_res[None, :, :], borrow_fr[:, None, :], 0),
                axis=2) + base_rows
            ratio = jnp.where((borrow_res > 0) & (lendable_b[None, :] > 0),
                              borrow_res * 1000
                              // jnp.maximum(lendable_b[None, :], 1),
                              jnp.int64(-1))
            drs = jnp.maximum(jnp.max(ratio, axis=1), floor_rows)
            any_b = jnp.any(borrow_res > 0, axis=1) | floor_any_rows
            share = jnp.where(any_b, drs * 1000
                              // jnp.maximum(weight_rows, 1), 0)
            return jnp.where(weight_rows == 0, MAXSHARE, share)

        def shares(u):
            """dominantResourceShare per local CQ. u: [QL,RF]."""
            return share_of_rows(u, nominal, base_b, floor_b,
                                 floor_any_b, weight_b)

        def share_of_row(u_row, nom_row, base_row, floor_q, floor_any_q,
                         weight_q):
            """One CQ's dominantResourceShare. Removals only move the
            popped CQ's row, so the heap loop updates ONE row's share
            per step instead of recomputing the whole [QL] vector —
            the K=1 instance of share_of_rows (bit-identical: same
            integer ops, reduced over a length-1 leading axis)."""
            return share_of_rows(
                u_row[None, :], nom_row[None, :], base_row[None, :],
                floor_q[None], floor_any_q[None], weight_q[None])[0]

        req_row = jnp.where(arange_ql[:, None] == 0, req_b[None, :], 0)

        def nominated_share(u):
            """share the preemptor's CQ would have WITH its requests
            (dominant_resource_share_with, m=1)."""
            return shares(u + req_row)[0]

        K = cand_q_b.shape[0]
        arange_k = jnp.arange(K)

        def pick_cq(sh, elig):
            """Max-share CQ; ties -> earliest first candidate in
            candidatesOrdering (the determinized heap order)."""
            m = jnp.max(jnp.where(elig, sh, -MAXSHARE))
            tie = jnp.where(elig & (sh == m), order_b, 2**30)
            return jnp.argmin(tie).astype(jnp.int32), jnp.any(elig)

        # --- main DRF-heap loop: one candidate per iteration, with the
        # share vector carried incrementally and an EARLY EXIT once the
        # preemptor fits or the heap drains — a fair-heavy cycle pays
        # for the candidates it actually pops, not the padded K ---
        def fwd_cond(carry):
            (_u, _cu, pos, active, _r, _t, _re, _s, done, _sh, _nom,
             t) = carry
            return (~done) & jnp.any(active & valid_q & (pos < count_b)) \
                & (t < K)

        def fwd_body(carry):
            (u, cu, pos, active, retry, targets, reason, step_of, done,
             sh, nom_share, t) = carry
            # a CQ with no candidates left can never be popped (the CPU
            # heap only ever holds CQs with candidates)
            qstar, any_elig = pick_cq(sh, active & valid_q
                                      & (pos < count_b))
            any_elig &= ~done
            q_oh = arange_ql == qstar                      # [QL]
            pos_q = jnp.sum(jnp.where(q_oh, pos, 0))
            k_oh = (cand_q_b == qstar) & (rank_b == pos_q)  # [K]
            k_valid = jnp.any(k_oh) & any_elig
            cand_u = jnp.sum(jnp.where(k_oh[:, None], cand_usage_b, 0),
                             axis=0)                       # [RF]
            cand_p = jnp.sum(jnp.where(k_oh, cand_prio_b, 0))
            own = qstar == 0

            def row(m):
                return jnp.sum(jnp.where(q_oh[:, None], m, 0), axis=0)

            u_q = row(u)
            nom_q_row = row(nominal)
            new_cand_share = share_of_row(
                u_q - cand_u, nom_q_row, row(base_b),
                jnp.sum(jnp.where(q_oh, floor_b, 0)),
                jnp.any(q_oh & floor_any_b),
                jnp.sum(jnp.where(q_oh, weight_b, 0)))
            old_share = jnp.sum(jnp.where(q_oh, sh, 0))
            if strat0_s2a:   # LessThanOrEqualToFinalShare (S2-a)
                strat_ok = nom_share <= new_cand_share
            else:            # LessThanInitialShare (S2-b)
                strat_ok = nom_share < old_share
            below = th_act & (cand_p < th)
            passed = own | below | strat_ok
            do = k_valid & passed

            q_chain_oh = jnp.any(q_oh[:, None, None] & chain_oh, axis=0)
            u, cu = remove_usage(u, cu, q_oh, q_chain_oh,
                                 jnp.where(do, cand_u, 0))
            # incremental share maintenance: only the popped CQ's row
            # moved (new_cand_share IS its post-removal share), and the
            # nominated share only moves on an own-CQ removal
            sh = jnp.where(q_oh & do, new_cand_share, sh)
            nom_share = jnp.where(
                own & do,
                share_of_row(row(u) + req_b, nominal[0], base_b[0],
                             floor_b[0], floor_any_b[0], weight_b[0]),
                nom_share)
            targets = targets | (k_oh & do)
            # reason: own -> InClusterQueue; strategy -> FairSharing;
            # below-threshold only -> ReclaimWhileBorrowing
            code = jnp.where(own, jnp.int8(0),
                             jnp.where(strat_ok, jnp.int8(1), jnp.int8(2)))
            reason = jnp.where(k_oh & do, code, reason)
            step_of = jnp.where(k_oh & do, t, step_of)
            retry = retry | (k_oh & k_valid & ~passed)
            pos = pos + jnp.where(q_oh & k_valid, 1, 0)
            exhausted_q = jnp.sum(jnp.where(q_oh, pos - count_b, 0)) >= 0
            u_q = jnp.sum(jnp.where(q_oh[:, None], u, 0), axis=0)
            nom_q = jnp.sum(jnp.where(q_oh[:, None], nominal, 0), axis=0)
            borrowing_q = jnp.any(frs_np_b & (u_q > nom_q))
            keep = jnp.where(own, ~exhausted_q,
                             jnp.where(do, ~exhausted_q & borrowing_q,
                                       ~exhausted_q))
            active = jnp.where(q_oh & k_valid, keep, active)
            done = done | (do & fits(u, cu, True))
            return (u, cu, pos, active, retry, targets, reason, step_of,
                    done, sh, nom_share, t + 1)

        init = (u0, cu0, jnp.zeros(QL, jnp.int32),
                jnp.ones(QL, bool), jnp.zeros(K, bool), jnp.zeros(K, bool),
                jnp.zeros(K, jnp.int8), jnp.full(K, -1, jnp.int32),
                jnp.zeros((), bool), shares(u0), nominated_share(u0),
                jnp.int32(0))
        (u, cu, pos, active, retry, targets, reason, step_of, done,
         _sh, _nom, pops) = jax.lax.while_loop(fwd_cond, fwd_body, init)

        # --- retry pass: second strategy, first retry candidate per CQ,
        # shares fixed at pass entry (preemption.go:412-431) ---
        if has_retry:
            sh_r = shares(u)
            nom_r = nominated_share(u)
            BIGR = jnp.int32(2**30)
            min_rank = jnp.min(
                jnp.where(retry[:, None]
                          & (cand_q_b[:, None] == arange_ql[None, :]),
                          rank_b[:, None], BIGR), axis=0)  # [QL]
            has_retry_q = min_rank < BIGR

            def retry_step(carry, t):
                u, cu, processed, targets, reason, step_of, done = carry
                elig = has_retry_q & ~processed & valid_q
                qstar, any_elig = pick_cq(sh_r, elig)
                any_elig &= ~done
                q_oh = arange_ql == qstar
                k_oh = retry & (cand_q_b == qstar) \
                    & (rank_b == jnp.sum(jnp.where(q_oh, min_rank, 0)))
                if strat1_s2a:
                    strat_ok = nom_r <= 0
                else:
                    strat_ok = nom_r < jnp.sum(jnp.where(q_oh, sh_r, 0))
                do = any_elig & strat_ok & jnp.any(k_oh)
                cand_u = jnp.sum(jnp.where(k_oh[:, None], cand_usage_b, 0),
                                 axis=0)
                q_chain_oh = jnp.any(q_oh[:, None, None] & chain_oh, axis=0)
                u, cu = remove_usage(u, cu, q_oh, q_chain_oh,
                                     jnp.where(do, cand_u, 0))
                targets = targets | (k_oh & do)
                reason = jnp.where(k_oh & do, jnp.int8(1), reason)
                step_of = jnp.where(k_oh & do, K + t, step_of)
                processed = processed | (q_oh & any_elig)
                done = done | (do & fits(u, cu, True))
                return (u, cu, processed, targets, reason, step_of,
                        done), None

            (u, cu, _, targets, reason, step_of, done), _ = jax.lax.scan(
                retry_step, (u, cu, jnp.zeros(QL, bool), targets, reason,
                             step_of, done),
                jnp.arange(QL, dtype=jnp.int32))

        # no fit => no targets (preemption.go:433-436)
        feasible = done
        targets = targets & feasible

        # --- fill-back in reverse REMOVAL order, skipping the fit-maker
        # (fill_back_workloads, preemption.go:445-457). A while_loop over
        # the steps that actually removed something (descending) — the
        # old K+QL-step scan paid for every padded step ---
        last_step = jnp.max(jnp.where(targets, step_of, -1))

        def back_cond(carry):
            _u, _cu, _kept, s, _n = carry
            return s >= 0

        def back_body(carry):
            u, cu, kept, s, n = carry
            k_oh = targets & (step_of == s)
            consider = jnp.any(k_oh) & (s != last_step)
            cand_u = jnp.where(consider,
                               jnp.sum(jnp.where(k_oh[:, None],
                                                 cand_usage_b, 0), axis=0), 0)
            qstar = jnp.sum(jnp.where(k_oh, cand_q_b, 0))
            q_oh = arange_ql == qstar
            q_chain_oh = jnp.any(q_oh[:, None, None] & chain_oh, axis=0)
            u2, cu2 = add_usage(u, cu, q_oh, q_chain_oh, cand_u)
            still = fits(u2, cu2, True)
            keep_back = consider & still
            u = jnp.where(keep_back, u2, u)
            cu = jnp.where(keep_back, cu2, cu)
            kept = kept | (k_oh & keep_back)
            s_next = jnp.max(jnp.where(targets & (step_of < s),
                                       step_of, -1))
            return u, cu, kept, s_next, n + 1

        s0 = last_step
        (_u, _cu, kept, _s, fb_iters) = jax.lax.while_loop(
            back_cond, back_body,
            (u, cu, jnp.zeros(K, bool), s0, jnp.int32(0)))
        targets = targets & ~kept

        stats = jnp.stack([
            jnp.sum(cand_q_b >= 0).astype(jnp.int32),
            pops, fb_iters,
            jnp.sum(kept).astype(jnp.int32)])
        return targets, feasible, reason, stats

    cand_q = cand_ql.astype(jnp.int32)
    cand_usage = cand_usage_table[cand_idx]
    cand_prio = cand_prio_table[cand_idx]
    return jax.vmap(one)(gq, gf, gr, gc, chain_local, requests, frs_np,
                         cand_q, cand_usage, cand_prio, threshold_active,
                         threshold, has_cohort, cand_rank, cq_count,
                         cq_order, base_other, floor_ratio, floor_any,
                         weight, lendable)


def decode_fair_targets(batch: FairBatch, targets_mask: np.ndarray,
                        feasible: np.ndarray, reasons: np.ndarray,
                        snapshot, wl_cq_by_entry: dict) -> dict:
    """entry_idx -> list[Target] (one fair problem per entry)."""
    out: dict = {}
    for bi, p in enumerate(batch.problems):
        ei = p.entry_idx
        if not feasible[bi]:
            out.setdefault(ei, [])
            continue
        targets = []
        k = p.num_candidates
        hit = np.flatnonzero(targets_mask[bi, :k])
        for ki in hit.tolist():
            cand = p.domain.infos[p.sel[ki]]
            targets.append(cpu_preempt.Target(
                cand, _REASONS[int(reasons[bi, ki])]))
        out[ei] = targets
    return out
