"""The TPU-native batched admission solver.

The reference's hot loop (pkg/scheduler/scheduler.go:197-353 calling
pkg/scheduler/flavorassigner + pkg/scheduler/preemption over the
pkg/cache snapshot) is an O(heads × flavors × resources × candidates)
sequential computation in Go. Here it is recast as one batched tensor
program, jit-compiled with JAX and executed on TPU:

- encode.py: snapshot -> padded tensor layout (the snapshot IS the wire
  format)
- kernel.py: the jitted solve — vectorized flavor assignment (phase A)
  + a lax.scan admit loop with intra-cycle accounting (phase B) that
  replicates the reference's sequential admit semantics exactly
- service.py: plugging the solver into the Scheduler as the admission
  path, with the CPU scheduler as the conformance oracle and fallback
"""

from kueue_tpu.solver.service import BatchSolver  # noqa: F401
