"""The jitted batched admission solve.

Replaces the reference's per-entry sequential loop
(pkg/scheduler/scheduler.go:234-335 + flavorassigner.go:406-537) with:

- Phase A (vectorized over all W workloads at once): flavor assignment —
  per (workload, podset, resource-group) pick the first flavor in the
  CQ's order that fits under the snapshot usage, honoring eligibility
  (taints/affinity, host-precomputed), borrowing limits and the
  whenCanBorrow=TryNextFlavor policy. Pod sets accumulate usage within a
  workload exactly like the reference's assignment.Usage.
- Phase B (lax.scan over the borrow->priority->FIFO order): the
  sequential admit loop with intra-cycle accounting — each step re-checks
  the chosen assignment against running usage and adds it (with cohort
  bubbling past guaranteed quota) only if it still fits. This replicates
  the reference's order-dependent semantics bit-for-bit for fit-mode
  entries while keeping all arithmetic on-device.

All quantities are int64 (memory is tracked in bytes). Preemption-mode
entries are resolved by the CPU path (kueue_tpu.scheduler.preemption)
after fit-mode entries are accounted; see solver/service.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

# Quantities are canonical integers (memory in bytes exceeds int32).
jax.config.update("jax_enable_x64", True)

# Plain int (not a jnp scalar): creating device values at import time would
# initialize the backend before callers can configure platforms.
NO_LIMIT = 2**62


def _available(nominal, borrow_limit, guaranteed, usage, cohort_subtree,
               cohort_usage, cq_cohort):
    """available[Q,F,R] (reference: resource_node.go:89-104, flattened to
    the CQ->cohort two-level tree the snapshot uses)."""
    no_cohort_avail = nominal - usage
    local = jnp.maximum(0, guaranteed - usage)
    c_idx = jnp.maximum(cq_cohort, 0)
    parent_avail = (cohort_subtree[c_idx] - cohort_usage[c_idx])
    stored_in_parent = nominal - guaranteed
    used_in_parent = jnp.maximum(0, usage - guaranteed)
    cap = stored_in_parent - used_in_parent + jnp.minimum(borrow_limit, NO_LIMIT // 4)
    parent_capped = jnp.minimum(parent_avail, cap)
    with_cohort = local + parent_capped
    has_cohort = (cq_cohort >= 0)[:, None, None]
    return jnp.where(has_cohort, with_cohort, no_cohort_avail)


def _choose_flavors_one_podset(req_p, eligible_p, wl_cq, usage, asg_usage,
                               avail, topo):
    """Phase A for one podset slot, vectorized over W.

    req_p: [W,R], eligible_p: [W,F], asg_usage: [W,F,R] accumulated from
    earlier podsets of the same workload.
    Returns (chosen_f_r [W,R] int32 (-1 = none), ok [W], borrow [W],
    new asg additions [W,F,R]).
    """
    W, R = req_p.shape
    F = eligible_p.shape[1]
    group_id = topo["group_id"][wl_cq]          # [W,R]
    flavor_group = topo["flavor_group"][wl_cq]  # [W,F]
    flavor_rank = topo["flavor_rank"][wl_cq]    # [W,F]
    nominal = topo["nominal"][wl_cq]            # [W,F,R]
    offered = topo["offered"][wl_cq]            # [W,F,R]
    avail_w = avail[wl_cq]                      # [W,F,R]
    usage_w = usage[wl_cq]                      # [W,F,R]
    prefer_no_borrow = topo["prefer_no_borrow"][wl_cq]  # [W]

    has_req = req_p > 0                          # [W,R]
    # relevant[w,f,r]: flavor f's group covers resource r and r is requested
    relevant = (group_id[:, None, :] == flavor_group[:, :, None]) & \
               (flavor_group[:, :, None] >= 0) & has_req[:, None, :]
    val = req_p[:, None, :] + asg_usage          # [W,F,R] incl. earlier podsets
    fits_r = offered & (val <= avail_w)
    borrow_r = (usage_w + val) > nominal         # needs borrowing on r

    fit_f = jnp.all(~relevant | fits_r, axis=2) & jnp.any(relevant, axis=2)  # [W,F]
    fit_f &= eligible_p
    borrow_f = jnp.any(relevant & borrow_r, axis=2)                           # [W,F]

    # Per group: first fitting flavor by rank; TryNextFlavor prefers a
    # no-borrow fit anywhere in the list over an earlier borrow fit
    # (reference: shouldTryNextFlavor, flavorassigner.go:519-537).
    INF = jnp.int32(10**6)
    rank_fit = jnp.where(fit_f, flavor_rank, INF)                  # [W,F]
    rank_fit_nb = jnp.where(fit_f & ~borrow_f, flavor_rank, INF)   # [W,F]

    # For each resource r, its group's candidate flavors are those with
    # flavor_group == group_id[r]; reduce over F per (w, r).
    same_group = (flavor_group[:, :, None] == group_id[:, None, :]) & \
                 (group_id[:, None, :] >= 0)                        # [W,F,R]
    rank_fit_r = jnp.where(same_group, rank_fit[:, :, None], INF)
    rank_fit_nb_r = jnp.where(same_group, rank_fit_nb[:, :, None], INF)
    best_rank = jnp.min(rank_fit_r, axis=1)        # [W,R]
    best_rank_nb = jnp.min(rank_fit_nb_r, axis=1)  # [W,R]
    use_nb = prefer_no_borrow[:, None] & (best_rank_nb < INF)
    target_rank = jnp.where(use_nb, best_rank_nb, best_rank)  # [W,R]

    cand = same_group & (flavor_rank[:, :, None] == target_rank[:, None, :]) & \
           fit_f[:, :, None]
    chosen_f_r = jnp.where((target_rank < INF) & has_req,
                           jnp.argmax(cand, axis=1).astype(jnp.int32), -1)  # [W,R]

    ok = jnp.all(~has_req | (chosen_f_r >= 0), axis=1)  # [W]
    one_hot = jax.nn.one_hot(jnp.maximum(chosen_f_r, 0), fit_f.shape[1],
                             axis=1, dtype=jnp.int64)   # [W,F,R]
    additions = one_hot * jnp.where(chosen_f_r >= 0, req_p, 0)[:, None, :]
    chosen_borrow = jnp.take_along_axis(
        borrow_f, jnp.maximum(chosen_f_r, 0), axis=1) & (chosen_f_r >= 0)
    borrow = jnp.any(chosen_borrow, axis=1)
    return chosen_f_r, ok, borrow, additions


def solve_cycle_impl(topo, usage, cohort_usage, requests, podset_active, wl_cq,
                     priority, timestamp, eligible, solvable, num_podsets: int):
    """One batched admission cycle.

    Returns dict with admitted[W] bool, chosen[W,P,R] int32 flavor index
    (-1 = none), borrows[W] bool, fit[W] bool, usage'[Q,F,R],
    cohort_usage'[C,F,R].
    """
    W, P, R = requests.shape
    F = eligible.shape[2]

    avail = _available(topo["nominal"], topo["borrow_limit"], topo["guaranteed"],
                       usage, topo["cohort_subtree"], cohort_usage,
                       topo["cq_cohort"])

    # --- Phase A: flavor assignment (podsets accumulate within a workload) ---
    asg_usage = jnp.zeros((W, F, R), jnp.int64)
    chosen_all = []
    ok_all = jnp.ones(W, bool)
    borrow_all = jnp.zeros(W, bool)
    for p in range(num_podsets):
        chosen_p, ok_p, borrow_p, additions = _choose_flavors_one_podset(
            requests[:, p, :], eligible[:, p, :], wl_cq, usage, asg_usage,
            avail, topo)
        active = podset_active[:, p]
        chosen_all.append(jnp.where(active[:, None], chosen_p, -1))
        ok_all &= jnp.where(active, ok_p, True)
        borrow_all |= jnp.where(active, borrow_p, False)
        asg_usage += jnp.where(active[:, None, None], additions, 0)
    chosen = jnp.stack(chosen_all, axis=1)  # [W,P,R]
    fit = ok_all & solvable & jnp.any(podset_active, axis=1)

    # --- Phase B: sequential admit with intra-cycle accounting ---
    # Order: non-borrowing first, then priority desc, then FIFO
    # (reference: entryOrdering.Less, scheduler.go:643-672).
    order = jnp.lexsort((timestamp, -priority, borrow_all.astype(jnp.int32),
                         (~fit).astype(jnp.int32)))

    def admit_step(carry, w_idx):
        usage_c, cohort_c, admitted = carry
        q = wl_cq[w_idx]
        c = jnp.maximum(topo["cq_cohort"][q], 0)
        has_cohort = topo["cq_cohort"][q] >= 0
        au = asg_usage[w_idx]  # [F,R]

        # Single-CQ availability (cheaper than re-deriving all of [Q,F,R]):
        nominal_q = topo["nominal"][q]
        guar_q = topo["guaranteed"][q]
        bl_q = topo["borrow_limit"][q]
        local = jnp.maximum(0, guar_q - usage_c[q])
        parent_avail = topo["cohort_subtree"][c] - cohort_c[c]
        cap = (nominal_q - guar_q) - jnp.maximum(0, usage_c[q] - guar_q) + \
            jnp.minimum(bl_q, NO_LIMIT // 4)
        avail_q = jnp.where(has_cohort, local + jnp.minimum(parent_avail, cap),
                            nominal_q - usage_c[q])

        still_fits = jnp.all((au == 0) | (au <= avail_q))
        admit = fit[w_idx] & still_fits

        old_over = jnp.maximum(0, usage_c[q] - guar_q)
        new_usage_q = usage_c[q] + jnp.where(admit, au, 0)
        new_over = jnp.maximum(0, new_usage_q - guar_q)
        usage_c = usage_c.at[q].set(new_usage_q)
        cohort_delta = jnp.where(has_cohort & admit, new_over - old_over, 0)
        cohort_c = cohort_c.at[c].add(cohort_delta)
        admitted = admitted.at[w_idx].set(admit)
        return (usage_c, cohort_c, admitted), None

    init = (usage, cohort_usage, jnp.zeros(W, bool))
    (usage_out, cohort_out, admitted), _ = jax.lax.scan(admit_step, init, order)

    return {"admitted": admitted, "chosen": chosen, "borrows": borrow_all,
            "fit": fit, "usage": usage_out, "cohort_usage": cohort_out}


solve_cycle = partial(jax.jit, static_argnames=("num_podsets",))(solve_cycle_impl)


# ---------------------------------------------------------------------------
# Cohort-parallel admit (v2): the TPU-first Phase B
# ---------------------------------------------------------------------------
#
# The sequential admit loop only needs ordering *within* a conflict domain
# (a cohort, or a standalone CQ): workloads in different domains touch
# disjoint usage state, so their relative order cannot change any decision.
# Reshaping the scan from W steps (2048 on the north-star shape) to
# L = max-workloads-per-domain steps (~8-32) with all domains advancing in
# parallel turns a latency-bound scalar loop into wide vector work — the
# shape TPUs are built for. Decisions are bit-identical to the global
# sequential scan (differentially tested).

def solve_phase_a_impl(topo, usage, cohort_usage, requests, podset_active,
                       wl_cq, eligible, solvable, num_podsets: int):
    """Phase A only: flavor assignment. Returns
    (fit[W], borrows[W], chosen[W,P,R], asg_usage[W,F,R])."""
    W, P, R = requests.shape
    F = eligible.shape[2]
    avail = _available(topo["nominal"], topo["borrow_limit"], topo["guaranteed"],
                       usage, topo["cohort_subtree"], cohort_usage,
                       topo["cq_cohort"])
    asg_usage = jnp.zeros((W, F, R), jnp.int64)
    chosen_all = []
    ok_all = jnp.ones(W, bool)
    borrow_all = jnp.zeros(W, bool)
    for p in range(num_podsets):
        chosen_p, ok_p, borrow_p, additions = _choose_flavors_one_podset(
            requests[:, p, :], eligible[:, p, :], wl_cq, usage, asg_usage,
            avail, topo)
        active = podset_active[:, p]
        chosen_all.append(jnp.where(active[:, None], chosen_p, -1))
        ok_all &= jnp.where(active, ok_p, True)
        borrow_all |= jnp.where(active, borrow_p, False)
        asg_usage += jnp.where(active[:, None, None], additions, 0)
    chosen = jnp.stack(chosen_all, axis=1)
    fit = ok_all & solvable & jnp.any(podset_active, axis=1)
    return fit, borrow_all, chosen, asg_usage


def solve_phase_b_domains_impl(topo, usage, cohort_usage, asg_usage, fit,
                               wl_cq, order_grid):
    """Phase B over an [L,D] order grid: row l holds the l-th workload of
    every conflict domain (-1 = padding). Valid lanes in a row touch
    pairwise-distinct CQs/cohorts, so one vectorized step admits a whole
    row at once; rows advance sequentially, preserving each domain's
    internal borrow->priority->FIFO order."""
    W = fit.shape[0]

    def admit_row(carry, idx_row):
        usage_c, cohort_c, admitted = carry
        valid = idx_row >= 0
        w = jnp.maximum(idx_row, 0)                       # [D]
        q = wl_cq[w]                                      # [D]
        c_raw = topo["cq_cohort"][q]
        c = jnp.maximum(c_raw, 0)
        has_cohort = c_raw >= 0
        au = asg_usage[w]                                 # [D,F,R]

        nominal_q = topo["nominal"][q]
        guar_q = topo["guaranteed"][q]
        bl_q = topo["borrow_limit"][q]
        usage_q = usage_c[q]
        local = jnp.maximum(0, guar_q - usage_q)
        parent_avail = topo["cohort_subtree"][c] - cohort_c[c]
        cap = (nominal_q - guar_q) - jnp.maximum(0, usage_q - guar_q) + \
            jnp.minimum(bl_q, NO_LIMIT // 4)
        avail_q = jnp.where(has_cohort[:, None, None],
                            local + jnp.minimum(parent_avail, cap),
                            nominal_q - usage_q)

        still_fits = jnp.all((au == 0) | (au <= avail_q), axis=(1, 2))
        admit = fit[w] & still_fits & valid               # [D]
        add = jnp.where(admit[:, None, None], au, 0)

        # valid lanes have distinct q/c; padded lanes contribute zeros, so
        # duplicate-index adds are harmless
        new_usage_q = usage_q + add
        old_over = jnp.maximum(0, usage_q - guar_q)
        new_over = jnp.maximum(0, new_usage_q - guar_q)
        usage_c = usage_c.at[q].add(add)
        cohort_delta = jnp.where((has_cohort & admit)[:, None, None],
                                 new_over - old_over, 0)
        cohort_c = cohort_c.at[c].add(cohort_delta)
        # max-scatter: duplicate padded w=0 lanes write 0, never clobber
        admitted = admitted.at[w].max(admit.astype(jnp.int8))
        return (usage_c, cohort_c, admitted), None

    init = (usage, cohort_usage, jnp.zeros(W, jnp.int8))
    (usage_out, cohort_out, admitted), _ = jax.lax.scan(
        admit_row, init, order_grid)
    return admitted.astype(bool), usage_out, cohort_out


solve_phase_a = partial(jax.jit, static_argnames=("num_podsets",))(solve_phase_a_impl)
solve_phase_b_domains = jax.jit(solve_phase_b_domains_impl)


def build_order_grid(fit, borrows, priority, timestamp, wl_cq, cq_cohort,
                     num_cohorts: int):
    """Host-side: global admit order -> [L,D] grid of workload indices.

    Domain = cohort, or a synthetic per-CQ domain for cohortless CQs.
    Within each domain, workloads keep their global-order rank; rows pad
    with -1. numpy only (runs between the two device calls)."""
    import numpy as np
    fit = np.asarray(fit)
    borrows = np.asarray(borrows)
    priority = np.asarray(priority)
    timestamp = np.asarray(timestamp)
    wl_cq = np.asarray(wl_cq)
    cq_cohort = np.asarray(cq_cohort)

    order = np.lexsort((timestamp, -priority, borrows.astype(np.int32),
                        (~fit).astype(np.int32)))
    order = order[fit[order]]  # non-fit entries can never admit
    cohort_of_wl = cq_cohort[wl_cq]
    # static domain space: all cohorts + one synthetic domain per CQ
    # (stable D across cycles -> no jit recompilation)
    domain = np.where(cohort_of_wl >= 0, cohort_of_wl,
                      num_cohorts + wl_cq).astype(np.int64)
    D = num_cohorts + len(cq_cohort)
    # rank of each workload within its domain, in global order
    ranks = np.empty(len(order), np.int64)
    counters = np.zeros(D, np.int64)
    dom_of_sorted = domain[order]
    for pos, d in enumerate(dom_of_sorted):
        ranks[pos] = counters[d]
        counters[d] += 1
    # bucket L to a power of two so repeated cycles reuse the compilation
    raw_l = max(1, int(counters.max()))
    L = 8
    while L < raw_l:
        L *= 2
    grid = np.full((L, D), -1, np.int32)
    grid[ranks, dom_of_sorted] = order.astype(np.int32)
    return grid


def solve_cycle_cohort_parallel(topo_dev, topo_np, usage, cohort_usage,
                                requests, podset_active, wl_cq, priority,
                                timestamp, eligible, solvable,
                                num_podsets: int):
    """The production single-chip path: Phase A on device, order grid on
    host, cohort-parallel Phase B on device. Same outputs as solve_cycle."""
    import numpy as np
    fit, borrows, chosen, asg_usage = solve_phase_a(
        topo_dev, usage, cohort_usage, requests, podset_active, wl_cq,
        eligible, solvable, num_podsets=num_podsets)
    grid = build_order_grid(fit, borrows, priority, timestamp,
                            np.asarray(wl_cq), topo_np.cq_cohort,
                            topo_np.cohort_subtree.shape[0])
    admitted, usage_out, cohort_out = solve_phase_b_domains(
        topo_dev, usage, cohort_usage, asg_usage, fit, wl_cq,
        jnp.asarray(grid))
    return {"admitted": admitted, "chosen": chosen, "borrows": borrows,
            "fit": fit, "usage": usage_out, "cohort_usage": cohort_out}


def topo_to_device(topo) -> dict:
    """numpy Topology arrays -> device dict for solve_cycle."""
    return {
        "cq_cohort": jnp.asarray(topo.cq_cohort),
        "nominal": jnp.asarray(topo.nominal),
        "borrow_limit": jnp.asarray(topo.borrow_limit),
        "guaranteed": jnp.asarray(topo.guaranteed),
        "offered": jnp.asarray(topo.offered),
        "group_id": jnp.asarray(topo.group_id),
        "flavor_group": jnp.asarray(topo.flavor_group),
        "flavor_rank": jnp.asarray(topo.flavor_rank),
        "prefer_no_borrow": jnp.asarray(topo.prefer_no_borrow),
        "cohort_subtree": jnp.asarray(topo.cohort_subtree),
    }
