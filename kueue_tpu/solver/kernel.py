"""The jitted batched admission solve.

Replaces the reference's per-entry sequential loop
(pkg/scheduler/scheduler.go:234-335 + flavorassigner.go:406-537) with:

- Phase A (vectorized over all W workloads at once): flavor assignment —
  per (workload, podset, resource-group) pick the first flavor in the
  CQ's order that fits under the snapshot usage, honoring eligibility
  (taints/affinity, host-precomputed), borrowing limits and the
  whenCanBorrow=TryNextFlavor policy. Pod sets accumulate usage within a
  workload exactly like the reference's assignment.Usage.
- Phase B (lax.scan over the borrow->priority->FIFO order): the
  sequential admit loop with intra-cycle accounting — each step re-checks
  the chosen assignment against running usage and adds it (with cohort
  bubbling past guaranteed quota) only if it still fits. This replicates
  the reference's order-dependent semantics bit-for-bit for fit-mode
  entries while keeping all arithmetic on-device.

All quantities are int64 (memory is tracked in bytes). Preemption-mode
entries are resolved by the CPU path (kueue_tpu.scheduler.preemption)
after fit-mode entries are accounted; see solver/service.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

# Quantities are canonical integers (memory in bytes exceeds int32).
jax.config.update("jax_enable_x64", True)

# Plain int (not a jnp scalar): creating device values at import time would
# initialize the backend before callers can configure platforms.
NO_LIMIT = 2**62


def _avail_level(quota, guaranteed, borrow_limit, usage, parent_avail):
    """One level of the availability walk (reference:
    resource_node.go:89-104): guaranteed remainder plus the
    borrow-limit-capped parent availability. `quota` is the node's subtree
    quota (nominal for CQs)."""
    local = jnp.maximum(0, guaranteed - usage)
    cap = (quota - guaranteed) - jnp.maximum(0, usage - guaranteed) + \
        jnp.minimum(borrow_limit, NO_LIMIT // 4)
    return local + jnp.minimum(parent_avail, cap)


def _cohort_avail(topo, cohort_usage):
    """available[C,F,R] for every cohort, walking parent chains top-down
    (reference: resource_node.go:89-104). Roots use subtree - usage; each
    deeper level adds its guaranteed remainder plus the borrow-limit-capped
    parent availability. The depth loop is statically unrolled to the
    tree's max depth (cq_chain.shape[1])."""
    subtree = topo["cohort_subtree"]
    guar = topo["cohort_guaranteed"]
    bl = topo["cohort_borrow_limit"]
    parent = jnp.maximum(topo["cohort_parent"], 0)
    depth = topo["cohort_depth"]
    max_depth = topo["cq_chain"].shape[1]
    avail = subtree - cohort_usage
    for d in range(1, max_depth):
        with_parent = _avail_level(subtree, guar, bl, cohort_usage,
                                   avail[parent])
        avail = jnp.where((depth == d)[:, None, None], with_parent, avail)
    return avail


def _available(nominal, borrow_limit, guaranteed, usage, cohort_avail,
               cq_cohort):
    """available[Q,F,R] (reference: resource_node.go:89-104); the cohort
    side of the walk is precomputed in cohort_avail."""
    no_cohort_avail = nominal - usage
    c_idx = jnp.maximum(cq_cohort, 0)
    with_cohort = _avail_level(nominal, guaranteed, borrow_limit, usage,
                               cohort_avail[c_idx])
    has_cohort = (cq_cohort >= 0)[:, None, None]
    return jnp.where(has_cohort, with_cohort, no_cohort_avail)


def _chain_avail(topo, cohort_c, chain):
    """Availability of the chain's direct cohort (chain[..., 0]) given the
    running cohort usage state. chain: [..., DC] int32, -1 padded past the
    root. Walks top-down: the first valid index from the end is the root."""
    lead_shape = chain.shape[:-1]
    DC = chain.shape[-1]
    F, R = topo["cohort_subtree"].shape[1:]
    avail = jnp.zeros(lead_shape + (F, R), jnp.int64)
    started = jnp.zeros(lead_shape, bool)
    for d in range(DC - 1, -1, -1):
        c = chain[..., d]
        valid = c >= 0
        c_ = jnp.maximum(c, 0)
        cu = cohort_c[c_]
        subtree = topo["cohort_subtree"][c_]
        root_avail = subtree - cu
        child_avail = _avail_level(subtree, topo["cohort_guaranteed"][c_],
                                   topo["cohort_borrow_limit"][c_], cu, avail)
        new_avail = jnp.where(started[..., None, None], child_avail, root_avail)
        avail = jnp.where(valid[..., None, None], new_avail, avail)
        started = started | valid
    return avail


def _chain_add_usage(topo, cohort_c, chain, delta):
    """Bubble a usage delta up the cohort chain (reference:
    resource_node.go:124-131): each level absorbs up to its guaranteed
    quota; the overflow continues to the parent. chain: [..., DC]; delta:
    [..., F, R] (zero where nothing was admitted). Chains updated in one
    call must touch pairwise-disjoint cohort trees when lead dims > 0."""
    DC = chain.shape[-1]
    for d in range(DC):
        c = chain[..., d]
        valid = c >= 0
        c_ = jnp.maximum(c, 0)
        add = jnp.where(valid[..., None, None], delta, 0)
        old_cu = cohort_c[c_]
        new_cu = old_cu + add
        cohort_c = cohort_c.at[c_].add(add)
        guar = topo["cohort_guaranteed"][c_]
        delta = jnp.where(valid[..., None, None],
                          jnp.maximum(0, new_cu - guar)
                          - jnp.maximum(0, old_cu - guar), 0)
    return cohort_c


def _choose_flavors_one_podset(req_p, eligible_p, wl_cq, usage, asg_usage,
                               avail, topo, start_rank_p=None):
    """Phase A for one podset slot, vectorized over W.

    req_p: [W,R], eligible_p: [W,F], asg_usage: [W,F,R] accumulated from
    earlier podsets of the same workload; start_rank_p: [W,R] first
    flavor rank to consider (LastTriedFlavorIdx resume, reference:
    flavorassigner.go:289-324).
    Returns (chosen_f_r [W,R] int32 (-1 = none), ok [W], borrow_r [W,R],
    new asg additions [W,F,R]).
    """
    W, R = req_p.shape
    F = eligible_p.shape[1]
    group_id = topo["group_id"][wl_cq]          # [W,R]
    flavor_group = topo["flavor_group"][wl_cq]  # [W,F]
    flavor_rank = topo["flavor_rank"][wl_cq]    # [W,F]
    nominal = topo["nominal"][wl_cq]            # [W,F,R]
    offered = topo["offered"][wl_cq]            # [W,F,R]
    avail_w = avail[wl_cq]                      # [W,F,R]
    usage_w = usage[wl_cq]                      # [W,F,R]
    prefer_no_borrow = topo["prefer_no_borrow"][wl_cq]  # [W]

    has_req = req_p > 0                          # [W,R]
    # relevant[w,f,r]: flavor f's group covers resource r and r is requested
    relevant = (group_id[:, None, :] == flavor_group[:, :, None]) & \
               (flavor_group[:, :, None] >= 0) & has_req[:, None, :]
    val = req_p[:, None, :] + asg_usage          # [W,F,R] incl. earlier podsets
    fits_r = offered & (val <= avail_w)
    borrow_r = (usage_w + val) > nominal         # needs borrowing on r

    fit_f = jnp.all(~relevant | fits_r, axis=2) & jnp.any(relevant, axis=2)  # [W,F]
    fit_f &= eligible_p
    borrow_f = jnp.any(relevant & borrow_r, axis=2)                           # [W,F]

    # Per group: first fitting flavor by rank; TryNextFlavor prefers a
    # no-borrow fit anywhere in the list over an earlier borrow fit
    # (reference: shouldTryNextFlavor, flavorassigner.go:519-537).
    INF = jnp.int32(10**6)
    rank_fit = jnp.where(fit_f, flavor_rank, INF)                  # [W,F]
    rank_fit_nb = jnp.where(fit_f & ~borrow_f, flavor_rank, INF)   # [W,F]

    # For each resource r, its group's candidate flavors are those with
    # flavor_group == group_id[r]; reduce over F per (w, r). Flavors
    # before the resume rank are excluded (LastTriedFlavorIdx).
    same_group = (flavor_group[:, :, None] == group_id[:, None, :]) & \
                 (group_id[:, None, :] >= 0)                        # [W,F,R]
    if start_rank_p is not None:
        same_group &= flavor_rank[:, :, None] >= start_rank_p[:, None, :]
    rank_fit_r = jnp.where(same_group, rank_fit[:, :, None], INF)
    rank_fit_nb_r = jnp.where(same_group, rank_fit_nb[:, :, None], INF)
    best_rank = jnp.min(rank_fit_r, axis=1)        # [W,R]
    best_rank_nb = jnp.min(rank_fit_nb_r, axis=1)  # [W,R]
    use_nb = prefer_no_borrow[:, None] & (best_rank_nb < INF)
    target_rank = jnp.where(use_nb, best_rank_nb, best_rank)  # [W,R]

    cand = same_group & (flavor_rank[:, :, None] == target_rank[:, None, :]) & \
           fit_f[:, :, None]
    chosen_f_r = jnp.where((target_rank < INF) & has_req,
                           jnp.argmax(cand, axis=1).astype(jnp.int32), -1)  # [W,R]

    ok = jnp.all(~has_req | (chosen_f_r >= 0), axis=1)  # [W]
    one_hot = jax.nn.one_hot(jnp.maximum(chosen_f_r, 0), fit_f.shape[1],
                             axis=1, dtype=jnp.int64)   # [W,F,R]
    additions = one_hot * jnp.where(chosen_f_r >= 0, req_p, 0)[:, None, :]
    chosen_borrow = jnp.take_along_axis(
        borrow_f, jnp.maximum(chosen_f_r, 0), axis=1) & (chosen_f_r >= 0)
    return chosen_f_r, ok, chosen_borrow, additions


def _drf_share(topo, usage, asg_usage, wl_cq):
    """Dominant resource share per workload, computed against the
    pre-cycle usage exactly like the CPU nominate step (reference:
    dominantResourceShare, clusterqueue.go:529-564 with m=1): the maximum
    over resources of (usage above remaining nominal quota / the root
    tree's lendable), scaled by 1000 and divided by the fair weight."""
    remaining = (topo["nominal"] - usage)[wl_cq]                # [W,F,R]
    offered = topo["offered"][wl_cq]
    b = jnp.where(offered, asg_usage - remaining, 0)
    borrowing = jnp.sum(jnp.maximum(0, b), axis=1)              # [W,R]
    has_borrow = jnp.any(borrowing > 0, axis=1)                 # [W]
    cohort = topo["cq_cohort"][wl_cq]
    root = topo["cohort_root"][jnp.maximum(cohort, 0)]
    lendable = topo["cohort_lendable"][root]                    # [W,R]
    ratio = jnp.where(lendable > 0,
                      borrowing * 1000 // jnp.maximum(lendable, 1),
                      jnp.int64(-1))
    drs = jnp.max(ratio, axis=1)                                # [W] >= -1
    weight = topo["fair_weight"][wl_cq]
    dws = jnp.where(weight > 0, drs * 1000 // jnp.maximum(weight, 1),
                    jnp.int64(NO_LIMIT))
    return jnp.where(has_borrow & (cohort >= 0), dws, 0)


def _phase_a(topo, usage, cohort_avail, requests, podset_active, wl_cq,
             eligible, solvable, num_podsets: int, start_rank=None):
    """Flavor assignment over all podsets (usage accumulates within a
    workload). Returns (fit[W], borrows[W], chosen[W,P,R],
    chosen_borrow[W,P,R], asg_usage[W,F,R])."""
    W, P, R = requests.shape
    F = eligible.shape[2]
    avail = _available(topo["nominal"], topo["borrow_limit"], topo["guaranteed"],
                       usage, cohort_avail, topo["cq_cohort"])
    asg_usage = jnp.zeros((W, F, R), jnp.int64)
    chosen_all, borrow_all_r = [], []
    ok_all = jnp.ones(W, bool)
    for p in range(num_podsets):
        chosen_p, ok_p, borrow_p, additions = _choose_flavors_one_podset(
            requests[:, p, :], eligible[:, p, :], wl_cq, usage, asg_usage,
            avail, topo,
            start_rank[:, p, :] if start_rank is not None else None)
        active = podset_active[:, p]
        chosen_all.append(jnp.where(active[:, None], chosen_p, -1))
        ok_all &= jnp.where(active, ok_p, True)
        borrow_all_r.append(jnp.where(active[:, None], borrow_p, False))
        asg_usage += jnp.where(active[:, None, None], additions, 0)
    chosen = jnp.stack(chosen_all, axis=1)        # [W,P,R]
    chosen_borrow = jnp.stack(borrow_all_r, axis=1)  # [W,P,R]
    borrows = jnp.any(chosen_borrow, axis=(1, 2))
    fit = ok_all & solvable & jnp.any(podset_active, axis=1)
    return fit, borrows, chosen, chosen_borrow, asg_usage


def solve_cycle_impl(topo, usage, cohort_usage, requests, podset_active, wl_cq,
                     priority, timestamp, eligible, solvable, num_podsets: int,
                     fair_sharing: bool = False, start_rank=None):
    """One batched admission cycle.

    Returns dict with admitted[W] bool, chosen[W,P,R] int32 flavor index
    (-1 = none), borrows[W] bool, chosen_borrow[W,P,R] bool, fit[W] bool,
    usage'[Q,F,R], cohort_usage'[C,F,R].
    """
    W, P, R = requests.shape

    cohort_avail = _cohort_avail(topo, cohort_usage)
    fit, borrow_all, chosen, chosen_borrow, asg_usage = _phase_a(
        topo, usage, cohort_avail, requests, podset_active, wl_cq, eligible,
        solvable, num_podsets, start_rank)

    # --- Phase B: sequential admit with intra-cycle accounting ---
    # Order: non-borrowing first, then DRF share (fair sharing), then
    # priority desc, then FIFO (reference: entryOrdering.Less,
    # scheduler.go:643-672).
    share = (_drf_share(topo, usage, asg_usage, wl_cq) if fair_sharing
             else jnp.zeros(W, jnp.int64))
    order = jnp.lexsort((timestamp, -priority, share,
                         borrow_all.astype(jnp.int32),
                         (~fit).astype(jnp.int32)))

    def admit_step(carry, w_idx):
        usage_c, cohort_c, admitted = carry
        q = wl_cq[w_idx]
        chain = topo["cq_chain"][q]  # [DC]
        has_cohort = topo["cq_cohort"][q] >= 0
        au = asg_usage[w_idx]  # [F,R]

        # Single-CQ availability (cheaper than re-deriving all of [Q,F,R]):
        nominal_q = topo["nominal"][q]
        guar_q = topo["guaranteed"][q]
        parent_avail = _chain_avail(topo, cohort_c, chain)
        avail_q = jnp.where(has_cohort,
                            _avail_level(nominal_q, guar_q,
                                         topo["borrow_limit"][q],
                                         usage_c[q], parent_avail),
                            nominal_q - usage_c[q])

        still_fits = jnp.all((au == 0) | (au <= avail_q))
        admit = fit[w_idx] & still_fits

        old_over = jnp.maximum(0, usage_c[q] - guar_q)
        new_usage_q = usage_c[q] + jnp.where(admit, au, 0)
        new_over = jnp.maximum(0, new_usage_q - guar_q)
        usage_c = usage_c.at[q].set(new_usage_q)
        cohort_delta = jnp.where(has_cohort & admit, new_over - old_over, 0)
        cohort_c = _chain_add_usage(topo, cohort_c, chain, cohort_delta)
        admitted = admitted.at[w_idx].set(admit)
        return (usage_c, cohort_c, admitted), None

    init = (usage, cohort_usage, jnp.zeros(W, bool))
    (usage_out, cohort_out, admitted), _ = jax.lax.scan(admit_step, init, order)

    return {"admitted": admitted, "chosen": chosen, "borrows": borrow_all,
            "chosen_borrow": chosen_borrow, "fit": fit, "usage": usage_out,
            "cohort_usage": cohort_out}


solve_cycle = partial(jax.jit, static_argnames=("num_podsets", "fair_sharing"))(
    solve_cycle_impl)


# ---------------------------------------------------------------------------
# Decision-only fetch: on-device compaction of the per-cycle outputs
# ---------------------------------------------------------------------------
#
# The staged fetch shipped five dense arrays per cycle — admitted/fit/
# borrows [W] bool plus chosen [W,P,R] int32 and chosen_borrow [W,P,R]
# bool — ~(3 + 5*P*R) bytes per batch row, even though decode only needs
# a flavor index (< F) and a handful of bits per row. The fused programs
# can instead compact the decisions ON DEVICE into the wire format below
# and the fetch ships only that (>5x smaller at every P*R):
#
# - dec_pr   uint8 [W, P*R]: (chosen + 1) | (chosen_borrow << 7) per
#   (podset, resource) lane — 0 means "no flavor" (chosen == -1), so the
#   format holds any F <= MAX_COMPACT_FLAVORS. Static shape: the batch
#   width is already bucketed, so the ladder warms one program per
#   bucket exactly like the dense variants.
# - dec_bits uint8 [3, ceil(W/8)]: the fit / admitted / borrows rows as
#   little-endian bit planes.
#
# Host-side unpack (service.unpack_decisions) restores the exact dense
# arrays, so decode and the output-invariant validation are bit-identical
# to the staged path (pinned by tests/test_transport.py).

# chosen + 1 must fit in 7 bits (bit 7 carries chosen_borrow)
MAX_COMPACT_FLAVORS = 126

# the packed decision keys, in fetch order (service imports this so the
# dispatch keys and the unpacker can never drift)
DECISION_KEYS = ("dec_pr", "dec_bits")


def dense_decision_nbytes(W: int, P: int, R: int) -> int:
    """Bytes the STAGED decision fetch ships for a [W] batch:
    admitted/fit/borrows [W] bool + chosen [W,P,R] int32 +
    chosen_borrow [W,P,R] bool. The one definition of the dense
    equivalent the >5x transport gates (bench transport_bytes row,
    tests/test_transport.py) measure the compact wire format against —
    if the staged key set ever changes, this is the only place the
    ratio's denominator lives."""
    return 3 * W + 5 * W * P * R


def _pack_bits(rows):
    """[N, W] bool -> [N, ceil(W/8)] uint8, little-endian within each
    byte (numpy.unpackbits(bitorder="little") inverts it exactly)."""
    N, W = rows.shape
    pad = (-W) % 8
    if pad:
        rows = jnp.concatenate(
            [rows, jnp.zeros((N, pad), bool)], axis=1)
    grouped = rows.reshape(N, -1, 8).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
    return jnp.sum(grouped * weights, axis=2, dtype=jnp.uint8)


def pack_decisions_impl(out: dict) -> dict:
    """Replace the five dense decision arrays in a solve output dict
    with the compact wire format (docstring above). Non-decision
    entries (usage/cohort_usage residency chain, preemption targets and
    stats) pass through untouched."""
    chosen = out["chosen"]                     # [W,P,R] int32
    cb = out["chosen_borrow"]                  # [W,P,R] bool
    W = chosen.shape[0]
    pr = (chosen + 1).astype(jnp.uint8).reshape(W, -1)
    pr = pr | (cb.reshape(W, -1).astype(jnp.uint8) << 7)
    bits = _pack_bits(jnp.stack([out["fit"], out["admitted"],
                                 out["borrows"]]))
    packed = {k: v for k, v in out.items()
              if k not in ("admitted", "fit", "borrows", "chosen",
                           "chosen_borrow")}
    packed["dec_pr"] = pr
    packed["dec_bits"] = bits
    return packed


# ---------------------------------------------------------------------------
# Cohort-parallel admit (v2): the TPU-first Phase B
# ---------------------------------------------------------------------------
#
# The sequential admit loop only needs ordering *within* a conflict domain
# (a cohort, or a standalone CQ): workloads in different domains touch
# disjoint usage state, so their relative order cannot change any decision.
# Reshaping the scan from W steps (2048 on the north-star shape) to
# L = max-workloads-per-domain steps (~8-32) with all domains advancing in
# parallel turns a latency-bound scalar loop into wide vector work — the
# shape TPUs are built for. Decisions are bit-identical to the global
# sequential scan (differentially tested).

def solve_phase_a_impl(topo, usage, cohort_usage, requests, podset_active,
                       wl_cq, eligible, solvable, num_podsets: int,
                       fair_sharing: bool = False, start_rank=None):
    """Phase A only: flavor assignment. Returns (fit[W], borrows[W],
    chosen[W,P,R], chosen_borrow[W,P,R], asg_usage[W,F,R], share[W])."""
    W = requests.shape[0]
    cohort_avail = _cohort_avail(topo, cohort_usage)
    fit, borrows, chosen, chosen_borrow, asg_usage = _phase_a(
        topo, usage, cohort_avail, requests, podset_active, wl_cq, eligible,
        solvable, num_podsets, start_rank)
    share = (_drf_share(topo, usage, asg_usage, wl_cq) if fair_sharing
             else jnp.zeros(W, jnp.int64))
    return fit, borrows, chosen, chosen_borrow, asg_usage, share


def solve_phase_b_domains_impl(topo, usage, cohort_usage, asg_usage, fit,
                               wl_cq, order_grid):
    """Phase B over an [L,D] order grid: row l holds the l-th workload of
    every conflict domain (-1 = padding). Valid lanes in a row touch
    pairwise-distinct CQs/cohorts, so one vectorized step admits a whole
    row at once; rows advance sequentially, preserving each domain's
    internal borrow->priority->FIFO order."""
    W = fit.shape[0]

    def admit_row(carry, idx_row):
        usage_c, cohort_c, admitted = carry
        valid = idx_row >= 0
        w = jnp.maximum(idx_row, 0)                       # [D]
        q = wl_cq[w]                                      # [D]
        chain = topo["cq_chain"][q]                       # [D,DC]
        has_cohort = topo["cq_cohort"][q] >= 0
        au = asg_usage[w]                                 # [D,F,R]

        nominal_q = topo["nominal"][q]
        guar_q = topo["guaranteed"][q]
        usage_q = usage_c[q]
        parent_avail = _chain_avail(topo, cohort_c, chain)
        avail_q = jnp.where(has_cohort[:, None, None],
                            _avail_level(nominal_q, guar_q,
                                         topo["borrow_limit"][q],
                                         usage_q, parent_avail),
                            nominal_q - usage_q)

        still_fits = jnp.all((au == 0) | (au <= avail_q), axis=(1, 2))
        admit = fit[w] & still_fits & valid               # [D]
        add = jnp.where(admit[:, None, None], au, 0)

        # valid lanes have distinct CQs/cohort trees; padded lanes
        # contribute zeros, so duplicate-index adds are harmless
        new_usage_q = usage_q + add
        old_over = jnp.maximum(0, usage_q - guar_q)
        new_over = jnp.maximum(0, new_usage_q - guar_q)
        usage_c = usage_c.at[q].add(add)
        cohort_delta = jnp.where((has_cohort & admit)[:, None, None],
                                 new_over - old_over, 0)
        cohort_c = _chain_add_usage(topo, cohort_c, chain, cohort_delta)
        # max-scatter: duplicate padded w=0 lanes write 0, never clobber
        admitted = admitted.at[w].max(admit.astype(jnp.int8))
        return (usage_c, cohort_c, admitted), None

    init = (usage, cohort_usage, jnp.zeros(W, jnp.int8))
    (usage_out, cohort_out, admitted), _ = jax.lax.scan(
        admit_row, init, order_grid)
    return admitted.astype(bool), usage_out, cohort_out


solve_phase_a = partial(jax.jit, static_argnames=("num_podsets", "fair_sharing"))(
    solve_phase_a_impl)
solve_phase_b_domains = jax.jit(solve_phase_b_domains_impl)


def solve_cycle_fused_impl(topo, usage, cohort_usage, requests, podset_active,
                           wl_cq, priority, timestamp, eligible, solvable,
                           num_podsets: int, max_rank: int,
                           fair_sharing: bool = False, start_rank=None,
                           compact: bool = False, cluster_args=None):
    """The production single-chip path, fully fused: Phase A, the
    domain-rank order grid, and the cohort-parallel Phase B run as ONE
    device program — no host round-trip between phases.

    max_rank (static): upper bound on workloads per conflict domain,
    computed host-side from wl_cq alone (independent of fit results —
    non-fit entries occupy grid slots but never admit)."""
    W = requests.shape[0]
    C = topo["cohort_subtree"].shape[0]

    cohort_avail = _cohort_avail(topo, cohort_usage)
    fit, borrows, chosen, chosen_borrow, asg_usage = _phase_a(
        topo, usage, cohort_avail, requests, podset_active, wl_cq, eligible,
        solvable, num_podsets, start_rank)
    share = (_drf_share(topo, usage, asg_usage, wl_cq) if fair_sharing
             else jnp.zeros(W, jnp.int64))

    # admit order (reference: entryOrdering.Less, scheduler.go:643-672)
    order = jnp.lexsort((timestamp, -priority, share,
                         borrows.astype(jnp.int32),
                         (~fit).astype(jnp.int32)))

    # conflict domain = root cohort, or a synthetic per-CQ domain
    cohort_of = topo["cq_cohort"][wl_cq]
    root_of = topo["cohort_root"][jnp.maximum(cohort_of, 0)]
    domain = jnp.where(cohort_of >= 0, root_of.astype(jnp.int32),
                       C + wl_cq.astype(jnp.int32))          # [W]
    D = C + topo["cq_cohort"].shape[0]

    # rank of each ordered entry within its domain: stable-sort the
    # ordered domains, then position minus segment start
    dom_of_order = domain[order]                              # [W]
    perm = jnp.argsort(dom_of_order, stable=True)
    sorted_dom = dom_of_order[perm]
    pos = jnp.arange(W)
    first = jnp.concatenate([jnp.ones(1, bool),
                             sorted_dom[1:] != sorted_dom[:-1]])
    seg_start = jax.lax.cummax(jnp.where(first, pos, 0))
    rank_sorted = pos - seg_start                             # [W]

    # grid[rank, domain] = workload index (drop ranks beyond the bound —
    # cannot happen when max_rank really bounds the per-domain counts)
    grid = jnp.full((max_rank, D), -1, jnp.int32)
    grid = grid.at[rank_sorted, sorted_dom].set(
        order[perm].astype(jnp.int32), mode="drop")

    admitted, usage_out, cohort_out = solve_phase_b_domains_impl(
        topo, usage, cohort_usage, asg_usage, fit, wl_cq, grid)
    out = {"admitted": admitted, "chosen": chosen, "borrows": borrows,
           "chosen_borrow": chosen_borrow, "fit": fit, "usage": usage_out,
           "cohort_usage": cohort_out}
    if cluster_args is not None:
        # Remote-cluster capacity columns scored in the SAME program:
        # nomination picks local vs remote in one argmax per ordered
        # workload (see score_cluster_columns_impl).
        out["mk_cluster"] = score_cluster_columns_impl(
            *cluster_args, requests, podset_active, wl_cq, order, admitted)
    return pack_decisions_impl(out) if compact else out


solve_cycle_fused = partial(
    jax.jit, static_argnames=("num_podsets", "max_rank", "fair_sharing",
                              "compact"))(
    solve_cycle_fused_impl)


def solve_cycle_with_preempt_impl(topo, usage, cohort_usage, requests,
                                  podset_active, wl_cq, priority, timestamp,
                                  eligible, solvable, preempt_args: tuple,
                                  num_podsets: int, max_rank: int,
                                  fair_sharing: bool = False,
                                  start_rank=None, fair_preempt_args=None,
                                  fs_strategies: tuple = (),
                                  compact: bool = False, cluster_args=None):
    """Mixed admission + preemption cycle as ONE device program: the fused
    fit solve plus the batched preemption target selection
    (preempt.solve_preempt_impl, and fairpreempt.solve_fair_impl for
    fair-sharing entries) against the same pre-cycle state. Preemption
    simulates against pre-cycle usage exactly like the reference's
    nominate-time GetTargets (scheduler.go:404-441) — it does NOT see
    this cycle's fit admissions, so the sub-programs are independent and
    compile into a single execute: one device sync per cycle, the
    dominant cost over a tunneled TPU link."""
    from kueue_tpu.solver.preempt import solve_preempt_impl
    out = solve_cycle_fused_impl(
        topo, usage, cohort_usage, requests, podset_active, wl_cq, priority,
        timestamp, eligible, solvable, num_podsets=num_podsets,
        max_rank=max_rank, fair_sharing=fair_sharing, start_rank=start_rank,
        cluster_args=cluster_args)
    if preempt_args is not None:
        targets, feasible, pstats = solve_preempt_impl(
            topo, usage, cohort_usage, *preempt_args)
        out["preempt_targets"] = targets
        out["preempt_feasible"] = feasible
        out["preempt_stats"] = pstats
    if fair_preempt_args is not None:
        from kueue_tpu.solver.fairpreempt import solve_fair_impl
        ft, ff, frs, fstats = solve_fair_impl(topo, usage, cohort_usage,
                                              *fair_preempt_args,
                                              strat=fs_strategies)
        out["fair_targets"] = ft
        out["fair_feasible"] = ff
        out["fair_reasons"] = frs
        out["fair_stats"] = fstats
    return pack_decisions_impl(out) if compact else out


solve_cycle_with_preempt = partial(
    jax.jit, static_argnames=("num_podsets", "max_rank", "fair_sharing",
                              "fs_strategies", "compact"))(
    solve_cycle_with_preempt_impl)


# ---------------------------------------------------------------------------
# MultiKueue remote clusters as capacity columns of the solve
# ---------------------------------------------------------------------------
#
# The reference places a multikueue workload by mirroring it to EVERY
# worker cluster and letting the first remote reservation win — a
# sequential per-workload controller loop (multikueuecluster.go:67-307)
# bolted onto the side of the admission cycle. Here remote clusters are
# encoded as extra flavor-capacity columns ([K,F,R], solver/encode.py
# encode_cluster_columns) and scored INSIDE the fused solve: one scan in
# the cycle's admission order picks, per admitted multikueue workload,
# the first cluster column (deterministic sorted-name order) with a
# flavor that fits the workload's total request, with intra-cycle
# accounting — the exact greedy the sequential controller converges to
# on a quiet fleet. The multikueue controller becomes the EXECUTOR of
# these device-made decisions (it mirrors only to the chosen cluster);
# a lost cluster's columns mask to zero capacity on the next snapshot,
# so re-placement falls out of the same scoring.


def score_cluster_columns_impl(ccap, coffer, cactive, mk_cq, requests,
                               podset_active, wl_cq, order, admitted):
    """chosen cluster column per workload ([W] int32, -1 = none/local).

    ccap [K,F,R] int64: remaining available remote capacity;
    coffer [K,F,R] bool: (flavor, resource) offered by the cluster;
    cactive [K] bool: reachable clusters (lost clusters mask False);
    mk_cq [Q] bool: CQ carries a multikueue admission check.

    Placement model: a cluster hosts the workload when ONE flavor
    column covers every requested resource under the remaining
    capacity (single-flavor fit — the remote's own flavor assignment
    refines within that envelope). Chosen capacity is consumed for
    later workloads in the same cycle (running scan state), matching
    the sequential oracle bit-for-bit (encode.place_remote_dicts)."""
    W = requests.shape[0]
    treq = jnp.sum(jnp.where(podset_active[:, :, None], requests, 0),
                   axis=1)                                   # [W,R]
    mk = mk_cq[wl_cq] & admitted                             # [W]

    def step(rem, w):
        req = treq[w]                                        # [R]
        has = req > 0
        covers = (req[None, None, :] <= rem) & coffer        # [K,F,R]
        fit_kf = jnp.all(covers | ~has[None, None, :], axis=2) & \
            jnp.any(coffer & has[None, None, :], axis=2)     # [K,F]
        fit_k = jnp.any(fit_kf, axis=1) & cactive            # [K]
        any_fit = jnp.any(fit_k)
        k = jnp.argmax(fit_k).astype(jnp.int32)              # first fitting
        f = jnp.argmax(fit_kf[k]).astype(jnp.int32)          # first flavor
        place = mk[w] & any_fit
        chosen = jnp.where(place, k, jnp.int32(-1))
        rem = rem.at[k, f].add(-jnp.where(place, req, 0))
        return rem, chosen

    _, chosen_ord = jax.lax.scan(step, ccap, order)
    return jnp.full(W, -1, jnp.int32).at[order].set(chosen_ord)


def max_rank_bound(wl_cq, cq_cohort, cohort_root) -> int:
    """Host-side static bound for solve_cycle_fused: the max number of
    batch workloads sharing one conflict domain, bucketed to a power of
    two for jit-cache stability."""
    import numpy as np
    wl_cq = np.asarray(wl_cq)
    cq_cohort = np.asarray(cq_cohort)
    cohort_of = cq_cohort[wl_cq]
    root_of = np.asarray(cohort_root)[np.maximum(cohort_of, 0)]
    C = len(np.asarray(cohort_root))
    domain = np.where(cohort_of >= 0, root_of, C + wl_cq)
    raw = int(np.bincount(domain).max()) if len(domain) else 1
    b = 8
    while b < raw:
        b *= 4  # powers of four: shape-diversity control (encode._bucket)
    return b


def build_order_grid(fit, borrows, priority, timestamp, wl_cq, cq_cohort,
                     num_cohorts: int, cohort_root=None, share=None):
    """Host-side: global admit order -> [L,D] grid of workload indices.

    Domain = root cohort (the whole tree is one capacity domain for
    hierarchical cohorts), or a synthetic per-CQ domain for cohortless
    CQs. Within each domain, workloads keep their global-order rank; rows
    pad with -1. numpy only (runs between the two device calls)."""
    import numpy as np
    fit = np.asarray(fit)
    borrows = np.asarray(borrows)
    priority = np.asarray(priority)
    timestamp = np.asarray(timestamp)
    wl_cq = np.asarray(wl_cq)
    cq_cohort = np.asarray(cq_cohort)

    share = (np.zeros(len(wl_cq), np.int64) if share is None
             else np.asarray(share))
    order = np.lexsort((timestamp, -priority, share,
                        borrows.astype(np.int32), (~fit).astype(np.int32)))
    order = order[fit[order]]  # non-fit entries can never admit
    cohort_of_wl = cq_cohort[wl_cq]
    if cohort_root is not None:
        cohort_of_wl = np.where(cohort_of_wl >= 0,
                                np.asarray(cohort_root)[np.maximum(cohort_of_wl, 0)],
                                -1)
    # static domain space: all cohorts + one synthetic domain per CQ
    # (stable D across cycles -> no jit recompilation)
    domain = np.where(cohort_of_wl >= 0, cohort_of_wl,
                      num_cohorts + wl_cq).astype(np.int64)
    D = num_cohorts + len(cq_cohort)
    # rank of each workload within its domain, in global order
    # (vectorized: stable-sort by domain, position minus segment start)
    dom_of_sorted = domain[order]
    n = len(order)
    ranks = np.zeros(n, np.int64)
    if n:
        perm = np.argsort(dom_of_sorted, kind="stable")
        sd = dom_of_sorted[perm]
        pos = np.arange(n)
        first = np.r_[True, sd[1:] != sd[:-1]]
        seg_start = np.maximum.accumulate(np.where(first, pos, 0))
        ranks[perm] = pos - seg_start
    # bucket L to a power of two so repeated cycles reuse the compilation
    raw_l = max(1, int(ranks.max()) + 1) if n else 1
    L = 8
    while L < raw_l:
        L *= 4  # powers of four: shape-diversity control
    grid = np.full((L, D), -1, np.int32)
    grid[ranks, dom_of_sorted] = order.astype(np.int32)
    return grid


def solve_cycle_cohort_parallel(topo_dev, topo_np, usage, cohort_usage,
                                requests, podset_active, wl_cq, priority,
                                timestamp, eligible, solvable,
                                num_podsets: int, fair_sharing: bool = False,
                                start_rank=None):
    """The production single-chip path: Phase A on device, order grid on
    host, cohort-parallel Phase B on device. Same outputs as solve_cycle."""
    import numpy as np
    fit, borrows, chosen, chosen_borrow, asg_usage, share = solve_phase_a(
        topo_dev, usage, cohort_usage, requests, podset_active, wl_cq,
        eligible, solvable, num_podsets=num_podsets,
        fair_sharing=fair_sharing, start_rank=start_rank)
    grid = build_order_grid(fit, borrows, priority, timestamp,
                            np.asarray(wl_cq), topo_np.cq_cohort,
                            topo_np.cohort_subtree.shape[0],
                            cohort_root=topo_np.cohort_root,
                            share=share if fair_sharing else None)
    admitted, usage_out, cohort_out = solve_phase_b_domains(
        topo_dev, usage, cohort_usage, asg_usage, fit, wl_cq,
        jnp.asarray(grid))
    return {"admitted": admitted, "chosen": chosen, "borrows": borrows,
            "chosen_borrow": chosen_borrow, "fit": fit, "usage": usage_out,
            "cohort_usage": cohort_out}


# ---------------------------------------------------------------------------
# Device-resident state: sparse usage deltas applied on device
# ---------------------------------------------------------------------------
#
# The fused cycle kernels RETURN post-cycle usage/cohort_usage device
# arrays; keeping them resident across cycles kills the per-cycle state
# re-encode + re-upload (VERDICT r3 missing #2). Host-side cache events
# between cycles (evictions, finishes, CPU-path admissions) arrive as a
# sparse correction set, applied on device before the next solve.
#
# Path independence makes this sound: cohort usage is a pure function of
# CQ usage — each level holds the sum of its children's over-guaranteed
# clamp, so applying aggregated per-(cq,flavor,resource) deltas with the
# difference-of-clamps at each chain level telescopes to the same state
# the CPU cache reaches event-by-event (resource_node.go:121-143).

def apply_state_deltas_impl(topo, usage, cohort_usage, dq, df, dr, dv,
                            lvl_c, lvl_seg):
    """Apply aggregated sparse usage deltas with cohort-chain bubbling.

    dq/df/dr: [D] int32 UNIQUE (cq, flavor, resource) coords (-1 pad);
    dv: [D] int64 net delta per coord.
    lvl_c: [L, D, 3] int32 unique cohort (cohort, flavor, resource)
    coords per chain level (-1 pad); lvl_seg: [L, D] int32 — row d of
    level l maps the l-1-level coord d (level 0: the delta coord d) to
    its cohort coord row in lvl_c[l] (-1 = chain ends / pad).
    Host side guarantees coord uniqueness within each level, so the
    gather-old / scatter-add / clamp-difference sequence is exact.
    """
    valid = dq >= 0
    dqs = jnp.maximum(dq, 0)
    dfs = jnp.maximum(df, 0)
    drs = jnp.maximum(dr, 0)
    dv = jnp.where(valid, dv, 0)
    old = usage[dqs, dfs, drs]
    usage = usage.at[dqs, dfs, drs].add(dv)  # pads add 0 at (0,0,0)
    g = topo["guaranteed"][dqs, dfs, drs]
    dover = jnp.maximum(0, old + dv - g) - jnp.maximum(0, old - g)  # [D]
    L = lvl_c.shape[0]
    for lvl in range(L):
        seg = lvl_seg[lvl]                       # [D]
        segs = jnp.maximum(seg, 0)
        delta_l = jnp.zeros(dq.shape[0], jnp.int64).at[segs].add(
            jnp.where(seg >= 0, dover, 0))
        c = lvl_c[lvl, :, 0]
        cs = jnp.maximum(c, 0)
        fs = jnp.maximum(lvl_c[lvl, :, 1], 0)
        rs = jnp.maximum(lvl_c[lvl, :, 2], 0)
        delta_l = jnp.where(c >= 0, delta_l, 0)
        oldc = cohort_usage[cs, fs, rs]
        cohort_usage = cohort_usage.at[cs, fs, rs].add(delta_l)
        gc = topo["cohort_guaranteed"][cs, fs, rs]
        dover = jnp.maximum(0, oldc + delta_l - gc) - jnp.maximum(0, oldc - gc)
    return usage, cohort_usage


apply_state_deltas = jax.jit(apply_state_deltas_impl)


def solve_cycle_resident_impl(topo, usage, cohort_usage, deltas, requests,
                              podset_active, wl_cq, priority, timestamp,
                              eligible, solvable, num_podsets: int,
                              max_rank: int, fair_sharing: bool = False,
                              start_rank=None, preempt_args=None,
                              fair_preempt_args=None,
                              fs_strategies: tuple = (),
                              compact: bool = False, cluster_args=None):
    """The device-resident production cycle: sparse correction prologue +
    the fused fit solve (+ the batched preemption programs when present),
    all ONE device program. usage/cohort_usage stay on device across
    cycles — the per-cycle host->device payload is the workload batch and
    the correction coords only."""
    if deltas is not None:
        usage, cohort_usage = apply_state_deltas_impl(
            topo, usage, cohort_usage, *deltas)
    if preempt_args is None and fair_preempt_args is None:
        return solve_cycle_fused_impl(
            topo, usage, cohort_usage, requests, podset_active, wl_cq,
            priority, timestamp, eligible, solvable,
            num_podsets=num_podsets, max_rank=max_rank,
            fair_sharing=fair_sharing, start_rank=start_rank,
            compact=compact, cluster_args=cluster_args)
    return solve_cycle_with_preempt_impl(
        topo, usage, cohort_usage, requests, podset_active, wl_cq,
        priority, timestamp, eligible, solvable, preempt_args,
        num_podsets=num_podsets, max_rank=max_rank,
        fair_sharing=fair_sharing, start_rank=start_rank,
        fair_preempt_args=fair_preempt_args, fs_strategies=fs_strategies,
        compact=compact, cluster_args=cluster_args)


solve_cycle_resident = partial(
    jax.jit, static_argnames=("num_podsets", "max_rank", "fair_sharing",
                              "fs_strategies", "compact"))(
    solve_cycle_resident_impl)


# ---------------------------------------------------------------------------
# Workload encode arena: device-resident batch rows, gathered by slot
# ---------------------------------------------------------------------------
#
# With the host-side encode arena (solver/arena.py) every pending
# workload's encoded rows live in a stable slot; the device keeps a twin
# of the arena arrays, so the per-cycle host->device payload shrinks to
# (a) the slot index array for this cycle's heads and (b) a bucketed
# scatter of the rows that changed since the last dispatch — instead of
# the full padded [W,P,R]/[W,P,F] batch upload.

# The arena ABI (field list) is owned by solver/arena.py — the host
# twin and the kernel build from the same tuple so they can never
# drift. arena.py has no jax dependency, so this import is acyclic.
from kueue_tpu.solver.arena import ARENA_FIELDS  # noqa: E402


def scatter_arena_rows_impl(arena: dict, upd_slots, upd_rows: dict):
    """Scatter this dispatch's changed rows into the device arena twin.
    upd_slots pads with an out-of-range index so mode="drop" ignores the
    padding lanes. A SEPARATE program from the solve on purpose: its
    shape key is (row bucket, arena capacity) only — fused into the
    solve it multiplied every solve variant by every row bucket."""
    return {name: arena[name].at[upd_slots].set(upd_rows[name],
                                                mode="drop")
            for name in ARENA_FIELDS}


scatter_arena_rows = jax.jit(scatter_arena_rows_impl)

# The production upload path (arena.prepare_device) DONATES the previous
# twin into the scatter: XLA aliases the output buffers onto the donated
# input instead of allocating a second full twin and copying the
# untouched rows — the twin double-buffers in place (at most the
# donated-in and the returned generation alive at once), so the
# changed-row upload overlaps the previous cycle's in-flight collect
# without doubling device memory. Backends without donation support
# (CPU) silently copy — same results, no aliasing win. After the call
# the donated arrays are DELETED (jax contract): callers must replace
# every reference with the returned dict, which prepare_device does
# atomically under the arena lock. The undonated variant above stays for
# read-only callers (tests, repeated warms against one zero twin).
scatter_arena_rows_donated = partial(jax.jit, donate_argnums=(0,))(
    scatter_arena_rows_impl)


def gather_arena_impl(arena: dict, slots):
    """[W]-padded slot indices (-1 = padding) -> the batch tensors,
    bit-identical to the host-assembled padded batch (padding rows are
    all-zero / False)."""
    s = jnp.maximum(slots, 0)
    valid = slots >= 0
    requests = jnp.where(valid[:, None, None], arena["requests"][s], 0)
    podset_active = arena["podset_active"][s] & valid[:, None]
    wl_cq = jnp.where(valid, arena["wl_cq"][s], 0)
    priority = jnp.where(valid, arena["priority"][s], 0)
    timestamp = jnp.where(valid, arena["timestamp"][s], 0.0)
    eligible = arena["eligible"][s] & valid[:, None, None]
    solvable = arena["solvable"][s] & valid
    return (requests, podset_active, wl_cq, priority, timestamp, eligible,
            solvable)


def solve_cycle_resident_arena_impl(topo, usage, cohort_usage, deltas,
                                    arena, slots,
                                    num_podsets: int, max_rank: int,
                                    fair_sharing: bool = False,
                                    start_rank=None, preempt_args=None,
                                    fair_preempt_args=None,
                                    fs_strategies: tuple = (),
                                    compact: bool = False,
                                    cluster_args=None):
    """The arena-resident production cycle: gather the head slots from
    the device arena twin into the batch tensors, then run the resident
    solve — one device program, with no per-cycle batch upload (changed
    rows arrive via the separate scatter_arena_rows prologue)."""
    batch = gather_arena_impl(arena, slots)
    return solve_cycle_resident_impl(
        topo, usage, cohort_usage, deltas, *batch,
        num_podsets=num_podsets, max_rank=max_rank,
        fair_sharing=fair_sharing, start_rank=start_rank,
        preempt_args=preempt_args, fair_preempt_args=fair_preempt_args,
        fs_strategies=fs_strategies, compact=compact,
        cluster_args=cluster_args)


solve_cycle_resident_arena = partial(
    jax.jit, static_argnames=("num_podsets", "max_rank", "fair_sharing",
                              "fs_strategies", "compact"))(
    solve_cycle_resident_arena_impl)


# Topology fields the kernels consume; topo_to_device (TPU) and the
# service's _topo_np (local CPU router) both build their dicts from this
# single list so they can never drift.
TOPO_FIELDS = (
    "cq_cohort", "nominal", "borrow_limit", "guaranteed", "offered",
    "group_id", "flavor_group", "flavor_rank", "prefer_no_borrow",
    "cohort_subtree", "cohort_parent", "cohort_depth", "cohort_root",
    "cohort_guaranteed", "cohort_borrow_limit", "cq_chain", "fair_weight",
    "cohort_lendable",
)


def topo_to_device(topo) -> dict:
    """numpy Topology arrays -> device dict for solve_cycle."""
    return {name: jnp.asarray(getattr(topo, name)) for name in TOPO_FIELDS}
