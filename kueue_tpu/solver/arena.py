"""Persistent workload encode arena: O(changed) per-cycle batch assembly.

The per-cycle `encode_workloads` loop reassembled the full [W,P,R]
requests, [W,P,F] eligibility and scalar rows from scratch for every
head, every cycle — even though between cycles only a handful of heads
are new, updated or freshly requeued. The arena gives every pending
workload's encoded rows a stable SLOT in a set of persistent host
arrays (with device-resident twins for the resident kernel) from the
moment it enters the queue until it is admitted or deleted:

- Rows are (re)encoded only when their validity key moves. The key is
  (topology token, metadata.resourceVersion), enforced as OBJECT
  IDENTITY: a resourceVersion bump always arrives on a fresh Workload
  object (the store clones on update, then the queue manager builds a
  fresh Info and fires the 'upsert' delta feed — which also covers
  hand-built objects whose resourceVersion never moves); 'del' frees
  the slot. Requeues of an unchanged Info keep the row.
- Per cycle, batch assembly is a vectorized gather of this cycle's head
  slots into the padded [W, ...] batch: `np.take` host-side (feeds the
  local-CPU fit router and the non-resident paths), or an index array
  shipped to the device so the gather runs there and the per-cycle
  batch upload disappears (kernel.solve_cycle_resident_arena).
- Only `start_rank` — the one genuinely per-cycle input (flavor-resume
  state moves with capacity generations) — is recomputed each cycle,
  by encode.fill_start_ranks.

The from-scratch `encode.encode_workloads` stays the equivalence
oracle: arena-assembled batches must be bit-identical to it
(tests/test_encode_arena.py). See solver/ENCODE.md for the lifecycle.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from kueue_tpu.api.corev1 import RESOURCE_PODS
from kueue_tpu.core import priority as prioritypkg
from kueue_tpu.resilience import faultinject
from kueue_tpu.solver import encode

# The host/device twin field list — the arena ABI, owned here and
# imported by kernel.py (this module has no jax dependency, so the
# import is acyclic and the two sides can never drift). Gathered per
# cycle into the WorkloadBatch; start_rank is deliberately absent:
# per-cycle, see module docstring.
ARENA_FIELDS = ("requests", "podset_active", "wl_cq", "priority",
                "timestamp", "eligible", "solvable")

# Changed-row scatter buckets: exactly two shapes, so the warm pass can
# precompile every scatter variant a run will hit; a bigger dirty set
# re-uploads the twin wholesale (one fixed shape, cheaper than minting
# per-size compiles).
_UPD_BUCKETS = (8, 512)

# Churn batches at least this big take the vectorized multi-row encode
# (_encode_rows) instead of the per-row path: below it, the batch
# bookkeeping costs more than the per-row numpy dispatch it saves.
_BATCH_ENCODE_MIN = 8


def _scramble_rows(upd_rows: dict) -> dict:
    """The scatter site's CORRUPT action: requests inflated past any
    real quota. Conservative by construction — a corrupted row can only
    fail Phase A on device (deny), and denied heads fall through to the
    CPU nomination oracle, so the admitted set stays correct while the
    twin is poisoned; recovery is the wholesale re-upload after the
    next recorded fault or residency reset (see RESILIENCE.md)."""
    out = dict(upd_rows)
    out["requests"] = np.full_like(upd_rows["requests"], 1 << 40)
    return out


class WorkloadArena:
    def __init__(self, max_podsets: int = 4):
        self.P = max_podsets
        self.token = -1          # topology token the rows are encoded for
        self.F = self.R = -1
        self.cap = 0             # allocated slots (bucketed powers of 4)
        self.size = 0            # high-water slot index
        self.slot_of: dict = {}  # workload key -> slot
        # Per-slot validity (plain lists: the ensure loop scalar-indexes
        # them, where list access beats ndarray scalar boxing): a row is
        # current iff info_at is the very Info carrying the slot hint
        # AND enc_obj is that Info's current obj. The (topo.token,
        # resourceVersion) invalidation key is enforced through object
        # identity: every resourceVersion bump arrives on a FRESH object
        # (the store clones on update; the queue manager then builds a
        # fresh Info and fires the upsert feed). Callers that swap a
        # live Info's obj or rebuild its requests in place MUST re-push
        # it through the Manager (every controller path does) — the
        # positional fast path cannot see a mutation that changes no
        # identity and fires no delta. See ENCODE.md.
        self.enc_obj: list = []  # the api.Workload the row encoded
        self.info_at: list = []  # the Info whose row this is
        self.free: list = []     # recycled slots
        # Per-slot encode generation (speculative pipeline): bumped on
        # every re-encode AND on every delta that invalidates the slot
        # (del/upsert), so an in-flight dispatch can prove its gathered
        # rows were untouched mid-flight (stages.SpeculationToken).
        self.gen = np.zeros(0, np.int64)
        # Positional fast path: the previous cycle's (entry ids, slots).
        # A head list position whose Info identity is unchanged AND whose
        # slot no delta touched since needs NO per-entry Python work —
        # the steady state for a requeued backlog. _last_entries pins the
        # previous cycle's Infos so a recycled id can never masquerade as
        # an unchanged entry.
        self._last_ids = None     # np.int64 [m]
        self._last_slots = None   # np.int32 [m]
        self._last_entries = None
        self._touched: set = set()  # slots invalidated since last ensure
        # queue-manager delta feed: ('upsert'|'del', key), appended under
        # the manager lock, drained at the start of every assemble()
        self._pending: deque = deque()
        # host arrays (allocated on first use / topology change)
        self.requests = None       # [S,P,R] int64
        self.podset_active = None  # [S,P] bool
        self.eligible = None       # [S,P,F] bool
        self.wl_cq = None          # [S] int32
        self.priority = None       # [S] int64
        self.timestamp = None      # [S] float64
        self.solvable = None       # [S] bool
        # device twin + upload bookkeeping
        self.dirty: set = set()  # slots changed since the last device upload
        self.dev = None          # {field: device array} or None
        self.dev_cap = -1
        self.dev_token = -1
        # engagement counters (perf artifacts)
        self.encoded_rows = 0
        self.gathers = 0
        self.full_uploads = 0
        self.row_uploads = 0

    # --- delta feed (queue manager listeners; see Manager.add_workload_listener) ---

    def note(self, kind: str, key: str) -> None:
        """Thread-safe enqueue; applied at the next assemble()."""
        self._pending.append((kind, key))

    def _drain(self) -> None:
        pending = self._pending
        while pending:
            try:
                kind, key = pending.popleft()
            except IndexError:  # pragma: no cover — racing producers
                break
            slot = self.slot_of.get(key)
            if slot is None:
                continue
            if kind == "del":
                del self.slot_of[key]
                self.enc_obj[slot] = None
                self.info_at[slot] = None
                self.free.append(slot)
            else:  # upsert: the object was replaced — row is stale
                self.enc_obj[slot] = None
            self.gen[slot] += 1  # in-flight speculation on this row aborts
            self._touched.add(slot)

    # --- slot storage ---

    def _alloc_arrays(self, cap: int, F: int, R: int) -> None:
        P = self.P
        self.requests = np.zeros((cap, P, R), np.int64)
        self.podset_active = np.zeros((cap, P), bool)
        self.eligible = np.zeros((cap, P, F), bool)
        self.wl_cq = np.zeros(cap, np.int32)
        self.priority = np.zeros(cap, np.int64)
        self.timestamp = np.zeros(cap, np.float64)
        self.solvable = np.zeros(cap, bool)

    def reserve(self, n: int, topo) -> None:
        """Pre-size for an expected pending-set cardinality so a long
        run never pays mid-run growth (each growth drops the device twin
        and re-bucket-compiles the gather kernel)."""
        self.begin_cycle(topo)
        if n > self.cap:
            self._grow(n)

    def begin_cycle(self, topo) -> None:
        """Topology-epoch invalidation: a new token (or reshaped F/R
        dims) makes every encoded row stale at once. Slots survive —
        rows re-encode lazily as their workloads next appear as heads."""
        _, F, R = topo.nominal.shape
        if topo.token == self.token and F == self.F and R == self.R:
            return
        self.token = topo.token
        if F != self.F or R != self.R:
            self.F, self.R = F, R
            if self.cap:
                self._alloc_arrays(self.cap, F, R)
        self.enc_obj = [None] * self.cap
        self._last_ids = None  # every row is stale: full rescan
        self.dirty.clear()
        self.dev = None  # stale twin: full re-upload on next dispatch

    def _grow(self, need: int) -> None:
        cap = encode._bucket(max(need, 256), 256)
        if cap <= self.cap:
            return
        if self.cap == 0:
            self._alloc_arrays(cap, self.F, self.R)
        else:
            for name in ARENA_FIELDS:
                old = getattr(self, name)
                arr = np.zeros((cap,) + old.shape[1:], old.dtype)
                arr[: self.cap] = old
                setattr(self, name, arr)
        self.enc_obj.extend([None] * (cap - self.cap))
        self.info_at.extend([None] * (cap - self.cap))
        gen = np.zeros(cap, np.int64)
        gen[: self.cap] = self.gen[: self.cap]
        self.gen = gen
        self.cap = cap
        self.dev = None  # shape moved: full re-upload on next dispatch

    def _alloc(self) -> int:
        if self.free:
            return self.free.pop()
        if self.size >= self.cap:
            self._grow(self.size + 1)
        slot = self.size
        self.size += 1
        return slot

    def release(self, key: str) -> None:
        """The workload left the pending set outside the queue-manager
        feed (admission: it holds quota now and can never be a head
        again until evicted — which re-adds it through the manager)."""
        self.note("del", key)

    # --- encoding & assembly ---

    def _encode_row(self, slot: int, info, snapshot, topo, ordering) -> None:
        """Encode one workload's cycle-stable rows IN PLACE (same
        semantics as encode._encode_one, which stays the oracle — the
        randomized equivalence suite pins the two together; writing row
        views directly skips its per-call scratch allocations, and row
        encodes are the arena's only per-churned-workload cost)."""
        self.dirty.add(slot)
        self.encoded_rows += 1
        self.gen[slot] += 1
        req_row = self.requests[slot]
        act_row = self.podset_active[slot]
        elig_row = self.eligible[slot]
        if self.solvable[slot]:
            # Invariant: non-solvable rows are already all-zero (every
            # encode bail path re-zeroes) — only a previously-solvable
            # occupant's data needs clearing.
            req_row[:] = 0
            act_row[:] = False
            elig_row[:] = False
            self.solvable[slot] = False
        cq = snapshot.cluster_queues.get(info.cluster_queue)
        if cq is None:
            # Unknown CQ: the oracle leaves the whole row zero.
            self.wl_cq[slot] = 0
            self.priority[slot] = 0
            self.timestamp[slot] = 0.0
            return
        qi = topo.cq_index[info.cluster_queue]
        self.wl_cq[slot] = qi
        self.priority[slot] = prioritypkg.priority(info.obj)
        self.timestamp[slot] = ordering.queue_order_timestamp(info.obj)
        if len(info.total_requests) > self.P:
            return  # CPU fallback row (zeros, not solvable)
        resource_index = topo.resource_index
        covers_pods = topo.covers_pods[qi]
        for pi, psr in enumerate(info.total_requests):
            reqs = dict(psr.requests)
            if covers_pods:
                reqs[RESOURCE_PODS] = psr.count
            for r, v in reqs.items():
                ri = resource_index.get(r)
                if ri is None or topo.group_id[qi, ri] < 0:
                    # Unencodable resource: discard the partial fill,
                    # exactly like the oracle's not-ok row.
                    req_row[:] = 0
                    act_row[:] = False
                    elig_row[:] = False
                    return
                req_row[pi, ri] = v
            act_row[pi] = True
            elig_row[pi] = encode.eligibility_row(info, pi, qi, cq,
                                                  snapshot, topo)
        self.solvable[slot] = True

    def _encode_rows(self, slots: list, infos: list, snapshot, topo,
                     ordering) -> None:
        """Vectorized multi-row churn encode (ROADMAP PR-2 follow-up):
        same semantics as ``_encode_row`` — the randomized equivalence
        suite pins the two to the from-scratch oracle — but the numpy
        work is ONE fancy-indexed write per arena field for the whole
        batch instead of ~15us/row of small-array dispatch. The
        per-workload dict walks (requests, eligibility-cache lookups)
        stay host Python; they were never the overhead — the per-row
        ndarray scalar stores were."""
        n = len(slots)
        self.encoded_rows += n
        slots_arr = np.asarray(slots, np.int64)
        self.dirty.update(slots)
        self.gen[slots_arr] += 1
        solv = self.solvable[slots_arr]
        if solv.any():
            # Only previously-solvable occupants hold non-zero data
            # (same invariant _encode_row relies on).
            clear = slots_arr[solv]
            self.requests[clear] = 0
            self.podset_active[clear] = False
            self.eligible[clear] = False
            self.solvable[clear] = False
        cqs = snapshot.cluster_queues
        resource_index = topo.resource_index
        qis = np.zeros(n, np.int32)
        prios = np.zeros(n, np.int64)
        tss = np.zeros(n, np.float64)
        solvable = np.zeros(n, bool)
        req_r: list = []
        req_p: list = []
        req_c: list = []
        req_v: list = []
        act_r: list = []
        act_p: list = []
        elig_rows: list = []
        P = self.P
        for k, info in enumerate(infos):
            cq = cqs.get(info.cluster_queue)
            if cq is None:
                continue  # unknown CQ: all-zero row, like the oracle
            qi = topo.cq_index[info.cluster_queue]
            qis[k] = qi
            prios[k] = prioritypkg.priority(info.obj)
            tss[k] = ordering.queue_order_timestamp(info.obj)
            if len(info.total_requests) > P:
                continue  # CPU fallback row (zeros, not solvable)
            covers_pods = topo.covers_pods[qi]
            slot = slots[k]
            triples: list = []
            ok = True
            for pi, psr in enumerate(info.total_requests):
                reqs = dict(psr.requests)
                if covers_pods:
                    reqs[RESOURCE_PODS] = psr.count
                for r, v in reqs.items():
                    ri = resource_index.get(r)
                    if ri is None or topo.group_id[qi, ri] < 0:
                        ok = False  # unencodable: whole row stays zero
                        break
                    triples.append((pi, ri, v))
                if not ok:
                    break
            if not ok:
                continue
            for pi in range(len(info.total_requests)):
                act_r.append(slot)
                act_p.append(pi)
                elig_rows.append(encode.eligibility_row(
                    info, pi, qi, cq, snapshot, topo))
            for pi, ri, v in triples:
                req_r.append(slot)
                req_p.append(pi)
                req_c.append(ri)
                req_v.append(v)
            solvable[k] = True
        self.wl_cq[slots_arr] = qis
        self.priority[slots_arr] = prios
        self.timestamp[slots_arr] = tss
        if req_r:
            self.requests[req_r, req_p, req_c] = req_v
        if act_r:
            self.podset_active[act_r, act_p] = True
            self.eligible[act_r, act_p] = np.stack(elig_rows)
        self.solvable[slots_arr] = solvable

    def slot_generations(self, slots) -> np.ndarray:
        """Current per-slot encode generations for ``slots``
        (speculation validation). Pending queue-manager deltas are
        drained first, so a del/upsert that arrived mid-flight but has
        not been through ``assemble`` yet still bumps the generation it
        invalidates."""
        self._drain()
        return self.gen[np.asarray(slots, np.int64)].copy()

    def ensure(self, entries: list, snapshot, topo, ordering) -> np.ndarray:
        """Slots for this cycle's heads, (re)encoding only the rows whose
        validity key moved. Returns [n] int32.

        The steady-state fast path is positional and fully vectorized: a
        head-list position holding the SAME Info as last cycle, whose
        slot no queue-manager delta touched since, is valid as-is (a
        requeued backlog re-pops in stable order, so at 2048 heads this
        skips the per-entry Python work entirely). Everything else takes
        the per-entry path: slot hint -> owning-Info identity -> encoded
        obj identity (see the class comment for why object identity
        enforces the (token, resourceVersion) key)."""
        self._drain()
        n = len(entries)
        ids = np.fromiter(map(id, entries), np.int64, n)
        last_ids = self._last_ids
        if last_ids is not None and last_ids.shape[0] == n:
            slots = self._last_slots.copy()
            same = ids == last_ids
            if self._touched:
                t = np.fromiter(self._touched, np.int64,
                                len(self._touched))
                same &= ~np.isin(slots, t)
            changed = np.flatnonzero(~same)
        else:
            slots = np.empty(n, np.int32)
            changed = range(n)
        self._touched.clear()
        enc_obj = self.enc_obj
        info_at = self.info_at
        slot_of = self.slot_of
        cap = self.cap
        enc_slots: list = []
        enc_infos: list = []
        for i in changed:
            info = entries[i]
            slot = info._arena_slot
            if not (0 <= slot < cap and info_at[slot] is info):
                key = info.key
                slot = slot_of.get(key)
                if slot is None:
                    slot = self._alloc()
                    slot_of[key] = slot
                    enc_obj = self.enc_obj  # rebind after growth
                    info_at = self.info_at
                    cap = self.cap
                info._arena_slot = slot
                info_at[slot] = info
            if enc_obj[slot] is not info.obj:
                # Deferred: churn batches big enough to amortize the
                # bookkeeping re-encode vectorized, in one pass. The
                # enc_obj mark lands only AFTER the encode succeeds —
                # a raising encode (an anticipated fallback path) must
                # leave the slot retryable, not sticky-stale.
                enc_slots.append(slot)
                enc_infos.append(info)
            slots[i] = slot
        if len(enc_slots) >= _BATCH_ENCODE_MIN:
            self._encode_rows(enc_slots, enc_infos, snapshot, topo,
                              ordering)
            for slot, info in zip(enc_slots, enc_infos):
                self.enc_obj[slot] = info.obj
        else:
            for slot, info in zip(enc_slots, enc_infos):
                self._encode_row(slot, info, snapshot, topo, ordering)
                self.enc_obj[slot] = info.obj
        self._last_ids = ids
        self._last_slots = slots
        # Copy: callers may mutate their list, and the pin must hold the
        # exact objects the ids were taken from (id-recycle guard).
        self._last_entries = list(entries)
        return slots

    def assemble(self, entries: list, snapshot, topo, ordering,
                 max_podsets: int):
        """(WorkloadBatch bit-identical to encode.encode_workloads,
        slots [n] int32). The batch arrays are fresh (not views into the
        arena), so downstream code may hold them across cycles."""
        slots = self.ensure(entries, snapshot, topo, ordering)
        n = len(entries)
        W = encode._bucket(max(1, n))
        P = max_podsets
        _, F, R = topo.nominal.shape
        batch = encode.WorkloadBatch(infos=list(entries), n=n)
        for name, shape, dtype in (
                ("requests", (W, P, R), np.int64),
                ("podset_active", (W, P), bool),
                ("wl_cq", (W,), np.int32),
                ("priority", (W,), np.int64),
                ("timestamp", (W,), np.float64),
                ("eligible", (W, P, F), bool),
                ("solvable", (W,), bool)):
            # np.take into the uninitialized rows, zero only the padding
            # tail (np.zeros + fancy-index assignment paid an extra full
            # pass over every array).
            out = np.empty(shape, dtype)
            if n:
                np.take(getattr(self, name), slots, axis=0, out=out[:n])
            out[n:] = 0
            setattr(batch, name, out)
        batch.start_rank = np.zeros((W, P, R), np.int32)
        encode.fill_start_ranks(batch.start_rank, entries, batch.solvable,
                                snapshot, topo, P)
        self.gathers += 1
        return batch, slots

    # --- device twin (the resident kernel's gather source) ---

    def drop_device(self) -> None:
        """Device state unknown (failed dispatch / residency reset):
        force a full re-upload at the next dispatch."""
        self.dev = None

    def _full_upload(self):
        import jax.numpy as jnp
        self.dev = {name: jnp.asarray(getattr(self, name))
                    for name in ARENA_FIELDS}
        self.dev_cap = self.cap
        self.dev_token = self.token
        self.dirty.clear()
        self.full_uploads += 1
        return self.dev, sum(getattr(self, name).nbytes
                             for name in ARENA_FIELDS)

    def prepare_device(self):
        """Returns (device twin dict, uploaded bytes), current as of the
        host arrays: a full upload when the twin is missing/stale or the
        dirty set is large, else rows dirtied since the last dispatch
        are scattered into the twin by kernel.scatter_arena_rows (its
        own small program — padded to one of two fixed row buckets so
        the warm pass can precompile every variant) and the returned
        arrays chain as the next twin (resident idiom, no fetch)."""
        if (self.dev is None or self.dev_cap != self.cap
                or self.dev_token != self.token):
            return self._full_upload()
        if not self.dirty:
            return self.dev, 0
        rows = sorted(self.dirty)
        if len(rows) > _UPD_BUCKETS[-1]:
            # Mass churn: one fixed-shape wholesale upload beats a
            # fresh per-size scatter compile.
            return self._full_upload()
        self.dirty.clear()
        for D in _UPD_BUCKETS:
            if len(rows) <= D:
                break
        # pad with cap (out of range): the kernel scatters mode="drop"
        upd_slots = np.full(D, self.cap, np.int32)
        upd_slots[: len(rows)] = rows
        upd_rows = {}
        nbytes = upd_slots.nbytes
        for name in ARENA_FIELDS:
            host = getattr(self, name)
            arr = np.zeros((D,) + host.shape[1:], host.dtype)
            arr[: len(rows)] = host[rows]
            upd_rows[name] = arr
            nbytes += arr.nbytes
        self.row_uploads += len(rows)
        # Injection site: a raise is a failed upload (the dispatch
        # error path owns it); CORRUPT mangles the rows in transit.
        # The corruptor inflates requests past any quota — mangled rows
        # can only DENY on device, never admit, so a corruption that
        # evades detection degrades those rows to the CPU fallback path
        # instead of poisoning decisions; any recorded fault drops the
        # twin wholesale (drop_device) and the next dispatch re-uploads
        # from the host arrays, which faults never touch.
        upd_rows = faultinject.site(faultinject.SITE_SCATTER, upd_rows,
                                    corrupt=_scramble_rows)
        # DONATED scatter: the old twin's buffers alias into the new
        # generation instead of a second full twin + copy — the upload
        # double-buffers in place while the previous cycle's collect is
        # still in flight (kernel.scatter_arena_rows_donated; the
        # donated dict is dead after this line, replaced atomically).
        # An injected raise above leaves self.dev untouched (undonated),
        # so the fault path's drop_device/full re-upload stays sound.
        from kueue_tpu.solver.kernel import scatter_arena_rows_donated
        self.dev = scatter_arena_rows_donated(self.dev, upd_slots,
                                              upd_rows)
        return self.dev, nbytes
