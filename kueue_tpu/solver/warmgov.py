"""Compile governor: compiles as a managed background event.

SURVEY.md §7 names dynamic shapes / recompilation storms as a hard part
of the TPU reformulation, and ROADMAP item 4 asks for the watchdog's
compile-absorbing cold clamp to become unnecessary in steady state.
Before this module, only the bench harness warmed shape buckets (an
inline ladder in perf/runner.py, best-effort, failures swallowed); a
production ``KueueManager`` paid every compile on the hot path, where it
was either absorbed by the supervised-dispatch cold clamp or — worse —
abandoned as a fault, poisoning the router and breaker with what is
really a legitimate compile.

The ``CompileGovernor`` owns the geometric shape-bucket ladder
(``width_ladder`` × ``rank_ladder``, refactored out of perf/runner.py
and ``BatchSolver.warm``) and walks it largest-impact-first on a
supervised background thread:

- Each bucket warm runs on a ``SupervisedWorker`` under a per-bucket
  deadline: a wedged remote compile abandons THAT bucket (retried at
  the ladder tail, then skipped) and the ladder continues — warmup can
  never wedge startup.
- A ``compile_warmup`` fault-injection site makes warmup chaos-testable
  like every other device path (resilience/faultinject.py).
- Executables load from the persistent XLA compilation cache, stamped
  into a per-topology layout (``<cacheDir>/topo-<fingerprint>``) so a
  topology change can never replay stale executables and a process
  restart reuses compiles — preserving the "restart is cheap" property
  (SURVEY.md §5). Per-bucket provenance (fresh / cache-hit / jit-cache)
  is read from jax's compilation-cache monitoring events.
- The scheduler consults ``route_ready()`` before committing a cycle to
  the device route: an un-warmed bucket routes the cycle to the CPU
  path (full reference semantics, no compile risk) under the
  ``cpu-warmup`` route name and enqueues a background warm via
  ``request()`` — so in steady state zero measured cycles carry a
  compile and the watchdog's cold clamp is a true last resort.
- Compile begin/end/fault events flow into the flight recorder (they
  annotate whatever cycle trace is concurrently open — showing exactly
  which cycles overlapped a background compile), the metrics registry
  (``compile_events_total{bucket,source}``, ``warmup_state``,
  ``warmup_faults_total``), ``/debug/warmup``, and the SIGUSR2 dumper.

See solver/COMPILE.md for the ladder, cache key, governor states, and
the route-gating contract.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Optional

from kueue_tpu.resilience import faultinject
from kueue_tpu.resilience.supervisor import SupervisedWorker
from kueue_tpu.resilience.watchdog import DispatchTimeout
from kueue_tpu.solver.encode import _bucket
from kueue_tpu.utils import vlog

# governor states (the warmup_state gauge encoding)
GOV_IDLE = "idle"        # never engaged — the route gate is inert
GOV_WARMING = "warming"  # ladder walk in progress
GOV_WARM = "warm"        # every bucket warm
GOV_PARTIAL = "partial"  # walk finished with skipped/failed buckets
WARMUP_STATE_CODES = {GOV_IDLE: 0, GOV_WARMING: 1, GOV_WARM: 2,
                      GOV_PARTIAL: 3}

# per-bucket states
B_PENDING = "pending"
B_WARMING = "warming"
B_WARM = "warm"
B_FAILED = "failed"    # faulted, retry scheduled at the ladder tail
B_SKIPPED = "skipped"  # gave up after max_attempts — operator surface

DEFAULT_BUCKET_DEADLINE_S = 120.0
DEFAULT_MAX_WIDTH = 2048
DEFAULT_MAX_ATTEMPTS = 2


# --- ladder derivation (the one copy; perf/runner.py delegates here) ---

def width_ladder(num_cqs: int, max_width: int = DEFAULT_MAX_WIDTH) -> list:
    """Geometric batch-width bucket ladder, largest-impact-first: the
    full-backlog bucket plus every drain bucket below it (encode
    buckets by powers of 4 from 8). ``heads()`` pops at most one head
    per CQ, so the full bucket is min(max_width, num_cqs)."""
    full = max(1, min(max_width, num_cqs))
    widths, b = [], 8
    while True:
        widths.append(b)
        if b >= full:
            break
        b *= 4
    widths.reverse()
    return widths


def rank_ladder(cohort_members: dict) -> tuple:
    """Conflict-domain rank buckets from the real topology: ``heads()``
    pops one head per CQ, so a batch's largest conflict domain is the
    largest cohort's CQ count, bucketed the way kernel.max_rank_bound
    buckets (powers of 4 from 8). The whole ladder from 8 through one
    bucket past the bound is warmed — drain-phase cycles can observe
    any smaller domain, and a cohort-less CQ tail can nudge the bound
    up."""
    bound = 8
    while bound < max(cohort_members.values() or [1]):
        bound *= 4
    ranks, r = [], 8
    while r <= bound * 4:
        ranks.append(r)
        r *= 4
    return tuple(ranks)


def parse_shape_rung(key) -> tuple:
    """Normalize one synthesized warm rung to a (B, K) pair. Accepts
    the ``"B{b}xK{k}"`` strings ``sim/adversary.preempt_shape_report``
    emits (``suggested_rungs`` — the ``soak_run --shapes`` feed) or a
    bare ``(B, K)`` tuple."""
    if isinstance(key, str):
        b_part, _, k_part = key.partition("x")
        if not (b_part.startswith("B") and k_part.startswith("K")):
            raise ValueError(f"bad shape rung {key!r} "
                             "(want 'B<n>xK<n>' or a (B, K) pair)")
        return int(b_part[1:]), int(k_part[1:])
    b, k = key
    return int(b), int(k)


def preempt_shape_ladder(cohort_members: dict, width: int,
                         extra=()) -> tuple:
    """Bucketed preemption-batch shapes {B,K,QL,CL,RF,U} the warm walk
    precompiles (encode_problems buckets every dim, so a handful of
    shape dicts cover the common storm geometries):

    - a RECLAIM shape: problems spanning the widest cohort (QL = its
      member bucket) with a candidate axis sized by the members (a few
      victims per CQ -- the K bucket only has to match the power-of-four
      bucket the real pool lands in, K itself is padded), and
    - a WITHIN-CQ shape: single-CQ problems (QL bucket 1) with a small
      pool,

    each at THREE problem-count rungs: B buckets by the number of
    preempt problems in the cycle, NOT the batch width, so the rungs
    descend geometrically from the full-backlog bucket (every head
    preempts) through width/4 (a full storm net of lenders -- the
    flagship reclaim storm encodes ~one problem per borrowing head)
    down to width/16 (a partial storm). CL/RF sit at their bucket
    floors -- chains and request slots bucket from small minimums that
    real topologies rarely exceed. U (the dedup row table) is pinned
    at its floor too, but honestly: U buckets on the cycle's DISTINCT
    victim (usage-row, priority) footprints -- workload content no
    topology-derived ladder can enumerate -- so a heterogeneous storm
    (>= 4 distinct footprints) lands off-ladder by construction. A
    shape outside the ladder (a deeper partial storm, a heterogeneous
    pool, an unusually wide one) costs ONE counted mid-traffic compile
    (mid_traffic_compiles / compile_events_total) that the jit cache
    then holds for the process and the persistent cache across
    restarts; request()'s background backfill is width-keyed and does
    not re-warm preemption shapes. Tuning U rungs from production
    compile_events data is a ROADMAP follow-up.

    ``extra`` closes that loop for the (B, K) plane today: synthesized
    off-ladder rungs — ``soak_run --shapes`` runs the adversarial
    geometry sweep (sim/adversary.preempt_shape_report) and its
    ``suggested_rungs`` are exactly the storm shapes the topology
    ladder above would NOT precompile — are accepted here as
    ``"B{b}xK{k}"`` strings or (B, K) pairs and become first-class
    rungs at the reclaim geometry (QL = the member bucket; CL/RF/U at
    their floors, like every topology rung)."""
    mm = max(cohort_members.values() or [1])
    k_reclaim = _bucket(max(8, 4 * mm))
    shapes = []
    for b in dict.fromkeys(_bucket(max(1, width // d), 1)
                           for d in (1, 4, 16)):
        shapes.append({"B": b, "K": k_reclaim, "QL": _bucket(mm, 1),
                       "CL": 8, "RF": 8, "U": 4})
        shapes.append({"B": b, "K": 8, "QL": 1, "CL": 8, "RF": 8,
                       "U": 4})
    for rung in extra:
        b, k = parse_shape_rung(rung)
        shapes.append({"B": _bucket(b, 1), "K": _bucket(k),
                       "QL": _bucket(mm, 1), "CL": 8, "RF": 8, "U": 4})
    # cohort-less topologies collapse the two geometries (QL bucket 1,
    # K floor 8): dedup so each variant compiles once
    out, seen = [], set()
    for s in shapes:
        key = tuple(sorted(s.items()))
        if key not in seen:
            seen.add(key)
            out.append(s)
    return tuple(out)


def snapshot_cohort_members(snapshot) -> dict:
    """cohort name (or CQ name when cohort-less) -> member CQ count."""
    members: dict = {}
    for name, cq in snapshot.cluster_queues.items():
        key = cq.cohort.name if cq.cohort is not None else name
        members[key] = members.get(key, 0) + 1
    return members


def topology_fingerprint(topo, max_podsets: int, mesh=None) -> str:
    """Stable cache-layout stamp: everything the compiled executables'
    shapes derive from (topology tensor dims + podset width) plus the
    toolchain identity (jax version, backend platform) and — for mesh
    solvers — the mesh LAYOUT (axis names + shape): a sharded program
    over a different host count is a different executable population,
    so its warm ladder and persistent-cache directory must not collide
    with another mesh shape's (ISSUE 13). Device IDs are deliberately
    NOT included (they renumber across restarts on some runtimes — the
    layout, not the numbering, shapes the program). The process-local
    ``topo.token`` is deliberately NOT included — it changes on every
    rebuild, and the whole point of the stamp is cross-process reuse
    that still refuses stale shapes."""
    import hashlib

    import jax
    mesh_dims = (tuple(mesh.axis_names), tuple(mesh.devices.shape)) \
        if mesh is not None else None
    dims = (topo.nominal.shape, topo.cohort_subtree.shape,
            topo.cq_chain.shape, max_podsets,
            jax.__version__, jax.default_backend(), mesh_dims)
    return hashlib.blake2b(repr(dims).encode(), digest_size=8).hexdigest()


# --- persistent-cache provenance (jax compilation-cache monitoring) ---
#
# jax emits /jax/compilation_cache/cache_{hits,misses} monitoring events
# whenever the persistent cache serves or misses a compile. One
# process-global listener feeds the counters; per-bucket provenance is
# the delta across that bucket's warm. Without a registered listener
# (old jax) — or with no persistent cache configured — no events fire
# and warms classify as "jit-cache".

_EVENTS = {"hits": 0, "misses": 0}
_events_lock = threading.Lock()
_events_registered = False


def _note_jax_event(name: str, **kwargs) -> None:
    if name.endswith("/cache_hits"):
        _EVENTS["hits"] += 1
    elif name.endswith("/cache_misses"):
        _EVENTS["misses"] += 1


def ensure_event_listener() -> bool:
    """Register the compilation-cache event listener (idempotent).
    False when this jax has no monitoring API."""
    global _events_registered
    with _events_lock:
        if _events_registered:
            return True
        try:
            from jax._src import monitoring
            monitoring.register_event_listener(_note_jax_event)
        except Exception:  # noqa: BLE001 — older jax without monitoring
            return False
        _events_registered = True
        return True


def compile_event_counts() -> tuple:
    """(persistent-cache hits, misses) observed so far in this process."""
    return (_EVENTS["hits"], _EVENTS["misses"])


class BucketState:
    """One ladder step's lifecycle + provenance (the /debug/warmup and
    warm_probe row)."""

    __slots__ = ("width", "ranks", "scatter", "state", "source",
                 "attempts", "programs", "compile_s", "error",
                 "fit_warm")

    def __init__(self, width: int, ranks: tuple, scatter: bool = False):
        self.width = width
        self.ranks = tuple(ranks)
        self.scatter = scatter      # this step also warms the arena scatter
        self.state = B_PENDING
        self.source = None          # fresh | cache-hit | jit-cache
        self.attempts = 0
        self.programs = 0
        self.compile_s = 0.0
        self.error = ""
        self.fit_warm = False       # fit-path variants warm (gate opens
                                    # before the longer preempt warms)

    def to_dict(self) -> dict:
        return {"width": self.width, "ranks": list(self.ranks),
                "state": self.state, "source": self.source,
                "attempts": self.attempts, "programs": self.programs,
                "compile_ms": round(self.compile_s * 1e3, 1),
                "error": self.error}


class CompileGovernor:
    """Supervised shape-bucket warmup + the scheduler's warm-state gate.

    Constructed idle (state ``idle``; ``route_ready`` always True so an
    attached-but-unused governor changes nothing). ``start()`` launches
    the background walk; ``run_sync()`` walks the ladder on the calling
    thread (the perf harness's pre-clock warmup). Both share the same
    fault-contained per-bucket machinery.
    """

    def __init__(self, solver, cache, *, metrics=None, recorder=None,
                 bucket_deadline_s: float = DEFAULT_BUCKET_DEADLINE_S,
                 cache_dir: str = "", max_width: int = DEFAULT_MAX_WIDTH,
                 deltas_buckets: tuple = (8,),
                 max_attempts: int = DEFAULT_MAX_ATTEMPTS,
                 expected_pending: Optional[int] = None,
                 fair_sharing: bool = False,
                 warm_preempt: bool = True, fs_flags: tuple = (),
                 extra_preempt_rungs: tuple = ()):
        self.solver = solver
        self.cache = cache
        self.metrics = metrics
        self.recorder = recorder
        self.bucket_deadline_s = bucket_deadline_s
        self.cache_dir = cache_dir
        self.max_width = max_width
        self.deltas_buckets = tuple(deltas_buckets)
        self.max_attempts = max_attempts
        self.expected_pending = expected_pending
        # fair_sharing is a STATIC kernel arg: a deployment with fair
        # sharing enabled dispatches genuinely different programs, so
        # the ladder must warm with the same flag (manager wires it
        # from cfg.fair_sharing.enable).
        self.fair_sharing = fair_sharing
        # Preemption/fair-share program variants ride the ladder's
        # largest bucket (warm_preempt): the batched preemption solve is
        # a distinct fused program per preemption-batch shape, and the
        # first preemption-heavy cycle after startup must not be the
        # compile that breaks max_mid_traffic_compiles=0. fs_flags is
        # the static strategy tuple the scheduler will dispatch with
        # (fairpreempt.strategy_flags) -- a mismatched tuple warms a
        # program nobody runs.
        self.warm_preempt = warm_preempt
        # Synthesized (B, K) rungs beyond the topology ladder — the
        # soak_run --shapes feed (see preempt_shape_ladder's ``extra``).
        self.extra_preempt_rungs = tuple(extra_preempt_rungs)
        self.fs_flags = tuple(fs_flags)
        self._preempt_shapes: tuple = ()
        self.state = GOV_IDLE
        self.buckets: dict = {}       # width -> BucketState (ladder order)
        self.warmup_faults = 0        # faulted bucket attempts (total)
        self.programs_warmed = 0
        self.unwarm_routed = 0        # cycles the gate sent to cpu-warmup
        self.cache_subdir = ""        # the stamped per-topology dir
        self._warm_widths: frozenset = frozenset()  # atomic hot-path read
        self._ranks: tuple = (8, 32)  # ladder ranks (for late requests)
        self._worker = SupervisedWorker("compile-warmup")
        self._lock = threading.Lock()
        self._requests: collections.deque = collections.deque()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._walked = False
        self._vacuous = False         # mesh/native: nothing to warm
        self._ctx = None              # solver WarmContext, once built
        self.log = vlog.logger("warmgov")

    # --- hot path (scheduler thread) ---

    def route_ready(self, heads: int) -> bool:
        """Gate consulted by the scheduler before committing a cycle to
        the device route: True when the batch-width bucket this head
        count encodes into has warm programs, or the governor was never
        engaged (an idle governor must not change routing), or the
        backend caches its dispatch paths elsewhere (mesh/native:
        vacuously warm, the gate must never divert)."""
        if self.state == GOV_IDLE or self._vacuous:
            return True
        w = _bucket(max(1, min(heads, self.max_width)))
        return w in self._warm_widths

    def request(self, heads: int) -> None:
        """The scheduler hit an un-warmed bucket mid-traffic (the cycle
        itself routed to the CPU path): enqueue a background warm for
        it. Idempotent per bucket; wakes — or lazily starts — the
        background worker. A bucket already SKIPPED (gave up after
        max_attempts) is not re-queued: that is an operator decision
        (tools/warm_probe.py)."""
        if self._vacuous:
            return
        self.unwarm_routed += 1
        w = _bucket(max(1, min(heads, self.max_width)))
        with self._lock:
            if w in self._warm_widths:
                return
            b = self.buckets.get(w)
            if b is not None and b.state in (B_WARMING, B_SKIPPED):
                return
            if b is None:
                b = BucketState(w, self._ranks)
                self.buckets[w] = b
            if w in self._requests:
                return
            self._requests.append(w)
        self._wake.set()
        self.start()  # no-op while the background thread is alive

    # --- lifecycle ---

    def start(self) -> None:
        """Launch (idempotently) the supervised background warmup
        thread: waits for a non-empty topology, walks the ladder
        largest-first, then parks serving ``request()`` retries.

        The route gate engages IMMEDIATELY (state leaves ``idle`` here,
        not when the walk begins): between start() and the walk seeing
        a topology there must be no window where an un-warmed cycle
        slips onto the device route and pays the compile the governor
        exists to absorb."""
        with self._lock:
            if self.state == GOV_IDLE:
                self.state = GOV_WARMING
            if self._thread is not None and self._thread.is_alive():
                self._set_gauge()
                return
            self._stop.clear()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="compile-governor")
            self._thread.start()
        self._set_gauge()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        self._worker.stop()

    def run_sync(self, expected_pending: Optional[int] = None) -> int:
        """Walk the whole ladder on the calling thread (the perf/bench
        harnesses' pre-clock warmup). Blocking, but each bucket still
        runs under the supervised per-bucket deadline, so a wedged
        remote compile abandons that bucket instead of hanging the
        harness, and a walk-level failure degrades to the route gate
        (logged + counted, like the background walk) instead of
        crashing the harness. Returns the number of programs warmed."""
        self._walked = True
        if expected_pending is not None:
            self.expected_pending = expected_pending
        return self._walk_contained()

    # --- the ladder walk ---

    def _has_topology(self) -> bool:
        hm = getattr(self.cache, "hm", None)
        return bool(hm is not None and hm.cluster_queues)

    def _run(self) -> None:
        while not self._stop.is_set() and not self._has_topology():
            self._stop.wait(0.05)
        if self._stop.is_set():
            return
        if not self._walked:
            self._walked = True
            # The topology gate above releases on the FIRST reconciled
            # CQ, which may be mid-startup (more CQs still landing):
            # re-walk until the structural generation token is stable
            # across a walk, so the ladder, cache fingerprint, and the
            # frozen WarmContext are never built from a partial
            # topology. Structural tokens only move on CQ/flavor
            # changes, so steady state walks exactly once.
            tok = self._gen_token()
            self._walk_contained()
            while not self._stop.is_set():
                new_tok = self._gen_token()
                if new_tok == tok:
                    break
                tok = new_tok
                self._reset_for_rewalk()
                self._walk_contained()
        # Serve mid-traffic requests (un-warmed buckets the route gate
        # diverted) until stopped.
        while not self._stop.is_set():
            self._wake.wait()
            self._wake.clear()
            while not self._stop.is_set():
                with self._lock:
                    w = (self._requests.popleft()
                         if self._requests else None)
                if w is None:
                    break
                b = self.buckets.get(w)
                if b is None or b.state in (B_WARM, B_SKIPPED):
                    continue
                if self._ctx is None:
                    # Walk never built a context (mesh/native backend):
                    # nothing to warm.
                    continue
                if not self._warm_one(b) and b.state == B_FAILED:
                    with self._lock:
                        self._requests.append(w)
                self._finish_state()

    def _gen_token(self):
        fn = getattr(self.cache, "generation_token", None)
        try:
            return fn() if fn is not None else None
        except Exception:  # noqa: BLE001 — stub caches in tests
            return None

    def _reset_for_rewalk(self) -> None:
        """The topology changed structurally since the last walk: every
        warmed program was keyed on the OLD dims, so the buckets' warm
        state is meaningless — hold the gate and walk the new ladder.
        (Old-dims compiles stay in the jit/persistent caches; only the
        governor's bookkeeping resets.)"""
        with self._lock:
            self.buckets.clear()
            self._requests.clear()
            self._warm_widths = frozenset()
            self._vacuous = False
            self._ctx = None
            self.state = GOV_WARMING
        self._set_gauge()

    def _walk_contained(self) -> int:
        """_walk with walk-level containment: a failure outside the
        per-bucket machinery (snapshot/encode error in warm_setup)
        degrades to the CPU-route gate — logged via vlog and counted in
        warmup_faults_total, never raised to the caller (the old bench
        warmup swallowed these silently; a production startup must not
        die on them)."""
        try:
            return self._walk()
        except Exception as exc:  # noqa: BLE001 — warmup must not crash
            self.warmup_faults += 1
            if self.metrics is not None:
                self.metrics.warmup_fault()
            self.log.error("warmgov.walkFault", error=repr(exc)[:200])
            with self._lock:
                self.state = GOV_PARTIAL
            self._set_gauge()
            return 0

    def _walk(self) -> int:
        snapshot = self.cache.snapshot()
        ctx = self.solver.warm_setup(snapshot, self.expected_pending)
        if ctx is None:
            # mesh/native backends cache their dispatch paths
            # separately: vacuously warm, the gate never diverts
            # (route_ready short-circuits on the flag — _warm_widths
            # stays empty, so without it every cycle would divert).
            with self._lock:
                self._vacuous = True
                self.state = GOV_WARM
            self._set_gauge()
            return 0
        self._ctx = ctx
        self._stamp_cache_dir(ctx.topo)
        widths = width_ladder(len(snapshot.cluster_queues), self.max_width)
        members = snapshot_cohort_members(snapshot)
        ranks = rank_ladder(members)
        if self.warm_preempt:
            self._preempt_shapes = preempt_shape_ladder(
                members, widths[0], extra=self.extra_preempt_rungs)
        with self._lock:
            self._ranks = ranks
            self.state = GOV_WARMING
            for i, w in enumerate(widths):
                b = self.buckets.get(w)
                if b is None:
                    # the scatter programs ride on the first (largest)
                    # step — they are per-arena-capacity, not per-width
                    self.buckets[w] = BucketState(w, ranks,
                                                  scatter=(i == 0))
                elif b.ranks != tuple(ranks) or (i == 0 and not b.scatter):
                    # A request() between start() and here created this
                    # bucket with the placeholder ranks (and no scatter
                    # claim): refresh it against the real ladder, and
                    # re-warm if it already ran — a bucket warmed at the
                    # wrong ranks is not warm (already-compiled subsets
                    # replay from the jit cache, so the re-warm is
                    # cheap).
                    b.ranks = tuple(ranks)
                    b.scatter = b.scatter or (i == 0)
                    b.fit_warm = False  # wrong-rank fit warms don't count
                    if b.state == B_WARM:
                        b.state = B_PENDING
            self._warm_widths = frozenset(
                w for w, st in self.buckets.items()
                if st.state == B_WARM or st.fit_warm)
        self._set_gauge()
        self.log.v(2, "warmgov.walkStart", widths=widths, ranks=ranks,
                   deadline_s=self.bucket_deadline_s,
                   cache_dir=self.cache_subdir or self.cache_dir)
        queue = collections.deque(
            self.buckets[w] for w in widths
            if self.buckets[w].state != B_WARM)
        while queue and not self._stop.is_set():
            b = queue.popleft()
            if not self._warm_one(b) and b.state == B_FAILED:
                queue.append(b)  # retry at the ladder tail, then skip
        self._finish_state()
        return self.programs_warmed

    def _warm_one(self, b: BucketState) -> bool:
        b.state = B_WARMING
        b.attempts += 1
        hits0, misses0 = compile_event_counts()
        t0 = time.perf_counter()
        self._annotate("compile-begin",
                       f"warmup bucket width={b.width} "
                       f"(attempt {b.attempts})",
                       width=b.width, attempt=b.attempts)
        try:
            n = self._worker.run(self._warm_body, b,
                                 deadline_s=self.bucket_deadline_s)
            if (b.scatter and self._preempt_shapes
                    and hasattr(self.solver, "warm_preempt_bucket")):
                # Separate supervised windows, one per (B rung, rank)
                # chunk: the preempt ladder is many compile batches of
                # its own, so pricing it all inside the fit phase's
                # deadline would make the knob's meaning scale with
                # the ladder (a chronically-over-deadline window would
                # retry into the same wall and SKIP, silently never
                # warming preemption). The route gate for this width
                # is already open (fit_warm) — a timeout in any chunk
                # retries the bucket at the ladder tail, replaying
                # completed chunks from the jit cache.
                for shapes, rank, sr in self._preempt_chunks(b.ranks):
                    n += self._worker.run(
                        lambda bb, s=shapes, r=rank, f=sr:
                            self._warm_preempt_chunk(bb, s, r, f),
                        b, deadline_s=self.bucket_deadline_s)
        except DispatchTimeout as exc:
            self._fault(b, exc, timeout=True)
            return False
        except Exception as exc:  # noqa: BLE001 — injected or real
            self._fault(b, exc, timeout=False)
            return False
        b.compile_s = time.perf_counter() - t0
        hits, misses = compile_event_counts()
        if misses > misses0:
            b.source = "fresh"       # at least one real compile
        elif hits > hits0:
            b.source = "cache-hit"   # served from the persistent cache
        else:
            b.source = "jit-cache"   # in-memory jit cache (or no cache)
        b.programs = n
        b.error = ""
        b.state = B_WARM
        self.programs_warmed += n
        with self._lock:
            self._warm_widths = frozenset(
                w for w, st in self.buckets.items()
                if st.state == B_WARM or st.fit_warm)
        if self.metrics is not None:
            self.metrics.compile_event(str(b.width), b.source, n)
        self._annotate("compile-end",
                       f"bucket width={b.width} warm: {n} program(s) "
                       f"{b.source} in {b.compile_s * 1e3:.0f}ms",
                       width=b.width, programs=n, source=b.source,
                       ms=round(b.compile_s * 1e3, 1))
        self.log.v(2, "warmgov.bucketWarm", width=b.width, programs=n,
                   source=b.source, ms=round(b.compile_s * 1e3, 1))
        return True

    def _warm_body(self, b: BucketState) -> int:
        # Injection site: a DELAY here is a wedged remote compile — the
        # per-bucket deadline abandons the bucket and the ladder
        # continues; a RAISE is a backend error mid-warm. Runs on the
        # supervised worker thread, never the scheduler's.
        faultinject.site(faultinject.SITE_WARMUP)
        ctx = self._ctx
        n = self.solver.warm_router(ctx, b.width)
        n += self.solver.warm_bucket(ctx, b.width, max_ranks=b.ranks,
                                     deltas_buckets=self.deltas_buckets,
                                     fair_sharing=self.fair_sharing)
        if b.scatter:
            n += self.solver.warm_scatter(ctx)
            # The width's FIT-path variants are warm: open the route
            # gate now, before the (much longer) preemption-variant
            # warm that follows in its own supervised window — holding
            # fit-only traffic on cpu-warmup until every preempt shape
            # compiles would multiply the cold-start-to-first-device-
            # route budget (bench cold_start) by the preempt ladder's
            # size. A preemption cycle arriving in this window pays a
            # counted mid-traffic compile, exactly as it would for an
            # off-ladder shape.
            b.fit_warm = True
            with self._lock:
                self._warm_widths = self._warm_widths | {b.width}
        return n

    def _warm_preempt_chunk(self, b: BucketState, shapes: tuple,
                            rank: int, sr: bool) -> int:
        # Preemption variants ride the largest (first) bucket only: a
        # preemption storm nominates against the full backlog, so the
        # full-width bucket is the one whose first mixed cycle must
        # not compile; the shape ladder's descending B rungs cover
        # partial storms, and anything deeper pays one counted
        # mid-traffic compile (request()'s background warm is
        # width-keyed and does not re-warm preemption shapes).
        return self.solver.warm_preempt_bucket(
            self._ctx, b.width, shapes, max_ranks=(rank,),
            deltas_buckets=self.deltas_buckets,
            fair_sharing=self.fair_sharing,
            fs_flags=self.fs_flags, start_rank=sr)

    def _preempt_chunks(self, ranks: tuple) -> list:
        """(shapes, rank, start_rank) work units for the preempt warm,
        one supervised window each: the ladder grouped by B rung (the
        mixed fair variant pairs a within-CQ batch with a cohort-wide
        batch at EQUAL B, so a rung's shapes must warm together),
        split per rank rung and per flavor-resume twin (requeued heads
        after an eviction carry resume state, so mid-storm preempt
        cycles routinely dispatch the start_rank variant). Each chunk
        is a handful of compiles — comparable to one fit-bucket warm —
        so the per-bucket deadline keeps its meaning instead of
        scaling with the whole ladder."""
        by_b: dict = {}
        for s in self._preempt_shapes:
            by_b.setdefault(s["B"], []).append(s)
        return [(tuple(shapes), r, sr)
                for r in dict.fromkeys(ranks)
                for shapes in by_b.values()
                for sr in (False, True)]

    def _fault(self, b: BucketState, exc: BaseException,
               timeout: bool) -> None:
        self.warmup_faults += 1
        b.error = repr(exc)[:200]
        b.state = B_FAILED if b.attempts < self.max_attempts else B_SKIPPED
        if self.metrics is not None:
            self.metrics.warmup_fault()
        self._annotate("compile-fault",
                       f"warmup bucket width={b.width} "
                       f"{'deadline' if timeout else 'fault'}: "
                       f"{exc!r}"[:200],
                       width=b.width, timeout=timeout, state=b.state)
        self.log.error("warmgov.bucketFault", width=b.width,
                       error=repr(exc)[:200], timeout=timeout,
                       attempts=b.attempts, state=b.state)

    def _finish_state(self) -> None:
        with self._lock:
            states = {b.state for b in self.buckets.values()}
            self.state = GOV_WARM if states <= {B_WARM} else GOV_PARTIAL
        self._set_gauge()

    def _stamp_cache_dir(self, topo) -> None:
        """Point the persistent compilation cache at the per-topology
        layout ``<cacheDir>/topo-<fingerprint>`` (solver.compileCacheDir
        knob): a topology change lands in a different directory, so a
        restart can never replay executables compiled for other shapes.
        Persists EVERY executable (min compile time 0): over a remote
        tunnel even a sub-second compile is a hot-path stall worth a
        disk read on restart."""
        ensure_event_listener()
        if not self.cache_dir:
            return
        from kueue_tpu.utils.runtime import enable_compilation_cache
        fp = topology_fingerprint(topo, self.solver.max_podsets,
                                  mesh=getattr(self.solver, "mesh", None))
        self.cache_subdir = os.path.join(self.cache_dir, f"topo-{fp}")
        enable_compilation_cache(self.cache_subdir,
                                 min_compile_time_secs=0.0)

    # --- surface (metrics / recorder / debug endpoints / dumper) ---

    def _set_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.set_warmup_state(self.state)

    def _annotate(self, kind: str, message: str, **fields) -> None:
        # Attaches to whatever cycle trace is concurrently open (the
        # governor runs off-thread): the trace shows which cycles
        # overlapped a background compile. No open trace = dropped.
        if self.recorder is not None:
            self.recorder.annotate(kind, message, **fields)

    def status(self) -> dict:
        """The /debug/warmup + SIGUSR2 + warm_probe producer."""
        with self._lock:
            buckets = [b.to_dict() for b in self.buckets.values()]
            warm = sorted(self._warm_widths)
        return {
            "state": self.state,
            "buckets": buckets,
            "warm_widths": warm,
            "programs_warmed": self.programs_warmed,
            "warmup_faults": self.warmup_faults,
            "unwarm_routed_cycles": self.unwarm_routed,
            "cache_dir": self.cache_dir,
            "cache_subdir": self.cache_subdir,
            "bucket_deadline_s": self.bucket_deadline_s,
            "deltas_buckets": list(self.deltas_buckets),
            "worker": self._worker.status(),
        }
