"""Circuit breaker for the solver's device route.

Fed by watchdog timeouts and dispatch/collect exceptions (the scheduler
calls ``record_fault``), it keeps a wedged or flapping accelerator from
eating every cycle's deadline:

- CLOSED: device route allowed; ``threshold`` CONSECUTIVE faults trip
  it open (one success resets the count — an isolated glitch on a
  healthy device must not accumulate forever).
- OPEN: ``allow_device`` is False — the scheduler pins cycles to the
  CPU fallback under the distinct route name "cpu-breaker" (excluded
  from the adaptive router's samples exactly like "cpu-strict": a
  fairness/safety intervention is not an economics signal). After the
  current backoff elapses the next ``allow_device`` transitions to
  HALF_OPEN and admits exactly one probe cycle.
- HALF_OPEN: the probe ran; ``record_success`` closes the breaker and
  resets the backoff, ``record_fault`` re-opens it with the backoff
  doubled (capped at ``backoff_max_s``), plus jitter so a fleet of
  schedulers sharing one recovering device doesn't probe in lockstep.

Time comes from the caller (the scheduler's injected clock), so tests
and the bench drive backoff deterministically with a FakeClock; jitter
comes from a seeded RNG for the same reason.
"""

from __future__ import annotations

import random

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CircuitBreaker:
    def __init__(self, threshold: int = 3, backoff_base_s: float = 1.0,
                 backoff_max_s: float = 60.0, jitter: float = 0.1,
                 seed: int = 0):
        if threshold < 1:
            raise ValueError("breaker threshold must be >= 1")
        self.threshold = threshold
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.jitter = jitter
        self._rng = random.Random(seed)
        self.state = CLOSED
        self.consecutive_faults = 0
        self._backoff_s = backoff_base_s
        self._retry_at = 0.0
        # Counters for metrics/artifacts.
        self.trips = 0            # CLOSED/HALF_OPEN -> OPEN transitions
        self.recoveries = 0       # HALF_OPEN -> CLOSED transitions
        self.faults = 0           # every record_fault
        self.blocked_cycles = 0   # allow_device() == False since last trip
        self.last_recovery_cycles = 0  # blocked+probe cycles of last outage

    def allow_device(self, now: float) -> bool:
        """May this cycle take the device route? OPEN past its backoff
        admits one half-open probe; the caller MUST follow the probe
        with record_success or record_fault before asking again."""
        if self.state == CLOSED:
            return True
        if self.state == OPEN and now >= self._retry_at:
            self.state = HALF_OPEN
            return True
        # OPEN within backoff — and HALF_OPEN, where a probe's outcome
        # hasn't been recorded yet (a second concurrent probe would make
        # the outcome unattributable).
        self.blocked_cycles += 1
        return False

    def record_fault(self, now: float) -> bool:
        """A device fault (dispatch/collect exception, watchdog timeout,
        detected corruption). Returns True when this fault TRIPPED the
        breaker (for metrics/events)."""
        self.faults += 1
        if self.state == HALF_OPEN:
            # Failed probe: back off harder before the next one. Counts
            # as a trip (HALF_OPEN -> OPEN) so self.trips agrees with
            # the breaker_trips_total metric the caller increments.
            self.trips += 1
            self.blocked_cycles += 1  # the probe cycle made no progress
            self._backoff_s = min(self._backoff_s * 2, self.backoff_max_s)
            self._open(now)
            return True
        self.consecutive_faults += 1
        if self.state == CLOSED \
                and self.consecutive_faults >= self.threshold:
            self.trips += 1
            self.blocked_cycles = 0
            self._open(now)
            return True
        return False

    def record_success(self, now: float) -> bool:
        """A device-routed cycle completed without a fault. Returns True
        when this closed a half-open breaker (a recovery)."""
        self.consecutive_faults = 0
        if self.state == HALF_OPEN:
            self.state = CLOSED
            self._backoff_s = self.backoff_base_s
            self.recoveries += 1
            # +1: the probe cycle itself is part of the outage window.
            self.last_recovery_cycles = self.blocked_cycles + 1
            self.blocked_cycles = 0
            return True
        return False

    def status(self) -> dict:
        """Structured state snapshot for the operator surface
        (/debug/breaker, the SIGUSR2 dumper, flight-recorder
        annotations). ``retry_at`` is in the caller's clock domain."""
        return {
            "state": self.state,
            "consecutive_faults": self.consecutive_faults,
            "threshold": self.threshold,
            "faults": self.faults,
            "trips": self.trips,
            "recoveries": self.recoveries,
            "blocked_cycles": self.blocked_cycles,
            "last_recovery_cycles": self.last_recovery_cycles,
            "backoff_s": self._backoff_s,
            "retry_at": self._retry_at,
        }

    def probe_inconclusive(self, now: float) -> None:
        """The admitted probe cycle never actually round-tripped the
        device (work gates sent everything to the CPU preemptor): it
        proved nothing, so re-arm the probe for the next cycle instead
        of leaving HALF_OPEN waiting for an outcome that never comes."""
        if self.state == HALF_OPEN:
            self.state = OPEN
            self._retry_at = now
            # The consumed probe cycle is still part of the outage
            # window last_recovery_cycles reports.
            self.blocked_cycles += 1

    def _open(self, now: float) -> None:
        self.state = OPEN
        self.consecutive_faults = 0
        delay = self._backoff_s * (1.0 + self.jitter * self._rng.random())
        self._retry_at = now + delay
