"""Supervised dispatch: the solver-worker thread that bounds trace/compile.

PR 3's watchdog bounded the COLLECT half of the device round trip (the
``device_get`` wait), but dispatch itself — tracing, compilation, the
argument transfer inside the kernel call — still ran inline on the
scheduler thread, so a device that wedges *during dispatch* (the
``hang`` action at the ``device_dispatch`` fault site, or a real dead
tunnel surfacing inside XLA) froze the scheduler forever. This module
closes that last unbounded path: dispatch runs on a persistent
supervised worker thread, and the scheduler waits for the hand-off with
the same regime-keyed watchdog deadline the collect already uses (the
cold-cycle clamp absorbs legitimate multi-second compiles).

A late dispatch is ABANDONED, exactly like a late collect: Python
cannot cancel a blocked device call, only stop waiting for it, so the
worker is orphaned (a poison pill makes it exit its loop once the
wedged call eventually returns or dies), a fresh worker is spawned
lazily for the next dispatch, ``DispatchTimeout`` propagates to the
scheduler's existing device-fault handler — residency invalidated,
heads requeued, fault fed to the circuit breaker — and the cycle
*completes*.

The worker threads are daemons on purpose: an orphan stuck in a dead
device call must never block interpreter shutdown (a
``ThreadPoolExecutor`` worker would — its atexit hook joins non-daemon
threads).
"""

from __future__ import annotations

import atexit
import queue
import threading
import time
import weakref
from typing import Optional

from kueue_tpu.resilience.watchdog import DispatchTimeout


class SupervisedTimeout(DispatchTimeout):
    """The supervised hand-off missed its deadline (a hang INSIDE the
    dispatch body — trace/compile/transfer). A distinct type from the
    collect-side DispatchTimeout so the scheduler's metrics can
    attribute the timeout to the right half of the round trip."""


# Live workers, drained at interpreter exit: a daemon thread that ran
# device work (XLA holds C++ thread state) must not be torn down while
# parked, or the runtime's teardown can abort with "terminate called
# without an active exception". Parked workers wake on the poison pill
# and join promptly; a genuinely wedged orphan times out and stays a
# daemon (nothing can join a dead device call).
_live_workers: "weakref.WeakSet" = weakref.WeakSet()


@atexit.register
def _drain_workers_at_exit() -> None:
    for w in list(_live_workers):
        w.close(join_timeout=1.0)


class _Request:
    __slots__ = ("fn", "args", "kwargs", "done", "result", "exc")

    def __init__(self, fn, args, kwargs):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.done = threading.Event()
        self.result = None
        self.exc: Optional[BaseException] = None


class SupervisedWorker:
    """A persistent daemon worker thread with a bounded hand-off.

    ``run(fn, *args, deadline_s=...)`` executes ``fn`` on the worker and
    waits at most ``deadline_s`` for it; a miss raises
    ``DispatchTimeout`` and orphans the worker (``orphaned`` counts
    them). The thread is REUSED across calls — spawning per dispatch
    would add thread start-up latency to every cycle; the only time a
    new thread is minted is after an abandonment (or lazily on first
    use). Exceptions raised by ``fn`` (injected faults, XLA errors)
    propagate to the caller unchanged. ``deadline_s=None`` runs ``fn``
    inline — supervision off is zero-thread, zero-cost.

    Single-supervisor contract: one caller thread at a time (the
    scheduler); the worker processes one request at a time.
    """

    def __init__(self, name: str = "supervised-worker"):
        self.name = name
        self._queue: Optional[queue.SimpleQueue] = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.timeouts = 0   # bounded waits that expired
        self.orphaned = 0   # workers abandoned mid-call
        self.calls = 0      # supervised calls handed off
        self._orphans: list = []  # abandoned threads, pruned when dead
        _live_workers.add(self)

    @staticmethod
    def _loop(q: "queue.SimpleQueue") -> None:
        while True:
            req = q.get()
            if req is None:  # poison pill: this worker was abandoned
                return
            try:
                req.result = req.fn(*req.args, **req.kwargs)
            except BaseException as exc:  # noqa: BLE001 — relayed to caller
                req.exc = exc
            req.done.set()

    def _ensure_worker(self) -> "queue.SimpleQueue":
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._queue = queue.SimpleQueue()
                self._thread = threading.Thread(
                    target=self._loop, args=(self._queue,), daemon=True,
                    name=self.name)
                self._thread.start()
            return self._queue

    def run(self, fn, *args, deadline_s: Optional[float] = None, **kwargs):
        """Run ``fn(*args, **kwargs)`` under supervision. Raises
        ``DispatchTimeout`` after ``deadline_s`` seconds; re-raises
        whatever ``fn`` raised otherwise."""
        if deadline_s is None:
            return fn(*args, **kwargs)
        q = self._ensure_worker()
        req = _Request(fn, args, kwargs)
        t0 = time.perf_counter()
        q.put(req)
        self.calls += 1
        if not req.done.wait(timeout=deadline_s):
            self._abandon()
            self.timeouts += 1
            raise SupervisedTimeout(deadline_s, time.perf_counter() - t0)
        if req.exc is not None:
            raise req.exc
        return req.result

    def _abandon(self) -> None:
        """Stop feeding the wedged worker; it exits its loop when (if)
        the stuck call ever returns. The next ``run`` mints a fresh
        worker so it is never queued behind the wedged call. The orphan
        stays tracked so ``close()`` can wait for stragglers at
        interpreter exit (an orphan mid-compile torn down with the
        runtime aborts the process)."""
        with self._lock:
            if self._queue is not None:
                self._queue.put(None)
            if self._thread is not None:
                self._orphans.append(self._thread)
            self._orphans = [t for t in self._orphans if t.is_alive()]
            self._thread = None
            self._queue = None
            self.orphaned += 1

    def stop(self) -> None:
        """Shut the (idle) worker down cleanly. Safe to call repeatedly;
        a worker mid-call drains its request first."""
        self.close(join_timeout=0.0)

    def close(self, join_timeout: float = 1.0) -> None:
        """stop(), then wait up to ``join_timeout`` (per thread) for
        the worker AND any orphans to exit — used at interpreter
        shutdown so no thread is torn down mid-device-call (XLA aborts
        the process if its C++ state unwinds under a live compile). A
        genuinely wedged orphan still times out; nothing can join a
        dead device call."""
        with self._lock:
            thread, self._thread = self._thread, None
            q, self._queue = self._queue, None
            orphans, self._orphans = self._orphans, []
        if q is not None:
            q.put(None)
        if join_timeout > 0:
            for t in ([thread] if thread is not None else []) + orphans:
                t.join(timeout=join_timeout)

    def status(self) -> dict:
        with self._lock:
            alive = self._thread is not None and self._thread.is_alive()
        return {"alive": alive, "calls": self.calls,
                "timeouts": self.timeouts, "orphaned": self.orphaned}
