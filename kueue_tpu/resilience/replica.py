"""Hot-standby replicated control plane (RESILIENCE.md §7).

PR 10 made crash-restart convergent from the durable checkpoint/WAL
log, but a cold restore still pays load + full replay + settle at the
worst possible moment — right after losing the leader at traffic. The
reference leans on k8s leader election plus a full cache rebuild
("etcd is the checkpoint", SURVEY.md §4/§5); we can beat it because
our WAL *is* the watch stream: a follower that continuously replays it
holds a **warm manager** the whole time, so failover costs roughly one
admission cycle, not a restore.

Three cooperating pieces:

- ``StandbyReplica`` — a follower process around its own Store +
  ``KueueManager``. It bootstraps from ``DurableLog.load_with_cursor``
  and then **tails** the leader's WAL (``read_tail`` — the
  rotation-aware cursor from sim/durable.py), applying each record as
  its original watch event through ``Store.apply_replicated``. Queue
  heaps, cache trees and snapshot masters advance through the ordinary
  reconciler paths — the same journal replay ``cache/incremental.py``
  already runs per cycle — and a solver handed to the standby
  pre-warms through the PR-7 persistent compile cache + governor. The
  replica tracks replication lag in records and (virtual) seconds,
  feeds the ``replication_lag_*`` gauges and an AgingWatch trend
  monitor, and falls back to a full re-bootstrap when its cursor drops
  behind the segment retention window (counted, never fatal).

- ``FencingToken`` / ``lead()`` — the leader lease with **fencing
  epochs**, arbitrated by the durable log (the one medium that
  outlives every process). ``lead()`` wires the token into the leader:
  ``Store._persist`` validates it before every WAL append (and the
  append re-checks under the log lock), and the scheduler's
  ``_validate_speculation`` consults it at the speculative commit
  point — so a deposed leader's in-flight cycle aborts un-decoded,
  and even its synchronous admission write raises ``Fenced`` before
  reaching the log. The store's admission records stay the
  exactly-once arbiter; fencing just guarantees a deposed writer can
  never author one.

- ``StandbyReplica.promote()`` — **sub-cycle promotion**: acquire the
  lease (bumping the fencing epoch FIRST, so the drain below reads a
  quiescent tail — a still-twitching old leader is fenced off before
  we stop listening to it), drain the replay tail, settle, attach the
  durable log (the promoted store now journals), take a compaction
  checkpoint (which also truncates any torn crash tail), and adopt
  the restore() posture: first cycle pinned synchronous, breaker and
  ladder at their fresh CLOSED/NORMAL rungs. The whole sequence is
  traced (route ``"promotion"``), counted (``promotions_total``) and
  reported (``/debug/recovery`` standby/promotion sections).
"""

from __future__ import annotations

import time as _time
import uuid
from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.meta import REAL_CLOCK, Clock
from kueue_tpu.resilience.recovery import _KIND_DEFAULT, _KIND_ORDER
from kueue_tpu.sim.durable import DurableLog, Fenced, TailCursor

DEFAULT_LEASE_DURATION_S = 15.0

# AgingWatch monitor defaults for the follower-lag trend source
# (obs/trend.py): sustained growth of >1 unapplied record per poll over
# the window means the follower is falling behind its leader's append
# rate — the replication analogue of ROADMAP item 5's monotone leaks.
LAG_SLOPE_THRESHOLD = 1.0
LAG_WINDOW = 16
LAG_WARMUP = 4


@dataclass
class FencingToken:
    """One replica's claim to a lease at a specific epoch. ``name``
    selects WHICH lease on the log: "" is the whole-plane leader lease
    (the hot-standby mode); an admission shard's token carries its
    shard name, so N shards hold N independent epochs on one durable
    medium (RESILIENCE.md §9). ``valid()`` is the cheap gate the
    scheduler polls; ``check()`` is the raising form the store's
    commit path uses. The token never refreshes its epoch — a deposed
    replica must construct a new one by re-acquiring the lease (and
    will get a HIGHER epoch)."""

    log: DurableLog
    identity: str
    epoch: int
    name: str = ""

    def valid(self) -> bool:
        try:
            self.check()
            return True
        except Fenced:
            return False

    def check(self) -> None:
        self.log.check_epoch(self.identity, self.epoch, self.name)

    def renew(self, now: float) -> bool:
        return self.log.renew_lease(self.identity, now, self.name)

    def release(self) -> None:
        self.log.release_lease(self.identity, self.name)


def lead(mgr, durable: DurableLog, identity: str = "",
         now: Optional[float] = None,
         duration: float = DEFAULT_LEASE_DURATION_S,
         force: bool = False) -> FencingToken:
    """Make ``mgr`` the fenced leader over ``durable``: acquire the
    lease (raising if another holder's lease is live and ``force`` is
    False), then wire the token through every commit gate — the
    store's persist path, the scheduler's speculative-commit
    validation, and the leader gate itself. Returns the token (the
    caller renews it via ``token.renew`` at its cycle cadence)."""
    identity = identity or f"kueue-leader-{uuid.uuid4().hex[:8]}"
    if now is None:
        now = mgr.clock.now()
    epoch = durable.acquire_lease(identity, now=now, duration=duration,
                                  force=force)
    if epoch is None:
        holder = durable.lease_status()["holder"]
        raise RuntimeError(
            f"cannot lead: lease held by {holder!r} and not expired")
    token = FencingToken(durable, identity, epoch)
    mgr.store.fencing = token
    mgr.scheduler.fencing_check = token.valid
    mgr.scheduler.leader_check = token.valid
    if mgr.metrics is not None:
        mgr.metrics.set_fencing_epoch(epoch)
    return token


@dataclass
class PromotionReport:
    """What one ``promote()`` did, for /debug/recovery and the chaos
    harness asserts. Times are wall seconds (the promotion itself is
    host work); the SLO gate on promotion-to-first-admission lives in
    virtual time on the scenario side (SCENARIOS.md failover)."""

    duration_s: float = 0.0
    epoch: int = 0
    drained_records: int = 0      # tail records applied during the drain
    torn_records: int = 0         # incomplete crash-tail records dropped
    resyncs: int = 0              # bootstrap fallbacks over this replica's life
    settle_reconciles: int = 0
    lag_records_at_entry: int = 0  # how far behind the follower was
    warnings: list = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "duration_s": round(self.duration_s, 6),
            "epoch": self.epoch,
            "drained_records": self.drained_records,
            "torn_records": self.torn_records,
            "resyncs": self.resyncs,
            "settle_reconciles": self.settle_reconciles,
            "lag_records_at_entry": self.lag_records_at_entry,
            "warnings": list(self.warnings),
        }


class StandbyReplica:
    """A warm follower of a durable log: its own Store + KueueManager,
    continuously advanced by WAL tail replay, promotable to leadership
    in sub-cycle time. See the module docstring for the contract.

    The replica's manager never runs admission cycles while standby
    (``scheduler.leader_check`` is wired to the promotion state), but
    every watch-driven structure — queue heaps, cache trees, snapshot
    masters, the encode arena's delta feed, a warming solver — stays
    live, which is the entire point."""

    def __init__(self, durable: DurableLog, cfg=None,
                 clock: Clock = REAL_CLOCK, solver=None,
                 identity: str = "",
                 registered_check_controllers: Optional[set] = None,
                 remote_clusters: Optional[dict] = None,
                 lease_duration: float = DEFAULT_LEASE_DURATION_S):
        self.durable = durable
        self.cfg = cfg
        self.clock = clock
        self.identity = identity or f"kueue-standby-{uuid.uuid4().hex[:8]}"
        self.lease_duration = lease_duration
        self._solver = solver
        self._check_controllers = registered_check_controllers
        self._remote_clusters = remote_clusters
        self.promoted = False
        self.polls = 0
        self.applied_records = 0
        self.resyncs = 0
        self.max_lag_records = 0      # worst pre-poll lag observed
        self._last_applied_t = 0.0
        self._token: Optional[FencingToken] = None
        self.last_promotion: Optional[PromotionReport] = None
        self.mgr = None
        self._cursor = TailCursor()
        self._bootstrap()

    # -- construction / resync -----------------------------------------

    def _bootstrap(self) -> None:
        """(Re)build the warm manager from the log's newest recoverable
        state — the follower's cold half, shared in shape with
        recovery.restore(): checkpoint objects in dependency order,
        then the tail as its original event stream, then settle. Runs
        at construction and again whenever the cursor falls behind the
        segment retention window (resync)."""
        from kueue_tpu.manager import KueueManager
        from kueue_tpu.sim import Store

        if self._solver is not None and hasattr(self._solver, "detach"):
            # On a resync rebuild the solver was bound to the discarded
            # manager; fresh construction makes this a no-op.
            self._solver.detach()
        parts, cursor = self.durable.load_with_cursor()
        store = Store(self.clock)
        mgr = KueueManager(
            cfg=self.cfg, clock=self.clock, solver=self._solver,
            registered_check_controllers=self._check_controllers,
            remote_clusters=self._remote_clusters, store=store,
            identity=self.identity)
        kinds = sorted(parts.objects,
                       key=lambda k: (_KIND_ORDER.get(k, _KIND_DEFAULT), k))
        for kind in kinds:
            for obj in parts.objects[kind].values():
                store.load_object(obj)
        for event, _kind, _key, obj, t in parts.records:
            store.apply_replicated(event, obj)
            self._last_applied_t = max(self._last_applied_t, t)
        store._rv = max(store._rv, parts.rv)
        mgr.run_until_idle(max_iterations=1_000_000)
        # The follower must never admit: the leader gate opens only at
        # promotion (and stays honest afterwards via the fencing token).
        mgr.scheduler.leader_check = self._leader_gate
        mgr.scheduler.standby_status = self.status
        # Follower-lag trend source (obs/trend.py): sustained growth of
        # the unapplied-record count is the replication leak signature;
        # the watch is sampled at every poll (the follower's "cycle").
        # The source must go silent at promotion: the cursor freezes
        # there, so a promoted leader's own appends would otherwise
        # read as unapplied "lag" and trip the soak gate as a leak.
        mgr.aging_watch.add(
            "replication_lag_records",
            lambda: 0.0 if self.promoted
            else float(self.lag_records or 0),
            slope_threshold=LAG_SLOPE_THRESHOLD,
            window=LAG_WINDOW, warmup=LAG_WARMUP)
        self._cursor = cursor
        self.applied_records += len(parts.records)
        self.mgr = mgr
        self._publish_lag()

    def _leader_gate(self) -> bool:
        if not self.promoted:
            return False
        return self._token is None or self._token.valid()

    # -- the follow loop -----------------------------------------------

    @property
    def lag_records(self) -> Optional[int]:
        """Records appended to the log this replica has not yet
        applied. None while a resync is pending (lag unknowable
        incrementally)."""
        return self.durable.records_ahead(self._cursor)

    @property
    def lag_seconds(self) -> float:
        """Virtual seconds between the newest record on the log and
        the newest this replica applied — 0 when fully caught up."""
        return max(0.0, self.durable.last_append_t - self._last_applied_t)

    def poll(self, max_records: int = 0) -> int:
        """Apply every record appended since the last poll (bounded by
        ``max_records`` if nonzero) and settle the reconcilers.
        Returns the number of records applied. A cursor past the
        retention window triggers a counted full re-bootstrap.

        Cost contract: ONE tail read per poll, O(new records) — the
        pre-poll lag observation comes from the batch itself instead
        of a separate scan, and a scan only happens when a bounded
        batch filled up (the leftover is otherwise zero by
        construction)."""
        if self.promoted:
            return 0
        batch = self.durable.read_tail(self._cursor, max_records)
        if batch.resync:
            self.resyncs += 1
            self._bootstrap()
            self.polls += 1
            return 0
        for event, _kind, _key, obj, t in batch.records:
            self.mgr.store.apply_replicated(event, obj)
            self._last_applied_t = max(self._last_applied_t, t)
        self._cursor = batch.cursor
        if batch.records:
            self.mgr.run_until_idle(max_iterations=1_000_000)
            self.applied_records += len(batch.records)
        leftover = 0
        if max_records and len(batch.records) >= max_records:
            leftover = self.durable.records_ahead(self._cursor) or 0
        behind = len(batch.records) + leftover
        if behind > self.max_lag_records:
            self.max_lag_records = behind
        self.polls += 1
        self._publish_lag(leftover)
        self.mgr.aging_watch.sample()
        return len(batch.records)

    def _publish_lag(self, lag: int = 0) -> None:
        if self.mgr is None or self.mgr.metrics is None:
            return
        self.mgr.metrics.replication_lag(lag, self.lag_seconds)
        self.mgr.metrics.set_fencing_epoch(self.durable.fencing_epoch)

    # -- promotion -----------------------------------------------------

    def promote(self, now: Optional[float] = None, force: bool = False,
                checkpoint_after: bool = True):
        """Become the leader in sub-cycle time. Sequence (order is the
        correctness argument): (1) acquire the lease, bumping the
        fencing epoch — from this instant the old leader's appends
        raise ``Fenced``, so (2) draining the replay tail reads a
        quiescent stream; (3) settle the reconcilers; (4) attach the
        durable log (this store now journals) and checkpoint, which
        also truncates any torn crash tail the drain parked before;
        (5) adopt the restore() posture — first cycle pinned
        synchronous, breaker/ladder already at their fresh rungs —
        and open the leader gate. Returns the (now leading) manager.

        ``force`` skips lease-expiry arbitration — the harness's "the
        leader is known dead" path; without it, promotion of a live
        leader's lease raises."""
        if self.promoted:
            return self.mgr
        t0 = _time.perf_counter()
        if now is None:
            now = self.clock.now()
        report = PromotionReport(
            lag_records_at_entry=self.lag_records or 0)
        epoch = self.durable.acquire_lease(
            self.identity, now=now, duration=self.lease_duration,
            force=force)
        if epoch is None:
            holder = self.durable.lease_status()["holder"]
            raise RuntimeError(
                f"cannot promote: lease held by {holder!r} and not "
                f"expired (pass force=True only when the leader is "
                f"known dead)")
        report.epoch = epoch

        t_drain = _time.perf_counter()
        while True:
            batch = self.durable.read_tail(self._cursor)
            if batch.resync:
                self.resyncs += 1
                self._bootstrap()
                continue
            for event, _kind, _key, obj, t in batch.records:
                self.mgr.store.apply_replicated(event, obj)
                self._last_applied_t = max(self._last_applied_t, t)
            self._cursor = batch.cursor
            self.applied_records += len(batch.records)
            report.drained_records += len(batch.records)
            if not batch.records:
                break
        drain_s = _time.perf_counter() - t_drain
        # The drain parks before an incomplete trailing record — a torn
        # crash tail from the dead leader's final append. Count it; the
        # checkpoint below truncates it (same fallback load() applies).
        if (self._cursor.generation == self.durable.generation
                and self.durable.wal_size() > self._cursor.offset):
            report.torn_records += 1
            report.warnings.append(
                "torn WAL tail record dropped at promotion (leader "
                "crashed mid-append); recovered to the last intact "
                "record")
        # The trace opens AFTER the drain: a resync mid-drain rebuilds
        # self.mgr, and the promotion spans must land on the recorder
        # the promoted manager actually serves.
        mgr = self.mgr
        rec = mgr.flight_recorder
        trace = rec.begin_cycle(mgr.scheduler.attempt_count)
        rec.span("promotion.drain", t_drain, drain_s)

        t_settle = _time.perf_counter()
        report.settle_reconciles = mgr.run_until_idle(
            max_iterations=1_000_000)
        rec.span("promotion.settle", t_settle,
                 _time.perf_counter() - t_settle)

        token = FencingToken(self.durable, self.identity, epoch)
        self._token = token
        mgr.store.fencing = token
        mgr.scheduler.fencing_check = token.valid
        mgr.store.attach_durable(self.durable)
        mgr.durable = self.durable
        if checkpoint_after:
            mgr.store.checkpoint_now()
        # Conservative takeover posture, exactly like restore(): the
        # first cycle runs synchronously — never a speculative dispatch
        # against a cache that finished catching up milliseconds ago.
        mgr.scheduler._pipeline_cooldown = max(
            mgr.scheduler._pipeline_cooldown, 1)
        self.promoted = True
        report.resyncs = self.resyncs
        report.duration_s = _time.perf_counter() - t0
        self.last_promotion = report
        mgr.last_promotion = report
        mgr.scheduler.last_promotion = report.to_dict()
        if mgr.metrics is not None:
            mgr.metrics.replica_promoted(epoch, report.duration_s)
            mgr.metrics.replication_lag(0, 0.0)
        if trace is not None:
            trace.route = "promotion"
            trace.heads = 0
            rec.annotate(
                "promotion",
                f"standby {self.identity} promoted at fencing epoch "
                f"{epoch}: {report.drained_records} tail record(s) "
                f"drained, torn={report.torn_records}",
                **{k: v for k, v in report.to_dict().items()
                   if k != "warnings"})
            rec.finish(trace)
        mgr.recorder.system_event(
            "Warning" if report.torn_records else "Normal", "Promoted",
            f"standby promoted to leader in "
            f"{report.duration_s * 1e3:.1f}ms (epoch {epoch}, "
            f"{report.drained_records} record(s) drained)")
        return mgr

    # -- operator surface ----------------------------------------------

    def status(self) -> dict:
        """The single producer /debug/recovery's standby section,
        tools/failover_probe.py and the tests share."""
        lag = self.lag_records
        return {
            "identity": self.identity,
            "role": "leader" if self.promoted else "standby",
            "promoted": self.promoted,
            "polls": self.polls,
            "applied_records": self.applied_records,
            "resyncs": self.resyncs,
            "lag_records": lag,
            "lag_seconds": round(self.lag_seconds, 6),
            "max_lag_records": self.max_lag_records,
            "cursor": {"generation": self._cursor.generation,
                       "offset": self._cursor.offset},
            "fencing_epoch": self.durable.fencing_epoch,
            "lease": self.durable.lease_status(),
            "last_promotion": (self.last_promotion.to_dict()
                               if self.last_promotion else None),
        }
