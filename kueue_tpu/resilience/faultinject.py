"""Seedable fault injection for the solver's device path.

Named injection sites wrap the places a flaky or vanished accelerator
can hurt the admission cycle (see RESILIENCE.md):

- ``device_dispatch``  — kernel dispatch (BatchSolver.dispatch)
- ``device_collect``   — the in-flight result fetch (BatchSolver.collect)
- ``arena_scatter``    — the encode arena's changed-row device scatter
- ``journal_replay``   — the solver's residency journal reconcile
- ``speculation_validate`` — the pipelined apply step's generation-token
  check (a raise forces a mis-speculation abort, PIPELINE.md)
- ``compile_warmup``   — the compile governor's per-bucket warm body
  (solver/COMPILE.md; a DELAY here is a wedged remote compile — the
  governor's per-bucket deadline abandons the bucket and the ladder
  continues, never wedging startup)
- ``store_write``      — the sim store's commit point, AFTER the WAL
  append and BEFORE the watch-event notify (sim/durable.py): a crash
  here is the "durable but unobserved" window — the write survives
  restart even though no live component ever saw it
- ``apply_commit``     — the scheduler's admission write, AFTER the
  cache assumption and BEFORE the store write: a crash here loses the
  in-memory assumption while the store still says pending — the
  workload must requeue on restore, never double-admit

Each site can, per a deterministic scripted schedule, RAISE (a dead
tunnel / XLA error), DELAY (a wedged ``device_get`` — the watchdog's
regime), CORRUPT the payload passing through it, or CRASH — simulate
process death mid-cycle by raising ``InjectedCrash``, which subclasses
``BaseException`` so NO containment layer (the scheduler's fallback
``except Exception`` handlers, the breaker, the supervisor) can absorb
it; it propagates to the top of the driving loop, where the
crash-restart harness (resilience/recovery.py, tools/crash_run.py)
discards the dead manager and restores from the durable store.
Corruption is
applied by the call site's own ``corrupt=`` callable, so every site
scrambles exactly the data that crosses it; the containment contract
(which corruptions the system must detect vs. deny conservatively) is
documented per site in RESILIENCE.md.

The default is OFF at zero cost: every hook is a module-level
``site(...)`` call that returns immediately while no injector is
installed (one global ``is None`` check — the ``device_fault_recovery``
bench row pins the disabled-path overhead at <1% of a cycle).

Schedules are deterministic. ``FaultInjector({site: {hit: action}})``
fires ``action`` on the hit-th time the site is reached (0-based);
``FaultInjector.scripted(seed, ...)`` derives such a schedule from a
seeded RNG so randomized chaos runs are exactly reproducible.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

SITE_DISPATCH = "device_dispatch"
SITE_COLLECT = "device_collect"
SITE_SCATTER = "arena_scatter"
SITE_REPLAY = "journal_replay"
# Speculative-pipeline validation (scheduler._process_inflight): a RAISE
# here is a FORCED MIS-SPECULATION — the abort path must fall back to
# the synchronous cycle with no double admission. Last in SITES so
# seeded scripted() schedules for the original four sites are unchanged.
SITE_SPECULATION = "speculation_validate"
# Compile-governor warmup (solver/warmgov.py): fires once per bucket
# warm attempt, OFF the scheduler thread — a fault here must only cost
# that bucket, never a cycle. Appended after SITE_SPECULATION so seeded
# scripted() schedules for the earlier sites are unchanged.
SITE_WARMUP = "compile_warmup"
# Crash-restart sites (RESILIENCE.md §6). Appended last so seeded
# scripted() schedules for the earlier sites are unchanged; scripted()
# defaults them to rate 0 (a crash ends the run — the kill-point sweep
# schedules them explicitly, one seeded (site, hit) per run).
SITE_STORE = "store_write"
SITE_APPLY = "apply_commit"
SITES = (SITE_DISPATCH, SITE_COLLECT, SITE_SCATTER, SITE_REPLAY,
         SITE_SPECULATION, SITE_WARMUP, SITE_STORE, SITE_APPLY)

RAISE = "raise"
DELAY = "delay"
CORRUPT = "corrupt"
# Simulated process death: raises InjectedCrash (a BaseException) that
# no fallback/containment layer may catch — valid at EVERY site.
CRASH = "crash"
ACTIONS = (RAISE, DELAY, CORRUPT, CRASH)


class DeviceFault(RuntimeError):
    """A contained device-path failure: dispatch/collect raised, the
    watchdog timed out, or output validation caught corruption. The
    scheduler feeds these to the circuit breaker; host-side encode bugs
    deliberately do NOT subclass this."""


class InjectedFault(DeviceFault):
    """Raised by a ``raise`` action at an injection site."""

    def __init__(self, site: str, hit: int):
        super().__init__(f"injected fault at {site} (hit {hit})")
        self.site = site
        self.hit = hit


class InjectedCrash(BaseException):
    """Simulated process death at an injection site. Deliberately a
    BaseException (like KeyboardInterrupt): every ``except Exception``
    containment layer on the way up — solver fallbacks, the breaker
    feed, admission error wrapping — must let it through, because a
    real SIGKILL gives none of them a turn. Only the crash-restart
    harness at the very top of the driving loop catches it, throws the
    manager away, and restores from the durable store."""

    def __init__(self, site: str, hit: int):
        super().__init__(f"injected crash at {site} (hit {hit})")
        self.site = site
        self.hit = hit


class FaultInjector:
    """A scripted schedule of faults, keyed (site, hit index).

    ``schedule``: {site: {hit_index: action}} where action is ``RAISE``,
    ``CORRUPT``, or ``(DELAY, seconds)``. Hit indices are 0-based per
    site and count every time the site is reached while this injector
    is installed.
    """

    def __init__(self, schedule: Optional[dict] = None):
        self.schedule: dict = {}
        for site, hits in (schedule or {}).items():
            if site not in SITES:
                raise ValueError(f"unknown injection site {site!r}")
            self.schedule[site] = dict(hits)
        self._lock = threading.Lock()
        self.hits: dict = {s: 0 for s in SITES}     # site -> times reached
        self.fired: dict = {s: 0 for s in SITES}    # site -> faults fired
        self.log: list = []                          # (site, hit, action)

    @classmethod
    def scripted(cls, seed: int, horizon: int = 64,
                 rates: Optional[dict] = None,
                 delay_s: float = 0.0) -> "FaultInjector":
        """A reproducible randomized schedule: for each site, each of
        the first ``horizon`` hits independently faults with the site's
        rate (default 0.2). Which action fires is drawn from the
        actions valid at that site (DELAY only where a deadline can
        catch it, CORRUPT only where a payload crosses). Same seed =>
        same schedule, regardless of execution interleaving."""
        rng = random.Random(seed)
        valid = {
            SITE_DISPATCH: (RAISE, (DELAY, delay_s)) if delay_s else (RAISE,),
            SITE_COLLECT: ((RAISE, CORRUPT, (DELAY, delay_s)) if delay_s
                           else (RAISE, CORRUPT)),
            SITE_SCATTER: (RAISE, CORRUPT),
            SITE_REPLAY: (RAISE,),
            SITE_SPECULATION: (RAISE,),  # forced mis-speculation
            # a wedged warmup compile (DELAY) is the governor's own
            # deadline's regime; RAISE is a backend error mid-warm
            SITE_WARMUP: (RAISE, (DELAY, delay_s)) if delay_s else (RAISE,),
            # crash-only sites: a crash ends the run, so scripted
            # schedules default them OFF (rate 0 below) — the kill-point
            # sweep installs explicit {site: {hit: CRASH}} schedules
            SITE_STORE: (CRASH,),
            SITE_APPLY: (CRASH,),
        }
        default_rate = {SITE_STORE: 0.0, SITE_APPLY: 0.0}
        schedule: dict = {}
        for site in SITES:
            rate = (rates or {}).get(site, default_rate.get(site, 0.2))
            hits = {}
            for i in range(horizon):
                if rng.random() < rate:
                    hits[i] = rng.choice(valid[site])
            if hits:
                schedule[site] = hits
        return cls(schedule)

    def _next(self, site: str):
        with self._lock:
            hit = self.hits[site]
            self.hits[site] = hit + 1
            action = self.schedule.get(site, {}).get(hit)
            if action is not None:
                self.fired[site] += 1
                self.log.append((site, hit, action))
            return hit, action

    @property
    def total_fired(self) -> int:
        return sum(self.fired.values())


# The one global the disabled path reads; module attribute access is
# the entire per-site cost when no injector is installed.
_active: Optional[FaultInjector] = None

# Scoped arming (RESILIENCE.md §9): co-resident managers — N admission
# shards in one process — each arm their OWN injector under a scope
# name, entered for the duration of that manager's cycle via the
# ``scope(...)`` context manager. Inside a scope, ONLY that scope's
# injector fires (a shard sweep killing shard 2 must not consume shard
# 0's scripted schedule); outside any scope only the module-global
# injector fires — the pre-shard contract, unchanged. The current
# scope is thread-local: shard cycles on different threads never see
# each other's arming.
_scoped: dict = {}
_scope_local = threading.local()


def install(injector: FaultInjector,
            scope: Optional[str] = None) -> FaultInjector:
    global _active
    if scope is not None:
        _scoped[scope] = injector
        return injector
    _active = injector
    return injector


def uninstall(scope: Optional[str] = None) -> None:
    global _active
    if scope is not None:
        _scoped.pop(scope, None)
        return
    _active = None


def active(scope: Optional[str] = None) -> Optional[FaultInjector]:
    if scope is not None:
        return _scoped.get(scope)
    return _active


def current_scope() -> Optional[str]:
    return getattr(_scope_local, "name", None)


class installed:
    """Context manager: install an injector for the block's duration
    (module-global by default, or under ``scope``)."""

    def __init__(self, injector: FaultInjector,
                 scope: Optional[str] = None):
        self.injector = injector
        self.scope = scope

    def __enter__(self) -> FaultInjector:
        return install(self.injector, scope=self.scope)

    def __exit__(self, *exc) -> None:
        uninstall(scope=self.scope)


class scope:
    """Context manager: attribute every ``site()`` hit on this thread
    to ``name``'s scoped injector for the block's duration. With no
    injector armed under ``name`` the sites are inert inside the block
    — the module-global injector does NOT leak in, which is the
    isolation property the shard sweep relies on. Re-entrant nesting
    restores the outer scope on exit."""

    def __init__(self, name: str):
        self.name = name
        self._outer: Optional[str] = None

    def __enter__(self) -> "scope":
        self._outer = getattr(_scope_local, "name", None)
        _scope_local.name = self.name
        return self

    def __exit__(self, *exc) -> None:
        _scope_local.name = self._outer


def site(name: str, payload=None,
         corrupt: Optional[Callable] = None):
    """The injection hook. Returns ``payload`` (possibly corrupted).

    With no injector installed this is a single global load + compare —
    the zero-cost default. With one installed: a RAISE action raises
    InjectedFault, ``(DELAY, s)`` sleeps ``s`` (simulating a wedged
    device call — the watchdog deadline is expected to fire), CORRUPT
    returns ``corrupt(payload)`` (or the payload untouched when the
    call site passed no corruptor — e.g. raise-only sites). Inside a
    ``scope(...)`` block the hit resolves against that scope's
    injector alone; outside, against the module-global one."""
    cur = getattr(_scope_local, "name", None)
    inj = _scoped.get(cur) if cur is not None else _active
    if inj is None:
        return payload
    hit, action = inj._next(name)
    if action is None:
        return payload
    if action == RAISE:
        raise InjectedFault(name, hit)
    if action == CRASH:
        raise InjectedCrash(name, hit)
    if action == CORRUPT:
        return corrupt(payload) if corrupt is not None else payload
    kind, seconds = action
    if kind != DELAY:
        raise ValueError(f"unknown injected action {action!r}")
    time.sleep(seconds)
    return payload
