"""Graceful load shedding: the cycle-budget degradation ladder.

The paper's north-star metric is admission-cycle p50/p99 at 50k pending
x 2k CQs x 32 flavors. Containment (watchdog, breaker, supervised
dispatch) bounds the cycle when the DEVICE fails — this module bounds
it when the LOAD exceeds what the configured cycle budget allows. The
scheduler feeds every cycle's wall seconds (the same spend its flight-
recorder trace records) plus a backlog-pressure proxy into a small
state machine:

    normal --overloaded x escalate_after--> shed --again--> survival
      ^                                       |                |
      +------- healthy x recovery_cycles -----+----------------+

- **normal**: no intervention; the ladder is one EWMA update + two
  compares per cycle (the ``overload_shed`` bench row pins the idle
  cost at <=1% of a cycle).
- **shed**: the scheduler caps the cycle's nominate heads at
  ``shed_heads`` (extras re-heap untouched — no status patches) and
  DEFERS preempt planning (target selection is the superlinear part of
  a preempt-heavy cycle; deferred preemptors keep their reserve-
  capacity semantics and retry when the ladder recovers).
- **survival**: everything shed does, with the head cap tightened to
  ``survival_heads`` (top-k by queue order) and the cycle pinned to the
  CPU-incremental route (``cpu-survival`` — the sequential path over
  the journal-replay snapshot: full reference semantics, no device
  sync, no compile risk; excluded from the adaptive router's samples
  like every other intervention route).

Overload is detected from cycle-time EWMA against the budget, with a
raw-cycle + backlog-growth trigger so a sudden storm escalates before
the EWMA catches up. Hysteresis: the ladder degrades at
``budget x enter_factor`` but only starts recovering below
``budget x exit_factor`` (exit < enter), and each rung-down requires
``recovery_cycles`` CONSECUTIVE healthy cycles — a borderline load
cannot flap the ladder every cycle. ``budget_s == 0`` disables the
ladder entirely (one compare per cycle).

Time comes from the scheduler's measurements, not a clock read here,
so tests drive the ladder with synthetic durations.
"""

from __future__ import annotations

from typing import Optional

NORMAL = "normal"
SHED = "shed"
SURVIVAL = "survival"
STATES = (NORMAL, SHED, SURVIVAL)

# degraded_state gauge encoding — the single source; metrics.py
# imports it as DEGRADED_STATE_CODES
STATE_CODES = {NORMAL: 0, SHED: 1, SURVIVAL: 2}

DEFAULT_SHED_HEADS = 256
DEFAULT_SURVIVAL_HEADS = 64
DEFAULT_ENTER_FACTOR = 1.0
DEFAULT_EXIT_FACTOR = 0.7
DEFAULT_ESCALATE_AFTER = 2
DEFAULT_RECOVERY_CYCLES = 3
DEFAULT_EWMA_ALPHA = 0.3


class DegradationLadder:
    def __init__(self, budget_s: float = 0.0,
                 shed_heads: int = DEFAULT_SHED_HEADS,
                 survival_heads: int = DEFAULT_SURVIVAL_HEADS,
                 enter_factor: float = DEFAULT_ENTER_FACTOR,
                 exit_factor: float = DEFAULT_EXIT_FACTOR,
                 escalate_after: int = DEFAULT_ESCALATE_AFTER,
                 recovery_cycles: int = DEFAULT_RECOVERY_CYCLES,
                 ewma_alpha: float = DEFAULT_EWMA_ALPHA):
        if budget_s < 0:
            raise ValueError("cycle budget must be >= 0 (0 disables)")
        if shed_heads < 1 or survival_heads < 1:
            raise ValueError("shed/survival head caps must be >= 1")
        if not 0 < exit_factor <= enter_factor:
            raise ValueError("need 0 < exit_factor <= enter_factor "
                             "(hysteresis band)")
        if escalate_after < 1 or recovery_cycles < 1:
            raise ValueError("escalate_after and recovery_cycles "
                             "must be >= 1")
        if not 0 < ewma_alpha <= 1:
            raise ValueError("ewma_alpha must be in (0, 1]")
        self.budget_s = budget_s
        self.shed_heads = shed_heads
        self.survival_heads = survival_heads
        self.enter_factor = enter_factor
        self.exit_factor = exit_factor
        self.escalate_after = escalate_after
        self.recovery_cycles = recovery_cycles
        self.ewma_alpha = ewma_alpha
        self.state = NORMAL
        self.ewma_s: Optional[float] = None
        self._over = 0       # consecutive overloaded cycles at this rung
        self._healthy = 0    # consecutive healthy cycles at this rung
        self._last_backlog: Optional[int] = None
        # Counters for /debug/degrade and the metrics feed.
        self.cycles_observed = 0
        self.cycles_shed = 0       # cycles that RAN in shed or survival
        self.escalations = 0       # rung-up transitions
        self.recoveries = 0        # rung-down transitions
        self.idle_cycles = 0       # idle ticks fed while degraded
        self.last_transition: Optional[str] = None  # "a->b"

    @property
    def enabled(self) -> bool:
        return self.budget_s > 0

    def head_cap(self) -> Optional[int]:
        """Max heads this cycle may nominate (None = uncapped)."""
        if self.state == SHED:
            return self.shed_heads
        if self.state == SURVIVAL:
            return self.survival_heads
        return None

    @property
    def defer_preemption(self) -> bool:
        """Shed and survival both skip preempt target selection."""
        return self.state != NORMAL

    @property
    def pin_cpu(self) -> bool:
        """Survival pins the CPU-incremental route."""
        return self.state == SURVIVAL

    @property
    def allow_pipeline(self) -> bool:
        """Speculative pipelining under degradation (ISSUE 6): shed
        keeps it — BOUNDED, because the head cap ran before routing and
        the scheduler bails any cycle that needs preempt planning back
        to the sync path — while survival (which pins the CPU route
        anyway) must drain the in-flight queue, not grow it. Before the
        speculative pipeline, ANY degraded rung was a hard pipeline
        gate, which threw away the device overlap exactly when cycle
        time mattered most."""
        return self.state != SURVIVAL

    def observe_idle(self) -> bool:
        """An idle scheduler tick (no heads popped). A degraded ladder
        with an empty queue used to hold its rung until traffic resumed
        — observe_cycle only ran for cycles that popped heads — so a
        storm's last shed cycle pinned the cap onto the NEXT burst.
        Idle ticks count toward the healthy-cycle streak (there is no
        cycle time to EWMA, and an empty queue means no backlog
        growth); returns True when the ladder rung down."""
        if self.budget_s <= 0 or self.state == NORMAL:
            return False
        self.idle_cycles += 1
        self._over = 0
        self._healthy += 1
        self._last_backlog = 0
        # The storm's EWMA is stale the moment the queue is empty: left
        # in place, the first (healthy) cycles after traffic resumes
        # would inherit it and spuriously re-escalate. No cycle ran, so
        # there is no cycle-time signal — drop the estimate.
        self.ewma_s = None
        if self._healthy >= self.recovery_cycles:
            self._move(NORMAL if self.state == SHED else SHED)
            self.recoveries += 1
            self._healthy = 0
            return True
        return False

    def observe_cycle(self, duration_s: float,
                      backlog: Optional[int] = None) -> bool:
        """Feed one completed cycle's wall seconds and (optionally) the
        cycle's backlog pressure — the caller's cheap proxy for pending
        demand (the scheduler passes heads popped minus admissions).
        Returns True when the ladder changed state; the caller reads the
        new rung from ``self.state``."""
        if self.budget_s <= 0:
            return False
        self.cycles_observed += 1
        if self.state != NORMAL:
            self.cycles_shed += 1
        e = self.ewma_s
        self.ewma_s = (duration_s if e is None
                       else e + self.ewma_alpha * (duration_s - e))
        growing = (backlog is not None and self._last_backlog is not None
                   and backlog > self._last_backlog)
        self._last_backlog = backlog
        # Overload: the smoothed cycle time blew the budget, OR this raw
        # cycle did while demand is still growing (storm onset — don't
        # wait for the EWMA to catch up).
        overloaded = (self.ewma_s > self.budget_s * self.enter_factor
                      or (duration_s > self.budget_s and growing))
        healthy = (self.ewma_s <= self.budget_s * self.exit_factor
                   and not growing)
        if overloaded:
            self._healthy = 0
            self._over += 1
            if self._over >= self.escalate_after and self.state != SURVIVAL:
                self._move(SHED if self.state == NORMAL else SURVIVAL)
                self.escalations += 1
                self._over = 0
                return True
        elif healthy:
            self._over = 0
            self._healthy += 1
            if self._healthy >= self.recovery_cycles and self.state != NORMAL:
                self._move(NORMAL if self.state == SHED else SHED)
                self.recoveries += 1
                self._healthy = 0
                return True
        else:
            # Hysteresis band (between exit and enter): hold the rung,
            # reset both streaks — neither escalation nor recovery may
            # accumulate across a borderline stretch.
            self._over = 0
            self._healthy = 0
        return False

    def _move(self, to: str) -> None:
        self.last_transition = f"{self.state}->{to}"
        self.state = to

    def status(self) -> dict:
        """Structured snapshot for /debug/degrade, the SIGUSR2 dumper,
        and flight-recorder reconciliation (same producer for all)."""
        return {
            "state": self.state,
            "enabled": self.enabled,
            "budget_ms": round(self.budget_s * 1e3, 3),
            "ewma_ms": (round(self.ewma_s * 1e3, 3)
                        if self.ewma_s is not None else None),
            "shed_heads": self.shed_heads,
            "survival_heads": self.survival_heads,
            "enter_factor": self.enter_factor,
            "exit_factor": self.exit_factor,
            "escalate_after": self.escalate_after,
            "recovery_cycles": self.recovery_cycles,
            "consecutive_overloaded": self._over,
            "consecutive_healthy": self._healthy,
            "last_backlog": self._last_backlog,
            "cycles_observed": self.cycles_observed,
            "cycles_shed": self.cycles_shed,
            "escalations": self.escalations,
            "recoveries": self.recoveries,
            "idle_cycles": self.idle_cycles,
            "allow_pipeline": self.allow_pipeline,
            "last_transition": self.last_transition,
        }
