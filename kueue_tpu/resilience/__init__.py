"""Device-fault containment and graceful degradation for the solver
hot path (RESILIENCE.md).

Five cooperating pieces:

- faultinject: seedable, scripted fault injection at named sites
  wrapping device dispatch, in-flight collect, the resident-arena
  scatter and the solver's journal replay — zero-cost when disabled.
- watchdog: per-dispatch deadlines derived from the router's
  regime-keyed rate estimates x a safety factor; a timed-out collect
  abandons the in-flight result instead of blocking the cycle forever.
- supervisor: dispatch (trace/compile/transfer) runs on a persistent
  supervised worker under the same watchdog deadline; a hang during
  dispatch is abandoned instead of freezing the scheduler.
- breaker: a circuit breaker fed by watchdog timeouts and dispatch
  exceptions; N consecutive faults pin cycles to the CPU fallback
  (route "cpu-breaker") until a half-open probe with exponential
  backoff + jitter re-admits the device path.
- degrade: the cycle-budget degradation ladder (normal -> shed ->
  survival) — bounds the cycle when the LOAD, not the device, exceeds
  what the configured budget allows.
"""

from kueue_tpu.resilience.breaker import (  # noqa: F401
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
)
from kueue_tpu.resilience.degrade import (  # noqa: F401
    NORMAL,
    SHED,
    SURVIVAL,
    DegradationLadder,
)
from kueue_tpu.resilience.faultinject import (  # noqa: F401
    DeviceFault,
    FaultInjector,
    InjectedCrash,
    InjectedFault,
    SITE_APPLY,
    SITE_COLLECT,
    SITE_DISPATCH,
    SITE_REPLAY,
    SITE_SCATTER,
    SITE_STORE,
    SITES,
)
from kueue_tpu.resilience.replica import (  # noqa: F401
    FencingToken,
    PromotionReport,
    StandbyReplica,
    lead,
)
from kueue_tpu.resilience.supervisor import (  # noqa: F401
    SupervisedTimeout,
    SupervisedWorker,
)
from kueue_tpu.resilience.watchdog import (  # noqa: F401
    DispatchTimeout,
    DispatchWatchdog,
)
