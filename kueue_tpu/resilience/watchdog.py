"""Dispatch watchdog: deadlines for the device round trip.

Round 5 lost its whole measurement window to a dead accelerator tunnel
that surfaced as an indefinitely blocked ``device_get``. The watchdog
bounds that wait: every dispatched cycle carries a deadline derived
from what a device cycle ACTUALLY costs here — the adaptive router's
regime-keyed rate samples (median observed device cycle seconds for the
predicted regime), falling back to the solver's measured sync floor —
times a configurable safety factor. A collect that misses its deadline
raises ``DispatchTimeout`` (a ``DeviceFault``): the scheduler abandons
the in-flight result, invalidates device-resident state (host mirrors
are the truth; the device twin is a cache), requeues the heads, and
records the fault with the circuit breaker.

The floor ``min_deadline_s`` keeps an optimistic estimate (a warm
sub-millisecond local backend) from turning scheduler GC pauses into
false timeouts; estimates are cycle-scale (~100 ms over a TPU tunnel),
so the default factor gives seconds of headroom while still catching a
wedged tunnel ~3 orders of magnitude before a human would.
"""

from __future__ import annotations

from typing import Optional

from kueue_tpu.resilience.faultinject import DeviceFault


class DispatchTimeout(DeviceFault):
    """The in-flight collect missed its deadline; the result was
    abandoned (the fetch thread is orphaned — Python cannot cancel a
    blocked device call, only stop waiting for it)."""

    def __init__(self, deadline_s: float, waited_s: float):
        super().__init__(
            f"dispatch collect exceeded its {deadline_s * 1e3:.0f}ms "
            f"deadline (waited {waited_s * 1e3:.0f}ms)")
        self.deadline_s = deadline_s
        self.waited_s = waited_s


class DispatchWatchdog:
    def __init__(self, safety_factor: float = 20.0,
                 min_deadline_s: float = 1.0,
                 max_deadline_s: float = 30.0):
        if safety_factor <= 0 or min_deadline_s <= 0:
            raise ValueError("watchdog factor and floor must be positive")
        self.safety_factor = safety_factor
        self.min_deadline_s = min_deadline_s
        self.max_deadline_s = max_deadline_s

    def deadline_s(self, estimate_s: Optional[float]) -> float:
        """Deadline for one dispatch+collect, given the best available
        estimate of a healthy device cycle's wall seconds (None when no
        sample exists yet — first cycles get the max: a cold cycle may
        legitimately carry a multi-second remote compile)."""
        if estimate_s is None or estimate_s <= 0:
            return self.max_deadline_s
        d = estimate_s * self.safety_factor
        return min(max(d, self.min_deadline_s), self.max_deadline_s)
