"""Crash-restart recovery: rebuild the control plane from the durable
store (RESILIENCE.md §6).

The reference's fault-tolerance story is structural — *etcd is the
checkpoint, restart is cheap* (SURVEY.md §5): nothing the process
holds in memory is authoritative, so recovery is "replay the store".
This module is that replay for the reproduction, over the sim Store's
checkpoint/WAL surface (``kueue_tpu/sim/durable.py``):

1. **Load** the newest recoverable state (checkpoint + intact WAL
   tail; a torn final record falls back with a counted warning).
2. **Rebuild** a fresh ``KueueManager`` around an empty store, then
   feed every recovered object through ``Store.load_object`` in
   dependency order — the ADDED watch events drive the SAME
   reconcilers that built the original caches, so queue heaps, cache
   trees and snapshot masters rebuild through the existing full-
   rebuild path, not a parallel one.
3. **Reset derived accelerator state**: a reused solver is
   ``detach()``-ed first (device residency, encode arena, topology
   cache and cache/queue bindings dropped — its jit caches and the
   persistent XLA compilation cache are the restart-is-cheap
   carry-over, re-warmed lazily through the PR-7 compile governor).
   Breaker and ladder start at their conservative fresh rungs (CLOSED
   / NORMAL with zero history) and the first post-restore cycle runs
   synchronously (pipeline cooldown) — never a speculative dispatch
   against a just-rebuilt cache.
4. **Resolve in-flight speculation by the store's admission records**:
   a cycle that was dispatched but never applied left NO trace in the
   store, so its workloads come back pending and simply requeue; a
   cycle that applied (the store write committed) comes back admitted.
   Either way the durable truth is the arbiter — never a double
   admission, never a stranded workload.

The recovery run is traced (route ``"recovery"`` with load/replay/
settle spans in the flight recorder), counted
(``restarts_total`` / ``recovery_seconds``), and reported
(``/debug/recovery`` + ``KueueManager.last_recovery``).
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.meta import REAL_CLOCK, Clock

# Dependency order for the replay: capacity objects before the queues
# that reference them, workloads last so every LocalQueue/ClusterQueue
# exists when the workload reconciler routes them. Unknown kinds land
# between the capacity plane and the workloads.
_KIND_ORDER = {
    "Namespace": 0, "LimitRange": 1, "ResourceFlavor": 2, "Cohort": 3,
    "AdmissionCheck": 4, "MultiKueueConfig": 5, "MultiKueueCluster": 6,
    "ClusterQueue": 7, "LocalQueue": 8, "WorkloadPriorityClass": 9,
    "Workload": 99,
}
_KIND_DEFAULT = 50


@dataclass
class RecoveryReport:
    """What one restore() rebuilt, for /debug/recovery and the chaos
    harness asserts."""

    duration_s: float = 0.0
    checkpoint_loaded: bool = False
    wal_records_replayed: int = 0
    torn_records: int = 0
    warnings: list = field(default_factory=list)
    objects: dict = field(default_factory=dict)   # kind -> count
    rv: int = 0
    admitted_restored: int = 0    # workloads restored holding quota
    pending_restored: int = 0     # workloads restored without quota
    settle_reconciles: int = 0    # reconciles to drain the rebuild
    # Which tail replay ran: "incremental" applies the WAL records as
    # their ORIGINAL watch events through Store.apply_replicated — the
    # hot-standby follower's live path (RESILIENCE.md §7) — while
    # "collapsed" folds the tail into final objects first (the PR-10
    # shape, kept for the bench A/B).
    replay_mode: str = "incremental"

    def to_dict(self) -> dict:
        return {
            "duration_s": round(self.duration_s, 6),
            "checkpoint_loaded": self.checkpoint_loaded,
            "wal_records_replayed": self.wal_records_replayed,
            "torn_records": self.torn_records,
            "warnings": list(self.warnings),
            "objects": dict(self.objects),
            "rv": self.rv,
            "admitted_restored": self.admitted_restored,
            "pending_restored": self.pending_restored,
            "settle_reconciles": self.settle_reconciles,
            "replay_mode": self.replay_mode,
        }


def restore(durable, cfg=None, clock: Clock = REAL_CLOCK, solver=None,
            registered_check_controllers: Optional[set] = None,
            remote_clusters: Optional[dict] = None,
            identity: str = "", checkpoint_after: bool = True,
            incremental: bool = True):
    """Build a fresh ``KueueManager`` from a durable log's newest
    recoverable state. Returns the manager; its ``last_recovery``
    carries the ``RecoveryReport``.

    ``solver`` may be the dead manager's solver object — it is
    ``detach()``-ed so every binding to the old control plane drops
    while its compile investment (jit caches + the persistent
    compilation cache) carries over. ``checkpoint_after`` compacts the
    log once the rebuild settles, so a crash-during-recovery restarts
    from the restored image instead of re-replaying the tail.

    ``incremental`` (default) replays the WAL tail as its ORIGINAL
    watch events through ``Store.apply_replicated`` — the same path
    the hot-standby follower streams live (resilience/replica.py), so
    cold restore and warm failover exercise one replay. False keeps
    the PR-10 collapsed replay (fold the tail into final objects,
    replay everything as ADDED) for the bench A/B delta."""
    from kueue_tpu.core import workload as wlpkg
    from kueue_tpu.manager import KueueManager
    from kueue_tpu.sim import Store

    t0 = _time.perf_counter()
    report = RecoveryReport()
    report.replay_mode = "incremental" if incremental else "collapsed"

    parts = durable.load_parts()
    t_load = _time.perf_counter()
    report.checkpoint_loaded = parts.checkpoint_loaded
    report.torn_records = parts.torn_records
    report.warnings = list(parts.warnings)
    report.rv = parts.rv

    if solver is not None and hasattr(solver, "detach"):
        # Drop every binding to the dead control plane BEFORE the new
        # manager constructs around the solver (Scheduler.__init__
        # rebinds cache/queues/recorder on a clean slate). Residency
        # and the arena are rebuildable caches; keeping them would
        # chain the first post-restore dispatch on pre-crash usage.
        solver.detach()

    store = Store(clock)
    mgr = KueueManager(
        cfg=cfg, clock=clock, solver=solver,
        registered_check_controllers=registered_check_controllers,
        remote_clusters=remote_clusters, store=store, identity=identity)

    rec = mgr.flight_recorder
    trace = rec.begin_cycle(0)
    # The load finished before the trace could open (the recorder
    # lives on the manager): render it at offset 0 with its true
    # duration rather than a negative start.
    rec.span("recovery.load", trace.t0 if trace is not None else t0,
             t_load - t0)

    t_replay = _time.perf_counter()
    if incremental:
        base, tail = parts.objects, parts.records
    else:
        collapsed = parts.collapse()
        base, tail = collapsed.objects, ()
        report.wal_records_replayed = collapsed.records_replayed
    kinds = sorted(base,
                   key=lambda k: (_KIND_ORDER.get(k, _KIND_DEFAULT), k))
    for kind in kinds:
        for obj in base[kind].values():
            store.load_object(obj)
    # The tail replays as the original event stream — creates, status
    # flips and finalizer deletes fire in exactly the order the dead
    # leader's controllers observed them, through the follower's
    # apply path (event fidelity preserved; not re-logged).
    for event, _kind, _key, obj, _t in tail:
        store.apply_replicated(event, obj)
        report.wal_records_replayed += 1
    for wl in store.list("Workload", copy_objects=False):
        if wlpkg.has_quota_reservation(wl):
            report.admitted_restored += 1
        else:
            report.pending_restored += 1
    report.objects = {k: len(v) for k, v in store._objects.items() if v}
    rec.span("recovery.replay", t_replay, _time.perf_counter() - t_replay)

    # The resourceVersion high-water mark may exceed any SURVIVING
    # object's rv (a deleted object can have held it): seed it from the
    # log so post-restore writes never re-mint a used rv.
    store._rv = max(store._rv, parts.rv)

    t_settle = _time.perf_counter()
    report.settle_reconciles = mgr.run_until_idle(
        max_iterations=1_000_000)
    rec.span("recovery.settle", t_settle, _time.perf_counter() - t_settle)

    # The restored store owns durability again; a post-settle
    # checkpoint compacts the log so the NEXT crash replays no tail.
    store.attach_durable(durable)
    mgr.durable = durable
    if checkpoint_after:
        store.checkpoint_now()

    # Conservative restart posture: breaker CLOSED / ladder NORMAL with
    # zero history (fresh objects), and the first cycle synchronous —
    # a speculative dispatch must never chain on a cache that settled
    # milliseconds ago with no router/watchdog evidence behind it.
    mgr.scheduler._pipeline_cooldown = max(
        mgr.scheduler._pipeline_cooldown, 1)

    report.duration_s = _time.perf_counter() - t0
    if trace is not None:
        trace.route = "recovery"
        trace.heads = 0
        trace.admitted = report.admitted_restored
        rec.annotate(
            "recovery",
            f"restored {sum(report.objects.values())} object(s): "
            f"{report.admitted_restored} admitted + "
            f"{report.pending_restored} pending workload(s), "
            f"{report.wal_records_replayed} WAL record(s) replayed, "
            f"torn={report.torn_records}",
            **{k: v for k, v in report.to_dict().items()
               if k not in ("warnings", "objects")})
        rec.finish(trace)
    mgr.metrics.restart_recovered(report.duration_s)
    mgr.recorder.system_event(
        "Warning" if report.torn_records else "Normal", "Restarted",
        f"control plane restored from the durable store in "
        f"{report.duration_s * 1e3:.1f}ms "
        f"({report.admitted_restored} admitted, "
        f"{report.pending_restored} pending)")
    mgr.last_recovery = report
    mgr.scheduler.last_recovery = report.to_dict()
    return mgr
