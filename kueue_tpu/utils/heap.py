"""Map-backed heap with PushIfNotPresent / PushOrUpdate / Delete.

Equivalent of the reference's pkg/util/heap/heap.go (183 LoC): a binary
heap whose items are addressable by key, used by the scheduler queues and
the preemption CQ-heap. Implemented as a lazy heapq: stale entries are
tombstoned and skipped on pop/peek.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Callable, Generic, Optional, TypeVar

T = TypeVar("T")


class Heap(Generic[T]):
    def __init__(self, key_func: Callable[[T], str], less_func: Callable[[T, T], bool]):
        self._key = key_func
        self._less = less_func
        self._items: dict[str, T] = {}
        self._heap: list = []  # entries: [_Cmp, seq, key]
        self._seq = itertools.count()

    class _Cmp:
        __slots__ = ("item", "less")

        def __init__(self, item, less):
            self.item = item
            self.less = less

        def __lt__(self, other):
            return self.less(self.item, other.item)

    def __len__(self) -> int:
        return len(self._items)

    def _push_entry(self, item: T, key: str) -> None:
        heapq.heappush(self._heap, (self._Cmp(item, self._less), next(self._seq), key))

    def push_if_not_present(self, item: T) -> bool:
        key = self._key(item)
        if key in self._items:
            return False
        self._items[key] = item
        self._push_entry(item, key)
        return True

    def push_or_update(self, item: T) -> None:
        key = self._key(item)
        self._items[key] = item
        self._push_entry(item, key)

    def delete(self, key: str) -> bool:
        return self._items.pop(key, None) is not None

    def get_by_key(self, key: str) -> Optional[T]:
        return self._items.get(key)

    def _prune(self) -> None:
        while self._heap:
            _, _, key = self._heap[0]
            current = self._items.get(key)
            if current is None or current is not self._heap[0][0].item:
                heapq.heappop(self._heap)  # stale/tombstoned
            else:
                return

    def peek(self) -> Optional[T]:
        self._prune()
        if not self._heap:
            return None
        return self._heap[0][0].item

    def pop(self) -> Optional[T]:
        self._prune()
        if not self._heap:
            return None
        entry = heapq.heappop(self._heap)
        key = entry[2]
        del self._items[key]
        return entry[0].item

    def items(self) -> list:
        return list(self._items.values())

    def keys(self) -> list:
        return list(self._items.keys())
