"""Primitive utilities (reference: pkg/util/*)."""
