"""Leader election over the object store (HA manager replicas).

Reference behavior being matched (not copied):
- cmd/kueue wires controller-runtime leader election with a
  coordination/v1 Lease; the scheduler declares NeedLeaderElection
  (pkg/scheduler/scheduler.go:144) so only the leader runs admission
  cycles.
- Non-leader replicas still run READ paths, and leader-aware
  reconcilers delegate writes until leadership is acquired, requeueing
  with a delay instead of erroring
  (pkg/controller/core/leader_aware_reconciler.go:89).

The Lease object lives in the same Store the rest of the control plane
uses (the apiserver stand-in), so failover semantics ride the store's
optimistic concurrency: acquire/renew is an expect_rv update, and a
conflicting writer simply loses the race — exactly the client-go
leaderelection.go acquire loop's contract.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from kueue_tpu.api.meta import REAL_CLOCK, Clock, ObjectMeta
from kueue_tpu.sim.store import AlreadyExists, Conflict, Store

LEASE_NAMESPACE = "kueue-system"
DEFAULT_LEASE_NAME = "kueue-manager"
DEFAULT_LEASE_DURATION = 15.0   # client-go defaults: 15s / 10s / 2s
DEFAULT_RENEW_DEADLINE = 10.0
DEFAULT_RETRY_PERIOD = 2.0


@dataclass
class LeaseSpec:
    holder_identity: str = ""
    lease_duration_seconds: float = DEFAULT_LEASE_DURATION
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0


@dataclass
class Lease:
    """coordination/v1 Lease equivalent for the sim store."""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LeaseSpec = field(default_factory=LeaseSpec)


class LeaderElector:
    """Single-step acquire/renew loop (client-go leaderelection.go
    tryAcquireOrRenew), driven by the manager's runtime: call
    ``tick()`` every retry_period. Callbacks fire on transitions."""

    def __init__(self, store: Store, identity: str,
                 lease_name: str = DEFAULT_LEASE_NAME,
                 lease_duration: float = DEFAULT_LEASE_DURATION,
                 renew_deadline: float = DEFAULT_RENEW_DEADLINE,
                 retry_period: float = DEFAULT_RETRY_PERIOD,
                 clock: Clock = REAL_CLOCK,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None):
        self.store = store
        self.identity = identity
        self.lease_name = lease_name
        self.lease_duration = lease_duration
        self.renew_deadline = min(renew_deadline, lease_duration)
        self.retry_period = retry_period
        self.clock = clock
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = False
        self._last_renew = 0.0

    def is_leader(self) -> bool:
        """Leadership is only trusted within renew_deadline of the last
        successful renew (client-go's RenewDeadline): a stalled leader
        whose runtime wakes up AFTER another replica could have acquired
        the lease must see itself demoted BEFORE its next tick — this
        check is what the scheduler's leader gate reads, so the
        dual-leader window is closed deterministically
        (renew_deadline <= lease_duration, the earliest takeover time)."""
        return (self._leading
                and self.clock.now() < self._last_renew + self.renew_deadline)

    def leader_identity(self) -> str:
        lease = self.store.try_get("Lease", LEASE_NAMESPACE, self.lease_name)
        if lease is None:
            return ""
        if self._expired(lease):
            return ""
        return lease.spec.holder_identity

    def tick(self) -> bool:
        """One acquire-or-renew attempt; returns is_leader afterwards."""
        won = self._try_acquire_or_renew()
        if won:
            self._last_renew = self.clock.now()
        if won and not self._leading:
            self._leading = True
            if self.on_started_leading is not None:
                self.on_started_leading()
        elif not won and self._leading:
            self._leading = False
            if self.on_stopped_leading is not None:
                self.on_stopped_leading()
        return self._leading

    def release(self) -> None:
        """Voluntarily give up the lease (graceful shutdown), so the
        next replica doesn't wait out the full lease duration."""
        if not self._leading:
            return
        lease = self.store.try_get("Lease", LEASE_NAMESPACE, self.lease_name)
        if lease is not None and lease.spec.holder_identity == self.identity:
            lease.spec.holder_identity = ""
            lease.spec.renew_time = 0.0
            try:
                self.store.update(lease,
                                  expect_rv=lease.metadata.resource_version)
            except (Conflict, KeyError):
                pass
        self._leading = False
        if self.on_stopped_leading is not None:
            self.on_stopped_leading()

    # -- internals --

    def _expired(self, lease: Lease) -> bool:
        return (not lease.spec.holder_identity
                or self.clock.now() >= lease.spec.renew_time
                + lease.spec.lease_duration_seconds)

    def _try_acquire_or_renew(self) -> bool:
        now = self.clock.now()
        lease = self.store.try_get("Lease", LEASE_NAMESPACE, self.lease_name)
        if lease is None:
            lease = Lease(metadata=ObjectMeta(name=self.lease_name,
                                              namespace=LEASE_NAMESPACE),
                          spec=LeaseSpec(
                              holder_identity=self.identity,
                              lease_duration_seconds=self.lease_duration,
                              acquire_time=now, renew_time=now))
            try:
                self.store.create(lease)
                return True
            except AlreadyExists:  # lost the creation race
                return False
        mine = lease.spec.holder_identity == self.identity
        if not mine and not self._expired(lease):
            return False
        lease.spec.renew_time = now
        if not mine:
            lease.spec.holder_identity = self.identity
            lease.spec.acquire_time = now
            lease.spec.lease_transitions += 1
        try:
            self.store.update(lease,
                              expect_rv=lease.metadata.resource_version)
        except (Conflict, KeyError):
            return False  # a concurrent replica renewed/acquired first
        return True


class LeaderAwareReconciler:
    """Wrap a reconciler so non-leader replicas delay writes instead of
    performing them (reference: leader_aware_reconciler.go:89 — requeue
    with RequeueAfter until this replica becomes the leader). Read-only
    event handling stays live on every replica, keeping caches warm for
    a fast failover."""

    def __init__(self, inner, elector: LeaderElector,
                 requeue_seconds: Optional[float] = None):
        """inner: a reconciler object (with .reconcile) or a bare
        reconcile callable."""
        self.inner = inner
        self._reconcile = (inner.reconcile if hasattr(inner, "reconcile")
                           else inner)
        self.elector = elector
        self.requeue_seconds = (requeue_seconds if requeue_seconds is not None
                                else elector.retry_period)

    def reconcile(self, key: str):
        if not self.elector.is_leader():
            return self.requeue_seconds
        return self._reconcile(key)

    def __getattr__(self, name):
        return getattr(self.inner, name)
