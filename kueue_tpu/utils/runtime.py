"""Process-runtime tuning for the manager binary.

The reference is a Go binary whose concurrent GC never stops the world
for more than fractions of a millisecond; CPython's generational GC, by
contrast, runs a full stop-the-world gen-2 scan of every tracked
container each time the gen-2 counter trips. A control plane holds
hundreds of thousands of long-lived objects (cached Workloads, CQ state,
queue heaps), so with the default thresholds (700, 10, 10) a busy
admission cycle allocates enough temporaries to trigger multiple full
collections per cycle — each one scanning the whole (growing) object
store for seconds at scale.

tune_gc() keeps young-generation collection (cheap, catches cycles in
temporaries) but makes full collections ~100x rarer. Called by the
manager entry point (the equivalent of runtime knobs in the reference's
cmd/kueue/main.go) and by the perf/bench harnesses; library code never
mutates global GC state on import.
"""

from __future__ import annotations

import gc

# (gen0 allocations, gen0-per-gen1, gen1-per-gen2); defaults are (700, 10, 10).
SCHEDULER_GC_THRESHOLDS = (50000, 25, 100)


def tune_gc(thresholds: tuple = SCHEDULER_GC_THRESHOLDS) -> tuple:
    """Apply scheduler-friendly GC thresholds; returns the previous ones."""
    prev = gc.get_threshold()
    gc.set_threshold(*thresholds)
    return prev


def enable_compilation_cache(cache_dir: str = None,
                             min_compile_time_secs: float = 0.5) -> None:
    """Persistent XLA compilation cache: over a remote-compile TPU tunnel
    a fresh kernel variant costs seconds, which lands in first-cycle /
    first-run latency (the north-star run's p99 was one compile per shape
    bucket). Caching serialized executables on disk amortizes that across
    process runs — the bench/perf harnesses and the manager all call this
    before touching jax. Safe on any backend; no-op if jax is too old.

    The compile governor (solver/warmgov.py) re-points the cache at a
    per-topology subdirectory and passes ``min_compile_time_secs=0`` so
    EVERY warmed executable persists — a sub-second compile is still a
    hot-path stall worth a disk read on restart."""
    import os
    import jax
    if cache_dir is None:
        cache_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")
    try:
        changed = jax.config.jax_compilation_cache_dir != cache_dir
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_time_secs)
        if changed:
            # jax latches the cache instance at the FIRST compile after
            # process start; a config update alone is silently ignored
            # once anything has compiled (the governor re-points the
            # cache mid-process, after warm_setup's zero-batch fills
            # already compiled). Reset so the new directory takes.
            from jax._src import compilation_cache
            compilation_cache.reset_cache()
    except Exception:  # noqa: BLE001 — older jax without the knobs
        pass


def ensure_live_backend(reexec_argv=None, timeout_s: float = 90.0) -> dict:
    """The axon TPU tunnel can die outright (device ops hang forever in
    native code). Probe it with a bounded thread; on timeout, re-exec
    the given argv on the local XLA-CPU backend with a visible marker —
    a labeled CPU-backend run beats a silent infinite hang. Returns
    {"backend": ..., "cpu_fallback": ...} once a backend is live, so
    harnesses can stamp every artifact they emit (VERDICT r4 ask #1:
    perf evidence must be attributable)."""
    import json
    import os
    import subprocess
    import sys
    import threading

    fallback = bool(os.environ.get("KUEUE_TPU_BENCH_CPU_FALLBACK"))
    if not fallback:
        ok = threading.Event()

        def probe():
            import jax
            import jax.numpy as jnp
            import numpy as np
            np.asarray(jax.jit(lambda a: a + 1)(jnp.ones(4, jnp.int32)))
            ok.set()

        t = threading.Thread(target=probe, daemon=True)
        t.start()
        t.join(timeout_s)
        if not ok.is_set():
            print(json.dumps({
                "backend_probe": "accelerator tunnel unresponsive; "
                                 "re-running on the local XLA-CPU backend "
                                 "(numbers are NOT TPU numbers)"}),
                file=sys.stderr, flush=True)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["PALLAS_AXON_POOL_IPS"] = ""
            env["KUEUE_TPU_BENCH_CPU_FALLBACK"] = "1"
            sys.stdout.flush()
            raise SystemExit(subprocess.call(
                reexec_argv or [sys.executable] + sys.argv, env=env))
    import jax
    return {"backend": jax.devices()[0].platform.lower(),
            "cpu_fallback": fallback}
