"""Bounded fan-out over an index range.

Equivalent of the reference's pkg/util/parallelize/parallelize.go:17-40
(`Until`: N items, up to 8 workers, first error wins), used there to
hide per-item apiserver latency in hot paths like preemption issuing
(preemption.go:195-235) and snapshot construction.

Here every caller is in-process, so the fan-out only pays when the
per-item work releases the GIL or blocks (a remote store client, say) —
callers measure and pick their worker count; `until(n, fn, workers=1)`
degenerates to the plain loop with zero overhead.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable

DEFAULT_WORKERS = 8

_pool = None
_pool_lock = threading.Lock()
# Re-entrancy marker: set while a chunk runs ON a shared-pool worker. A
# nested until(workers>1) from inside a worker could otherwise exhaust
# the bounded pool (every thread blocked on futures that have no free
# thread to run) and deadlock; nested calls degrade to the sequential
# path instead.
_in_pool_worker = threading.local()


def _shared_pool() -> ThreadPoolExecutor:
    global _pool
    with _pool_lock:
        if _pool is None:
            _pool = ThreadPoolExecutor(max_workers=DEFAULT_WORKERS,
                                       thread_name_prefix="parallelize")
        return _pool


def _run_chunk(fn: Callable[[int], None], lo: int, hi: int, errs: list,
               errs_lock) -> None:
    for i in range(lo, hi):
        try:
            fn(i)
        except Exception as e:  # noqa: BLE001 — aggregate, re-raise later
            with errs_lock:
                errs.append(e)


def _run_chunk_pooled(fn: Callable[[int], None], lo: int, hi: int,
                      errs: list, errs_lock) -> None:
    _in_pool_worker.active = True
    try:
        _run_chunk(fn, lo, hi, errs, errs_lock)
    finally:
        _in_pool_worker.active = False


def until(n: int, fn: Callable[[int], None],
          workers: int = DEFAULT_WORKERS) -> None:
    """Run fn(i) for every i in range(n), at most `workers` at a time
    (one contiguous chunk per worker, like the reference's
    workqueue-chunked Until). All items are attempted even when some
    fail (errgroup-with-collect semantics), then the first exception is
    re-raised — identically in the sequential and parallel paths.
    Re-entrant calls from inside a pool worker run sequentially (the
    shared bounded pool cannot safely nest — see _in_pool_worker)."""
    errs: list = []
    errs_lock = threading.Lock()
    workers = min(workers, DEFAULT_WORKERS, n)
    if getattr(_in_pool_worker, "active", False):
        workers = 1
    if n <= 1 or workers <= 1:
        _run_chunk(fn, 0, n, errs, errs_lock)
    else:
        pool = _shared_pool()
        chunk = (n + workers - 1) // workers
        futures = [pool.submit(_run_chunk_pooled, fn, lo, min(lo + chunk, n),
                               errs, errs_lock)
                   for lo in range(0, n, chunk)]
        for f in futures:
            f.result()
    if errs:
        raise errs[0]
