"""Scheduler pacing: re-run immediately on success, exponential backoff
on failure.

Equivalent of the reference's pkg/util/wait/backoff.go:30-88
(UntilWithBackoff with SpeedSignal): KeepGoing re-runs the function
immediately; SlowDown applies exponential backoff from 1ms to 100ms.
"""

from __future__ import annotations

import threading
from enum import Enum
from typing import Callable


class SpeedSignal(Enum):
    KEEP_GOING = 0
    SLOW_DOWN = 1


KeepGoing = SpeedSignal.KEEP_GOING
SlowDown = SpeedSignal.SLOW_DOWN

_BASE_DELAY = 0.001
_MAX_DELAY = 0.100


def until_with_backoff(stop: threading.Event, fn: Callable[[], SpeedSignal],
                       sleep: Callable[[float], None] = None) -> None:
    """Run fn until `stop` is set; pace according to its SpeedSignal."""
    delay = _BASE_DELAY
    while not stop.is_set():
        signal = fn()
        if signal == KeepGoing:
            delay = _BASE_DELAY
            continue
        if sleep is not None:
            sleep(delay)
        else:
            stop.wait(delay)
        delay = min(delay * 2, _MAX_DELAY)
