"""Leveled structured logging (the reference's logr/zap V-convention).

Equivalent of the verbosity scheme the reference uses throughout
(SURVEY.md §5; pkg/scheduler/logging.go:1-54): numeric V levels on top
of Python's logging —

- V(2): per-cycle summaries (admitted/skipped counts, cycle latency)
- V(3): per-workload transitions (admit / requeue / evict)
- V(5): the scheduler's per-entry nomination attempts
- V(6): full cache-snapshot dumps at cycle start

``set_verbosity(n)`` (or KUEUE_TPU_V in the environment, read at import)
enables levels <= n. Messages are key=value structured, one line each,
through the standard ``logging`` machinery so handlers/formatters can be
swapped by embedders.
"""

from __future__ import annotations

import logging
import os

_BASE = logging.getLogger("kueue_tpu")
# V(n) maps onto descending DEBUG sublevels so standard handlers order
# them sensibly: V0/V1 -> INFO, V2+ -> DEBUG-and-below.
_LEVEL_FOR_V = {0: logging.INFO, 1: logging.INFO}

_verbosity = 0


def set_verbosity(v: int) -> None:
    """Enable V(level) messages for level <= v (the --v flag analogue)."""
    global _verbosity
    _verbosity = int(v)
    _BASE.setLevel(logging.DEBUG if v >= 2 else logging.INFO)


def verbosity() -> int:
    return _verbosity


def env_verbosity() -> int:
    """The KUEUE_TPU_V override as read at import (0 when unset) —
    embedders reconcile their config level against THIS, not the
    mutable global, so one loud embedder can't ratchet another."""
    return _env_v


def enabled(v: int) -> bool:
    return v <= _verbosity


def _fmt(msg: str, kv: dict) -> str:
    if not kv:
        return msg
    parts = " ".join(f"{k}={v}" for k, v in kv.items())
    return f"{msg} {parts}"


class VLogger:
    """logr-style leveled logger bound to a component name."""

    def __init__(self, name: str):
        self._log = _BASE.getChild(name)

    def v(self, level: int, msg: str, **kv) -> None:
        if level > _verbosity:
            return
        pylevel = _LEVEL_FOR_V.get(level, logging.DEBUG)
        self._log.log(pylevel, _fmt(msg, kv))

    def info(self, msg: str, **kv) -> None:
        self.v(0, msg, **kv)

    def error(self, msg: str, **kv) -> None:
        self._log.error(_fmt(msg, kv))


def logger(name: str) -> VLogger:
    return VLogger(name)


# Environment override (the --v flag analogue for embedders without
# config access); applied through set_verbosity so the logger LEVEL
# moves too, or V>=2 records would be dropped by standard handlers.
_env_v = int(os.environ.get("KUEUE_TPU_V", "0") or 0)
if _env_v:
    set_verbosity(_env_v)


def dump_snapshot(log: VLogger, snapshot) -> None:
    """V(6): the full usage snapshot at cycle start (reference:
    logAdmissionAttemptIfVerbose -> dumpCache, logging.go:22-41)."""
    if not enabled(6):
        return
    for name, cq in sorted(snapshot.cluster_queues.items()):
        usage = {f"{fr.flavor}/{fr.resource}": v
                 for fr, v in sorted(cq.resource_node.usage.items())}
        log.v(6, "snapshot.clusterQueue", name=name,
              cohort=cq.cohort.name if cq.cohort else "",
              workloads=len(cq.workloads), usage=usage)


def dump_attempts(log: VLogger, entries) -> None:
    """V(5): per-entry nomination outcomes (reference: logging.go:43-54)."""
    if not enabled(5):
        return
    from kueue_tpu.scheduler import flavorassigner as fa
    for e in entries:
        log.v(5, "attempt", workload=e.info.key,
              clusterQueue=e.info.cluster_queue,
              mode=fa.mode_name(e.assignment.representative_mode()),
              status=e.status or "notNominated",
              targets=len(e.preemption_targets or []),
              message=e.inadmissible_msg[:120])
