"""Controller configuration schema: defaults + validation.

Equivalent of the reference's apis/config/v1beta1
(configuration_types.go:30-79, defaults.go:66-191) and pkg/config
(config.go:150, validation.go). Server-endpoint/cert fields that only
make sense against a real apiserver are represented but unused by the
sim runtime.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

# defaults (reference: apis/config/v1beta1/defaults.go:31-51)
DEFAULT_NAMESPACE = "kueue-system"
DEFAULT_CLIENT_CONNECTION_QPS = 20.0
DEFAULT_CLIENT_CONNECTION_BURST = 30
DEFAULT_PODS_READY_TIMEOUT_SECONDS = 5 * 60.0
DEFAULT_QUEUE_VISIBILITY_UPDATE_INTERVAL_SECONDS = 5
DEFAULT_CLUSTER_QUEUES_MAX_COUNT = 10
DEFAULT_MULTIKUEUE_GC_INTERVAL_SECONDS = 60.0
DEFAULT_MULTIKUEUE_ORIGIN = "multikueue"
DEFAULT_MULTIKUEUE_WORKER_LOST_TIMEOUT_SECONDS = 15 * 60.0
DEFAULT_REQUEUING_BACKOFF_BASE_SECONDS = 60
DEFAULT_REQUEUING_BACKOFF_MAX_SECONDS = 3600
DEFAULT_REQUEUING_BACKOFF_JITTER = 0.0001

# requeuing timestamp choices (reference: configuration_types.go:243-257)
EVICTION_TIMESTAMP = "Eviction"
CREATION_TIMESTAMP = "Creation"

# fair-sharing preemption strategies (reference: configuration_types.go:381-397)
LESS_THAN_OR_EQUAL_TO_FINAL_SHARE = "LessThanOrEqualToFinalShare"
LESS_THAN_INITIAL_SHARE = "LessThanInitialShare"

DEFAULT_INTEGRATIONS = ["batch/job"]

ALL_INTEGRATIONS = [
    "batch/job",
    "jobset.x-k8s.io/jobset",
    "kubeflow.org/tfjob",
    "kubeflow.org/pytorchjob",
    "kubeflow.org/paddlejob",
    "kubeflow.org/xgboostjob",
    "kubeflow.org/mxjob",
    "kubeflow.org/mpijob",
    "ray.io/rayjob",
    "ray.io/raycluster",
    "pod",
    "deployment",
]


@dataclass
class RequeuingStrategy:
    """reference: configuration_types.go:233-271"""
    timestamp: str = EVICTION_TIMESTAMP
    backoff_limit_count: Optional[int] = None  # None = endless requeuing
    backoff_base_seconds: int = DEFAULT_REQUEUING_BACKOFF_BASE_SECONDS
    backoff_max_seconds: int = DEFAULT_REQUEUING_BACKOFF_MAX_SECONDS
    backoff_jitter: float = DEFAULT_REQUEUING_BACKOFF_JITTER


@dataclass
class WaitForPodsReady:
    """reference: configuration_types.go:189-231"""
    enable: bool = False
    timeout_seconds: float = DEFAULT_PODS_READY_TIMEOUT_SECONDS
    block_admission: bool = True
    requeuing_strategy: RequeuingStrategy = field(default_factory=RequeuingStrategy)
    recovery_timeout_seconds: Optional[float] = None


@dataclass
class ClientConnection:
    qps: float = DEFAULT_CLIENT_CONNECTION_QPS
    burst: int = DEFAULT_CLIENT_CONNECTION_BURST


@dataclass
class ClusterQueueVisibility:
    max_count: int = DEFAULT_CLUSTER_QUEUES_MAX_COUNT


@dataclass
class QueueVisibility:
    """reference: configuration_types.go:348-367"""
    update_interval_seconds: int = DEFAULT_QUEUE_VISIBILITY_UPDATE_INTERVAL_SECONDS
    cluster_queues: ClusterQueueVisibility = field(default_factory=ClusterQueueVisibility)


@dataclass
class FairSharingConfig:
    """reference: configuration_types.go:381-397"""
    enable: bool = False
    preemption_strategies: list[str] = field(default_factory=list)


@dataclass
class MultiKueueConfig:
    """reference: configuration_types.go:211-231"""
    gc_interval_seconds: float = DEFAULT_MULTIKUEUE_GC_INTERVAL_SECONDS
    origin: str = DEFAULT_MULTIKUEUE_ORIGIN
    worker_lost_timeout_seconds: float = DEFAULT_MULTIKUEUE_WORKER_LOST_TIMEOUT_SECONDS


@dataclass
class PodIntegrationOptions:
    """reference: configuration_types.go:326-346 — which namespaces the pod
    integration may touch (kube-system etc. are always excluded)."""
    namespace_selector_exclude: list[str] = field(
        default_factory=lambda: ["kube-system", DEFAULT_NAMESPACE])


@dataclass
class Integrations:
    """reference: configuration_types.go:307-324"""
    frameworks: list[str] = field(default_factory=lambda: list(DEFAULT_INTEGRATIONS))
    external_frameworks: list[str] = field(default_factory=list)
    pod_options: PodIntegrationOptions = field(default_factory=PodIntegrationOptions)


@dataclass
class Resources:
    """reference: configuration_types.go:369-379"""
    exclude_resource_prefixes: list[str] = field(default_factory=list)


@dataclass
class LeaderElectionConfig:
    """HA replica coordination (reference: cmd/kueue/main.go leader
    election flags + config.Configuration LeaderElection; the scheduler
    is leader-gated via NeedLeaderElection, scheduler.go:144)."""
    leader_elect: bool = False
    # reference default resource name (defaults.go DefaultLeaderElectionID)
    resource_name: str = "c1f6bfd2.kueue.x-k8s.io"
    lease_duration_seconds: float = 15.0
    retry_period_seconds: float = 2.0


DEFAULT_STRICT_AFTER_BLOCKED_CYCLES = 8

# Cycle deadline budget / degradation ladder defaults: the ladder
# module owns them (a directly-constructed DegradationLadder and a
# config-driven one must never disagree); only the budget default —
# pure config policy, 0 disables — lives here.
from kueue_tpu.resilience.degrade import (  # noqa: E402
    DEFAULT_ESCALATE_AFTER as DEFAULT_ESCALATE_AFTER_CYCLES,
    DEFAULT_ENTER_FACTOR as DEFAULT_OVERLOAD_ENTER_FACTOR,
    DEFAULT_EWMA_ALPHA as DEFAULT_CYCLE_EWMA_ALPHA,
    DEFAULT_EXIT_FACTOR as DEFAULT_OVERLOAD_EXIT_FACTOR,
    DEFAULT_RECOVERY_CYCLES,
    DEFAULT_SHED_HEADS,
    DEFAULT_SURVIVAL_HEADS,
)

DEFAULT_CYCLE_BUDGET_S = 0.0        # 0 disables the ladder


@dataclass
class SchedulerConfig:
    """Admission-cycle bounding (kueue_tpu/resilience/degrade.py; no
    reference analogue): a wall-clock budget per cycle and the
    graceful load-shedding ladder engaged when sustained load exceeds
    it. ``cycle_budget_s == 0`` disables the ladder entirely."""
    cycle_budget_s: float = DEFAULT_CYCLE_BUDGET_S
    # shed: cap nominate heads at this many (extras re-heap untouched)
    # and defer preempt planning
    shed_heads: int = DEFAULT_SHED_HEADS
    # survival: tighter top-k cap, cycle pinned to the CPU-incremental
    # route ("cpu-survival")
    survival_heads: int = DEFAULT_SURVIVAL_HEADS
    # hysteresis band: degrade when cycle-time EWMA > budget x enter,
    # recover only below budget x exit (exit <= enter)
    overload_enter_factor: float = DEFAULT_OVERLOAD_ENTER_FACTOR
    overload_exit_factor: float = DEFAULT_OVERLOAD_EXIT_FACTOR
    # consecutive overloaded cycles before stepping a rung up / healthy
    # cycles before stepping one down
    escalate_after_cycles: int = DEFAULT_ESCALATE_AFTER_CYCLES
    recovery_cycles: int = DEFAULT_RECOVERY_CYCLES
    cycle_ewma_alpha: float = DEFAULT_CYCLE_EWMA_ALPHA


# Durable-store defaults (kueue_tpu/sim/durable.py + RESILIENCE.md §6).
DEFAULT_STORE_CHECKPOINT_EVERY = 512


@dataclass
class StoreConfig:
    """Durability for the sim object store — the "etcd is the
    checkpoint, restart is cheap" property (SURVEY.md §5,
    resilience/recovery.py). ``durable`` turns on the checkpoint/WAL
    event log; ``wal_dir`` empty keeps it in fsync-free process memory
    (tests, crash harnesses — the log object outliving the manager IS
    the simulated disk), a path puts checkpoint.bin + wal.log in real
    files. A full checkpoint compacts the WAL every
    ``checkpoint_every`` records."""
    durable: bool = False
    wal_dir: str = ""
    checkpoint_every: int = DEFAULT_STORE_CHECKPOINT_EVERY


# Cycle flight recorder defaults (kueue_tpu/obs/OBSERVABILITY.md).
DEFAULT_FLIGHT_RECORDER_CAPACITY = 256

# Workload journey ledger defaults (kueue_tpu/obs/journey.py).
DEFAULT_JOURNEY_LEDGER_CAPACITY = 8192
DEFAULT_JOURNEY_EXEMPLARS = 8


@dataclass
class ObservabilityConfig:
    """Flight-recorder wiring (kueue_tpu/obs): every scheduler cycle
    produces a structured trace held in a bounded ring of the last
    ``flight_recorder_capacity`` cycles, served via /debug/cycles and
    feeding the cycle_phase_seconds histograms. Disabling drops span
    capture to a single compare per phase (the trace_overhead bench row
    pins both modes at <=1% of a cycle). ``query_plane_enable`` wires
    the snapshot-backed read plane (obs/queryplane.py): every cycle
    seal publishes an immutable pending-position view served by the
    visibility server instead of walking live queue state per request;
    disabling reverts reads to the live (per-request) visibility API
    and restores the maintainer's snapshot shell recycling.

    ``journey_enable`` wires the workload journey ledger
    (obs/journey.py): every workload accumulates a causally-stamped
    span timeline (queued -> requeued(cycle)... -> admitted) in a
    bounded LRU of ``journey_ledger_capacity`` active journeys, with
    the ``journey_exemplars`` slowest completed journeys retained in
    full for /debug/journeys. Disabling drops every hook to one
    is-None compare (the journey_overhead bench row pins both modes
    at <=1% of a cycle) and reverts the wait-time histograms to their
    direct call sites."""
    flight_recorder_enable: bool = True
    flight_recorder_capacity: int = DEFAULT_FLIGHT_RECORDER_CAPACITY
    query_plane_enable: bool = True
    journey_enable: bool = True
    journey_ledger_capacity: int = DEFAULT_JOURNEY_LEDGER_CAPACITY
    journey_exemplars: int = DEFAULT_JOURNEY_EXEMPLARS

# Device-fault containment defaults (kueue_tpu/resilience) — single
# source for both the dataclass defaults and load()'s fallbacks.
DEFAULT_WATCHDOG_SAFETY_FACTOR = 20.0
DEFAULT_WATCHDOG_MIN_DEADLINE_S = 1.0
DEFAULT_WATCHDOG_MAX_DEADLINE_S = 30.0
DEFAULT_BREAKER_FAULT_THRESHOLD = 3
DEFAULT_BREAKER_BACKOFF_BASE_S = 1.0
DEFAULT_BREAKER_BACKOFF_MAX_S = 60.0

# Compile governor defaults (solver/warmgov.py + solver/COMPILE.md).
DEFAULT_WARMUP_DEADLINE_S = 120.0


@dataclass
class SolverConfig:
    """TPU-solver plane wiring — new in this build (no reference analogue;
    plays the role BASELINE.json assigns to the AdmissionCheck-style solver
    extension). The CPU scheduler path is always available as fallback."""
    enable: bool = False
    max_heads: int = 2048          # padded batch width per solve
    max_flavors: int = 32
    # narrower cycles than this skip the accelerator (dispatch overhead
    # exceeds the win); 0 forces the solver for every cycle
    min_heads: int = 64
    device: str = ""               # "" = default jax backend
    fallback_on_error: bool = True
    # overlap the decision fetch of cycle N with dispatch of cycle N+1
    # (all-fit cycles; decisions land one cycle later)
    pipeline: bool = True
    # speculative dispatch depth: how many cycles may be in flight at
    # once. 2 (the production default) overlaps the donated arena
    # upload + next solve with TWO outstanding round trips; only
    # honored when every queued dispatch carries a SpeculationToken
    # (the scheduler collapses to 1 otherwise). 1 = the single-slot
    # pipeline.
    pipeline_depth: int = 2
    # "adaptive": measure admitted/sec per engine and run each cycle on
    # the faster one; "always"/"never" pin the device/CPU path
    routing: str = "adaptive"
    # Starvation bound: after this many consecutive cycles with a
    # blocked preempt-mode entry, pin strict sequential semantics
    # (reference resourcesToReserve ordering) until it unblocks; 0
    # disables the bound (the documented unbounded deviation)
    strict_after_blocked_cycles: int = DEFAULT_STRICT_AFTER_BLOCKED_CYCLES
    # Device-fault containment (kueue_tpu/resilience/RESILIENCE.md).
    # Watchdog: every device round trip carries a deadline of
    # (estimated device cycle seconds) x safety factor, clamped to
    # [min, max] — a collect past it is abandoned instead of blocking
    # the cycle on a wedged tunnel. min guards a sub-ms local-backend
    # estimate against GC-pause false positives; max is also the
    # no-estimate cold-start deadline (a first cycle may carry a
    # multi-second remote compile).
    watchdog_safety_factor: float = DEFAULT_WATCHDOG_SAFETY_FACTOR
    watchdog_min_deadline_s: float = DEFAULT_WATCHDOG_MIN_DEADLINE_S
    watchdog_max_deadline_s: float = DEFAULT_WATCHDOG_MAX_DEADLINE_S
    # Supervised dispatch (resilience/supervisor.py): run the dispatch
    # body (trace/compile/transfer) on a persistent worker thread under
    # the watchdog deadline, so a hang DURING dispatch is abandoned
    # instead of freezing the scheduler. Off = dispatch runs inline.
    supervise_dispatch: bool = True
    # Breaker: this many CONSECUTIVE device faults pin cycles to the
    # CPU fallback (route "cpu-breaker") until a half-open probe — after
    # exponential backoff from base to max, with jitter — succeeds.
    breaker_fault_threshold: int = DEFAULT_BREAKER_FAULT_THRESHOLD
    breaker_backoff_base_s: float = DEFAULT_BREAKER_BACKOFF_BASE_S
    breaker_backoff_max_s: float = DEFAULT_BREAKER_BACKOFF_MAX_S
    # Compile governor (solver/warmgov.py + solver/COMPILE.md).
    # compileCacheDir: root of the persistent XLA compilation cache;
    # the governor stamps a per-topology subdirectory
    # (topo-<fingerprint>) into the layout so a topology change cannot
    # replay stale executables, and a process restart reuses compiles.
    # "" keeps the default repo-local .jax_cache behavior.
    compile_cache_dir: str = ""
    # warmupAtStartup: launch the governor's supervised background
    # warmup thread from KueueManager construction — until a shape
    # bucket is warm, cycles that would dispatch it route "cpu-warmup"
    # (no hot-path compile). Off by default: deterministic drivers
    # (tests, tools) attach and start the governor explicitly.
    warmup_at_startup: bool = False
    # Per-bucket warmup deadline: a wedged remote compile abandons the
    # bucket (retried once, then skipped) and the ladder continues.
    warmup_deadline_s: float = DEFAULT_WARMUP_DEADLINE_S


@dataclass
class Configuration:
    # logging verbosity (the --v flag analogue; reference wires zap
    # through cmd/kueue/main.go): V2 cycle summaries, V5 attempts,
    # V6 snapshot dumps
    verbosity: int = 0
    namespace: str = DEFAULT_NAMESPACE
    manage_jobs_without_queue_name: bool = False
    client_connection: ClientConnection = field(default_factory=ClientConnection)
    wait_for_pods_ready: Optional[WaitForPodsReady] = None
    integrations: Integrations = field(default_factory=Integrations)
    queue_visibility: QueueVisibility = field(default_factory=QueueVisibility)
    fair_sharing: FairSharingConfig = field(default_factory=FairSharingConfig)
    multi_kueue: MultiKueueConfig = field(default_factory=MultiKueueConfig)
    resources: Resources = field(default_factory=Resources)
    scheduler: SchedulerConfig = field(default_factory=SchedulerConfig)
    store: StoreConfig = field(default_factory=StoreConfig)
    solver: SolverConfig = field(default_factory=SolverConfig)
    observability: ObservabilityConfig = field(
        default_factory=ObservabilityConfig)
    leader_election: LeaderElectionConfig = field(
        default_factory=LeaderElectionConfig)
    feature_gates: dict[str, bool] = field(default_factory=dict)


def set_defaults(cfg: Configuration) -> Configuration:
    """SetDefaults_Configuration (reference: defaults.go:66-191).
    Dataclass defaults cover the static values; this normalizes the
    conditional ones."""
    if cfg.wait_for_pods_ready is not None and not cfg.wait_for_pods_ready.enable:
        # timeout/block only meaningful when enabled (defaults.go:121-139)
        cfg.wait_for_pods_ready.block_admission = False
    if cfg.fair_sharing.enable and not cfg.fair_sharing.preemption_strategies:
        cfg.fair_sharing.preemption_strategies = [
            LESS_THAN_OR_EQUAL_TO_FINAL_SHARE, LESS_THAN_INITIAL_SHARE]
    return cfg


def validate(cfg: Configuration) -> list[str]:
    """reference: pkg/config/validation.go — returns a list of error strings."""
    errs = []
    w = cfg.wait_for_pods_ready
    if w is not None and w.enable:
        if w.timeout_seconds <= 0:
            errs.append("waitForPodsReady.timeout must be positive")
        rs = w.requeuing_strategy
        if rs.timestamp not in (EVICTION_TIMESTAMP, CREATION_TIMESTAMP):
            errs.append(f"waitForPodsReady.requeuingStrategy.timestamp: "
                        f"unsupported value {rs.timestamp!r}")
        if rs.backoff_limit_count is not None and rs.backoff_limit_count < 0:
            errs.append("waitForPodsReady.requeuingStrategy.backoffLimitCount "
                        "must be >= 0")
        if rs.backoff_base_seconds < 0:
            errs.append("waitForPodsReady.requeuingStrategy.backoffBaseSeconds "
                        "must be >= 0")
        if rs.backoff_max_seconds < 0:
            errs.append("waitForPodsReady.requeuingStrategy.backoffMaxSeconds "
                        "must be >= 0")
    for strategy in cfg.fair_sharing.preemption_strategies:
        if strategy not in (LESS_THAN_OR_EQUAL_TO_FINAL_SHARE, LESS_THAN_INITIAL_SHARE):
            errs.append(f"fairSharing.preemptionStrategies: unsupported value "
                        f"{strategy!r}")
    seen = set()
    for fw in cfg.integrations.frameworks:
        if fw not in ALL_INTEGRATIONS:
            errs.append(f"integrations.frameworks: unsupported framework {fw!r}")
        if fw in seen:
            errs.append(f"integrations.frameworks: duplicate framework {fw!r}")
        seen.add(fw)
    if cfg.multi_kueue.gc_interval_seconds < 0:
        errs.append("multiKueue.gcInterval must be >= 0")
    if cfg.multi_kueue.worker_lost_timeout_seconds < 0:
        errs.append("multiKueue.workerLostTimeout must be >= 0")
    if not _valid_label_value(cfg.multi_kueue.origin):
        errs.append("multiKueue.origin must be a valid label value")
    if cfg.solver.max_heads <= 0 or cfg.solver.max_flavors <= 0:
        errs.append("solver.maxHeads and solver.maxFlavors must be positive")
    if cfg.solver.strict_after_blocked_cycles < 0:
        errs.append("solver.strictAfterBlockedCycles must be >= 0 "
                    "(0 disables the starvation bound)")
    if cfg.solver.routing not in ("adaptive", "always", "never"):
        errs.append("solver.routing must be adaptive, always, or never")
    if cfg.solver.pipeline_depth < 1:
        errs.append("solver.pipelineDepth must be >= 1")
    if cfg.solver.watchdog_safety_factor <= 0 \
            or cfg.solver.watchdog_min_deadline_s <= 0 \
            or cfg.solver.watchdog_max_deadline_s \
            < cfg.solver.watchdog_min_deadline_s:
        errs.append("solver.watchdog: safetyFactor and minDeadline must be "
                    "positive with maxDeadline >= minDeadline")
    if cfg.solver.breaker_fault_threshold < 1:
        errs.append("solver.breakerFaultThreshold must be >= 1")
    if cfg.solver.breaker_backoff_base_s <= 0 \
            or cfg.solver.breaker_backoff_max_s \
            < cfg.solver.breaker_backoff_base_s:
        errs.append("solver.breakerBackoff: base must be positive and "
                    "max >= base")
    if cfg.solver.warmup_deadline_s <= 0:
        errs.append("solver.warmupDeadline must be positive")
    if cfg.observability.flight_recorder_capacity < 1:
        errs.append("observability.flightRecorderCapacity must be >= 1")
    if cfg.observability.journey_ledger_capacity < 1:
        errs.append("observability.journeyLedgerCapacity must be >= 1")
    if cfg.observability.journey_exemplars < 1:
        errs.append("observability.journeyExemplars must be >= 1")
    sc = cfg.scheduler
    if sc.cycle_budget_s < 0:
        errs.append("scheduler.cycleBudget must be >= 0 (0 disables "
                    "the degradation ladder)")
    if sc.shed_heads < 1 or sc.survival_heads < 1:
        errs.append("scheduler.shedHeads and scheduler.survivalHeads "
                    "must be >= 1")
    if not 0 < sc.overload_exit_factor <= sc.overload_enter_factor:
        errs.append("scheduler.overloadExitFactor must be in (0, "
                    "overloadEnterFactor] (the hysteresis band)")
    if sc.escalate_after_cycles < 1 or sc.recovery_cycles < 1:
        errs.append("scheduler.escalateAfterCycles and "
                    "scheduler.recoveryCycles must be >= 1")
    if not 0 < sc.cycle_ewma_alpha <= 1:
        errs.append("scheduler.cycleEwmaAlpha must be in (0, 1]")
    if cfg.store.checkpoint_every < 0:
        errs.append("store.checkpointEvery must be >= 0 (0 disables "
                    "automatic WAL compaction)")
    return errs


def _valid_label_value(v: str) -> bool:
    if len(v) > 63:
        return False
    if not v:
        return True
    ok = lambda c: c.isalnum() or c in "-_."
    return v[0].isalnum() and v[-1].isalnum() and all(ok(c) for c in v)


def load(raw: dict) -> Configuration:
    """Build a Configuration from a plain dict (the file format), apply
    defaults, and raise ValueError on validation failure
    (reference: pkg/config/config.go:150 Load)."""
    cfg = Configuration()
    if "namespace" in raw:
        cfg.namespace = raw["namespace"]
    cfg.manage_jobs_without_queue_name = raw.get("manageJobsWithoutQueueName", False)
    if "waitForPodsReady" in raw:
        w = raw["waitForPodsReady"]
        rs = w.get("requeuingStrategy", {})
        cfg.wait_for_pods_ready = WaitForPodsReady(
            enable=w.get("enable", False),
            timeout_seconds=w.get("timeout", DEFAULT_PODS_READY_TIMEOUT_SECONDS),
            block_admission=w.get("blockAdmission", True),
            recovery_timeout_seconds=w.get("recoveryTimeout"),
            requeuing_strategy=RequeuingStrategy(
                timestamp=rs.get("timestamp", EVICTION_TIMESTAMP),
                backoff_limit_count=rs.get("backoffLimitCount"),
                backoff_base_seconds=rs.get("backoffBaseSeconds",
                                            DEFAULT_REQUEUING_BACKOFF_BASE_SECONDS),
                backoff_max_seconds=rs.get("backoffMaxSeconds",
                                           DEFAULT_REQUEUING_BACKOFF_MAX_SECONDS),
                backoff_jitter=rs.get("backoffJitter",
                                      DEFAULT_REQUEUING_BACKOFF_JITTER),
            ),
        )
    if "integrations" in raw:
        i = raw["integrations"]
        pod_opts = i.get("podOptions", {})
        cfg.integrations = Integrations(
            frameworks=i.get("frameworks", list(DEFAULT_INTEGRATIONS)),
            external_frameworks=i.get("externalFrameworks", []),
            pod_options=PodIntegrationOptions(
                namespace_selector_exclude=pod_opts.get(
                    "namespaceSelectorExclude",
                    ["kube-system", DEFAULT_NAMESPACE])),
        )
    if "queueVisibility" in raw:
        q = raw["queueVisibility"]
        cfg.queue_visibility = QueueVisibility(
            update_interval_seconds=q.get(
                "updateIntervalSeconds", DEFAULT_QUEUE_VISIBILITY_UPDATE_INTERVAL_SECONDS),
            cluster_queues=ClusterQueueVisibility(
                max_count=q.get("clusterQueues", {}).get(
                    "maxCount", DEFAULT_CLUSTER_QUEUES_MAX_COUNT)),
        )
    if "fairSharing" in raw:
        f = raw["fairSharing"]
        cfg.fair_sharing = FairSharingConfig(
            enable=f.get("enable", False),
            preemption_strategies=f.get("preemptionStrategies", []),
        )
    if "multiKueue" in raw:
        m = raw["multiKueue"]
        cfg.multi_kueue = MultiKueueConfig(
            gc_interval_seconds=m.get("gcInterval", DEFAULT_MULTIKUEUE_GC_INTERVAL_SECONDS),
            origin=m.get("origin", DEFAULT_MULTIKUEUE_ORIGIN),
            worker_lost_timeout_seconds=m.get(
                "workerLostTimeout", DEFAULT_MULTIKUEUE_WORKER_LOST_TIMEOUT_SECONDS),
        )
    if "resources" in raw:
        cfg.resources = Resources(
            exclude_resource_prefixes=raw["resources"].get("excludeResourcePrefixes", []))
    if "scheduler" in raw:
        sc = raw["scheduler"]
        cfg.scheduler = SchedulerConfig(
            cycle_budget_s=sc.get("cycleBudget", DEFAULT_CYCLE_BUDGET_S),
            shed_heads=sc.get("shedHeads", DEFAULT_SHED_HEADS),
            survival_heads=sc.get("survivalHeads", DEFAULT_SURVIVAL_HEADS),
            overload_enter_factor=sc.get(
                "overloadEnterFactor", DEFAULT_OVERLOAD_ENTER_FACTOR),
            overload_exit_factor=sc.get(
                "overloadExitFactor", DEFAULT_OVERLOAD_EXIT_FACTOR),
            escalate_after_cycles=sc.get(
                "escalateAfterCycles", DEFAULT_ESCALATE_AFTER_CYCLES),
            recovery_cycles=sc.get("recoveryCycles",
                                   DEFAULT_RECOVERY_CYCLES),
            cycle_ewma_alpha=sc.get("cycleEwmaAlpha",
                                    DEFAULT_CYCLE_EWMA_ALPHA),
        )
    if "store" in raw:
        st = raw["store"]
        cfg.store = StoreConfig(
            durable=st.get("durable", False),
            wal_dir=st.get("walDir", ""),
            checkpoint_every=st.get("checkpointEvery",
                                    DEFAULT_STORE_CHECKPOINT_EVERY),
        )
    if "solver" in raw:
        s = raw["solver"]
        cfg.solver = SolverConfig(
            enable=s.get("enable", False),
            max_heads=s.get("maxHeads", 2048),
            max_flavors=s.get("maxFlavors", 32),
            min_heads=s.get("minHeads", 64),
            device=s.get("device", ""),
            fallback_on_error=s.get("fallbackOnError", True),
            pipeline=s.get("pipeline", True),
            pipeline_depth=s.get("pipelineDepth", 2),
            routing=s.get("routing", "adaptive"),
            strict_after_blocked_cycles=s.get(
                "strictAfterBlockedCycles",
                DEFAULT_STRICT_AFTER_BLOCKED_CYCLES),
            watchdog_safety_factor=s.get(
                "watchdogSafetyFactor", DEFAULT_WATCHDOG_SAFETY_FACTOR),
            watchdog_min_deadline_s=s.get(
                "watchdogMinDeadline", DEFAULT_WATCHDOG_MIN_DEADLINE_S),
            watchdog_max_deadline_s=s.get(
                "watchdogMaxDeadline", DEFAULT_WATCHDOG_MAX_DEADLINE_S),
            breaker_fault_threshold=s.get(
                "breakerFaultThreshold", DEFAULT_BREAKER_FAULT_THRESHOLD),
            breaker_backoff_base_s=s.get(
                "breakerBackoffBase", DEFAULT_BREAKER_BACKOFF_BASE_S),
            breaker_backoff_max_s=s.get(
                "breakerBackoffMax", DEFAULT_BREAKER_BACKOFF_MAX_S),
            supervise_dispatch=s.get("superviseDispatch", True),
            compile_cache_dir=s.get("compileCacheDir", ""),
            warmup_at_startup=s.get("warmupAtStartup", False),
            warmup_deadline_s=s.get("warmupDeadline",
                                    DEFAULT_WARMUP_DEADLINE_S),
        )
    if "observability" in raw:
        o = raw["observability"]
        cfg.observability = ObservabilityConfig(
            flight_recorder_enable=o.get("flightRecorderEnable", True),
            flight_recorder_capacity=o.get(
                "flightRecorderCapacity", DEFAULT_FLIGHT_RECORDER_CAPACITY),
            query_plane_enable=o.get("queryPlaneEnable", True),
            journey_enable=o.get("journeyEnable", True),
            journey_ledger_capacity=o.get(
                "journeyLedgerCapacity", DEFAULT_JOURNEY_LEDGER_CAPACITY),
            journey_exemplars=o.get(
                "journeyExemplars", DEFAULT_JOURNEY_EXEMPLARS),
        )
    cfg.feature_gates = dict(raw.get("featureGates", {}))
    cfg = set_defaults(cfg)
    errs = validate(cfg)
    if errs:
        raise ValueError("invalid configuration: " + "; ".join(errs))
    return cfg
