"""Preemption target selection and eviction issuing.

Equivalent of the reference's pkg/scheduler/preemption/preemption.go:
- findCandidates: lower-priority workloads in own CQ + borrowing CQs in
  the cohort per reclaimWithinCohort policy
- candidatesOrdering: evicted-first -> other-CQ-first -> lowest-priority
  -> most-recently-admitted
- minimalPreemptions: greedy remove until fit, then fill-back in reverse
- fairPreemptions: max-DRF-share CQ heap with strategies S2-a/S2-b
- the reclaim oracle feeding `reclaim` mode to the flavor assigner
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import find_condition
from kueue_tpu.cache.snapshot import ClusterQueueSnapshot, Snapshot
from kueue_tpu.core import priority as prioritypkg
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.scheduler import flavorassigner as fa
from kueue_tpu.utils.heap import Heap

PARALLEL_PREEMPTIONS = 8

HUMAN_READABLE_REASONS = {
    api.IN_CLUSTER_QUEUE_REASON: "prioritization in the ClusterQueue",
    api.IN_COHORT_RECLAMATION_REASON: "reclamation within the cohort",
    api.IN_COHORT_FAIR_SHARING_REASON: "fair sharing within the cohort",
    api.IN_COHORT_RECLAIM_WHILE_BORROWING_REASON:
        "reclamation within the cohort while borrowing",
}


@dataclass
class Target:
    workload_info: wlpkg.Info
    reason: str


def _strategy_s2a(preemptor_new_share, preemptee_old_share, preemptee_new_share) -> bool:
    """LessThanOrEqualToFinalShare (KEP-1714 rule S2-a)."""
    return preemptor_new_share <= preemptee_new_share


def _strategy_s2b(preemptor_new_share, preemptee_old_share, preemptee_new_share) -> bool:
    """LessThanInitialShare (rule S2-b)."""
    return preemptor_new_share < preemptee_old_share


def parse_strategies(names: list) -> list:
    if not names:
        return [_strategy_s2a, _strategy_s2b]
    mapping = {"LessThanOrEqualToFinalShare": _strategy_s2a,
               "LessThanInitialShare": _strategy_s2b}
    return [mapping[n] for n in names]


class Preemptor:
    def __init__(self, ordering: Optional[wlpkg.Ordering] = None,
                 enable_fair_sharing: bool = False,
                 fs_strategies: Optional[list] = None,
                 clock=None,
                 apply_preemption: Optional[Callable] = None):
        """apply_preemption(workload, preempting_cq, reason, message) performs the
        eviction write (SSA in the reference, store write here)."""
        from kueue_tpu.api.meta import REAL_CLOCK
        self.ordering = ordering or wlpkg.Ordering()
        self.enable_fair_sharing = enable_fair_sharing
        self.fs_strategies = fs_strategies or parse_strategies(None)
        self.clock = clock or REAL_CLOCK
        self.apply_preemption = apply_preemption or (lambda wl, cq, reason, msg: None)
        # Eviction-issuing fan-out width (reference: preemption.go:195
        # uses 8). 1 = sequential: the right default for the in-process
        # store (see issue_preemptions docstring).
        self.eviction_workers = 1

    # --- entry points ---

    def get_targets(self, wl: wlpkg.Info, assignment: fa.Assignment,
                    snapshot: Snapshot) -> list:
        frs_need_preemption = fa.flavor_resources_need_preemption(assignment)
        requests = assignment.total_requests_for(wl)
        return self.get_targets_internal(wl, requests, frs_need_preemption, snapshot)

    def get_targets_internal(self, wl: wlpkg.Info, requests: dict,
                             frs_need_preemption: set, snapshot: Snapshot) -> list:
        cq = snapshot.cluster_queues[wl.cluster_queue]
        candidates = self.find_candidates(wl.obj, cq, frs_need_preemption)
        if not candidates:
            return []
        candidates.sort(key=self._candidate_sort_key(cq.name))

        same_queue_candidates = [c for c in candidates if c.cluster_queue == cq.name]

        # Borrowing while preempting others' workloads causes flapping; only
        # allowed via borrowWithinCohort or fair sharing
        # (reference: preemption.go:131-172).
        if len(same_queue_candidates) == len(candidates):
            return minimal_preemptions(requests, cq, snapshot, frs_need_preemption,
                                       candidates, True, None)

        borrow_within_cohort, threshold_prio = can_borrow_within_cohort(cq, wl.obj)
        if self.enable_fair_sharing:
            return self.fair_preemptions(wl, requests, snapshot, frs_need_preemption,
                                         candidates, threshold_prio)
        if borrow_within_cohort:
            if not queue_under_nominal(frs_need_preemption, cq):
                candidates = [c for c in candidates
                              if c.cluster_queue == cq.name
                              or prioritypkg.priority(c.obj) < threshold_prio]
            return minimal_preemptions(requests, cq, snapshot, frs_need_preemption,
                                       candidates, True, threshold_prio)

        if queue_under_nominal(frs_need_preemption, cq):
            targets = minimal_preemptions(requests, cq, snapshot, frs_need_preemption,
                                          candidates, False, None)
            if targets:
                return targets

        return minimal_preemptions(requests, cq, snapshot, frs_need_preemption,
                                   same_queue_candidates, True, None)

    def issue_preemptions(self, preemptor: wlpkg.Info, targets: list) -> int:
        """Mark targets evicted (reference: preemption.go:195-235, an
        8-way parallelize.Until fan-out). eviction_workers mirrors that
        knob: >1 fans out on the shared bounded pool — worth it only
        when apply_preemption blocks (a remote store); the in-process
        store is GIL-bound pure Python, where the measured fan-out is a
        ~10-20% loss even chunked (tools/measure_evictions.py), so the
        default stays sequential."""
        from kueue_tpu.utils import parallelize

        def issue(i: int) -> None:
            target = targets[i]
            obj = target.workload_info.obj
            cond = find_condition(obj.status.conditions, api.WORKLOAD_EVICTED)
            if cond is None or cond.status != "True":
                message = (f"Preempted to accommodate a workload (UID: "
                           f"{preemptor.obj.metadata.uid}) due to "
                           f"{HUMAN_READABLE_REASONS[target.reason]}")
                self.apply_preemption(obj, preemptor.cluster_queue,
                                      target.reason, message)

        parallelize.until(len(targets), issue, workers=self.eviction_workers)
        return len(targets)

    # --- candidate discovery (reference: preemption.go:488-532) ---

    def find_candidates(self, wl: api.Workload, cq: ClusterQueueSnapshot,
                        frs_need_preemption: set) -> list:
        candidates = []
        wl_priority = prioritypkg.priority(wl)
        preemption = cq.preemption

        if preemption.within_cluster_queue != api.PREEMPTION_NEVER:
            consider_same_prio = (preemption.within_cluster_queue
                                  == api.PREEMPTION_LOWER_OR_NEWER_EQUAL_PRIORITY)
            preemptor_ts = self.ordering.queue_order_timestamp(wl)
            for cand in cq.workloads.values():
                cand_priority = prioritypkg.priority(cand.obj)
                if cand_priority > wl_priority:
                    continue
                if cand_priority == wl_priority and not (
                        consider_same_prio
                        and preemptor_ts < self.ordering.queue_order_timestamp(cand.obj)):
                    continue
                if not workload_uses_resources(cand, frs_need_preemption):
                    continue
                candidates.append(cand)

        if cq.cohort is not None and preemption.reclaim_within_cohort != api.PREEMPTION_NEVER:
            only_lower = preemption.reclaim_within_cohort != api.PREEMPTION_ANY
            # The borrowing domain spans the whole cohort tree for
            # hierarchical cohorts (root's subtree), which reduces to the
            # flat member set for single-level cohorts.
            for cohort_cq in cq.cohort.root().subtree_cqs():
                if cohort_cq is cq or not cq_is_borrowing(cohort_cq, frs_need_preemption):
                    continue
                for cand in cohort_cq.workloads.values():
                    if only_lower and prioritypkg.priority(cand.obj) >= wl_priority:
                        continue
                    if not workload_uses_resources(cand, frs_need_preemption):
                        continue
                    candidates.append(cand)
        return candidates

    def _candidate_sort_key(self, cq_name: str):
        """candidatesOrdering (reference: preemption.go:587-614). The
        status-derived components are memoized on the Info keyed by the
        object's resourceVersion (a candidate appears in many problems
        per cycle when cohorts share victims)."""
        now = self.clock.now()

        def sort_key(c: wlpkg.Info):
            obj = c.obj
            rv = obj.metadata.resource_version
            cached = getattr(c, "_cand_key_cache", None)
            if cached is None or cached[0] != rv:
                cond = find_condition(obj.status.conditions,
                                      api.WORKLOAD_QUOTA_RESERVED)
                reserved_at = (cond.last_transition_time
                               if cond and cond.status == "True" else None)
                cached = (rv, not wlpkg.is_evicted(obj),
                          prioritypkg.priority(obj), reserved_at,
                          obj.metadata.uid)
                c._cand_key_cache = cached
            _, not_evicted, prio, reserved_at, uid = cached
            in_cq = c.cluster_queue == cq_name
            return (not_evicted, in_cq, prio,
                    -(reserved_at if reserved_at is not None else now), uid)

        return sort_key

    # --- fair sharing (reference: preemption.go:343-438) ---

    def fair_preemptions(self, wl: wlpkg.Info, requests: dict, snapshot: Snapshot,
                         frs_need_preemption: set, candidates: list,
                         allow_borrowing_below_priority: Optional[int]) -> list:
        nominated_cq = snapshot.cluster_queues[wl.cluster_queue]
        # Determinized heap ties: equal-share CQs pop in order of their
        # first candidate's position in candidatesOrdering (the reference
        # leaves ties to binary-heap internals; the device kernel and this
        # path share this rule so decisions stay bit-comparable).
        first_pos: dict = {}
        for i, c in enumerate(candidates):
            first_pos.setdefault(c.cluster_queue, i)
        cq_heap = _cq_heap_from_candidates(candidates, False, snapshot,
                                           first_pos)
        new_nominated_share, _ = nominated_cq.dominant_resource_share_with(requests)
        targets: list = []
        fits = False
        retry_candidates: list = []
        while len(cq_heap) > 0 and not fits:
            cand_cq = cq_heap.pop()
            if cand_cq.cq is nominated_cq:
                cand_wl = cand_cq.workloads[0]
                snapshot.remove_workload(cand_wl)
                targets.append(Target(cand_wl, api.IN_CLUSTER_QUEUE_REASON))
                if workload_fits(requests, nominated_cq, True):
                    fits = True
                    break
                new_nominated_share, _ = nominated_cq.dominant_resource_share_with(requests)
                cand_cq.workloads = cand_cq.workloads[1:]
                if cand_cq.workloads:
                    cand_cq.share, _ = cand_cq.cq.dominant_resource_share()
                    cq_heap.push_if_not_present(cand_cq)
                continue

            for i, cand_wl in enumerate(cand_cq.workloads):
                below_threshold = (allow_borrowing_below_priority is not None
                                   and prioritypkg.priority(cand_wl.obj)
                                   < allow_borrowing_below_priority)
                new_cand_share, _ = cand_cq.cq.dominant_resource_share_without(
                    cand_wl.flavor_resource_usage())
                strategy_ok = self.fs_strategies[0](
                    new_nominated_share, cand_cq.share, new_cand_share)
                if below_threshold or strategy_ok:
                    snapshot.remove_workload(cand_wl)
                    reason = (api.IN_COHORT_FAIR_SHARING_REASON if strategy_ok
                              else api.IN_COHORT_RECLAIM_WHILE_BORROWING_REASON)
                    targets.append(Target(cand_wl, reason))
                    if workload_fits(requests, nominated_cq, True):
                        fits = True
                        break
                    cand_cq.workloads = cand_cq.workloads[i + 1:]
                    if cand_cq.workloads and cq_is_borrowing(cand_cq.cq, frs_need_preemption):
                        cand_cq.share = new_cand_share
                        cq_heap.push_if_not_present(cand_cq)
                    break
                else:
                    retry_candidates.append(cand_wl)

        if not fits and len(self.fs_strategies) > 1:
            cq_heap = _cq_heap_from_candidates(retry_candidates, True, snapshot,
                                               first_pos)
            while len(cq_heap) > 0 and not fits:
                cand_cq = cq_heap.pop()
                if self.fs_strategies[1](new_nominated_share, cand_cq.share, 0):
                    cand_wl = cand_cq.workloads[0]
                    snapshot.remove_workload(cand_wl)
                    targets.append(Target(cand_wl, api.IN_COHORT_FAIR_SHARING_REASON))
                    if workload_fits(requests, nominated_cq, True):
                        fits = True

        if not fits:
            _restore(snapshot, targets)
            return []
        targets = fill_back_workloads(targets, requests, nominated_cq, snapshot, True)
        _restore(snapshot, targets)
        return targets


def make_reclaim_oracle(preemptor: Preemptor, snapshot: Snapshot) -> Callable:
    """IsReclaimPossible (reference: preemption_oracle.go:40-51): the CQ can
    take fr/quantity back from the cohort without preempting its own
    workloads."""

    def is_reclaim_possible(cq: ClusterQueueSnapshot, wl: wlpkg.Info,
                            fr, quantity: int) -> bool:
        if cq.borrowing_with(fr, quantity):
            return False
        targets = preemptor.get_targets_internal(
            wl, {fr: quantity}, {fr}, snapshot)
        if not targets:
            return False
        return all(t.workload_info.cluster_queue != cq.name for t in targets)

    return is_reclaim_possible


# --- minimal preemption heuristic (reference: preemption.go:237-310) ---

def minimal_preemptions(requests: dict, cq: ClusterQueueSnapshot, snapshot: Snapshot,
                        frs_need_preemption: set, candidates: list,
                        allow_borrowing: bool,
                        allow_borrowing_below_priority: Optional[int]) -> list:
    targets: list = []
    fits = False
    for cand in candidates:
        cand_cq = snapshot.cluster_queues[cand.cluster_queue]
        reason = api.IN_CLUSTER_QUEUE_REASON
        if cq is not cand_cq:
            if not cq_is_borrowing(cand_cq, frs_need_preemption):
                continue
            reason = api.IN_COHORT_RECLAMATION_REASON
            if allow_borrowing_below_priority is not None:
                if prioritypkg.priority(cand.obj) >= allow_borrowing_below_priority:
                    # A candidate at/above the threshold forbids borrowing for
                    # the remainder (reference: preemption.go:252-270).
                    allow_borrowing = False
                else:
                    reason = api.IN_COHORT_RECLAIM_WHILE_BORROWING_REASON
        snapshot.remove_workload(cand)
        targets.append(Target(cand, reason))
        if workload_fits(requests, cq, allow_borrowing):
            fits = True
            break
    if not fits:
        _restore(snapshot, targets)
        return []
    targets = fill_back_workloads(targets, requests, cq, snapshot, allow_borrowing)
    _restore(snapshot, targets)
    return targets


def fill_back_workloads(targets: list, requests: dict, cq: ClusterQueueSnapshot,
                        snapshot: Snapshot, allow_borrowing: bool) -> list:
    for i in range(len(targets) - 2, -1, -1):
        snapshot.add_workload(targets[i].workload_info)
        if workload_fits(requests, cq, allow_borrowing):
            targets[i] = targets[-1]
            targets.pop()
        else:
            snapshot.remove_workload(targets[i].workload_info)
    return targets


def _restore(snapshot: Snapshot, targets: list) -> None:
    for t in targets:
        snapshot.add_workload(t.workload_info)


# --- helpers ---

def can_borrow_within_cohort(cq: ClusterQueueSnapshot, wl: api.Workload):
    bwc = cq.preemption.borrow_within_cohort
    if bwc is None or bwc.policy == api.BORROW_WITHIN_COHORT_NEVER:
        return False, None
    threshold = prioritypkg.priority(wl)
    if bwc.max_priority_threshold is not None and bwc.max_priority_threshold < threshold:
        threshold = bwc.max_priority_threshold + 1
    return True, threshold


def cq_is_borrowing(cq: ClusterQueueSnapshot, frs_need_preemption: set) -> bool:
    if cq.cohort is None:
        return False
    return any(cq.borrowing(fr) for fr in frs_need_preemption)


def workload_uses_resources(wl: wlpkg.Info, frs_need_preemption: set) -> bool:
    return not frs_need_preemption.isdisjoint(wl.flavor_resource_keys())


def workload_fits(requests: dict, cq: ClusterQueueSnapshot, allow_borrowing: bool) -> bool:
    for fr, v in requests.items():
        if not allow_borrowing and cq.borrowing_with(fr, v):
            return False
        if v > cq.available(fr):
            return False
    return True


def queue_under_nominal(frs_need_preemption: set, cq: ClusterQueueSnapshot) -> bool:
    return all(cq.usage_for(fr) < cq.quota_for(fr).nominal
               for fr in frs_need_preemption)


class _CandidateCQ:
    __slots__ = ("cq", "workloads", "share", "order")

    def __init__(self, cq, workloads, share, order=0):
        self.cq = cq
        self.workloads = workloads
        self.share = share
        self.order = order


def _cq_heap_from_candidates(candidates: list, first_only: bool,
                             snapshot: Snapshot,
                             first_pos: Optional[dict] = None) -> Heap:
    first_pos = first_pos or {}
    cq_heap: Heap = Heap(
        key_func=lambda c: c.cq.name,
        less_func=lambda a, b: (a.share > b.share
                                or (a.share == b.share
                                    and a.order < b.order)))
    for cand in candidates:
        existing = cq_heap.get_by_key(cand.cluster_queue)
        if existing is None:
            cq = snapshot.cluster_queues[cand.cluster_queue]
            share, _ = cq.dominant_resource_share()
            cq_heap.push_or_update(_CandidateCQ(
                cq, [cand], share,
                first_pos.get(cand.cluster_queue, 0)))
        elif not first_only:
            existing.workloads.append(cand)
    return cq_heap
