"""Partial admission: binary search over the reducible pod count.

Equivalent of the reference's
pkg/scheduler/flavorassigner/podset_reducer.go:29-86: scale each PodSet
between min_count..count proportionally; the predicate is "assignment
fits (or can preempt)".
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple


def reduction_space(pod_sets: list) -> Tuple[list, list, int]:
    """(full counts, per-podset reducible deltas, total delta) — the
    search space both the sequential reducer and the solver's batched
    lockstep search iterate (podset_reducer.go:29-45). Shared so the two
    can never drift on the interpolation."""
    full_counts = [ps.count for ps in pod_sets]
    deltas = [ps.count - (ps.min_count if ps.min_count is not None else ps.count)
              for ps in pod_sets]
    return full_counts, deltas, sum(deltas)


def counts_for_index(full_counts: list, deltas: list, total_delta: int,
                     i: int) -> list:
    """Proportional scaling of each PodSet at reduction index i
    (podset_reducer.go:47-56)."""
    return [full - (d * i) // total_delta
            for full, d in zip(full_counts, deltas)]


class PodSetReducer:
    def __init__(self, pod_sets: list, fits: Callable[[list], Tuple[object, bool]]):
        self.pod_sets = pod_sets
        self.full_counts, self.deltas, self.total_delta = \
            reduction_space(pod_sets)
        self.fits = fits

    def _counts_for_index(self, i: int) -> list:
        return counts_for_index(self.full_counts, self.deltas,
                                self.total_delta, i)

    def search(self) -> Tuple[Optional[object], bool]:
        """Find the largest counts that pass fits() (smallest reduction
        index), via binary search like Go's sort.Search."""
        if self.total_delta == 0:
            return None, False
        last_good_idx = -1
        last_result = None
        lo, hi = 0, self.total_delta + 1  # search smallest i with fits true
        while lo < hi:
            mid = (lo + hi) // 2
            result, ok = self.fits(self._counts_for_index(mid))
            if ok:
                last_good_idx = mid
                last_result = result
                hi = mid
            else:
                lo = mid + 1
        return last_result, lo == last_good_idx
