"""Per-workload, per-PodSet, per-resource-group flavor assignment.

Equivalent of the reference's pkg/scheduler/flavorassigner/flavorassigner.go:
walks the CQ's flavor list in order (resuming from LastTriedFlavorIdx —
the FlavorFungibility state machine), checking taints, node affinity and
quota fit; classifies each (flavor, resource) as fit/preempt/reclaim/noFit
with borrow flags; whenCanBorrow/whenCanPreempt policies decide whether to
try the next flavor.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from kueue_tpu import features
from kueue_tpu.api import kueue as api
from kueue_tpu.api.corev1 import PodSpec, RESOURCE_PODS, find_untolerated_taint
from kueue_tpu.cache.snapshot import ClusterQueueSnapshot
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.core.resources import FlavorResource

# API-level assignment modes, ordered by preference
# (reference: flavorassigner.go:205-233)
NO_FIT = 0
PREEMPT = 1
FIT = 2

# granular modes (reference: flavorassigner.go:238-258)
_G_NOFIT = 0
_G_PREEMPT = 1
_G_RECLAIM = 2
_G_FIT = 3


def _granular_to_api(mode: int) -> int:
    if mode == _G_FIT:
        return FIT
    if mode in (_G_PREEMPT, _G_RECLAIM):
        return PREEMPT
    return NO_FIT


def mode_name(mode: int) -> str:
    return {NO_FIT: "NoFit", PREEMPT: "Preempt", FIT: "Fit"}[mode]


@dataclass(slots=True)
class FlavorAssignment:
    name: str
    mode: int
    tried_flavor_idx: int = 0
    borrow: bool = False


@dataclass(slots=True)
class PodSetAssignmentResult:
    name: str = ""
    flavors: Optional[dict] = None  # resource -> FlavorAssignment
    reasons: list = field(default_factory=list)
    error: Optional[str] = None
    requests: dict = field(default_factory=dict)
    count: int = 0

    def representative_mode(self) -> int:
        if not self.reasons and self.error is None:
            return FIT
        if not self.flavors:
            return NO_FIT
        return min(fa.mode for fa in self.flavors.values())


@dataclass(slots=True)
class Assignment:
    pod_sets: list = field(default_factory=list)
    borrowing: bool = False
    usage: dict = field(default_factory=dict)  # FlavorResource -> int
    last_state: wlpkg.AssignmentClusterQueueState = field(
        default_factory=wlpkg.AssignmentClusterQueueState)
    _representative_mode: Optional[int] = None

    def borrows(self) -> bool:
        return self.borrowing

    def representative_mode(self) -> int:
        if not self.pod_sets:
            return NO_FIT
        if self._representative_mode is None:
            self._representative_mode = min(
                ps.representative_mode() for ps in self.pod_sets)
        return self._representative_mode

    def message(self) -> str:
        msgs = []
        for ps in self.pod_sets:
            if ps.error is not None:
                return f"failed to assign flavors to pod set {ps.name}: {ps.error}"
            if ps.reasons:
                msgs.append(f"couldn't assign flavors to pod set {ps.name}: "
                            + ", ".join(sorted(ps.reasons)))
        return "; ".join(msgs)

    def to_api(self) -> list:
        out = []
        for ps in self.pod_sets:
            flavors = {res: fa.name for res, fa in (ps.flavors or {}).items()}
            out.append(api.PodSetAssignment(
                name=ps.name, flavors=flavors,
                resource_usage=dict(ps.requests), count=ps.count))
        return out

    def total_requests_for(self, wl: wlpkg.Info) -> dict:
        usage: dict = {}
        for i, psr in enumerate(wl.total_requests):
            for res, q in psr.requests.items():
                flv = self.pod_sets[i].flavors[res].name if self.pod_sets[i].flavors else ""
                fr = FlavorResource(flv, res)
                usage[fr] = usage.get(fr, 0) + q
        return usage


def flavor_resources_need_preemption(assignment: Assignment) -> set:
    out = set()
    for ps in assignment.pod_sets:
        for res, fa in (ps.flavors or {}).items():
            if fa.mode == PREEMPT:
                out.add(FlavorResource(fa.name, res))
    return out


def flavor_selector_matches(pod_spec: PodSpec, allowed_keys: set,
                            flavor_labels: dict) -> bool:
    """Node-affinity match against flavor nodeLabels, restricted to the
    resource group's label keys (reference: flavorassigner.go:539-583)."""
    for k, v in pod_spec.node_selector.items():
        if k in allowed_keys and flavor_labels.get(k) != v:
            return False
    aff = pod_spec.affinity
    if aff and aff.node_affinity and aff.node_affinity.required:
        terms = []
        for t in aff.node_affinity.required.node_selector_terms:
            exprs = [e for e in t.match_expressions if e.key in allowed_keys]
            if not exprs:
                # An empty term matches everything and terms are ORed.
                terms = []
                break
            terms.append(exprs)
        if terms:
            matched = any(all(e.matches(flavor_labels) for e in exprs)
                          for exprs in terms)
            if not matched:
                return False
    return True


class FlavorAssigner:
    def __init__(self, wl: wlpkg.Info, cq: ClusterQueueSnapshot,
                 resource_flavors: dict, enable_fair_sharing: bool = False,
                 oracle: Optional[Callable] = None):
        """oracle(cq, wl, fr, quantity) -> bool: IsReclaimPossible."""
        self.wl = wl
        self.cq = cq
        self.resource_flavors = resource_flavors
        self.enable_fair_sharing = enable_fair_sharing
        self.oracle = oracle or (lambda cq, wl, fr, q: False)

    def assign(self, counts: Optional[list] = None) -> Assignment:
        if self.wl.last_assignment is not None and self._last_assignment_outdated():
            self.wl.last_assignment = None
        if not counts:
            return self._assign_flavors(self.wl.total_requests)
        scaled = [psr.scaled_to(counts[i]) for i, psr in enumerate(self.wl.total_requests)]
        return self._assign_flavors(scaled)

    def _last_assignment_outdated(self) -> bool:
        la = self.wl.last_assignment
        return (self.cq.allocatable_resource_generation > la.cluster_queue_generation
                or (self.cq.cohort is not None
                    and self.cq.cohort.allocatable_resource_generation > la.cohort_generation))

    def _assign_flavors(self, requests: list) -> Assignment:
        assignment = Assignment()
        assignment.last_state = wlpkg.AssignmentClusterQueueState(
            cluster_queue_generation=self.cq.allocatable_resource_generation,
            cohort_generation=(self.cq.cohort.allocatable_resource_generation
                               if self.cq.cohort else 0))

        for ps_idx, psr in enumerate(requests):
            ps_requests = dict(psr.requests)
            if self.cq.rg_by_resource(RESOURCE_PODS) is not None:
                ps_requests[RESOURCE_PODS] = psr.count

            ps_result = PodSetAssignmentResult(
                name=psr.name, flavors={}, requests=ps_requests, count=psr.count)

            for res_name in ps_requests:
                if res_name in ps_result.flavors:
                    continue  # covered by an earlier resource-group pass
                flavors, reasons, error = self._find_flavor_for_podset_resource(
                    ps_idx, ps_requests, res_name, assignment.usage)
                if error is not None or not flavors:
                    ps_result.flavors = None
                    ps_result.reasons = reasons
                    ps_result.error = error
                    break
                ps_result.flavors.update(flavors)
                ps_result.reasons.extend(reasons)

            self._append(assignment, ps_requests, ps_result)
            if ps_result.error is not None or (ps_requests and not ps_result.flavors):
                return assignment
        return assignment

    def _append(self, assignment: Assignment, requests: dict,
                ps: PodSetAssignmentResult) -> None:
        assignment.pod_sets.append(ps)
        flavor_idx = {}
        for res, fa in (ps.flavors or {}).items():
            if fa.borrow:
                assignment.borrowing = True
            fr = FlavorResource(fa.name, res)
            assignment.usage[fr] = assignment.usage.get(fr, 0) + requests[res]
            flavor_idx[res] = fa.tried_flavor_idx
        assignment.last_state.last_tried_flavor_idx.append(flavor_idx)

    def _find_flavor_for_podset_resource(self, ps_idx: int, requests: dict,
                                         res_name: str, assignment_usage: dict):
        """Returns (flavors: dict[res -> FlavorAssignment] | None,
        reasons: list, error: str | None)."""
        rg = self.cq.rg_by_resource(res_name)
        if rg is None:
            return None, [f"resource {res_name} unavailable in ClusterQueue"], None

        group_requests = {r: v for r, v in requests.items() if r in rg.covered_resources}
        pod_spec = self.wl.obj.spec.pod_sets[ps_idx].template.spec
        reasons: list = []
        best_assignment = None
        best_mode = _G_NOFIT
        attempted_idx = -1

        idx = (self.wl.last_assignment.next_flavor_to_try(ps_idx, res_name)
               if self.wl.last_assignment else 0)
        fungibility_on = features.enabled(features.FLAVOR_FUNGIBILITY)
        while idx < len(rg.flavors):
            attempted_idx = idx
            f_name = rg.flavors[idx]
            idx += 1
            flavor = self.resource_flavors.get(f_name)
            if flavor is None:
                reasons.append(f"flavor {f_name} not found")
                continue
            taint = find_untolerated_taint(flavor.spec.node_taints, pod_spec.tolerations)
            if taint is not None:
                reasons.append(f"untolerated taint {taint.key} in flavor {f_name}")
                continue
            if not flavor_selector_matches(pod_spec, rg.label_keys, flavor.spec.node_labels):
                reasons.append(f"flavor {f_name} doesn't match node affinity")
                continue

            needs_borrowing = False
            assignments: dict = {}
            representative_mode = _G_FIT
            for r_name, val in group_requests.items():
                fr = FlavorResource(f_name, r_name)
                mode, borrow, reason = self._fits_resource_quota(
                    fr, val + assignment_usage.get(fr, 0))
                if reason:
                    reasons.append(reason)
                representative_mode = min(representative_mode, mode)
                needs_borrowing = needs_borrowing or borrow
                if representative_mode == _G_NOFIT:
                    break
                assignments[r_name] = FlavorAssignment(
                    name=f_name, mode=_granular_to_api(mode), borrow=borrow)

            if fungibility_on:
                if not _should_try_next_flavor(representative_mode,
                                               self.cq.flavor_fungibility,
                                               needs_borrowing):
                    best_assignment = assignments
                    best_mode = representative_mode
                    break
                if representative_mode > best_mode:
                    best_assignment = assignments
                    best_mode = representative_mode
            elif representative_mode > best_mode:
                best_assignment = assignments
                best_mode = representative_mode
                if best_mode == _G_FIT:
                    return best_assignment, [], None

        if fungibility_on:
            for fa in (best_assignment or {}).values():
                # Reached the last flavor -> restart from the first next time.
                fa.tried_flavor_idx = (-1 if attempted_idx == len(rg.flavors) - 1
                                       else attempted_idx)
            if best_mode == _G_FIT:
                return best_assignment, [], None
        return best_assignment, reasons, None

    def _fits_resource_quota(self, fr: FlavorResource, val: int):
        """(granular mode, borrow, reason) — reference:
        flavorassigner.go:591-636."""
        reason = None
        borrow = False
        quota = self.cq.quota_for(fr)
        used = self.cq.usage_for(fr)
        mode = _G_NOFIT
        if val <= quota.nominal:
            # Could fit if quota is reclaimed from the cohort or all
            # workloads in the CQ are preempted.
            mode = _G_PREEMPT

        if self._can_preempt_while_borrowing():
            if ((quota.borrowing_limit is None
                 or val <= quota.nominal + quota.borrowing_limit)
                    and val <= self.cq.potential_available(fr)):
                mode = _G_PREEMPT
                borrow = val > quota.nominal
        if (quota.borrowing_limit is not None
                and used + val > quota.nominal + quota.borrowing_limit):
            return mode, borrow, (f"borrowing limit for {fr.resource} in flavor "
                                  f"{fr.flavor} exceeded")

        if self.oracle(self.cq, self.wl, fr, val):
            mode = _G_RECLAIM

        lack = val - self.cq.available(fr)
        if lack <= 0:
            return _G_FIT, used + val > quota.nominal, None

        if self.cq.cohort is None:
            if mode == _G_NOFIT:
                reason = (f"insufficient quota for {fr.resource} in flavor "
                          f"{fr.flavor} in ClusterQueue")
            else:
                reason = (f"insufficient unused quota for {fr.resource} in flavor "
                          f"{fr.flavor}, {lack} more needed")
        else:
            reason = (f"insufficient unused quota in cohort for {fr.resource} in "
                      f"flavor {fr.flavor}, {lack} more needed")
        return mode, borrow, reason

    def _can_preempt_while_borrowing(self) -> bool:
        p = self.cq.preemption
        return ((p.borrow_within_cohort is not None
                 and p.borrow_within_cohort.policy != api.BORROW_WITHIN_COHORT_NEVER)
                or (self.enable_fair_sharing
                    and p.reclaim_within_cohort != api.PREEMPTION_NEVER))


def _should_try_next_flavor(representative_mode: int,
                            fungibility: api.FlavorFungibility,
                            needs_borrowing: bool) -> bool:
    """reference: flavorassigner.go:519-537."""
    policy_preempt = fungibility.when_can_preempt
    policy_borrow = fungibility.when_can_borrow
    if representative_mode in (_G_PREEMPT, _G_RECLAIM) and policy_preempt == api.PREEMPT:
        if not needs_borrowing or policy_borrow == api.BORROW:
            return False
    if representative_mode == _G_FIT and needs_borrowing and policy_borrow == api.BORROW:
        return False
    if representative_mode == _G_FIT and not needs_borrowing:
        return False
    return True
