"""Admission engine: the sequential (CPU) scheduler.

This is the conformance oracle for the batched TPU solver in
kueue_tpu.solver, and the fallback path (reference: pkg/scheduler).
"""

from kueue_tpu.scheduler.flavorassigner import (  # noqa: F401
    FIT,
    NO_FIT,
    PREEMPT,
    Assignment,
    FlavorAssigner,
)
from kueue_tpu.scheduler.scheduler import Scheduler  # noqa: F401
