"""One admission cycle: heads -> snapshot -> nominate -> sort -> admit.

Equivalent of the reference's pkg/scheduler/scheduler.go:197-353
(MultiplePreemptions path):
1. queues.heads() (blocks until any CQ head exists)
2. cache.snapshot()
3. nominate(): per-head validation + flavor assignment + preemption targets
4. sort by borrows -> DRF share -> priority -> FIFO
5. sequential admit with intra-cycle usage accounting: skip overlapping
   preemption targets, re-check fit after earlier admissions, reserve
   capacity for blocked preemptors
6. requeue non-admitted heads with Pending condition patches

The batched TPU solver (kueue_tpu.solver) replaces steps 3-5; this CPU
path is the conformance oracle and fallback.
"""

from __future__ import annotations

import threading
import time as _time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from kueue_tpu import features
from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import REAL_CLOCK, Clock
from kueue_tpu.cache import Cache, Snapshot
from kueue_tpu.cache.snapshot import ClusterQueueSnapshot
from kueue_tpu.core import limitrange as limitrangepkg
from kueue_tpu.core import priority as prioritypkg
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.core.resources import container_limits_violations
from kueue_tpu.obs import FlightRecorder
from kueue_tpu.queue import Manager, RequeueReason
from kueue_tpu.resilience.breaker import CLOSED, CircuitBreaker
from kueue_tpu.resilience.degrade import NORMAL, DegradationLadder
from kueue_tpu.resilience import faultinject
from kueue_tpu.resilience.faultinject import DeviceFault
from kueue_tpu.resilience.watchdog import DispatchTimeout, DispatchWatchdog
from kueue_tpu.scheduler import flavorassigner as fa
from kueue_tpu.scheduler import stages
from kueue_tpu.scheduler.podset_reducer import PodSetReducer
from kueue_tpu.scheduler.preemption import Preemptor, Target, make_reclaim_oracle
from kueue_tpu.utils import vlog
from kueue_tpu.utils.wait import KeepGoing, SlowDown, SpeedSignal, until_with_backoff

# entry statuses (reference: scheduler.go:355-366)
NOT_NOMINATED = ""
NOMINATED = "nominated"
SKIPPED = "skipped"
ASSUMED = "assumed"


@dataclass
class Entry:
    info: wlpkg.Info
    assignment: fa.Assignment = field(default_factory=fa.Assignment)
    preemption_targets: list = field(default_factory=list)
    dominant_resource_share: int = 0
    dominant_resource_name: str = ""
    status: str = NOT_NOMINATED
    inadmissible_msg: str = ""
    requeue_reason: RequeueReason = RequeueReason.GENERIC

    def net_usage(self) -> dict:
        """Capacity needed net of preempted resources
        (reference: scheduler.go:385-400)."""
        if self.assignment.representative_mode() == fa.FIT:
            return self.assignment.usage
        usage = dict(self.assignment.usage)
        for target in self.preemption_targets:
            for fr, v in target.workload_info.flavor_resource_usage().items():
                if fr in usage:
                    usage[fr] = max(0, usage[fr] - v)
        return usage


class SchedulerClient:
    """Host-environment interface for the scheduler's reads/writes.

    The reference talks to the kube-apiserver; here the in-process object
    store (kueue_tpu.sim) implements this, and tests use fakes.
    """

    def namespace_labels(self, namespace: str) -> Optional[dict]:
        return {}

    def limit_ranges(self, namespace: str) -> list:
        return []

    def apply_admission(self, wl: api.Workload) -> None:
        """Persist admission status. Raise KeyError if deleted."""

    def patch_not_admitted(self, wl: api.Workload) -> None:
        """Persist the Pending/QuotaReserved=False condition."""

    def event(self, wl: api.Workload, event_type: str, reason: str, message: str) -> None:
        pass


class Scheduler:
    def __init__(self, queues: Manager, cache: Cache, client: SchedulerClient,
                 ordering: Optional[wlpkg.Ordering] = None,
                 fair_sharing_enabled: bool = False,
                 fs_preemption_strategies: Optional[list] = None,
                 clock: Clock = REAL_CLOCK,
                 metrics=None,
                 solver=None, solver_min_heads: int = 64,
                 recorder: Optional[FlightRecorder] = None):
        from kueue_tpu.scheduler.preemption import parse_strategies
        self.queues = queues
        self.cache = cache
        self.client = client
        self.ordering = ordering or wlpkg.Ordering()
        self.fair_sharing_enabled = fair_sharing_enabled
        self.clock = clock
        self.attempt_count = 0
        # Cumulative admissions sealed by this scheduler instance's
        # cycles — the per-shard admitted_total feed (parallel/shards.py
        # reads the delta per cycle; standalone managers just get a
        # free lifetime counter).
        self.admitted_total = 0
        self.preemption_fallbacks = 0  # device-preemption error fallbacks
        self.metrics = metrics
        # Optional kueue_tpu.solver.BatchSolver: batched fit-mode admission
        # on TPU; CPU path handles the remainder (preemption, partial
        # admission) and acts as the fallback when None.
        self.solver = solver
        if solver is not None and hasattr(solver, "bind_cache"):
            # Device-resident solver state: the cache journal reconciles
            # it across cycles (no per-cycle state re-encode/upload).
            solver.bind_cache(cache)
        if solver is not None and hasattr(solver, "bind_queues"):
            # Workload encode arena: the queue manager's delta feed
            # maintains per-workload encoded rows across cycles.
            solver.bind_queues(queues)
        # Cycle flight recorder (kueue_tpu/obs): every schedule() call
        # that popped heads produces a CycleTrace (route, regime, phase
        # spans, fault/breaker annotations) in a bounded ring, feeding
        # /debug/cycles and the cycle_phase_seconds histograms.
        self.recorder = recorder if recorder is not None else FlightRecorder()
        if solver is not None and hasattr(solver, "bind_recorder"):
            solver.bind_recorder(self.recorder)
        # Speculative admission pipeline: overlap the solve of snapshot
        # N with the apply of cycle N-1 (see _schedule_pipelined and
        # scheduler/PIPELINE.md). Every dispatch carries a generation
        # stamp (stages.SpeculationToken) and the apply step validates
        # it before committing — mis-speculation abandons the in-flight
        # result and falls back to the synchronous path. Off by default
        # — decisions land one cycle later, so conformance suites and
        # latency-sensitive deployments keep the synchronous cycle; the
        # manager/bench production wiring turns it on.
        self.pipeline_enabled = False
        # In-flight speculative cycles, oldest first. Depth 1 (the
        # default) reproduces the single-slot pipeline exactly; depth 2
        # lets dispatch N+2 launch while N's decisions are still on the
        # wire — the donated arena upload and the next solve overlap
        # TWO round trips instead of one. Deepening past 1 is only
        # honored when every queued dispatch carries a SpeculationToken
        # (the full staleness witness); a token-less dispatch collapses
        # the effective depth to 1. One mis-speculation aborts EVERY
        # queued cycle (they chain on the same device state), so no
        # stale admission can ride out on the deeper queue.
        self._inflight_q: deque = deque()
        self.pipeline_depth = 1
        self._pipeline_cooldown = 0
        # Speculation outcome counters (the pipelined hit-rate story):
        # hits = validated-and-committed speculative cycles, aborts =
        # mis-speculations (by validation reason).
        self.speculation_hits = 0
        self.speculation_aborts = 0
        self.speculation_abort_reasons: dict = {}
        # Which pipelined shape the last _schedule_pipelined call took
        # (device-pipelined / device-dispatch-only / device-nofit): the
        # cycle trace's route label for pipelined cycles.
        self._pipeline_trace_route = "device-pipelined"
        self._drained_admitted = None  # last _drain_pipeline's admissions
        # Adaptive routing (the production config): measure admitted/sec
        # per (engine, cycle regime) over a sliding window and run each
        # cycle on the faster engine for its predicted regime,
        # re-exploring the minority engine periodically. "always" pins
        # the device path (conformance suites), "never" pins CPU.
        self.solver_routing = "always"
        # {(engine, regime): [(admitted, secs), ...]}; regime is "fit"
        # or "preempt" — the two backlog shapes route differently (a
        # preempt-heavy cycle is sequential-simulation-bound; a fit
        # cycle is batched-assignment-bound), so one global estimate
        # per engine lets whichever regime dominates early lock the
        # router for the other (VERDICT r4 weak #2).
        self._route_stats: dict = {}
        self._route_explore: dict = {"fit": 0, "preempt": 0}
        # Last device preempt-plan solve stats (candidate pool size,
        # auction/fill-back rounds, fill-back outcomes), decoded from the
        # kernel's stats outputs: annotated onto the cycle trace's
        # preempt-plan span and surfaced via /debug/router
        # (obs.router_status) so operators can see what the batched
        # preemptor actually did.
        self.last_preempt_plan: dict = {}
        self._last_regime = "fit"    # sticky regime predictor
        self._cycle_regime = "fit"   # observed regime of the cycle run
        self._last_cycle_admitted = 0
        # Engine engagement counters for the perf artifacts: how many
        # cycles ran per engine ("device-pipelined" = collected
        # pipelined cycles; hit rate = pipelined / all device cycles).
        self.cycle_counts: dict = {}
        # Starvation bound (VERDICT r4 ask #7): the solver mixed-cycle
        # equivalence class admits device fit entries before blocked
        # preempt-mode entries reserve, so a sustained fit stream can
        # starve a blocked preemptor indefinitely. After a preempt-mode
        # entry has been blocked for this many consecutive observed
        # cycles, route cycles to the strict sequential path (full
        # reference semantics: global sort + resourcesToReserve,
        # scheduler.go:443-462) until no blocked preemptor remains —
        # the preemptor then admits exactly when the reference would.
        # 0 disables the bound.
        from kueue_tpu.config import DEFAULT_STRICT_AFTER_BLOCKED_CYCLES
        self.strict_after_blocked_cycles = DEFAULT_STRICT_AFTER_BLOCKED_CYCLES
        self._blocked_preempt_streak = 0
        self._preemptless_cycles = 0  # consecutive cycles w/o preempt mode
        # Device-fault containment (kueue_tpu/resilience): the watchdog
        # derives a deadline for every device round trip from the
        # router's regime-keyed rate estimates (falling back to the
        # measured sync floor); the breaker, fed by watchdog timeouts
        # and dispatch/collect exceptions, pins cycles to the CPU
        # fallback ("cpu-breaker" — excluded from router samples like
        # "cpu-strict") after N consecutive faults and re-admits the
        # device path through half-open probes with backed-off jitter.
        self.breaker = CircuitBreaker()
        self.watchdog: Optional[DispatchWatchdog] = DispatchWatchdog()
        # Compile governor (solver/warmgov.py): when attached (the
        # manager/perf wiring), the scheduler consults its warm-state
        # before committing a cycle to the device route — an un-warmed
        # shape bucket routes to the CPU path as "cpu-warmup" instead
        # of blocking on a hot-path compile, and the governor warms the
        # bucket in the background. None (or an idle governor) leaves
        # routing untouched.
        self.warm_gov = None
        self.solver_faults = 0          # device faults observed (total)
        self._cycle_faults = 0          # device faults within this cycle
        # Optional observer hook (the manager wires it to the sim event
        # recorder): on_fault(kind, message) for fault/trip/recovery
        # and degradation-ladder transitions.
        self.on_fault: Optional[Callable[[str, str], None]] = None
        # Cycle deadline budget (kueue_tpu/resilience/degrade.py): the
        # ladder watches every cycle's wall seconds (the same spend the
        # flight-recorder trace records) against scheduler.cycleBudget
        # and, under pressure, walks normal -> shed (head cap + deferred
        # preempt planning) -> survival (tighter cap + the cycle pinned
        # to the CPU-incremental route "cpu-survival"). Disabled by
        # default (budget 0); the manager wires the config knobs.
        self.ladder = DegradationLadder()
        self._cycle_degraded = NORMAL  # ladder state this cycle RAN under
        self._degrade_deferred = 0     # preempt plans deferred this cycle
        self.shed_heads_requeued = 0   # heads re-heaped by the cap (total)
        self.preempt_plans_deferred = 0  # deferred preempt plans (total)
        self._drain_cost = 0.0  # pipeline-drain seconds within this cycle
        self._cycle_evictions = 0  # evictions issued within this cycle
        # Transport accounting baseline at cycle start (solver counter
        # snapshot): _finish_trace stamps the per-cycle DELTAS — bytes
        # on the wire and device round trips — onto the cycle trace,
        # so /debug/cycles and tools/transport_probe.py can price every
        # cycle's host<->device traffic without lifetime-counter math.
        self._cycle_io0 = (0, 0, 0, 0)
        # Snapshot-backed query plane (obs/queryplane.py): when attached
        # (manager wiring), every cycle seal publishes an immutable read
        # view — the cycle's nominate order, the generation token, and
        # (sync cycles) the cycle's snapshot handout, whose ownership
        # transfers to the plane instead of being released back to the
        # maintainer. None = reads fall back to the live visibility API.
        self.query_plane = None
        self._cycle_order: Optional[list] = None  # admission-sorted keys
        self._seal_snapshot = None  # handout pending transfer at seal
        # The sync cycle's live snapshot handout, tracked between take
        # and retire so an abandonment path (a crash that escaped
        # mid-cycle, a sharded plane discarding a dead shard's
        # scheduler) can release it — the local in the aborted
        # schedule() frame is otherwise unreachable and would leak a
        # handout the shared cache counts forever.
        self._cycle_snapshot = None
        # Workload journey ledger (obs/journey.py + ISSUE 14): when
        # attached (manager wiring), every admit/requeue/shed/defer
        # site stamps a causally-tagged journey span, and the ledger
        # becomes THE emission site for the reservation/admission
        # wait-time histograms (reconcile-by-construction). None =
        # every hook is one is-None compare (the journey_overhead
        # bench contract) and the histograms keep their direct calls.
        self.journeys = None
        # Aging watch (obs/trend.py): sampled once per cycle seal when
        # attached — the ROADMAP item 5 monotone-resource trend
        # monitors (/debug/aging).
        self.aging = None
        # Below this head count the accelerator dispatch overhead exceeds
        # the win; narrow cycles go through the CPU path even with a
        # solver configured (SolverConfig.min_heads; 0 = always solve).
        self.solver_min_heads = solver_min_heads
        # Preemption work gate: the device preemptor saves roughly
        # (CPU simulate ~12us - encode/decode ~4us) per candidate, so it
        # must cover the marginal sync cost — the full measured dispatch
        # floor when no fit entries dispatch this cycle, zero otherwise.
        # solver_sync_floor_ms overrides the measured floor (tests use 0
        # to force the device path on tiny problems). The per-candidate
        # CPU-cost constants are machine-dependent — tune per deployment.
        self.solver_sync_floor_ms: Optional[float] = None
        self.preempt_cand_us = 8.0  # minimal preemptor: simulate/candidate
        self.fair_cand_us = 3.0     # fairPreemptions: share compare/cand
        self.preemptor = Preemptor(
            ordering=self.ordering,
            enable_fair_sharing=fair_sharing_enabled,
            fs_strategies=parse_strategies(fs_preemption_strategies),
            clock=clock,
            apply_preemption=self._apply_preemption)
        # Leveled structured logging (reference: pkg/scheduler/logging.go):
        # V(2) cycle summaries, V(5) attempts, V(6) snapshot dumps.
        self.log = vlog.logger("scheduler")
        # Synchronous by default; swap for async in production wiring
        # (reference: routine wrapper, scheduler.go:590).
        self.admission_routine: Callable[[Callable], None] = lambda f: f()
        # Crash-restart recovery (resilience/recovery.py): restore()
        # stamps its report here for /debug/recovery and the dumper.
        self.last_recovery: Optional[dict] = None
        # MultiKueue batched-column placement (ISSUE 13): when the
        # manager wires on_placement (to MultiKueueController.
        # note_placement), every admitted workload whose CQ routes
        # through a multikueue check gets a cluster choice AT ADMISSION
        # TIME — device-routed cycles take the fused solve's mk_cluster
        # column, CPU-routed cycles run the identical sequential oracle
        # (encode.place_remote_dicts) against the snapshot's capacity
        # columns — and the controller mirrors only to that cluster,
        # eliminating the per-workload mirror-everywhere race from the
        # admission hot path.
        self.on_placement: Optional[Callable[[str, str], None]] = None
        self._mk_admits: list = []  # (Info, cq snapshot) this apply stage
        # HA: only the leader runs admission cycles (reference:
        # NeedLeaderElection, scheduler.go:144). None = standalone.
        self.leader_check: Optional[Callable[[], bool]] = None
        # Fencing (resilience/replica.py + RESILIENCE.md §7): when a
        # leader lease with fencing epochs is in effect, the
        # speculative commit point consults this alongside the
        # generation token — a deposed leader's in-flight cycle aborts
        # un-decoded (reason "fenced") before the store's own Fenced
        # backstop can even be reached. None = no lease regime.
        self.fencing_check: Optional[Callable[[], bool]] = None
        # Hot-standby operator surface: a StandbyReplica wires its
        # status producer here (on the follower AND carried through
        # promotion), and promote() stamps its report — both served by
        # /debug/recovery (obs/status.recovery_status).
        self.standby_status: Optional[Callable[[], dict]] = None
        self.last_promotion: Optional[dict] = None
        # Sharded control plane (parallel/shards.py): an admission
        # shard's scheduler pops ONLY the CQs its layout assigns it —
        # cq_filter(cq_name) -> bool, threaded into every heads() pop.
        # None = unsharded (pop everything), the standalone default.
        self.cq_filter: Optional[Callable[[str], bool]] = None
        # /debug/shards producer: the ShardedControlPlane wires its
        # status() onto the PLANE manager's scheduler (the one serving
        # the debug surface), mirroring standby_status above.
        self.shards_status: Optional[Callable[[], dict]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # --- lifecycle ---

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True, name="scheduler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.queues.broadcast()
        if self._thread is not None:
            self._thread.join(timeout=5)
        # A snapshot parked for a seal that never happened (exception
        # mid-cycle) must not outlive the scheduler.
        self._flush_seal_snapshot()
        # Never strand an in-flight speculative cycle at shutdown: its
        # deferred-nomination handout must go back to the snapshot
        # maintainer and its device-residency + arena claims must drop,
        # or a solver reused across a restart would chain the NEXT
        # manager's first cycle on the dead manager's usage mirror
        # (ISSUE 10 satellite — previously stop() left _inflight set
        # and both leaked until process exit).
        if self._inflight is not None:
            self._abandon_pipeline()

    def _run(self) -> None:
        until_with_backoff(self._stop, lambda: self.schedule(timeout=0.2))

    # --- the cycle ---

    def schedule(self, timeout: Optional[float] = None) -> SpeedSignal:
        if self.leader_check is not None and not self.leader_check():
            # Non-leader replica: watch caches stay warm, but an
            # in-flight pipelined cycle must be ABANDONED, not drained —
            # the new leader may admit these same heads, so our device
            # decisions are stale the moment leadership lapses.
            if self._inflight is not None:
                self._abandon_pipeline()
            return SlowDown
        self.attempt_count += 1
        if (self.solver is not None and hasattr(self.solver, "bind_cache")
                and getattr(self.solver, "_cache", None) is None):
            # Solvers attached after construction (tests, tools) still get
            # the journal-backed device-resident state.
            self.solver.bind_cache(self.cache)
        if (self.solver is not None and hasattr(self.solver, "bind_queues")
                and getattr(self.solver, "_queues", None) is None):
            self.solver.bind_queues(self.queues)
        if (self.solver is not None and hasattr(self.solver, "bind_recorder")
                and getattr(self.solver, "_recorder", None)
                is not self.recorder):
            self.solver.bind_recorder(self.recorder)
        heads = self.queues.heads(timeout=timeout,
                                  cq_filter=self.cq_filter)
        if not heads:
            if self._inflight is not None:
                # A headless drain still round-trips the device (collect
                # + decode + admit): trace it under its own route name.
                # heads=0 is honest — the drained batch's heads were
                # counted by the cycle that dispatched them.
                trace = self.recorder.begin_cycle(self.attempt_count)
                self._journey_begin_cycle("drain")
                self._cycle_evictions = 0
                self._cycle_faults = 0
                self._cycle_io0 = self._io_counters()
                self._cycle_order = None
                self._flush_seal_snapshot()
                self._cycle_degraded = self.ladder.state
                sig = self._drain_pipeline()
                self._finish_trace(trace, "drain", heads=0,
                                   admitted=self._drained_admitted)
                return sig
            # Idle tick: a degraded ladder with an empty queue must not
            # hold its rung until traffic resumes — quiescence IS
            # health (PR-5 follow-up).
            self._observe_idle()
            return KeepGoing
        start = self.clock.now()
        wall0 = _time.perf_counter()
        trace = self.recorder.begin_cycle(self.attempt_count)
        self._journey_begin_cycle()
        self._drain_cost = 0.0
        self._cycle_evictions = 0
        self._cycle_faults = 0
        self._cycle_io0 = self._io_counters()
        self._cycle_order = None
        self._flush_seal_snapshot()
        self._degrade_deferred = 0
        # The ladder rung this cycle RUNS under (transitions only happen
        # at cycle end, in _observe_budget): shed/survival cap the heads
        # NOW — extras re-heap untouched, no status churn.
        self._cycle_degraded = self.ladder.state
        heads_popped = len(heads)
        cap = self.ladder.head_cap()
        if cap is not None and len(heads) > cap:
            heads = self._shed_extra_heads(heads, cap)
        collects0 = getattr(self.solver, "counters", {}).get("collects", 0) \
            if self.solver is not None else 0
        route = self._route_mode(heads)
        if (route == "device" and self.strict_after_blocked_cycles
                and self._blocked_preempt_streak
                >= self.strict_after_blocked_cycles):
            # Starvation bound engaged: a fairness intervention, not an
            # economics signal — the non-routable label keeps it out of
            # the router's samples, and the distinct name makes the
            # bound's engagement visible in the perf artifacts. Stays
            # engaged until the blocked preemptor admits, becomes
            # infeasible, or goes away.
            route = "cpu-strict"
        if route in ("device", "cpu") and self.ladder.pin_cpu:
            # Survival rung: the cycle is pinned to the CPU-incremental
            # route — full reference semantics over the journal-replay
            # snapshot, no device sync, no compile risk. Covers BOTH
            # economics routes: an adaptive "cpu" choice in survival
            # must still be renamed, or the capped cycle would land in
            # the router's cpu samples and hide from survival_cycles.
            # Like cpu-strict this is an intervention, not an economics
            # signal, and like cpu-strict it is consulted BEFORE the
            # breaker so it can never consume (and wedge) a half-open
            # probe. (cpu-forced/cpu-strict/cpu-breaker keep their own
            # names — each is already a non-sample with its own
            # operator meaning; _route_record skips every degraded
            # cycle regardless.)
            route = "cpu-survival"
        if route == "device" and self.warm_gov is not None \
                and not self.warm_gov.route_ready(len(heads)):
            # Compile governor (solver/warmgov.py): this cycle's batch
            # width encodes into a bucket with no warm programs, so a
            # dispatch would carry a jit compile on the hot path — the
            # exact stall the governor exists to keep off measured
            # cycles. Route to the CPU path (full reference semantics,
            # no compile risk) under a distinct name and ask the
            # governor to warm the bucket in the background. Like
            # cpu-strict/cpu-survival this is an intervention, not an
            # economics signal (never a router sample), and it is
            # consulted BEFORE the breaker so it can never consume
            # (and wedge) a half-open probe.
            self.warm_gov.request(len(heads))
            route = "cpu-warmup"
        if route == "device" \
                and not self.breaker.allow_device(self.clock.now()):
            # Breaker open: pin the cycle to the CPU fallback under a
            # distinct route name — a containment intervention, not an
            # economics signal, so (like cpu-strict) it never lands in
            # the router's samples. The CPU sequential path carries full
            # reference semantics, so correctness is unaffected.
            # Consulted only AFTER the strict gate: allow_device()
            # consumes the half-open probe, and a probe admitted on a
            # cycle another gate then routes off-device would leave the
            # breaker wedged in HALF_OPEN with no outcome ever recorded.
            route = "cpu-breaker"
        if self.journeys is not None:
            # Spans emitted from here on carry the decided route (the
            # pipelined path may refine it to device-pipelined/-nofit
            # on its trace; the journey stamp keeps the decision the
            # entries were actually routed under).
            self.journeys.set_route(route)
        # Cooldown elapses per schedule() call, not per device-routed
        # call — a CPU-routed stretch must not freeze it.
        cooling = self._pipeline_cooldown > 0
        if cooling:
            self._pipeline_cooldown -= 1

        if route == "device" and not cooling and self._pipeline_ok(heads):
            signal = self._schedule_pipelined(heads, start)
            if signal is not None:
                # _process_inflight set the regime of the COLLECTED
                # cycle (fit, or preempt for pipelined mixed) — the
                # routing sample lands under it.
                progress = (None if self._last_cycle_admitted is None
                            else self._last_cycle_admitted
                            + self._cycle_evictions)
                self._route_record("device", progress,
                                   _time.perf_counter() - wall0
                                   - self._drain_cost)
                self._note_device_cycle(collects0)
                self._observe_budget(_time.perf_counter() - wall0,
                                     heads_popped,
                                     self._last_cycle_admitted)
                self._finish_trace(trace, self._pipeline_trace_route,
                                   heads=len(heads),
                                   admitted=self._last_cycle_admitted)
                return signal
            # Pipeline not applicable this cycle: continue on the
            # synchronous path with a FRESH full snapshot. The pipelined
            # attempt's snapshot is LIGHT (shares the live cache's trees)
            # and must never be handed to the sync path, which simulates
            # preemption and accounts usage on its snapshot.
        elif self._inflight is not None:
            # The gate closed (cooldown, StrictFIFO appeared, pipeline
            # toggled off) with a cycle still in flight: drain it BEFORE
            # the sync snapshot, or its device-applied admissions would be
            # invisible to nominate() and its workloads stranded.
            self._drain_pipeline()

        t_ph = _time.perf_counter()
        snapshot = self.cache.snapshot()
        self._cycle_snapshot = snapshot
        self._span("snapshot", t_ph)
        vlog.dump_snapshot(self.log, snapshot)

        # The explicit stage machine (stages.py carries the typed
        # contracts; the speculative pipeline above runs the same
        # stages with solve overlapped across cycles).
        nom = self._stage_nominate(heads, snapshot, route, timeout)
        self._stage_apply(nom, timeout)
        applied = self._stage_requeue(nom)
        entries = nom.entries
        if self.query_plane is not None:
            # The cycle's nominate order (solver-routed entries first,
            # then the admission-sorted CPU entries — exactly the order
            # the apply loop consumed): the query plane's decision-only
            # position column, captured once per cycle.
            self._cycle_order = [e.info.key for e in entries]
        result_success = applied.success
        admitted_n = applied.admitted
        skipped_preemptions = nom.skipped_preemptions
        # Observed regime of this cycle feeds the regime-keyed router:
        # the sample lands under what the cycle WAS, and the next
        # cycle's engine choice predicts it will look the same.
        regime = applied.regime
        self._cycle_regime = regime
        self._last_regime = regime
        # A preempt-mode entry is blocked only when it found NO feasible
        # targets (the reserve-capacity branch): feed the starvation
        # bound. An entry that selected targets is PROGRESSING — it
        # issued evictions (PENDING_PREEMPTION) or lost an intra-cycle
        # race (overlap/fit skip) that resolves by itself; counting
        # either as blocked let healthy preemption churn ratchet the
        # streak to the bound and pin device-routed cycles to cpu-strict
        # (ADVICE r5 medium). This mirrors _collect_pipelined_preempt,
        # which sets blocked_any only for target-less entries. Cycles
        # with NO preempt-mode entry at all: a blocked preemptor parks
        # inadmissible between capacity releases, so a SHORT arrival-
        # only stretch (up to the bound) keeps the starvation evidence
        # intact — but past that grace the evidence decays one cycle at
        # a time (never a wholesale reset), so it cannot carry over to
        # an UNRELATED later preemptor after the original one vanished
        # (ADVICE r5 follow-up), while a parked preemptor that re-heaps
        # within the grace still accumulates toward the bound. While
        # the bound is ENGAGED the decay is immediate, so a vanished
        # preemptor releases strict mode within ~K cycles.
        blocked = applied.blocked_preemptor
        if self._degrade_deferred:
            # Deferred preempt plans look exactly like blocked
            # preemptors (target-less PREEMPT entries), but the ladder
            # chose not to plan them — shedding must not ratchet the
            # starvation bound into cpu-strict on top of itself.
            blocked = False
        if blocked:
            self._blocked_preempt_streak += 1
            self._preemptless_cycles = 0
        elif regime == "preempt":
            self._blocked_preempt_streak = 0  # preemptors made progress
            self._preemptless_cycles = 0
        elif self._blocked_preempt_streak > 0:
            self._preemptless_cycles += 1
            bound = self.strict_after_blocked_cycles
            engaged = bound and self._blocked_preempt_streak >= bound
            if engaged or self._preemptless_cycles > max(bound, 1):
                self._blocked_preempt_streak -= 1
        self.cycle_counts[route] = self.cycle_counts.get(route, 0) + 1
        if route == "device":
            self._note_device_cycle(collects0)
        # The cycle is done with its snapshot: without a query plane the
        # incremental maintainer may recycle un-materialized shells into
        # the next handout; with one attached, ownership transfers to
        # the read plane at seal instead (_finish_trace publishes it).
        self._retire_cycle_snapshot(snapshot)
        if route in ("device", "cpu"):
            # Progress = admissions + evictions: a pure-eviction cycle
            # admits zero on EITHER engine, and an all-zero rate pair
            # would pin the router to its tie-break default.
            self._route_record(route, admitted_n + self._cycle_evictions,
                               _time.perf_counter() - wall0
                               - self._drain_cost)
        self.log.v(2, "cycle", engine=route, heads=len(entries),
                   admitted=admitted_n,
                   ms=round((_time.perf_counter() - wall0) * 1e3, 1))

        if self.metrics is not None:
            self.metrics.admission_attempt(result_success, self.clock.now() - start)
            for cq_name, count in skipped_preemptions.items():
                self.metrics.preemption_skips(cq_name, count)
        self._observe_budget(_time.perf_counter() - wall0, heads_popped,
                             admitted_n)
        self._finish_trace(trace, route, heads=len(entries),
                           admitted=admitted_n)
        return KeepGoing if result_success else SlowDown

    # --- the stage machine (typed contracts in scheduler/stages.py) ---

    def _stage_nominate(self, heads: list, snapshot: Snapshot, route: str,
                        timeout) -> stages.NominatedCycle:
        """NOMINATE stage: route the device share through the solve
        stage, CPU-nominate the remainder (validation + flavor
        assignment + preemption discovery) against the cycle snapshot,
        and sort by the admission order. Returns the NominatedCycle the
        apply stage consumes."""
        solver_entries: list = []
        pre_entries: list = []
        if route == "device":
            solver_entries, pre_entries, heads = self._stage_solve(
                heads, snapshot, timeout)
        t_ph = _time.perf_counter()
        defer_shed = self.ladder.defer_preemption
        entries = pre_entries + self.nominate(heads, snapshot,
                                              defer_preemption=defer_shed)
        if defer_shed:
            # Shed/survival: preempt planning (target selection — the
            # superlinear part of a preempt-heavy cycle) is deferred;
            # target-less preempt entries keep their reserve-capacity
            # semantics in apply and re-heap for when the ladder
            # recovers.
            self._defer_preempt_plans(entries)
        entries.sort(key=self._entry_sort_key())
        self._span("nominate", t_ph)
        return stages.NominatedCycle(snapshot=snapshot, entries=entries,
                                     solver_entries=solver_entries,
                                     route=route)

    def _stage_apply(self, nom: stages.NominatedCycle, timeout) -> None:
        """APPLY stage: sequential admit with intra-cycle usage
        accounting — skip overlapping preemption targets, re-check fit
        after earlier admissions, reserve capacity for blocked
        preemptors, issue evictions (reference: scheduler.go:238-330).
        Mutates the nominated entries in place."""
        snapshot = nom.snapshot
        t_ph = _time.perf_counter()
        preempted_workloads: set = set()
        skipped_preemptions = nom.skipped_preemptions
        for e in nom.entries:
            mode = e.assignment.representative_mode()
            if mode == fa.NO_FIT:
                continue
            cq = snapshot.cluster_queues[e.info.cluster_queue]

            if mode == fa.PREEMPT and not e.preemption_targets:
                # Reserve capacity so lower-priority workloads can't admit
                # ahead of the blocked preemptor (reference: scheduler.go:245-253).
                cq.add_usage(resources_to_reserve(e, cq))
                continue

            pending = {t.workload_info.key for t in e.preemption_targets}
            if pending & preempted_workloads:
                self._set_skipped(e, "Workload has overlapping preemption targets "
                                     "with another workload")
                skipped_preemptions[cq.name] = skipped_preemptions.get(cq.name, 0) + 1
                continue

            usage = e.net_usage()
            if not cq.fits(usage):
                self._set_skipped(e, "Workload no longer fits after processing "
                                     "another workload")
                if mode == fa.PREEMPT:
                    skipped_preemptions[cq.name] = skipped_preemptions.get(cq.name, 0) + 1
                continue
            preempted_workloads.update(pending)
            cq.add_usage(usage)

            if mode != fa.FIT:
                if e.preemption_targets:
                    # Next attempt should try all flavors again.
                    e.info.last_assignment = None
                    preempted = self.preemptor.issue_preemptions(e.info, e.preemption_targets)
                    self._cycle_evictions += preempted
                    if preempted:
                        e.inadmissible_msg += (f". Pending the preemption of "
                                               f"{preempted} workload(s)")
                        e.requeue_reason = RequeueReason.PENDING_PREEMPTION
                continue

            if not self.cache.pods_ready_for_all_admitted_workloads():
                # waitForPodsReady blockAdmission (reference: scheduler.go:316-327).
                # Patch a clone: e.info.obj may alias the store's object
                # (shared watch events).
                patch = wlpkg.clone_for_status_update(e.info.obj)
                wlpkg.unset_quota_reservation_with_condition(
                    patch, "Waiting",
                    "waiting for all admitted workloads to be in PodsReady condition",
                    self.clock.now())
                self.client.patch_not_admitted(patch)
                self.cache.wait_for_pods_ready(timeout=timeout)

            e.status = NOMINATED
            try:
                self.admit(e, cq)
            except Exception as exc:  # noqa: BLE001 — cache/API races surface here
                e.inadmissible_msg = f"Failed to admit workload: {exc}"
        self._flush_mk_placements(snapshot)
        self._span("apply", t_ph)

    def _stage_requeue(self, nom: stages.NominatedCycle) -> stages.AppliedCycle:
        """Requeue sweep closing the apply stage: re-heap every
        non-admitted entry (solver-routed entries rejoin here), count
        admissions, and report the cycle's observed regime + blocked-
        preemptor evidence for the starvation bound."""
        entries = nom.solver_entries + nom.entries
        nom.entries = entries  # the merged list (trace head count)
        vlog.dump_attempts(self.log, entries)
        result_success = False
        admitted_n = 0
        t_ph = _time.perf_counter()
        for e in entries:
            if e.status != ASSUMED:
                self.requeue_and_update(e)
            else:
                result_success = True
                admitted_n += 1
                self._solver_release_workload(e.info.key)
        self.admitted_total += admitted_n
        self._span("requeue", t_ph)
        regime = "preempt" if any(
            e.preemption_targets
            or e.assignment.representative_mode() == fa.PREEMPT
            for e in entries) else "fit"
        # A preempt-mode entry is blocked only when it found NO feasible
        # targets (the reserve-capacity branch) — see the streak logic
        # in schedule() for why.
        blocked = any(
            e.status != ASSUMED
            and e.assignment.representative_mode() == fa.PREEMPT
            and not e.preemption_targets
            for e in entries)
        return stages.AppliedCycle(admitted=admitted_n,
                                   success=result_success,
                                   regime=regime,
                                   blocked_preemptor=blocked)

    def _observe_idle(self) -> None:
        """An idle scheduler tick (no heads popped): feed the
        degradation ladder's recovery counter. A degraded ladder with
        an empty queue used to hold its rung until traffic resumed
        (PR-5 follow-up) — quiescence is the healthiest signal there
        is, so idle ticks rung the ladder down."""
        lad = self.ladder
        if not lad.enabled or lad.state == NORMAL:
            return
        prev = lad.state
        if not lad.observe_idle():
            return
        msg = (f"degraded-mode {prev}->{lad.state}: queue idle for "
               f"{lad.recovery_cycles} scheduler tick(s)")
        self.log.v(2, "degrade.transition", previous=prev, state=lad.state,
                   idle=True)
        if self.metrics is not None:
            self.metrics.set_degraded_state(lad.state)
        if self.on_fault is not None:
            self.on_fault("degrade-recovered" if lad.state == NORMAL
                          else "degrade", msg)

    # --- pipelined dispatch (device-resident state, all-fit cycles) ---
    #
    # Overlaps the decision fetch of cycle N with the head-pop + encode +
    # dispatch of cycle N+1 (VERDICT r3 missing #2): cycle N+1's device
    # input state is cycle N's device OUTPUT state (resident chaining), so
    # N+1 can dispatch before N's decisions ever reach the host — the
    # ~100ms tunnel round trip is hidden behind N's decode+admit work.
    #
    # Documented semantic deviations from the sequential cycle (pinned by
    # tests/test_solver.py::TestPipelinedEquivalence):
    # - heads for cycle N+1 are popped BEFORE cycle N's requeues: an entry
    #   skipped in N retries in N+2 instead of N+1 (StrictFIFO CQs gate
    #   pipelining off entirely — their requeued head must block).
    # - the fit router's prediction runs against a mirror that lags by the
    #   one in-flight cycle; a mispredicted entry is requeued and the next
    #   cycle runs synchronously (cooldown), where fresh state routes it
    #   to CPU preempt-mode nomination exactly like the sync path.

    # --- flight recorder (kueue_tpu/obs) ---

    def _span(self, name: str, t0: float) -> float:
        """Record a scheduler-side phase span ending now; returns now so
        consecutive phases chain without a second perf_counter call."""
        t1 = _time.perf_counter()
        self.recorder.span(name, t0, t1 - t0)
        return t1

    def _io_counters(self) -> tuple:
        """(upload_bytes, fetch_bytes, dispatches, collects) from the
        solver's lifetime counters — the transport baseline snapshotted
        at cycle start so _finish_trace can stamp per-cycle deltas."""
        c = getattr(self.solver, "counters", None)
        if not c:
            return (0, 0, 0, 0)
        return (c.get("upload_bytes", 0), c.get("fetch_bytes", 0),
                c.get("dispatches", 0), c.get("collects", 0))

    def _journey_begin_cycle(self, route: str = "") -> None:
        """Stamp the journey ledger's cycle context (attempt id +
        structural generation token) so every span this cycle emits is
        causally tagged. One is-None compare when no ledger is wired."""
        led = self.journeys
        if led is None:
            return
        led.begin_cycle(self.attempt_count, self.cache.generation_token())
        if route:
            led.set_route(route)

    def _finish_trace(self, trace, route: str, heads: int,
                      admitted: Optional[int]) -> None:
        """Seal this cycle's trace and feed the observability metrics.
        The cycle_phase_seconds histogram is fed FROM the trace's span
        sums, so /debug/cycles and /metrics reconcile by construction;
        the breaker gauge updates every cycle regardless of the
        recorder (it is a metrics concern, not a tracing one)."""
        if self.metrics is not None:
            self.metrics.set_breaker_state(self.breaker.state)
            self.metrics.set_degraded_state(self.ladder.state)
        if trace is not None:
            trace.route = route
            trace.regime = self._cycle_regime
            trace.heads = heads
            trace.admitted = admitted
            trace.evictions = self._cycle_evictions
            trace.faults = self._cycle_faults
            trace.breaker = self.breaker.state
            trace.degraded = self._cycle_degraded
            io = self._io_counters()
            base = self._cycle_io0
            trace.upload_bytes = io[0] - base[0]
            trace.fetch_bytes = io[1] - base[1]
            trace.dispatches = io[2] - base[2]
            trace.collects = io[3] - base[3]
            self.recorder.finish(trace)
            if self.metrics is not None:
                self.metrics.cycle_observed(route, heads,
                                            trace.phase_sums())
        # Query-plane seal rides the same point (independent of the
        # recorder being enabled): the read plane refreshes atomically
        # at every cycle seal.
        self._publish_query_plane(route)
        # Journey ledger + aging watch ride the seal too: the ledger
        # refreshes its per-cycle gauges (requeues_per_admission), the
        # watch samples its monotone-resource monitors exactly once per
        # cycle — both one is-None compare when not wired.
        if self.journeys is not None:
            self.journeys.seal_cycle()
        if self.aging is not None:
            self.aging.sample()

    def _flush_seal_snapshot(self) -> None:
        """Release a snapshot parked for seal but never published — an
        exception escaped schedule() between _retire_cycle_snapshot and
        _finish_trace (the chaos harnesses catch and keep driving).
        Without this the next cycle's reset would strand the handout
        and live_handouts could never return to zero."""
        snap, self._seal_snapshot = self._seal_snapshot, None
        if snap is not None:
            self.cache.release_snapshot(snap)
        # A cycle snapshot still tracked here means the previous cycle
        # aborted between take and retire (an InjectedCrash escaping
        # mid-cycle) — release it the same way.
        snap, self._cycle_snapshot = self._cycle_snapshot, None
        if snap is not None:
            self.cache.release_snapshot(snap)

    def _retire_cycle_snapshot(self, snapshot: Snapshot) -> None:
        """The sync cycle is done with its snapshot handout. Without a
        query plane it goes straight back to the maintainer (shell
        recycling); with one attached its ownership transfers to the
        read plane at seal — readers serve status queries from it until
        the next full-snapshot view rotates it out, and it stays
        counted in ``cache.live_handouts`` while held (the SNAPSHOTS.md
        reader-consumer contract)."""
        self._cycle_snapshot = None
        if self.query_plane is None:
            self.cache.release_snapshot(snapshot)
        else:
            self._seal_snapshot = snapshot

    def _publish_query_plane(self, route: str) -> None:
        qp = self.query_plane
        snap, self._seal_snapshot = self._seal_snapshot, None
        order, self._cycle_order = self._cycle_order, None
        if qp is None:
            if snap is not None:  # plane detached mid-cycle: don't leak
                self.cache.release_snapshot(snap)
            return
        qp.publish(self.attempt_count, route, order, snapshot=snap)

    # --- cycle deadline budget (kueue_tpu/resilience/degrade.py) ---

    def _shed_extra_heads(self, heads: list, cap: int) -> list:
        """Shed/survival head cap: keep the top-``cap`` heads by the
        admission order's available prefix — priority (when the gate
        is on, mirroring _entry_sort_key) then queue-order timestamp —
        and re-heap the rest untouched. Timestamp alone would invert
        priority exactly when the system is overloaded and priority
        matters most: a high-priority arrival mid-storm has a YOUNG
        timestamp and would be shed every cycle behind older
        low-priority heads. No status patches, no Pending churn: a
        shed head simply waits a cycle."""
        prio_on = features.enabled(features.PRIORITY_SORTING_WITHIN_COHORT)
        heads.sort(key=lambda w: (
            -prioritypkg.priority(w.obj) if prio_on else 0,
            self.ordering.queue_order_timestamp(w.obj)))
        keep, extra = heads[:cap], heads[cap:]
        for w in extra:
            if self.journeys is not None:
                self.journeys.shed(w)
            self.queues.requeue_workload(
                w, RequeueReason.FAILED_AFTER_NOMINATION)
        self.shed_heads_requeued += len(extra)
        self.recorder.annotate(
            "shed", f"head cap {self._cycle_degraded}: kept {cap} of "
                    f"{cap + len(extra)} heads",
            state=self._cycle_degraded, kept=cap, requeued=len(extra))
        return keep

    def _defer_preempt_plans(self, entries: list) -> None:
        """Shed/survival: entries nominated with deferred preemption
        (targets None) get NO target selection this cycle — they keep
        reserve-capacity semantics in the admit loop and re-heap
        immediately so they retry as soon as the ladder recovers."""
        for e in entries:
            if e.preemption_targets is None:
                e.preemption_targets = []
                e.inadmissible_msg = ("Preemption planning deferred "
                                      "(load shedding)")
                e.requeue_reason = RequeueReason.FAILED_AFTER_NOMINATION
                self._degrade_deferred += 1
                self.preempt_plans_deferred += 1

    def _observe_budget(self, duration_s: float, heads: int,
                        admitted: Optional[int]) -> None:
        """Feed the cycle's wall seconds + backlog pressure (heads
        popped minus admissions — the cheap unserved-demand proxy) to
        the degradation ladder; transitions land as flight-recorder
        annotations, metric counters, and system events — the same
        sealed-trace feed path the breaker uses, so /debug/degrade and
        the traces reconcile by construction. Called while the cycle's
        trace is still open (before _finish_trace)."""
        lad = self.ladder
        if not lad.enabled:
            return
        if self._cycle_degraded != NORMAL and self.metrics is not None:
            self.metrics.cycle_shed(self._cycle_degraded)
        prev = lad.state
        backlog = heads - (admitted or 0)
        if not lad.observe_cycle(duration_s, backlog=backlog):
            return
        recovered = lad.state == NORMAL
        msg = (f"degraded-mode {prev}->{lad.state}: cycle ewma "
               f"{(lad.ewma_s or 0) * 1e3:.1f}ms vs budget "
               f"{lad.budget_s * 1e3:.1f}ms, backlog {backlog}")
        self.recorder.annotate("degrade", msg, state=lad.state,
                               previous=prev,
                               ewma_ms=round((lad.ewma_s or 0) * 1e3, 3),
                               budget_ms=round(lad.budget_s * 1e3, 3))
        self.log.v(2, "degrade.transition", previous=prev, state=lad.state,
                   ewma_ms=round((lad.ewma_s or 0) * 1e3, 1))
        if self.on_fault is not None:
            self.on_fault("degrade-recovered" if recovered else "degrade",
                          msg)

    # --- adaptive mode routing (the production "routed system") ---

    def _route_mode(self, heads: list) -> str:
        """Which engine runs this cycle: "device" (solver path, incl.
        pipelining), "cpu" (adaptively routed to the sequential path), or
        "cpu-forced" (no solver / narrow cycle — not a routing sample).

        The adaptive decision is keyed by the PREDICTED cycle regime
        (the last observed one — backlogs are strongly autocorrelated):
        fit-heavy and preempt-heavy cycles have opposite engine
        economics, so each regime carries its own per-engine estimate."""
        if self.solver is None or len(heads) < self.solver_min_heads \
                or self.solver_routing == "never":
            return "cpu-forced"
        if self.solver_routing != "adaptive":
            return "device"
        regime = self._last_regime
        rates = {}
        for m in ("device", "cpu"):
            samples = self._route_stats.get((m, regime), ())
            if len(samples) < 2:
                return m
            # Median of per-sample rates: robust to SEVERAL compile-
            # inflated cycles (the old trim-one estimator stayed
            # poisoned when multiple shape buckets compiled early —
            # VERDICT r4 weak #7).
            rs = sorted(a / max(t, 1e-9) for a, t in samples)
            rates[m] = rs[len(rs) // 2]
        best = "device" if rates["device"] >= rates["cpu"] else "cpu"
        loser = "cpu" if best == "device" else "device"
        self._route_explore[regime] += 1
        # Budgeted exploration: keep the loser's estimate fresh (the
        # backlog drifts), but when it loses BADLY each probe costs a
        # multiple of a normal cycle — back the period off so a short
        # run isn't dominated by probes of a hopeless engine.
        period = 16 if rates[loser] * 4 >= rates[best] else 64
        if self._route_explore[regime] % period == 0:
            return loser
        return best

    def _route_record(self, mode: str, admitted, secs: float) -> None:
        if self.solver_routing != "adaptive" or admitted is None \
                or mode not in ("cpu", "device"):
            return
        if self._cycle_degraded != NORMAL:
            # A shed/survival cycle ran with capped heads and deferred
            # preempt planning: its progress-per-second says nothing
            # about either engine's real economics. Interventions are
            # not routing samples.
            return
        lst = self._route_stats.setdefault((mode, self._cycle_regime), [])
        lst.append((admitted, secs))
        if len(lst) > 8:
            lst.pop(0)

    def _solver_invalidate(self) -> None:
        """Duck-typed: custom solvers without residency just skip this."""
        inval = getattr(self.solver, "invalidate_resident", None)
        if inval is not None:
            inval()

    # --- device-fault containment (kueue_tpu/resilience) ---

    def _solver_fault(self, where: str, exc: BaseException) -> None:
        """A device fault (dispatch/collect exception, watchdog timeout,
        detected corruption): count it, feed the breaker, and drop the
        device-resident state — the host mirrors are the truth and the
        device twin is a rebuildable cache, so invalidation is always
        safe and makes the next device cycle re-establish from a fresh
        full snapshot."""
        self.solver_faults += 1
        self._cycle_faults += 1
        tripped = self.breaker.record_fault(self.clock.now())
        # Only the supervised dispatch worker raises SupervisedTimeout;
        # a collect-side watchdog timeout (plain DispatchTimeout) must
        # not land in the supervised counter even when it surfaces
        # through the sync path's "solve" site — and a supervised
        # abandonment must not land in dispatch_timeouts_total, whose
        # contract is collect-watchdog abandonments. Exactly one of
        # the two counters per timeout.
        from kueue_tpu.resilience.supervisor import SupervisedTimeout
        supervised = isinstance(exc, SupervisedTimeout)
        timeout = isinstance(exc, DispatchTimeout) and not supervised
        self.recorder.annotate(
            "fault", f"{where}: {exc!r}"[:200], site=where,
            timeout=timeout, tripped=tripped,
            supervised=supervised, breaker=self.breaker.state,
            consecutive=self.breaker.consecutive_faults)
        if self.metrics is not None:
            self.metrics.device_fault(
                where, timeout=timeout,
                tripped=tripped, supervised=supervised)
        self.log.v(2, "solver.fault", where=where, error=repr(exc)[:200],
                   breaker=self.breaker.state,
                   consecutive=self.breaker.consecutive_faults)
        if self.on_fault is not None:
            self.on_fault("fault", f"{where}: {exc}")
            if tripped:
                self.on_fault("breaker-open",
                              f"device route suspended after {where}: {exc}")
        self._solver_invalidate()

    def _prepare_failed(self, exc: BaseException) -> None:
        """prepare()/encode failures are host-side unless a fault site
        or device error surfaced through them (journal-replay injection,
        a dead backend raising mid-encode): only DeviceFaults feed the
        breaker — a host encode bug tripping the breaker would mask
        itself behind the CPU fallback."""
        if isinstance(exc, DeviceFault):
            self._solver_fault("prepare", exc)
        else:
            self._solver_invalidate()

    def _note_device_cycle(self, collects_before: int) -> None:
        """A device-routed cycle ended. A completed collect with no
        fault recorded is a breaker success (closes a half-open probe);
        a cycle that never round-tripped (work gates sent everything to
        the CPU preemptor, dispatch-only pipeline fill) proves nothing —
        a pending probe is re-armed instead of being consumed."""
        if self._cycle_faults:
            return
        c = getattr(self.solver, "counters", None)
        if c is not None and c.get("collects", 0) <= collects_before:
            self.breaker.probe_inconclusive(self.clock.now())
            return
        if self.breaker.record_success(self.clock.now()):
            self.recorder.annotate(
                "breaker-closed",
                f"device route restored after "
                f"{self.breaker.last_recovery_cycles} cycle(s)",
                recovery_cycles=self.breaker.last_recovery_cycles)
            if self.metrics is not None:
                self.metrics.fault_recovered(
                    self.breaker.last_recovery_cycles)
            self.log.v(2, "solver.breakerClosed",
                       recovery_cycles=self.breaker.last_recovery_cycles)
            if self.on_fault is not None:
                self.on_fault(
                    "breaker-closed",
                    f"device route restored after "
                    f"{self.breaker.last_recovery_cycles} cycle(s)")

    def _dispatch_deadline(self) -> Optional[float]:
        """Watchdog deadline for this cycle's device round trip: the
        median observed device cycle seconds for the predicted regime
        (the router's rate samples), falling back to the solver's
        measured sync floor, x the watchdog's safety factor. None when
        the watchdog is disabled."""
        if self.watchdog is None:
            return None
        est = None
        samples = (self._route_stats.get(("device", self._last_regime))
                   or self._route_stats.get(("device", "fit")))
        if samples:
            secs = sorted(t for _a, t in samples)
            est = secs[len(secs) // 2]
        else:
            sync = getattr(self.solver, "_sync_samples", None)
            if sync:
                # Recent-window MAX, not the sync floor: the floor is a
                # best-case MIN by construction, and a deadline keyed on
                # it would turn a legitimately heavy (but healthy) cycle
                # into a spurious timeout.
                est = max(sync) / 1e3  # samples are milliseconds
        return self.watchdog.deadline_s(est)

    def _supervise_deadline(self) -> Optional[float]:
        """Deadline for the SUPERVISED dispatch body (trace/compile/
        transfer): the watchdog's cold clamp, not the warm regime
        deadline — a dispatch legitimately carries jit compiles (a
        fresh shape bucket mid-run, a cold start) whose cost is not
        regime-priced, so only the operator's compile-absorbing bound
        may abandon it. None when the watchdog is disabled."""
        if self.watchdog is None:
            return None
        return self.watchdog.max_deadline_s

    def _solver_note_unapplied(self, key: str) -> None:
        note = getattr(self.solver, "note_unapplied", None)
        if note is not None:
            note(key)

    def _solver_release_workload(self, key: str) -> None:
        """Admitted workloads leave the pending set without a queue-
        manager delete: recycle their encode-arena slot."""
        rel = getattr(self.solver, "release_workload", None)
        if rel is not None:
            rel(key)

    @property
    def _inflight(self) -> Optional[stages.InFlightCycle]:
        """The OLDEST in-flight speculative cycle (the one the next
        collect processes), or None when the pipeline is empty.
        Read-only: mutation goes through _inflight_q, which carries the
        dispatch-depth queue."""
        q = self._inflight_q
        return q[0] if q else None

    def _pipeline_ok(self, heads: list) -> bool:
        s = self.solver
        # Breaker not CLOSED => the cycle is a half-open probe: it must
        # run synchronously so its outcome is known by cycle end (a
        # pipelined dispatch wouldn't resolve until the NEXT cycle).
        # Ladder: shed allows BOUNDED pipelining — the head cap already
        # ran before routing, and _schedule_pipelined bails to sync on
        # any cycle that needs preempt planning (deferred under shed) —
        # but survival pins the CPU route, so the in-flight queue must
        # drain rather than grow (ladder.allow_pipeline).
        return (s is not None and self.pipeline_enabled
                and self.breaker.state == CLOSED
                and self.ladder.allow_pipeline
                and getattr(s, "resident_capable", False)
                and not self.cache.pods_ready_tracking
                and len(heads) >= self.solver_min_heads
                and not self.queues.any_strict_fifo())

    def _schedule_pipelined(self, heads: list, start) -> Optional[SpeedSignal]:
        """Dispatch this cycle and process the previous in-flight one.
        Returns None to fall back to the synchronous path (any in-flight
        cycle has been drained first)."""
        solver = self.solver
        self._pipeline_trace_route = "device-pipelined"
        # Validate EVERY in-flight speculation BEFORE dispatching the
        # next cycle: a new dispatch chains on the in-flight device
        # state, so aborting a predecessor after the fact would doom
        # the successor too. The chain runs old->new: an invalid token
        # dooms the failing cycle and everything dispatched AFTER it
        # (flushed as "chained" by _abort_speculation), while validated
        # PREDECESSORS collect normally first — their results don't
        # depend on the failing cycle, and the sync fallback cycle must
        # not run with their admissions still un-collected.
        for early in tuple(self._inflight_q):
            if early.token is None:
                continue
            ok, reason = self._validate_speculation(early)
            if not ok:
                while self._inflight_q and self._inflight_q[0] is not early:
                    self._drain_one(self._inflight_q.popleft(),
                                    sample=True)
                if self._inflight_q and self._inflight_q[0] is early:
                    # (a predecessor's own processing may have aborted
                    # and flushed the queue — then there is nothing
                    # left to abort here)
                    self._inflight_q.popleft()
                    self._abort_speculation(early, reason)
                return None  # sync path owns this cycle's heads
        # Light snapshot: the all-fit pipelined cycle never simulates on
        # it (usage truth is the device-resident state); cloning 2k
        # resource trees per cycle was a measurable share of the cycle.
        t_ph = _time.perf_counter()
        snapshot = self.cache.snapshot(light=True)
        self._span("snapshot", t_ph)
        valid_heads, invalid_entries = [], []
        for w in heads:
            if self.cache.is_assumed_or_admitted(w):
                continue
            err = self._validate_head(w, snapshot)
            if err is None:
                valid_heads.append(w)
            else:
                e = Entry(info=w)
                e.inadmissible_msg, e.requeue_reason = err
                invalid_entries.append(e)
        if not valid_heads:
            self._drain_pipeline()
            return None  # sync path handles the (all-invalid) heads
        try:
            plan = solver.prepare(snapshot, valid_heads)
        except Exception as exc:  # noqa: BLE001 — encode: sync fallback
            self._prepare_failed(exc)
            plan = None
        prev = self._inflight
        if (plan is not None and plan.resident and prev is not None
                and plan.rs is not prev.inflight.plan.rs):
            # Residency was re-established under the in-flight cycle (a
            # topology change or journal overflow): the fresh state was
            # encoded from a snapshot that cannot include the in-flight
            # admissions. Dispatching on it would double-book quota —
            # drain first and let the sync path rebuild from fresh state.
            self._drain_pipeline()
            return None
        nofit_entries, nofit_idx = [], set()
        pend_ws, pend_idx = [], set()
        bail = (plan is None or not plan.resident or plan.fit_pred is None)
        if not bail and not plan.fit_pred.all():
            # Predicted non-fit entries: the device-NoFit shortcut set
            # requeues at dispatch time; preempt-capable entries ride
            # the SAME resident dispatch as a fused target-selection
            # batch (pipelined mixed cycles — VERDICT r4 ask #4), their
            # evictions issuing at collect time one cycle later.
            # Partial-admission probes and fair-sharing preemption keep
            # the sync path (lockstep reducer rounds / DRF shares drift
            # too fast for a one-cycle lag).
            for i, w in enumerate(plan.batch.infos):
                if plan.fit_pred[i]:
                    continue
                e = self._device_nofit_entry(w, snapshot)
                if e is not None:
                    nofit_entries.append(e)
                    nofit_idx.add(i)
                elif (not self.fair_sharing_enabled
                      and not (features.enabled(features.PARTIAL_ADMISSION)
                               and w.can_be_partially_admitted())):
                    pend_ws.append(w)
                    pend_idx.add(i)
                else:
                    bail = True
                    break
        if not bail and pend_ws and self.ladder.defer_preemption:
            # Shed rung: pipelining stays on for all-fit cycles (the
            # bounded allowance), but preempt planning is deferred and
            # the sync path owns the deferral semantics — a cycle that
            # needs target selection bails.
            bail = True
        if not bail and len(pend_ws) * 4 > len(valid_heads):
            # Preempt-dominated cycle: the pipelined-mixed machinery
            # (full snapshot + candidate index + one-cycle eviction lag)
            # costs more than the hidden sync buys, and the lag hurts
            # packing. The sync path owns it — and the router decides
            # sync-device vs CPU from there.
            bail = True
        pmeta, pbatch = None, None
        prev_signal = None
        if not bail and pend_ws:
            if self._inflight is not None:
                # Collect the in-flight cycle FIRST: its admissions must
                # be in the cache before the preempt nomination snapshot,
                # or the collect-time fits-guard would run against state
                # that is one cycle stale and could issue evictions the
                # fresh-state reference would not (over-eviction). The
                # background fetch has been running since its dispatch,
                # so this drain is mostly decode+admit, not a round trip.
                # sample=False: this cycle's routing sample charges the
                # drained admissions against the FULL mixed-cycle cost.
                prev_signal = self._drain_pipeline(sample=False)
            t_ph = _time.perf_counter()
            pmeta, pbatch, bail = self._prepare_pipelined_preempt(plan,
                                                                  pend_ws)
            self._span("preempt-plan", t_ph)
            if bail:
                self._last_cycle_admitted = None
        if bail:
            # Reducer/fair cycle (or no router, or preempt encode
            # failure): the synchronous path owns those semantics —
            # drain and fall through; the sync cycle processes these
            # same popped heads directly with a FRESH full snapshot
            # (the light one here must NEVER reach the sync path: its
            # trees alias the live cache and the sync path simulates on
            # them). Cooldown one cycle so sustained contention doesn't
            # pay a discarded prepare() every cycle.
            self._drain_pipeline()
            self._pipeline_cooldown = 1
            return None
        if len(nofit_idx) == len(plan.batch.infos):
            # Whole cycle is device-proved NoFit: nothing to dispatch.
            # Not a routing sample either — a NoFit backlog admits zero
            # on EITHER engine, so recording it would just bias.
            for e in invalid_entries:
                self.requeue_and_update(e)
            for e in nofit_entries:
                self.requeue_and_update(e)
            self.cycle_counts["device-nofit"] = \
                self.cycle_counts.get("device-nofit", 0) + 1
            self._pipeline_trace_route = "device-nofit"
            if self._inflight is not None:
                return self._drain_pipeline()
            self._last_cycle_admitted = None
            return SlowDown
        try:
            inflight = solver.dispatch(
                plan, fair_sharing=self.fair_sharing_enabled,
                preempt_batch=pbatch, deadline_s=self._dispatch_deadline(),
                supervise_deadline_s=self._supervise_deadline())
            solver.start_fetch(inflight)
        except Exception as exc:  # noqa: BLE001 — device: sync fallback
            self._solver_fault("dispatch", exc)
            if pmeta is not None:
                self.cache.release_snapshot(pmeta[2])
            self._drain_pipeline()
            return None
        for e in invalid_entries:
            self.requeue_and_update(e)
        for e in nofit_entries:
            self.requeue_and_update(e)
        # Generation stamp of the speculated-on state: validated by
        # _process_inflight before the result may commit (PIPELINE.md).
        token = stages.SpeculationToken.stamp(self.cache, solver, plan,
                                              snapshot)
        self._inflight_q.append(stages.InFlightCycle(
            inflight=inflight, snapshot=snapshot, nofit_idx=nofit_idx,
            pend_idx=pend_idx, pmeta=pmeta, token=token))
        # Effective dispatch depth: deepening past one in-flight cycle
        # is only sound when EVERY queued dispatch carries the full
        # SpeculationToken staleness witness — a token-less dispatch
        # (custom solver, no arena feed) collapses the depth to 1.
        depth = max(1, self.pipeline_depth)
        if any(ic.token is None for ic in self._inflight_q):
            depth = 1
        if len(self._inflight_q) <= depth:
            if prev_signal is not None:
                # Mixed-cycle pre-drain: _last_cycle_admitted still
                # holds the drained admissions — schedule() charges them
                # against THIS cycle's full wall (the sample=False
                # contract).
                return prev_signal
            self._last_cycle_admitted = None  # not a routing sample
            self.cycle_counts["device-dispatch-only"] = \
                self.cycle_counts.get("device-dispatch-only", 0) + 1
            self._pipeline_trace_route = "device-dispatch-only"
            return KeepGoing  # pipeline deepening: results a call later
        signal = KeepGoing
        while len(self._inflight_q) > depth:
            prev = self._inflight_q.popleft()
            signal = self._process_inflight(prev, start)
        return signal

    def _abandon_pipeline(self) -> None:
        """Drop the in-flight cycle WITHOUT applying its decisions
        (leadership lost): requeue its heads for whoever leads next and
        invalidate residency — the device state includes admissions that
        will never be confirmed, and the store may move under another
        leader before we see it again."""
        if not self._inflight_q:
            return
        while self._inflight_q:
            self._requeue_inflight(self._inflight_q.popleft())
        self._solver_invalidate()

    def _requeue_inflight(self, prev: stages.InFlightCycle) -> None:
        """The abandon sweep shared by every in-flight-discard path
        (mis-speculation abort, collect fault, leadership loss):
        release the deferred preempt-nomination snapshot and re-heap
        every batch row not already requeued at dispatch time (the
        device-NoFit shortcut set). pend rows requeue here too — their
        evictions never issued."""
        if prev.pmeta is not None:
            self.cache.release_snapshot(prev.pmeta[2])
        for i, w in enumerate(prev.inflight.plan.batch.infos):
            if i in prev.nofit_idx:
                continue  # already requeued at dispatch time
            if self.journeys is not None:
                self.journeys.requeued(
                    w, NOMINATED, RequeueReason.FAILED_AFTER_NOMINATION,
                    "in-flight speculative cycle abandoned")
            self.queues.requeue_workload(
                w, RequeueReason.FAILED_AFTER_NOMINATION)

    def _flush_inflight_queue(self, why: str) -> None:
        """Discard every still-queued in-flight cycle un-decoded
        (collateral of a device fault on an older chained cycle):
        requeue their heads and release their deferred snapshots. Not
        speculation aborts — nothing about THEIR state was proven
        stale; the chain they rode was simply invalidated."""
        if not self._inflight_q:
            return
        flushed = 0
        while self._inflight_q:
            self._requeue_inflight(self._inflight_q.popleft())
            flushed += 1
        self.recorder.annotate(
            "pipeline-flush",
            f"{flushed} chained in-flight cycle(s) discarded: {why}",
            reason=why, flushed=flushed)

    def _prepare_pipelined_preempt(self, plan, pend_ws: list):
        """Nominate predicted-non-fit, preempt-capable entries against a
        FRESH FULL snapshot (nomination's reclaim oracle SIMULATES — it
        must never run on a light snapshot's live trees) and encode
        their target-selection problems to ride the resident dispatch.
        Returns (pmeta, pbatch, bail): pmeta = (pending entries, cq_by,
        full snapshot) for collect-time eviction issuing, pbatch = the
        encoded problem batch or None (all entries blocked), bail=True
        means the sync path must own this cycle."""
        from kueue_tpu.solver import preempt as devpreempt
        from kueue_tpu.solver.candidates import candidate_index
        full_snap = None
        try:
            full_snap = self.cache.snapshot()
            pre_entries = self.nominate(pend_ws, full_snap,
                                        defer_preemption=True)
            pending, ready = [], []
            for e in pre_entries:
                if e.preemption_targets is None:
                    e.preemption_targets = []
                    pending.append(e)
                else:
                    ready.append(e)  # NO_FIT on true state (mirror lag)
            for e in ready:
                self.requeue_and_update(e)
            if not pending:
                self.cache.release_snapshot(full_snap)
                return None, None, False
            cand_index = candidate_index(full_snap, self.ordering,
                                         self.clock.now())
            problems, requests_by, cq_by, frs_by = [], {}, {}, {}
            for i, e in enumerate(pending):
                requests_by[i] = e.assignment.total_requests_for(e.info)
                frs_by[i] = fa.flavor_resources_need_preemption(e.assignment)
                cq_by[i] = e.info.cluster_queue
                problems.extend(devpreempt.build_problems(
                    i, e.info, requests_by[i], frs_by[i], full_snap,
                    self.preemptor, cand_index))
            pbatch = None
            if problems:
                pbatch = devpreempt.encode_problems(
                    problems, full_snap, plan.topo, requests_by, cq_by,
                    frs_by)
            return (pending, cq_by, full_snap), pbatch, False
        except Exception:  # noqa: BLE001 — encode failure: sync fallback
            self.preemption_fallbacks += 1
            if full_snap is not None:
                # the deferred-nomination handout never reached pmeta:
                # release it here or it leaks (live_handouts contract)
                self.cache.release_snapshot(full_snap)
            return None, None, True

    def _drain_pipeline(self, sample: bool = True) -> SpeedSignal:
        """sample=False: the caller owns the routing sample (the mixed
        pipelined path drains as a STEP of its own cycle and must charge
        the drained admissions against the FULL cycle cost — recording a
        cheap decode-only sample here made the device engine look fast
        exactly when its cycles were slowest)."""
        if not self._inflight_q:
            return KeepGoing
        sig = KeepGoing
        drained_total = None
        while self._inflight_q:
            prev = self._inflight_q.popleft()
            sig, admitted = self._drain_one(prev, sample)
            if admitted is not None:
                drained_total = (drained_total or 0) + admitted
        # The drained admissions, surviving _drain_one's sample-branch
        # consumption (the headless-drain trace reports them; at
        # depth 2 a drain can collect two cycles' worth).
        self._drained_admitted = drained_total
        if not sample:
            self._last_cycle_admitted = drained_total
        return sig

    def _drain_one(self, prev: stages.InFlightCycle,
                   sample: bool) -> tuple:
        """Process one in-flight cycle with drain accounting; returns
        (signal, admitted-or-None). With ``sample``, the drained cycle
        is recorded as DEVICE work even when the draining cycle was
        routed to CPU (exploration) — and its time (via _drain_cost)
        AND its evictions are excluded from the enclosing cycle's own
        sample, so each engine's rate reflects only its own progress
        per second. _process_inflight sets _cycle_regime to the
        drained cycle's regime."""
        t0 = _time.perf_counter()
        ev0 = self._cycle_evictions
        sig = self._process_inflight(prev, self.clock.now())
        admitted = self._last_cycle_admitted
        if sample:
            dt = _time.perf_counter() - t0
            drained_ev = self._cycle_evictions - ev0
            self._cycle_evictions = ev0
            self._drain_cost += dt
            if admitted is not None:
                self._route_record("device", admitted + drained_ev, dt)
            self._last_cycle_admitted = None  # consumed
        return sig, admitted

    def _process_inflight(self, prev: stages.InFlightCycle,
                          start) -> SpeedSignal:
        inflight, snapshot = prev.inflight, prev.snapshot
        nofit_idx, pend_idx, pmeta = (prev.nofit_idx, prev.pend_idx,
                                      prev.pmeta)
        solver = self.solver
        valid_heads = inflight.plan.batch.infos
        # Speculation validation BEFORE the result may commit: the
        # generation token proves the state the solve assumed still
        # describes the live cache (structural epochs, residency
        # identity, arena slot generations, journal cursor health).
        # Mis-speculation abandons the result un-decoded and the heads
        # retry on the synchronous path — never a stale admission.
        # Deliberately re-checked even when _schedule_pipelined already
        # validated this inflight at entry: in threaded deployments the
        # store's watch handlers mutate the cache concurrently, so
        # churn can land between the entry check and this commit point
        # — the re-check is two tuple compares + one small gather.
        ok, reason = self._validate_speculation(prev)
        if not ok:
            return self._abort_speculation(prev, reason)
        try:
            decisions, aux = solver.collect(inflight, snapshot)
        except Exception as exc:  # noqa: BLE001 — fetch: retry the heads
            # Watchdog timeouts land here too: the in-flight result is
            # abandoned (never decoded), residency is invalidated, the
            # heads re-heap — the cycle completes instead of blocking
            # on a wedged device_get.
            self._solver_fault("collect", exc)
            self._requeue_inflight(prev)
            # Deeper pipeline: every still-queued cycle chained on the
            # residency the fault just invalidated — flush them too
            # (heads re-heap, nothing decoded, no double admission).
            self._flush_inflight_queue("collect-fault")
            self._pipeline_cooldown = 1
            # An aborted collect admitted nothing: a previous cycle's
            # count must not leak into the drain trace or the drain
            # sample branch's routing record.
            self._last_cycle_admitted = None
            return SlowDown
        if prev.token is not None:
            # Validated AND collected: the speculation committed.
            self.speculation_hits += 1
            if self.metrics is not None:
                self.metrics.speculation_hit()
        entries = []
        any_nonfit = False
        t_ph = _time.perf_counter()
        for i, w in enumerate(valid_heads):
            if i in nofit_idx or i in pend_idx:
                continue  # NoFit: requeued at dispatch; pend: below
            decision = decisions.get(i)
            e = Entry(info=w)
            if decision is None:
                # Router predicted fit on the lagging mirror but the
                # device (true state) disagreed: re-heap and run the next
                # cycle synchronously so preempt-mode nomination applies.
                e.inadmissible_msg = "Workload didn't fit on the batched path"
                e.requeue_reason = RequeueReason.FAILED_AFTER_NOMINATION
                any_nonfit = True
                entries.append(e)
                continue
            assignment, admitted = decision
            e.assignment = assignment
            w.last_assignment = assignment.last_state
            if not admitted:
                self._set_skipped(e, "Workload no longer fits after "
                                     "processing another workload")
                entries.append(e)
                continue
            cq = snapshot.cluster_queues[w.cluster_queue]
            e.status = NOMINATED
            try:
                self.admit(e, cq)
            except Exception as exc:  # noqa: BLE001
                e.inadmissible_msg = f"Failed to admit workload: {exc}"
                self._solver_note_unapplied(w.key)
            entries.append(e)
        self._flush_mk_placements(snapshot)
        self._span("apply", t_ph)
        if any_nonfit:
            self._pipeline_cooldown = 1
        if pmeta is not None:
            t_ph = _time.perf_counter()
            entries.extend(self._collect_pipelined_preempt(
                inflight, pmeta, aux, entries))
            self._span("preempt-plan", t_ph)
            self._cycle_regime = "preempt"
        else:
            self._cycle_regime = "fit"
        self._last_regime = self._cycle_regime
        if self.query_plane is not None:
            # The collected cycle's processing order (batch order + the
            # pipelined preempt entries): the query plane's nominate-
            # order column for pipelined cycles.
            self._cycle_order = [e.info.key for e in entries]
        result_success = False
        admitted_n = 0
        vlog.dump_attempts(self.log, entries)
        t_ph = _time.perf_counter()
        for e in entries:
            if e.status != ASSUMED:
                self.requeue_and_update(e)
            else:
                result_success = True
                admitted_n += 1
                self._solver_release_workload(e.info.key)
        self.admitted_total += admitted_n
        self._span("requeue", t_ph)
        self._last_cycle_admitted = admitted_n
        self.cycle_counts["device-pipelined"] = \
            self.cycle_counts.get("device-pipelined", 0) + 1
        self.log.v(2, "cycle", engine="device-pipelined",
                   heads=len(valid_heads), admitted=admitted_n)
        if self.metrics is not None:
            self.metrics.admission_attempt(result_success,
                                           self.clock.now() - start)
        return KeepGoing if result_success else SlowDown

    def _validate_speculation(self, prev: stages.InFlightCycle) -> tuple:
        """(ok, reason) for the in-flight cycle's generation token.
        Routed through the ``speculation_validate`` injection site so
        chaos suites can force a mis-speculation; a token-less inflight
        (custom solvers) validates trivially."""
        if self.fencing_check is not None and not self.fencing_check():
            # Deposed mid-flight: another replica holds the lease at a
            # higher fencing epoch, so this result must never commit —
            # the new leader may already be admitting these heads.
            return False, "fenced"
        try:
            faultinject.site(faultinject.SITE_SPECULATION)
            if prev.token is not None:
                return prev.token.validate(self.cache, self.solver)
        except DeviceFault:
            return False, "injected"
        return True, ""

    def _abort_speculation(self, prev: stages.InFlightCycle,
                           reason: str) -> SpeedSignal:
        """Mis-speculation: the state the in-flight solve was computed
        against moved mid-flight. Abandon the result UN-DECODED (the
        assume/forget protocol's cheap half: nothing was assumed yet,
        so there is nothing to forget), requeue its heads for the
        synchronous fallback cycle, and invalidate residency — the
        device state chained Phase B usage for admissions that will
        never be confirmed. Deliberately NOT a breaker fault: nothing
        device-side failed, so the device route stays open and the
        next cycle (cooldown -> synchronous) re-establishes from fresh
        state."""
        self.speculation_aborts += 1
        self.speculation_abort_reasons[reason] = \
            self.speculation_abort_reasons.get(reason, 0) + 1
        self.recorder.annotate(
            "speculation-abort",
            f"speculative result abandoned: {reason}", reason=reason,
            aborts=self.speculation_aborts)
        if self.metrics is not None:
            self.metrics.speculation_abort(reason)
        self.log.v(2, "speculation.abort", reason=reason,
                   aborts=self.speculation_aborts)
        self._requeue_inflight(prev)
        # Deeper pipeline: the still-queued cycles chained Phase B on
        # the same speculated state — one abort dooms them all (depth 2
        # aborts BOTH in-flight cycles; neither decodes, neither can
        # double-admit). Counted as their own aborts under "chained".
        while self._inflight_q:
            chained = self._inflight_q.popleft()
            self.speculation_aborts += 1
            self.speculation_abort_reasons["chained"] = \
                self.speculation_abort_reasons.get("chained", 0) + 1
            if self.metrics is not None:
                self.metrics.speculation_abort("chained")
            self._requeue_inflight(chained)
        self._solver_invalidate()
        self._pipeline_cooldown = 1
        # An aborted speculation admitted nothing: the drain trace and
        # the drain sample branch must not see a stale count.
        self._last_cycle_admitted = None
        return SlowDown


    def _note_preempt_stats(self, aux, preempt_batch=None,
                            fair_batch=None) -> None:
        """Aggregate the device preempt/fair solve stats ([B,4] per
        program: pool, scanned/pops, fill-back rounds, filled back) into
        the operator surface: last_preempt_plan (/debug/router) + a
        preempt-plan annotation on the open cycle trace."""
        if not aux:
            return
        agg: dict = {}
        for key, name, batch in (("preempt_stats", "minimal",
                                  preempt_batch),
                                 ("fair_stats", "fair", fair_batch)):
            st = aux.get(key)
            if st is None or len(st) == 0:
                continue
            # real problem count from the batch, NOT the stats shape:
            # st's leading dim is the padded power-of-four bucket B,
            # and a pool>0 heuristic undercounts — a real minimal
            # problem can carry an EMPTY pool (sel[in_cq] with every
            # ordered candidate in another CQ of the cohort)
            problems = (len(batch.problems) if batch is not None
                        else int((st[:, 0] > 0).sum()))
            agg[name] = {
                "problems": problems,
                "pool": int(st[:, 0].sum()),
                "scanned": int(st[:, 1].sum()),
                "fillback_rounds_max": int(st[:, 2].max()),
                "filled_back": int(st[:, 3].sum()),
            }
        if not agg:
            return
        self.last_preempt_plan = agg
        flat = {f"{n}_{k}": v for n, d in agg.items()
                for k, v in d.items()}
        self.recorder.annotate(
            "preempt-plan",
            "batched preemption solve stats", **flat)

    def _collect_pipelined_preempt(self, inflight, pmeta, aux,
                                   fit_entries: list) -> list:
        """Collect-time half of a pipelined mixed cycle: decode the
        device-selected targets and issue the evictions ONE CYCLE after
        the targets were chosen. Guards against the lag: this cycle's
        own device admissions are accounted on the nomination snapshot
        before the fit re-check, overlapping target sets are skipped
        exactly like the sync admit loop (scheduler.go:266-273), and a
        victim that completed in the window is skipped (its capacity
        already freed — evicting it would be pure over-eviction).
        Returns the processed preempt-mode entries for requeue."""
        from kueue_tpu.solver import preempt as devpreempt
        pending, cq_by, full_snap = pmeta
        targets_by: dict = {}
        if aux is not None and "preempt" in aux \
                and inflight.preempt_batch is not None:
            # pipelined cycles never carry a fair batch (fair cycles
            # bail to sync)
            self._note_preempt_stats(
                aux, preempt_batch=inflight.preempt_batch)
            t, f = aux["preempt"]
            targets_by = devpreempt.decode_targets(
                inflight.preempt_batch, t, f, full_snap, cq_by)
        for e in fit_entries:
            if e.status == ASSUMED:
                cq = full_snap.cluster_queues.get(e.info.cluster_queue)
                if cq is not None:
                    cq.add_usage(e.assignment.usage)
        preempted: set = set()
        blocked_any = False
        for i, e in enumerate(pending):
            e.preemption_targets = targets_by.get(i, [])
            if not e.preemption_targets:
                blocked_any = True  # no feasible targets: blocked
                continue
            live = [t for t in e.preemption_targets
                    if self.cache.is_assumed_or_admitted(t.workload_info)]
            if len(live) != len(e.preemption_targets):
                # A victim completed during the pipeline lag: its
                # capacity is already free — retry with fresh state
                # instead of over-evicting the survivors.
                e.preemption_targets = []
                e.requeue_reason = RequeueReason.FAILED_AFTER_NOMINATION
                continue
            keys = {t.workload_info.key for t in e.preemption_targets}
            if keys & preempted:
                self._set_skipped(e, "Workload has overlapping preemption "
                                     "targets with another workload")
                continue
            cq = full_snap.cluster_queues[e.info.cluster_queue]
            usage = e.net_usage()
            if not cq.fits(usage):
                self._set_skipped(e, "Workload no longer fits after "
                                     "processing another workload")
                continue
            preempted.update(keys)
            cq.add_usage(usage)
            e.info.last_assignment = None
            n = self.preemptor.issue_preemptions(e.info,
                                                 e.preemption_targets)
            self._cycle_evictions += n
            if n:
                e.inadmissible_msg += (f". Pending the preemption of "
                                       f"{n} workload(s)")
                e.requeue_reason = RequeueReason.PENDING_PREEMPTION
        if pending:
            self._blocked_preempt_streak = (
                self._blocked_preempt_streak + 1 if blocked_any else 0)
            self._preemptless_cycles = 0
            self.cycle_counts["pipelined-preempt"] = \
                self.cycle_counts.get("pipelined-preempt", 0) + 1
        # The deferred nomination snapshot's late mutations are done.
        self.cache.release_snapshot(full_snap)
        return pending

    # --- batched TPU admission (kueue_tpu.solver) ---

    def _stage_solve(self, heads: list, snapshot: Snapshot, timeout):
        """SOLVE stage: run the batched solver over the validated heads.

        One device sync per cycle: the solver's host-side router (exact
        Phase A on the local CPU backend) says which heads the device
        will fit; the rest are CPU-nominated NOW — against the pre-cycle
        snapshot, exactly like the reference's nominate phase
        (scheduler.go:404-441) — and their preempt-mode target selection
        ships in the same device execute as the fit solve.

        Returns (solver entries, nominated preempt/nofit entries for the
        main admit loop, remaining heads for post-sync CPU nomination —
        empty unless routing was unavailable or mispredicted)."""
        from kueue_tpu.solver import preempt as devpreempt
        valid_heads, invalid_entries = [], []
        for w in heads:
            if self.cache.is_assumed_or_admitted(w):
                continue
            err = self._validate_head(w, snapshot)
            if err is None:
                valid_heads.append(w)
            else:
                e = Entry(info=w)
                e.inadmissible_msg, e.requeue_reason = err
                invalid_entries.append(e)

        try:
            plan = self.solver.prepare(snapshot, valid_heads)
        except Exception as exc:  # noqa: BLE001 — encode: CPU fallback
            self._prepare_failed(exc)
            return invalid_entries, [], valid_heads
        if plan is None:
            return invalid_entries, [], valid_heads

        # Route: entries the device won't fit get their CPU nomination
        # (flavor assignment + preemption candidates) before the sync.
        fit_pred = plan.fit_pred
        if fit_pred is None:
            pred_other = []
        else:
            pred_other = [w for i, w in enumerate(valid_heads)
                          if not fit_pred[i]]
        # Device-NoFit shortcut: Phase A already proved these entries
        # can't fit, and a Never/Never preemption policy (with no partial
        # admission possible) means the CPU assigner could only restate
        # NoFit — skip its per-flavor walk entirely. Deviation: the
        # Pending message is the batch-path generic one instead of the
        # per-flavor reason list (the resume state is equivalent — a
        # NoFit walk always ends exhausted, i.e. restart from rank 0).
        nonfit_total = len(pred_other)
        nofit_entries = []
        partial_ws = []
        if pred_other:
            rest = []
            for w in pred_other:
                e = self._device_nofit_entry(w, snapshot)
                if e is not None:
                    nofit_entries.append(e)
                elif self._batched_reducer_eligible(w, snapshot):
                    partial_ws.append(w)
                else:
                    rest.append(w)
            pred_other = rest
        if partial_ws:
            # Batched partial admission (podset_reducer.go:29-86): all
            # entries' binary searches advance in lockstep, one Phase A
            # batch per round on the local CPU backend, then ONE full
            # assigner run per successful entry at its found counts.
            entries_or_ws = self._batched_partial_admission(
                partial_ws, plan, snapshot)
            for item in entries_or_ws:
                if isinstance(item, Entry):
                    nofit_entries.append(item)
                else:
                    pred_other.append(item)
        # Preempt-mode target selection is deferred to the device —
        # including fairPreemptions' DRF-heap loop (solver/fairpreempt.py)
        # — except under a mesh with fair sharing (the sharded execute
        # carries only the minimal-preemption program).
        defer = not (self.fair_sharing_enabled
                     and self.solver.mesh is not None) \
            or self.ladder.defer_preemption
        t_ph = _time.perf_counter()
        pre_entries = nofit_entries + self.nominate(pred_other, snapshot,
                                                    defer_preemption=defer)
        pending = [e for e in pre_entries if e.preemption_targets is None]
        if pending and self.ladder.defer_preemption:
            # Shed/survival: skip target selection entirely (no
            # candidate index, no device preempt batch) — the deferred
            # entries keep reserve-capacity semantics and re-heap.
            self._defer_preempt_plans(pending)
            pending = []
        else:
            for e in pending:
                e.preemption_targets = []
        t_ph = self._span("nominate", t_ph)
        # NB: count ALL predicted-non-fit entries (incl. the device-NoFit
        # shortcut set), or an all-NoFit cycle would look like a fit cycle
        # to the dispatch-skip and preemption work gates.
        fit_count = (len(valid_heads) - nonfit_total
                     if fit_pred is not None else len(valid_heads))
        pbatch = None
        requests_by, cq_by = {}, {}
        floor_ms = (self.solver_sync_floor_ms
                    if self.solver_sync_floor_ms is not None
                    else (self.solver.estimated_sync_ms() if pending else 0.0))
        if pending:
            # Cheap pre-gate: an upper bound on candidate count (domain
            # workload totals) decides whether building the candidate
            # index is worth it at all — small simulations go straight to
            # the CPU preemptor.
            shares = fit_count > 0 and self.solver.mesh is None
            marginal_sync_us = 0.0 if shares else floor_ms * 1000.0
            sizes: dict = {}
            bound = 0
            for e in pending:
                cq = snapshot.cluster_queues[e.info.cluster_queue]
                key = (cq.cohort.root().name if cq.cohort is not None
                       else cq.name)
                if key not in sizes:
                    members = (cq.cohort.root().subtree_cqs()
                               if cq.cohort is not None else [cq])
                    sizes[key] = sum(len(c.workloads) for c in members)
                # x2: build_problems may emit two problems per entry (the
                # under-nominal reclaim attempt + the same-queue fallback)
                bound += 2 * sizes[key]
            # fairPreemptions' CPU loop only compares per-CQ share
            # aggregates (~3us/candidate) vs the minimal preemptor's
            # per-candidate simulation (~8us net)
            per_cand_us = (self.fair_cand_us if self.fair_sharing_enabled
                           else self.preempt_cand_us)
            if bound * per_cand_us <= marginal_sync_us:
                self._cpu_preempt_targets(pending, snapshot)
                pending = []
        fbatch = None
        if pending:
            try:
                from kueue_tpu.solver.candidates import candidate_index
                cand_index = candidate_index(snapshot, self.ordering,
                                             self.clock.now())
                problems, fair_problems, frs_by = [], [], {}
                for i, e in enumerate(pending):
                    requests_by[i] = e.assignment.total_requests_for(e.info)
                    frs_by[i] = fa.flavor_resources_need_preemption(e.assignment)
                    cq_by[i] = e.info.cluster_queue
                    if self.fair_sharing_enabled:
                        from kueue_tpu.solver import fairpreempt
                        mins, fairs = fairpreempt.build_fair_problems(
                            i, e.info, requests_by[i], frs_by[i], snapshot,
                            self.preemptor, cand_index)
                        problems.extend(mins)
                        fair_problems.extend(fairs)
                    else:
                        problems.extend(devpreempt.build_problems(
                            i, e.info, requests_by[i], frs_by[i], snapshot,
                            self.preemptor, cand_index))
                # Precise work gate: ~8us/candidate net device saving must
                # cover the marginal sync — zero when fit entries dispatch
                # anyway (the fused single-chip kernel ships preemption in
                # the fit execute; the mesh path pays a separate dispatch
                # either way).
                # Per-candidate CPU cost differs by algorithm: the
                # minimal preemptor SIMULATES per candidate (~12us, ~8us
                # net of encode), while fairPreemptions only compares
                # per-CQ share aggregates (~3us net) — so fair problems
                # must clear a lower bar before the device pays.
                total_cost_us = (sum(p.num_candidates for p in problems)
                                 * self.preempt_cand_us
                                 + sum(p.num_candidates
                                       for p in fair_problems)
                                 * self.fair_cand_us)
                if (problems or fair_problems) \
                        and total_cost_us > marginal_sync_us:
                    if problems:
                        pbatch = devpreempt.encode_problems(
                            problems, snapshot, plan.topo, requests_by,
                            cq_by, frs_by)
                    if fair_problems:
                        from kueue_tpu.solver import fairpreempt
                        fbatch = fairpreempt.encode_fair_problems(
                            fair_problems, snapshot, plan.topo, requests_by,
                            cq_by, frs_by)
                else:
                    # Routing decision, not a failure: small simulations
                    # are cheaper on the CPU preemptor.
                    self._cpu_preempt_targets(pending, snapshot)
                    pending = []
            except Exception:  # noqa: BLE001 — encode failure: CPU targets
                self.preemption_fallbacks += 1
                pbatch = fbatch = None
                self._cpu_preempt_targets(pending, snapshot)
                pending = []
        self._span("preempt-plan", t_ph)
        if fit_count == 0 and pbatch is None and fbatch is None:
            # Nothing needs the device this cycle: no fit-mode entries and
            # preemption resolved on CPU — skip the dispatch entirely.
            return invalid_entries, pre_entries, []

        try:
            from kueue_tpu.solver.fairpreempt import strategy_flags
            decisions, pre = self.solver.solve_prepared(
                plan, snapshot, preempt_batch=pbatch,
                fair_sharing=self.fair_sharing_enabled,
                fair_batch=fbatch,
                fs_flags=strategy_flags(self.preemptor.fs_strategies),
                deadline_s=self._dispatch_deadline(),
                supervise_deadline_s=self._supervise_deadline())
        except Exception as exc:  # noqa: BLE001 — device: CPU fallback
            self._solver_fault("solve", exc)
            if pending:
                self.preemption_fallbacks += 1
                self._cpu_preempt_targets(pending, snapshot)
            pred_fit = [w for i, w in enumerate(valid_heads)
                        if fit_pred is None or fit_pred[i]]
            return invalid_entries, pre_entries, pred_fit

        if pre is not None and (pbatch is not None or fbatch is not None):
            self._note_preempt_stats(pre, preempt_batch=pbatch,
                                     fair_batch=fbatch)
            targets_by_entry = {}
            if pbatch is not None and "preempt" in pre:
                t, f = pre["preempt"]
                targets_by_entry.update(devpreempt.decode_targets(
                    pbatch, t, f, snapshot, cq_by))
            if fbatch is not None and "fair" in pre:
                from kueue_tpu.solver import fairpreempt
                ft, ff, frr = pre["fair"]
                targets_by_entry.update(fairpreempt.decode_fair_targets(
                    fbatch, ft, ff, frr, snapshot, cq_by))
            for i, e in enumerate(pending):
                e.preemption_targets = targets_by_entry.get(i, [])
            self._retry_partial_admission(pending, snapshot)

        solver_entries = list(invalid_entries)
        pre_keys = {e.info.key for e in pre_entries}
        remaining = [w for i, w in enumerate(valid_heads)
                     if decisions.get(i) is None and w.key not in pre_keys]
        # Snapshot accounting only matters when more entries (the CPU
        # remainder or the pre-nominated preempt/nofit set) will read the
        # snapshot after us.
        account = bool(remaining) or bool(pre_entries)
        for i, w in enumerate(valid_heads):
            if w.key in pre_keys:
                continue  # CPU-nominated; decisions only cover fit routing
            decision = decisions.get(i)
            if decision is None:
                continue
            assignment, admitted = decision
            e = Entry(info=w, assignment=assignment)
            w.last_assignment = assignment.last_state
            if not admitted:
                # Assigned against the pre-cycle snapshot but no longer fit
                # after intra-cycle accounting (phase B) — skip, don't
                # re-assign (reference: scheduler.go:266-273).
                self._set_skipped(e, "Workload no longer fits after "
                                     "processing another workload")
                solver_entries.append(e)
                continue
            cq = snapshot.cluster_queues[w.cluster_queue]
            if account:
                # Account on the snapshot so the CPU remainder sees it.
                cq.add_usage(assignment.usage)
            self._wait_pods_ready_if_needed(e, timeout)
            e.status = NOMINATED
            try:
                self.admit(e, cq)
            except Exception as exc:  # noqa: BLE001
                e.inadmissible_msg = f"Failed to admit workload: {exc}"
                self._solver_note_unapplied(w.key)
            solver_entries.append(e)
        return solver_entries, pre_entries, remaining

    def _device_nofit_entry(self, w: wlpkg.Info,
                            snapshot: Snapshot) -> Optional[Entry]:
        """A device-proved non-fit entry whose CQ can never preempt and
        which can't be partially admitted needs no CPU nomination: the
        sequential assigner could only restate NoFit. Returns the ready
        Entry, or None when the CPU path must run (preemption possible /
        reducer-eligible)."""
        cq = snapshot.cluster_queues[w.cluster_queue]
        p = cq.preemption
        if (p.within_cluster_queue != api.PREEMPTION_NEVER
                or p.reclaim_within_cohort != api.PREEMPTION_NEVER):
            return None
        if features.enabled(features.PARTIAL_ADMISSION) \
                and w.can_be_partially_admitted():
            return None
        e = Entry(info=w)  # empty assignment => representative NO_FIT
        e.inadmissible_msg = ("couldn't assign flavors: insufficient quota "
                              "(batched assignment)")
        return e

    def _batched_reducer_eligible(self, w: wlpkg.Info,
                                  snapshot: Snapshot) -> bool:
        """Batched partial admission requires probes that can't pass via
        preemption (Never/Never policy makes the reducer's predicate
        pure fit — exactly what the batched Phase A evaluates)."""
        if not features.enabled(features.PARTIAL_ADMISSION) \
                or not w.can_be_partially_admitted():
            return False
        p = snapshot.cluster_queues[w.cluster_queue].preemption
        return (p.within_cluster_queue == api.PREEMPTION_NEVER
                and p.reclaim_within_cohort == api.PREEMPTION_NEVER)

    def _batched_partial_admission(self, partial_ws: list, plan,
                                   snapshot: Snapshot) -> list:
        """Returns a mix of ready Entries (reduced-fit or NoFit) and raw
        workloads to hand back to CPU nomination (fallback)."""
        from kueue_tpu.solver.service import CPU_FALLBACK
        try:
            results = self.solver.batched_partial_admission(
                plan, snapshot, partial_ws)
        except Exception:  # noqa: BLE001 — encode failure: CPU reducer
            results = None
        if results is None:
            return list(partial_ws)
        out: list = []
        oracle = make_reclaim_oracle(self.preemptor, snapshot)
        for i, w in enumerate(partial_ws):
            counts = results.get(i)
            if counts is CPU_FALLBACK:
                out.append(w)
                continue
            e = Entry(info=w)
            if counts is None:
                e.inadmissible_msg = ("couldn't assign flavors: "
                                      "insufficient quota "
                                      "(batched assignment)")
                out.append(e)
                continue
            cq = snapshot.cluster_queues[w.cluster_queue]
            assigner = fa.FlavorAssigner(w, cq, snapshot.resource_flavors,
                                         self.fair_sharing_enabled, oracle)
            e.assignment = assigner.assign(counts)
            e.inadmissible_msg = e.assignment.message()
            w.last_assignment = e.assignment.last_state
            out.append(e)
        return out

    def _cpu_preempt_targets(self, pending: list, snapshot: Snapshot) -> None:
        """Fallback / gate routing: resolve deferred preempt-mode entries
        with the CPU preemptor (assignments are already computed)."""
        for e in pending:
            e.preemption_targets = self.preemptor.get_targets(
                e.info, e.assignment, snapshot)
        self._retry_partial_admission(pending, snapshot)

    def _retry_partial_admission(self, pending: list, snapshot: Snapshot) -> None:
        """No feasible target set: the CPU path would now attempt partial
        admission (get_assignments' reducer branch)."""
        if not features.enabled(features.PARTIAL_ADMISSION):
            return
        for e in pending:
            if not e.preemption_targets and e.info.can_be_partially_admitted():
                e.assignment, e.preemption_targets = self.get_assignments(
                    e.info, snapshot)
                e.inadmissible_msg = e.assignment.message()
                e.info.last_assignment = e.assignment.last_state

    def _validate_head(self, w: wlpkg.Info, snapshot: Snapshot):
        """Pre-admission validation (the non-assignment part of nominate).
        Returns None if admissible, else (message, requeue reason)."""
        cq = snapshot.cluster_queues.get(w.cluster_queue)
        ns_labels = self.client.namespace_labels(w.obj.metadata.namespace)
        if wlpkg.has_retry_checks(w.obj) or wlpkg.has_rejected_checks(w.obj):
            return "The workload has failed admission checks", RequeueReason.GENERIC
        if w.cluster_queue in snapshot.inactive_cluster_queue_sets:
            return f"ClusterQueue {w.cluster_queue} is inactive", RequeueReason.GENERIC
        if cq is None:
            return f"ClusterQueue {w.cluster_queue} not found", RequeueReason.GENERIC
        if ns_labels is None:
            return "Could not obtain workload namespace", RequeueReason.GENERIC
        if cq.namespace_selector is None or not cq.namespace_selector.matches(ns_labels):
            return ("Workload namespace doesn't match ClusterQueue selector",
                    RequeueReason.NAMESPACE_MISMATCH)
        if (err := self._validate_resources(w)) is not None:
            return err, RequeueReason.GENERIC
        if (err := self._validate_limit_range(w)) is not None:
            return err, RequeueReason.GENERIC
        return None

    def _wait_pods_ready_if_needed(self, e: Entry, timeout) -> None:
        if not self.cache.pods_ready_for_all_admitted_workloads():
            # Patch a clone: e.info.obj may alias the store's object
            # (shared watch events).
            patch = wlpkg.clone_for_status_update(e.info.obj)
            wlpkg.unset_quota_reservation_with_condition(
                patch, "Waiting",
                "waiting for all admitted workloads to be in PodsReady condition",
                self.clock.now())
            self.client.patch_not_admitted(patch)
            self.cache.wait_for_pods_ready(timeout=timeout)

    # --- nomination (reference: scheduler.go:404-441) ---

    def nominate(self, heads: list, snapshot: Snapshot,
                 defer_preemption: bool = False) -> list:
        entries = []
        for w in heads:
            cq = snapshot.cluster_queues.get(w.cluster_queue)
            e = Entry(info=w)
            if self.cache.is_assumed_or_admitted(w):
                continue
            err = self._validate_head(w, snapshot)
            if err is not None:
                e.inadmissible_msg, e.requeue_reason = err
            else:
                e.assignment, e.preemption_targets = self.get_assignments(
                    w, snapshot, defer_preemption=defer_preemption)
                e.inadmissible_msg = e.assignment.message()
                w.last_assignment = e.assignment.last_state
                if self.fair_sharing_enabled and e.assignment.representative_mode() != fa.NO_FIT:
                    e.dominant_resource_share, e.dominant_resource_name = \
                        cq.dominant_resource_share_with(e.assignment.total_requests_for(w))
            entries.append(e)
        return entries

    def get_assignments(self, wl: wlpkg.Info, snapshot: Snapshot,
                        defer_preemption: bool = False):
        """reference: scheduler.go:469-507."""
        cq = snapshot.cluster_queues[wl.cluster_queue]
        oracle = make_reclaim_oracle(self.preemptor, snapshot)
        assigner = fa.FlavorAssigner(wl, cq, snapshot.resource_flavors,
                                     self.fair_sharing_enabled, oracle)
        full = assigner.assign()
        mode = full.representative_mode()
        if mode == fa.FIT:
            return full, []
        if defer_preemption and mode == fa.PREEMPT:
            return full, None  # targets resolved by _solve_preemption_batch
        targets: list = []
        if mode == fa.PREEMPT:
            targets = self.preemptor.get_targets(wl, full, snapshot)

        if not features.enabled(features.PARTIAL_ADMISSION) or targets:
            return full, targets

        if wl.can_be_partially_admitted():
            def fits(counts: list):
                assignment = assigner.assign(counts)
                m = assignment.representative_mode()
                if m == fa.FIT:
                    return (assignment, []), True
                if m == fa.PREEMPT:
                    t = self.preemptor.get_targets(wl, assignment, snapshot)
                    if t:
                        return (assignment, t), True
                return None, False

            reducer = PodSetReducer(wl.obj.spec.pod_sets, fits)
            result, found = reducer.search()
            if found:
                return result
        return full, []

    # --- validation (reference: scheduler.go:509-566) ---

    def _validate_resources(self, wl: wlpkg.Info) -> Optional[str]:
        reasons = []
        for ps in wl.obj.spec.pod_sets:
            spec = ps.template.spec
            bad = container_limits_violations(
                list(spec.init_containers) + list(spec.containers))
            if bad:
                reasons.append(f"podSets[{ps.name}][{', '.join(bad)}] "
                               f"requests exceed limits")
        if reasons:
            return "resource validation failed: " + "; ".join(reasons)
        return None

    def _validate_limit_range(self, wl: wlpkg.Info) -> Optional[str]:
        ranges = self.client.limit_ranges(wl.obj.metadata.namespace)
        if not ranges:
            return None
        summary = limitrangepkg.summarize(*ranges)
        reasons = []
        for ps in wl.obj.spec.pod_sets:
            reasons.extend(limitrangepkg.validate_pod_spec(
                ps.template.spec, summary, path=f"podSets[{ps.name}]"))
        if reasons:
            return "didn't satisfy LimitRange constraints: " + "; ".join(reasons)
        return None

    # --- admission (reference: scheduler.go:571-623) ---

    def admit(self, e: Entry, cq: ClusterQueueSnapshot) -> None:
        new_wl = wlpkg.clone_for_status_update(e.info.obj)
        admission = api.Admission(cluster_queue=e.info.cluster_queue,
                                  pod_set_assignments=e.assignment.to_api())
        now = self.clock.now()
        wlpkg.set_quota_reservation(new_wl, admission, now)
        checks = wlpkg.admission_checks_for_workload(new_wl, cq.admission_checks)
        if wlpkg.has_all_checks(new_wl, checks):
            wlpkg.sync_admitted_condition(new_wl, now)
        self.cache.assume_workload(new_wl, info=wlpkg.Info.from_assignment(
            new_wl, e.info.cluster_queue, e.assignment))
        e.status = ASSUMED
        if self.on_placement is not None:
            # Batched-column MultiKueue placement: remember the admit
            # ORDER (the oracle's intra-cycle capacity accounting is
            # order-dependent); _flush_mk_placements filters to CQs
            # that actually route through a multikueue check.
            self._mk_admits.append((e.info, cq))

        def apply():
            # Crash window between the cache assumption above and the
            # store's admission write (RESILIENCE.md §6): a process
            # death here loses the in-memory assumption while the
            # durable store still says pending — on restore the
            # workload must requeue and re-admit exactly once.
            faultinject.site(faultinject.SITE_APPLY)
            try:
                self.client.apply_admission(new_wl)
            except KeyError:
                # Deleted or CQ gone: roll back the assumption.
                try:
                    self.cache.forget_workload(new_wl)
                except KeyError:
                    pass
                return
            wait_time = wlpkg.queued_wait_time(new_wl, now)
            self.client.event(new_wl, "Normal", "QuotaReserved",
                              f"Quota reserved in ClusterQueue {admission.cluster_queue}, "
                              f"wait time since queued was {wait_time:.0f}s")
            if self.journeys is not None:
                # THE emission site for the reservation-time wait
                # histograms (ISSUE 14 reconcile-by-construction): the
                # ledger observes quota_reserved_wait_time (+
                # admission_wait_time when the write also admits) AND
                # stamps the journey span, so /debug/journeys and
                # /metrics share one producer.
                self.journeys.quota_reserved(
                    new_wl, admission.cluster_queue, wait_time,
                    wlpkg.is_admitted(new_wl))
            elif self.metrics is not None:
                self.metrics.quota_reserved(admission.cluster_queue, wait_time)
                if wlpkg.is_admitted(new_wl):
                    self.metrics.admitted(admission.cluster_queue, wait_time)
            if wlpkg.is_admitted(new_wl):
                self.client.event(new_wl, "Normal", "Admitted",
                                  f"Admitted by ClusterQueue {admission.cluster_queue}, "
                                  f"wait time since reservation was 0s")

        self.admission_routine(apply)

    def _flush_mk_placements(self, snapshot: Snapshot) -> None:
        """Resolve this apply stage's MultiKueue placements and forward
        them to the controller (ISSUE 13 batched columns). Device-routed
        cycles pin the fused solve's mk_cluster decisions; the remaining
        (CPU-nominated) admissions run the identical sequential oracle
        against the snapshot's capacity columns, CONTINUING from the
        device's intra-cycle accounting — one consistent greedy per
        cycle, zero per-workload controller probing on the hot path."""
        admits, self._mk_admits = self._mk_admits, []
        if self.on_placement is None or not admits:
            return
        cols = getattr(snapshot, "remote_clusters", ())
        checks = getattr(snapshot, "mk_check_names", frozenset())
        if not cols or not checks:
            return
        from kueue_tpu.api.corev1 import RESOURCE_PODS
        from kueue_tpu.solver import encode as solver_encode
        device = getattr(self.solver, "last_placements", None) or {}
        mk, reqs, pinned = [], [], []
        for info, cq in admits:
            if checks.isdisjoint(cq.admission_checks):
                continue
            covers_pods = any(RESOURCE_PODS in rg.covered_resources
                              for rg in cq.resource_groups)
            mk.append(info)
            # the one shared request-vector fold (the controller's
            # in-flight debit consumes the identical vector)
            reqs.append(wlpkg.mk_request_vector(info, covers_pods))
            pinned.append(device.get(info.key))
        if not mk:
            return
        placed = solver_encode.place_remote_dicts(cols, reqs, pinned=pinned)
        for info, cluster in zip(mk, placed):
            if cluster is not None:
                self.on_placement(info.key, cluster)

    def _apply_preemption(self, wl: api.Workload, preempting_cq: str,
                          reason: str, message: str) -> None:
        target = wlpkg.clone_for_status_update(wl)
        now = self.clock.now()
        wlpkg.set_evicted_condition(target, api.EVICTED_BY_PREEMPTION, message, now)
        wlpkg.set_preempted_condition(target, reason, message, now)
        self.client.apply_admission(target)
        self.client.event(target, "Normal", "Preempted", message)
        if self.metrics is not None:
            self.metrics.preempted(preempting_cq, reason)
        if self.journeys is not None:
            # Victim's journey re-opens: it will requeue and re-admit,
            # and the preemption is part of WHY its admission was slow.
            self.journeys.preempted(wlpkg.key(wl), preempting_cq, reason)

    # --- requeue (reference: scheduler.go:674-692) ---

    def requeue_and_update(self, e: Entry) -> None:
        if e.status != NOT_NOMINATED and e.requeue_reason == RequeueReason.GENERIC:
            e.requeue_reason = RequeueReason.FAILED_AFTER_NOMINATION
        if self.journeys is not None:
            # Every non-admitted entry on every route passes through
            # here: the journey's per-cycle evidence of WHERE a slow
            # admission's cycles went (status + reason + message, all
            # stamped with this cycle's id/generation/route).
            self.journeys.requeued(e.info, e.status, e.requeue_reason,
                                   e.inadmissible_msg)
        self.queues.requeue_workload(e.info, e.requeue_reason)
        if e.status in (NOT_NOMINATED, SKIPPED):
            # Clone only when the Pending condition would actually change:
            # at scale most cycles re-requeue already-Pending entries and
            # the per-entry status clone dominated the requeue path.
            if wlpkg.pending_patch_needed(e.info.obj, "Pending",
                                          e.inadmissible_msg):
                patch = wlpkg.clone_for_status_update(e.info.obj)
                wlpkg.unset_quota_reservation_with_condition(
                    patch, "Pending", e.inadmissible_msg, self.clock.now())
                self.client.patch_not_admitted(patch)
            self.client.event(e.info.obj, "Normal", "Pending", e.inadmissible_msg[:1024])

    @staticmethod
    def _set_skipped(e: Entry, msg: str) -> None:
        e.status = SKIPPED
        e.inadmissible_msg = msg
        e.requeue_reason = RequeueReason.GENERIC

    # --- ordering (reference: scheduler.go:625-672) ---

    def _entry_sort_key(self):
        def sort_key(e: Entry):
            borrows = e.assignment.borrows()
            share = e.dominant_resource_share if self.fair_sharing_enabled else 0
            prio = (prioritypkg.priority(e.info.obj)
                    if features.enabled(features.PRIORITY_SORTING_WITHIN_COHORT) else 0)
            ts = self.ordering.queue_order_timestamp(e.info.obj)
            return (borrows, share, -prio, ts)
        return sort_key


def resources_to_reserve(e: Entry, cq: ClusterQueueSnapshot) -> dict:
    """How much capacity a blocked preemptor reserves
    (reference: scheduler.go:444-462)."""
    if e.assignment.representative_mode() != fa.PREEMPT:
        return e.assignment.usage
    reserved = {}
    for fr, usage in e.assignment.usage.items():
        quota = cq.quota_for(fr)
        if e.assignment.borrowing:
            if quota.borrowing_limit is None:
                reserved[fr] = usage
            else:
                reserved[fr] = min(usage, quota.nominal + quota.borrowing_limit
                                   - cq.usage_for(fr))
        else:
            reserved[fr] = max(0, min(usage, quota.nominal - cq.usage_for(fr)))
    return reserved
