"""Typed inter-stage contract for the admission cycle.

``Scheduler.schedule()`` is an explicit three-stage machine — see
kueue_tpu/scheduler/PIPELINE.md for the full protocol:

- **nominate**: pop + validate heads, assign flavors / discover
  preemption candidates against the cycle snapshot (CPU side).
- **solve**: the batched device solve (fit Phase A/B + fused preemption
  target selection) for the routed share of the heads.
- **apply**: admit survivors with intra-cycle accounting, issue
  evictions, requeue everything else.

The dataclasses below are the contracts the stages hand each other, for
both the synchronous cycle (all three stages inside one ``schedule()``
call) and the speculative pipeline, where the solve stage for snapshot N
runs while cycle N-1's apply is still mutating the cache. Speculative
results are only committed after ``SpeculationToken.validate`` proves
the state they were computed against still describes the live cache —
the assume/forget + generation-token optimistic-concurrency protocol
(SURVEY.md §2.7 "assume-cache"): structural epochs, device-residency
identity, per-slot encode-arena generations, and the solver's journal
cursor health. Mis-speculation abandons the in-flight result (heads
re-heap, residency drops) and the cycle falls back to the synchronous
path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np


@dataclass
class SpeculationToken:
    """Generation stamp of the state a speculative solve was computed
    against. Cheap by construction: three epoch ints, one object
    identity, and one small int64 gather — never a snapshot comparison.

    - ``epochs``: the cache's structural generation token
      (topology/cohort/flavor-spec). Workload churn deliberately does
      NOT invalidate — the resident solver state reconciles usage
      movement through the usage journal; only structural edits make
      in-flight decisions unsound.
    - ``journal_seq``: the journal cursor the dispatch snapshot froze
      at (diagnostics; staleness itself is fine, losing entries is not).
    - ``resident``: the ResidentState identity the plan chained on, or
      None for a non-resident dispatch.
    - ``slots``/``slot_gens``: the encode-arena slots the dispatched
      batch gathered, with their per-slot generations — a mid-flight
      upsert/delete of a dispatched workload bumps its slot generation
      and the speculation aborts instead of admitting a stale object.
    """

    journal_seq: int = -1
    epochs: tuple = ()
    resident: object = None
    slots: object = None
    slot_gens: object = None

    # reason slugs per position of the canonical epochs tuple
    # (incremental.snapshot_generations / Cache.generation_token order)
    _EPOCH_REASONS = ("topology-epoch", "cohort-epoch",
                      "flavor-spec-epoch")

    @classmethod
    def stamp(cls, cache, solver, plan, snapshot) -> "SpeculationToken":
        from kueue_tpu.cache.incremental import snapshot_generations
        slots = getattr(plan, "slots", None)
        # Prefer the encode-time capture (service.Plan.slot_gens): a
        # delta landing between encode and this stamp must read as
        # staleness, not get baked into the witness.
        gens = getattr(plan, "slot_gens", None)
        if gens is None and slots is not None:
            slot_fn = getattr(solver, "slot_generations", None)
            if slot_fn is not None:
                gens = slot_fn(slots)
        return cls(
            journal_seq=getattr(snapshot, "journal_seq", -1),
            # The SNAPSHOT's generations, not the cache's current ones:
            # the token witnesses the state the solve was computed
            # against, so an epoch bump that raced in between the
            # snapshot and this stamp reads as the staleness it is.
            epochs=snapshot_generations(snapshot),
            resident=getattr(plan, "rs", None) if plan.resident else None,
            slots=slots,
            slot_gens=gens,
        )

    def validate(self, cache, solver) -> tuple:
        """(ok, reason). Reasons are stable slugs for the abort counter
        labels: topology-epoch | cohort-epoch | flavor-spec-epoch |
        residency | arena-slots | journal-overflow."""
        if self.epochs:
            live = cache.generation_token()
            if self.epochs != live:
                for i, reason in enumerate(self._EPOCH_REASONS):
                    if self.epochs[i] != live[i]:
                        return False, reason
        if self.resident is not None \
                and getattr(solver, "_resident", None) is not self.resident:
            return False, "residency"
        if self.slot_gens is not None:
            slot_fn = getattr(solver, "slot_generations", None)
            gens = slot_fn(self.slots) if slot_fn is not None else None
            if gens is None or not np.array_equal(gens, self.slot_gens):
                return False, "arena-slots"
        overflowed = getattr(cache, "journal_overflowed", None)
        if overflowed is not None and overflowed():
            return False, "journal-overflow"
        return True, ""


@dataclass
class InFlightCycle:
    """A dispatched, un-collected speculative cycle — what the solve
    stage hands the (next call's) apply stage.

    - ``inflight``: the solver's InFlight (device result references).
    - ``snapshot``: the light snapshot the cycle was encoded against.
    - ``nofit_idx``: batch rows already requeued at dispatch time via
      the device-NoFit shortcut.
    - ``pend_idx``/``pmeta``: the pipelined-mixed preemption rows and
      their (pending entries, cq_by, full snapshot) collect-time state.
    - ``token``: the speculation stamp validated before commit.
    """

    inflight: object
    snapshot: object
    nofit_idx: set = field(default_factory=set)
    pend_idx: set = field(default_factory=set)
    pmeta: object = None
    token: Optional[SpeculationToken] = None


@dataclass
class NominatedCycle:
    """Output of the nominate stage (plus the solve stage's CPU-side
    spillover): everything the apply stage admits from.

    ``entries`` are sorted by the admission order
    (borrows -> DRF share -> priority -> FIFO); ``solver_entries``
    were already admitted/skipped by the device solve and only rejoin
    for the requeue sweep.
    """

    snapshot: object = None
    entries: list = field(default_factory=list)
    solver_entries: list = field(default_factory=list)
    route: str = ""
    # filled by the apply stage: per-CQ preemption-skip counts for the
    # admission_cycle_preemption_skips gauge
    skipped_preemptions: dict = field(default_factory=dict)


@dataclass
class AppliedCycle:
    """Output of the apply stage: the cycle's admission outcome."""

    admitted: int = 0
    success: bool = False
    regime: str = "fit"
    blocked_preemptor: bool = False
