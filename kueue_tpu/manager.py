"""Manager assembly: the equivalent of cmd/kueue/main.go:98-336.

Builds the full control plane in-process: sim store (the apiserver role),
queue manager + cache, core controllers, webhook admission on writes,
the scheduler with a store-backed client, and (optionally) the TPU batch
solver. Tests and the perf harness drive it via `run_until_idle()` +
`schedule_once()` for deterministic cycles, or `start()` for the
threaded scheduler loop.
"""

from __future__ import annotations

from typing import Optional

from kueue_tpu import config as cfgpkg
from kueue_tpu import features
from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import REAL_CLOCK, Clock
from kueue_tpu.cache import Cache
from kueue_tpu.controller.core import setup_core_controllers
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.metrics import Registry
from kueue_tpu.queue import Manager as QueueManager
from kueue_tpu.scheduler.scheduler import Scheduler, SchedulerClient
from kueue_tpu.sim import NotFound, Store
from kueue_tpu.sim.runtime import EventRecorder, Runtime


class StoreSchedulerClient(SchedulerClient):
    """SchedulerClient over the sim store (the reference scheduler's only
    API interactions: namespace Get, SSA admission writes, Pending
    patches, events — scheduler.go:421,571-623,674-692)."""

    def __init__(self, store: Store, recorder: EventRecorder):
        self.store = store
        self.recorder = recorder

    def namespace_labels(self, namespace: str) -> Optional[dict]:
        ns = self.store.try_get("Namespace", "", namespace)
        return ns.metadata.labels if ns is not None else {}

    def limit_ranges(self, namespace: str) -> list:
        return self.store.list("LimitRange", namespace=namespace)

    def apply_admission(self, wl: api.Workload) -> None:
        # Status-subresource write, like the reference's SSA
        # ApplyAdmissionStatus (workload.go): the scheduler only writes
        # status, and it already holds a fresh clone — no read-back
        # round trip, no spec re-validation, no full-object deep copy.
        self.store.update_status(wl, owned_status=True)

    def patch_not_admitted(self, wl: api.Workload) -> None:
        # Merge ONLY the conditions onto the CURRENT status (a
        # strategic-merge patch, like the reference's Pending patches):
        # an admission-check controller may have written
        # admission_checks/requeue_state since the scheduler's snapshot,
        # and a whole-status overwrite from the stale base would revert
        # them.
        current = self.store.try_get("Workload", wl.metadata.namespace,
                                     wl.metadata.name, copy_object=False)
        if current is None:
            return
        patch = wlpkg.clone_for_status_update(current)
        patch.status.conditions = wl.status.conditions
        self.store.update_status(patch, owned_status=True)

    def event(self, wl: api.Workload, event_type: str, reason: str,
              message: str) -> None:
        self.recorder.event(wl, event_type, reason, message)


class KueueManager:
    def __init__(self, cfg: Optional[cfgpkg.Configuration] = None,
                 clock: Clock = REAL_CLOCK, solver=None,
                 registered_check_controllers: Optional[set] = None,
                 remote_clusters: Optional[dict] = None,
                 store: Optional[Store] = None, identity: str = ""):
        """store/identity: HA replicas share one Store (the apiserver
        stand-in) and elect a leader over it; identity names this
        replica in the lease (auto-generated when empty)."""
        self.cfg = cfgpkg.set_defaults(cfg or cfgpkg.Configuration())
        from kueue_tpu.utils import vlog
        # Don't clobber a KUEUE_TPU_V env override with the config
        # default: the louder of config and the ENV override wins (not
        # the mutable global — a previous manager's level must not
        # ratchet this one's).
        vlog.set_verbosity(max(self.cfg.verbosity, vlog.env_verbosity()))
        self.clock = clock
        self.store = store if store is not None else Store(clock)
        # Durable store (sim/durable.py + RESILIENCE.md §6): with
        # store.durable the manager-owned store journals every committed
        # mutation to a checkpoint/WAL log, and restore() (below)
        # rebuilds a whole control plane from it after a crash. A store
        # passed IN keeps its caller-owned durability (HA replicas share
        # one store; recovery re-attaches after the replay).
        self.durable = getattr(self.store, "_durable", None)
        st = self.cfg.store
        if store is None and st.durable and self.durable is None:
            from kueue_tpu.sim.durable import DurableLog
            self.durable = DurableLog(dir=st.wal_dir or None,
                                      checkpoint_every=st.checkpoint_every)
            self.store.attach_durable(self.durable)
        # Crash-restart recovery report (resilience/recovery.py):
        # populated by restore() on a recovered manager.
        self.last_recovery = None
        self.recorder = EventRecorder()
        self.metrics = Registry()
        # metrics: every reconcile lands in reconcile_seconds{controller}
        # (the coarse latency signal for the wall_s - cycle_time_total
        # gap in the perf artifacts).
        self.runtime = Runtime(clock, metrics=self.metrics)

        w = self.cfg.wait_for_pods_ready
        ordering = wlpkg.Ordering(
            pods_ready_requeuing_timestamp=(
                w.requeuing_strategy.timestamp if w else cfgpkg.EVICTION_TIMESTAMP))
        self.queues = QueueManager(
            ordering=ordering, clock=clock,
            namespace_labels=lambda ns: self._namespace_labels(ns),
            excluded_resource_prefixes=self.cfg.resources.exclude_resource_prefixes)
        self.cache = Cache(
            pods_ready_tracking=bool(w and w.enable and w.block_admission),
            excluded_resource_prefixes=self.cfg.resources.exclude_resource_prefixes)

        # built-in admission-check controllers are always registered
        # (reference: cmd/kueue/main.go:240-263)
        from kueue_tpu.controller.admissionchecks import multikueue as mkpkg
        from kueue_tpu.controller.admissionchecks import provisioning as provpkg
        check_controllers = set(registered_check_controllers or set())
        check_controllers |= {provpkg.CONTROLLER_NAME, mkpkg.CONTROLLER_NAME}

        # Cycle flight recorder (kueue_tpu/obs): per-cycle phase traces
        # in a bounded ring, served via serve_visibility()'s /debug/*.
        # Created before the controllers so the workload reconciler's
        # per-event spans (reconcile.workload.{event}) share it.
        from kueue_tpu.obs import FlightRecorder
        o = self.cfg.observability
        self.flight_recorder = FlightRecorder(
            capacity=o.flight_recorder_capacity,
            enabled=o.flight_recorder_enable)

        # Workload journey ledger (obs/journey.py + ISSUE 14): every
        # workload accumulates a causally-stamped span timeline fed
        # from the queue manager's delta feed (arrivals), the
        # scheduler's admit/requeue/shed sites, the workload
        # controller's eviction paths and the MultiKueue planned-mirror
        # lifecycle. The ledger is also THE emission site for the
        # admission wait-time histograms, so /debug/journeys and
        # /metrics reconcile by construction. Created before the
        # controllers (the workload reconciler seals check-gated
        # admissions through it).
        self.journey_ledger = None
        if o.journey_enable:
            from kueue_tpu.obs import JourneyLedger
            self.journey_ledger = JourneyLedger(
                capacity=o.journey_ledger_capacity,
                exemplars=o.journey_exemplars,
                metrics=self.metrics, clock=clock,
                generation_source=self.cache.generation_token)
            self.queues.add_journey_listener(
                self.journey_ledger.note_queue_delta)

        self.controllers = setup_core_controllers(
            self.runtime, self.store, self.queues, self.cache, self.recorder,
            cfg=self.cfg, metrics=self.metrics,
            registered_check_controllers=check_controllers,
            obs_recorder=self.flight_recorder,
            journeys=self.journey_ledger)

        self.provisioning = provpkg.setup_provisioning_controller(
            self.runtime, self.store, self.recorder)
        self.multikueue = mkpkg.setup_multikueue_controller(
            self.runtime, self.store, self.recorder,
            remote_clusters=remote_clusters,
            origin=self.cfg.multi_kueue.origin,
            worker_lost_timeout=self.cfg.multi_kueue.worker_lost_timeout_seconds)

        # Periodic remote-orphan GC (reference: multikueuecluster.go GC
        # interval): without this timer gc_orphans existed but nothing
        # scheduled it, so mirrors whose local original vanished during
        # a worker-cluster outage leaked until a manual sweep. Runs on
        # the runtime like the queue-visibility cron so deterministic
        # drivers (advance()) exercise it; <=0 disables.
        gc_interval = self.cfg.multi_kueue.gc_interval_seconds
        if gc_interval > 0 and remote_clusters:

            def gc_orphans(_key):
                self.multikueue.gc_orphans()
                return float(gc_interval)

            gc_ctrl = self.runtime.controller("multikueue-gc", gc_orphans)
            gc_ctrl.enqueue("gc")

        # job integrations (reference: jobframework.SetupControllers via
        # cmd/kueue/main.go:229-290). Registration is idempotent across
        # managers; wiring is per-runtime.
        from kueue_tpu.controller import jobs as jobs_registry
        from kueue_tpu.controller.jobframework import (
            get_integration, setup_integrations)
        if get_integration("batch/job") is None:
            jobs_registry.register_all()
        self.integrations = setup_integrations(
            self.runtime, self.store, self.recorder, self.cfg)

        # admission webhooks on the sim store (reference:
        # webhooks.Setup, cmd/kueue/main.go:265-268)
        from kueue_tpu.webhooks import setup_webhooks
        setup_webhooks(self.store, self.cfg)

        self.scheduler_client = StoreSchedulerClient(self.store, self.recorder)
        self.scheduler = Scheduler(
            self.queues, self.cache, self.scheduler_client,
            ordering=ordering,
            fair_sharing_enabled=self.cfg.fair_sharing.enable,
            fs_preemption_strategies=self.cfg.fair_sharing.preemption_strategies,
            clock=clock, metrics=self.metrics, solver=solver,
            solver_min_heads=self.cfg.solver.min_heads,
            recorder=self.flight_recorder)
        # MultiKueue batched-column placement wiring (ISSUE 13): the
        # cache stamps every snapshot with the controller's remote
        # capacity columns, the admission cycle scores them (fused
        # solve on device routes, the identical sequential oracle on
        # CPU routes), and the controller executes the decisions (one
        # mirror per workload instead of the mirror-everywhere race).
        if remote_clusters:
            self.cache.remote_capacity_source = self.multikueue.capacity_columns
            self.scheduler.on_placement = self.multikueue.note_placement
            self.multikueue.journeys = self.journey_ledger
        self.scheduler.journeys = self.journey_ledger
        # Aging watch (obs/trend.py + ROADMAP item 5): EWMA-slope trend
        # monitors over the monotone resources long-horizon soak gates
        # on, sampled once per cycle seal and served on /debug/aging.
        # Always wired — the per-cycle cost is a handful of float ops.
        from kueue_tpu.obs import AgingWatch
        from kueue_tpu.obs.trend import rss_kb
        self.aging_watch = AgingWatch()
        self.aging_watch.add(
            # Snapshot handouts not yet released between cycles: the
            # steady state is flat (the query plane legitimately holds
            # one); sustained growth is an abandoned-cycle leak.
            "live_handouts", lambda: self.cache.live_handouts,
            slope_threshold=0.05)
        if self.durable is not None and self.durable.checkpoint_every > 0:
            # WAL records since the last checkpoint: bounded by the
            # compaction interval when healthy; a level past 2x the
            # interval means compaction stalled (slope is useless on a
            # sawtooth, the bound is not).
            self.aging_watch.add(
                "wal_records_since_checkpoint",
                lambda: self.durable.records_since_checkpoint,
                slope_threshold=None,
                bound=2.0 * self.durable.checkpoint_every)
        if self.journey_ledger is not None:
            self.aging_watch.add(
                # ROADMAP item 5's requeue-amplification invariant: the
                # ratio stabilizes on a healthy system; a sustained
                # upward trend is a requeue-backoff pile-up.
                "requeue_amplification",
                lambda: self.journey_ledger.requeues_per_admission,
                slope_threshold=0.02, window=32)
        if solver is not None:
            self.aging_watch.add(
                # Arena slot occupancy grows while a backlog fills and
                # plateaus after; growth sustained past a long window
                # is slot leakage (rows never released at admission).
                "arena_occupied",
                lambda: ((solver._arena.size - len(solver._arena.free))
                         if getattr(solver, "_arena", None) is not None
                         else 0.0),
                slope_threshold=1.0, window=64)
            self.aging_watch.add(
                # Zero mid-traffic compiles after warmup (the PR-7
                # north-star bound, now a live trend): ANY sustained
                # growth flags.
                "mid_traffic_compiles",
                lambda: getattr(solver, "counters", {}).get(
                    "mid_traffic_compiles", 0),
                slope_threshold=0.01, window=16)
        self.aging_watch.add(
            # Peak RSS plateaus after warmup; a sustained climb of
            # >1MB/cycle over a long window is the flat-RSS-trend
            # invariant failing.
            "rss_kb", rss_kb, slope_threshold=1024.0, window=64,
            warmup=16)
        self.scheduler.aging = self.aging_watch
        self.visibility_server = None  # started by serve_visibility()
        # Snapshot-backed query plane (obs/queryplane.py + ISSUE 12):
        # every cycle seal publishes an immutable pending-position /
        # status view (nominate-order column + the cycle's snapshot
        # handout, ownership transferred from the scheduler), and the
        # visibility server reads ONLY sealed views — a read storm
        # never touches the live heaps the admission cycle mutates.
        self.query_plane = None
        if self.cfg.observability.query_plane_enable:
            from kueue_tpu.obs.queryplane import QueryPlane
            self.query_plane = QueryPlane(self.cache, self.queues,
                                          metrics=self.metrics)
            self.scheduler.query_plane = self.query_plane
        # Cycle deadline budget (kueue_tpu/resilience/degrade.py): with
        # scheduler.cycleBudget > 0 the degradation ladder watches every
        # cycle's wall seconds and sheds load (head caps, deferred
        # preempt planning, the cpu-survival route) under sustained
        # overload. Engine-agnostic — wired with or without a solver.
        sc = self.cfg.scheduler
        if sc.cycle_budget_s > 0:
            from kueue_tpu.resilience.degrade import DegradationLadder
            self.scheduler.ladder = DegradationLadder(
                budget_s=sc.cycle_budget_s,
                shed_heads=sc.shed_heads,
                survival_heads=sc.survival_heads,
                enter_factor=sc.overload_enter_factor,
                exit_factor=sc.overload_exit_factor,
                escalate_after=sc.escalate_after_cycles,
                recovery_cycles=sc.recovery_cycles,
                ewma_alpha=sc.cycle_ewma_alpha)
        if solver is not None:
            # Production solver wiring: pipelined dispatch + adaptive
            # engine routing + the persistent compilation cache.
            self.scheduler.pipeline_enabled = self.cfg.solver.pipeline
            self.scheduler.pipeline_depth = self.cfg.solver.pipeline_depth
            self.scheduler.solver_routing = self.cfg.solver.routing
            self.scheduler.strict_after_blocked_cycles = \
                self.cfg.solver.strict_after_blocked_cycles
            # Device-fault containment (kueue_tpu/resilience): watchdog
            # deadlines + circuit breaker from the solver config, and
            # fault/trip/recovery events onto the sim event recorder so
            # the outage timeline is visible in the artifacts.
            from kueue_tpu.resilience.breaker import CircuitBreaker
            from kueue_tpu.resilience.watchdog import DispatchWatchdog
            s = self.cfg.solver
            self.scheduler.watchdog = DispatchWatchdog(
                safety_factor=s.watchdog_safety_factor,
                min_deadline_s=s.watchdog_min_deadline_s,
                max_deadline_s=s.watchdog_max_deadline_s)
            self.scheduler.breaker = CircuitBreaker(
                threshold=s.breaker_fault_threshold,
                backoff_base_s=s.breaker_backoff_base_s,
                backoff_max_s=s.breaker_backoff_max_s)
            if hasattr(solver, "supervise_dispatch"):
                # Supervised dispatch: the trace/compile half of the
                # round trip carries the watchdog deadline too.
                solver.supervise_dispatch = s.supervise_dispatch
            from kueue_tpu.utils.runtime import enable_compilation_cache
            enable_compilation_cache(s.compile_cache_dir or None)
        # Compile governor (solver/warmgov.py): compiles become a
        # managed background event — a supervised warmup thread walks
        # the shape-bucket ladder (loading from the persistent cache,
        # stamped per topology under solver.compileCacheDir) while the
        # scheduler routes un-warmed buckets to the CPU path
        # ("cpu-warmup") instead of paying a hot-path compile. Attached
        # whenever a warm-capable solver is present so /debug/warmup
        # and the dumper always work; the background walk starts here
        # only with solver.warmupAtStartup (deterministic drivers call
        # start_warmup()/run_sync themselves).
        self.warm_governor = None
        if solver is not None and hasattr(solver, "warm_setup"):
            from kueue_tpu.scheduler.preemption import parse_strategies
            from kueue_tpu.solver.fairpreempt import strategy_flags
            from kueue_tpu.solver.warmgov import CompileGovernor
            s = self.cfg.solver
            self.warm_governor = CompileGovernor(
                solver, self.cache, metrics=self.metrics,
                recorder=self.flight_recorder,
                bucket_deadline_s=s.warmup_deadline_s,
                cache_dir=s.compile_cache_dir,
                max_width=s.max_heads,
                fair_sharing=self.cfg.fair_sharing.enable,
                fs_flags=strategy_flags(parse_strategies(
                    self.cfg.fair_sharing.preemption_strategies)))
            self.scheduler.warm_gov = self.warm_governor
            if s.warmup_at_startup:
                self.warm_governor.start()
        # Fault/breaker/degrade transitions land as Scheduler system
        # events — the outage + degraded-mode timeline in the artifacts.
        # Wired with or without a solver: the degradation ladder watches
        # the CPU path too.
        self.scheduler.on_fault = (
            lambda kind, msg: self.recorder.system_event(
                "Normal" if kind in ("breaker-closed", "degrade-recovered")
                else "Warning",
                {"fault": "DeviceFault",
                 "breaker-open": "BreakerOpen",
                 "breaker-closed": "BreakerClosed",
                 "degrade": "DegradedMode",
                 "degrade-recovered": "DegradedModeRecovered",
                 }.get(kind, kind),
                msg))

        # QueueVisibility top-N snapshot cron (reference:
        # clusterqueue_controller.go:553+ — a timed task per CQ on the
        # configured interval, NOT per reconcile; the visibility API
        # itself computes live and doesn't depend on these).
        qv = self.cfg.queue_visibility
        if qv.update_interval_seconds > 0:  # <=0 disables the feature

            def refresh_snapshots(_key):
                for name in list(self.queues.cluster_queues.keys()):
                    self.queues.update_snapshot(name,
                                                qv.cluster_queues.max_count)
                return float(qv.update_interval_seconds)

            qv_ctrl = self.runtime.controller("queuevisibility",
                                              refresh_snapshots)
            qv_ctrl.enqueue("cron")

        # Leader election (HA): the scheduler is leader-gated — the
        # reference's NeedLeaderElection (scheduler.go:144) — while the
        # watch-driven caches stay live on every replica for fast
        # failover. The elector renews through a runtime controller so
        # deterministic drivers (run_until_idle/advance) exercise
        # acquire/renew/expiry with the injected clock.
        self.elector = None
        le = self.cfg.leader_election
        if le.leader_elect:
            import uuid
            from kueue_tpu.utils.leaderelection import (
                LeaderAwareReconciler, LeaderElector)
            self.identity = identity or f"kueue-manager-{uuid.uuid4().hex[:8]}"
            self.elector = LeaderElector(
                self.store, self.identity, lease_name=le.resource_name,
                lease_duration=le.lease_duration_seconds,
                retry_period=le.retry_period_seconds, clock=clock)
            self.scheduler.leader_check = self.elector.is_leader

            # Every reconciler becomes leader-aware: non-leader replicas
            # delay status WRITES (requeue-after) while the watch-driven
            # caches above stay live on every replica — the reference's
            # leader_aware_reconciler.go:89 split. The elector itself
            # runs as a runtime controller so the deterministic drivers
            # exercise acquire/renew/expiry with the injected clock.
            for ctrl in self.runtime.controllers:
                # Delayed by lease_duration, not retry_period: leadership
                # can't change faster than a lease expiry, and a tight
                # requeue would have thousands of parked keys polling a
                # real clock on every standby replica.
                ctrl._reconcile = LeaderAwareReconciler(
                    ctrl._reconcile, self.elector,
                    requeue_seconds=le.lease_duration_seconds).reconcile
            ctrl = self.runtime.controller(
                "leaderelection",
                lambda _key: (self.elector.tick(),
                              le.retry_period_seconds)[1])
            ctrl.enqueue("lease")

    def _namespace_labels(self, ns: str) -> Optional[dict]:
        obj = self.store.try_get("Namespace", "", ns)
        return obj.metadata.labels if obj is not None else {}

    # -- crash-restart durability (resilience/recovery.py) --------------

    def shutdown(self, checkpoint: bool = True) -> None:
        """Graceful process exit: stop the scheduler loop AND abandon
        the in-flight speculative cycle (its snapshot handout goes back
        to the maintainer, device residency + arena claims drop — never
        strand; the requeued heads are moot for THIS process but keep
        the queues consistent if the caller drives more cycles), stop
        the warm governor and visibility server, and take a final
        durable checkpoint so a restart replays no WAL tail. The
        manager object stays readable (store, caches) but must not
        schedule again."""
        self.scheduler.stop()
        if self.warm_governor is not None:
            self.warm_governor.stop()
        if self.visibility_server is not None:
            self.visibility_server.stop()
            self.visibility_server = None
        if self.query_plane is not None:
            # Release the reader-held snapshot handout (the sealed
            # view's backing): live_handouts must return to zero after
            # a shutdown — the same leak contract abandoned speculative
            # cycles honor.
            self.query_plane.close()
        if self.journey_ledger is not None:
            # Drop every retained journey (active LRU + exemplars):
            # the ledger's leak contract is zero retained journeys
            # after shutdown, mirroring live_handouts.
            self.journey_ledger.close()
        if checkpoint and self.durable is not None:
            from kueue_tpu.sim.durable import Fenced
            try:
                self.store.checkpoint_now()
            except Fenced:
                # A DEPOSED leader shutting down gracefully: its stale
                # image must not replace the checkpoint (that would
                # rotate away the new leader's live WAL tail). Skip —
                # the durable truth belongs to the current epoch.
                pass
        if getattr(self.store, "fencing", None) is not None:
            # A leading manager hands the lease off instead of making
            # the standby wait out the full duration (the successor's
            # acquire bumps the fencing epoch as usual).
            self.store.fencing.release()

    @classmethod
    def standby(cls, durable, cfg=None, clock: Clock = REAL_CLOCK,
                solver=None, **kwargs):
        """Build a hot-standby follower of ``durable`` — a warm
        manager continuously advanced by WAL tail replay, promotable
        to leadership in sub-cycle time (RESILIENCE.md §7). Returns a
        ``resilience.replica.StandbyReplica``; drive ``poll()`` at
        your cycle cadence and call ``promote()`` on leader loss."""
        from kueue_tpu.resilience.replica import StandbyReplica
        return StandbyReplica(durable, cfg=cfg, clock=clock,
                              solver=solver, **kwargs)

    @classmethod
    def restore(cls, durable, cfg=None, clock: Clock = REAL_CLOCK,
                solver=None, **kwargs) -> "KueueManager":
        """Rebuild a control plane from a durable log's newest
        recoverable state (a crashed predecessor's checkpoint + WAL
        tail). See kueue_tpu/resilience/recovery.py for the recovery
        contract; the returned manager's ``last_recovery`` carries the
        report."""
        from kueue_tpu.resilience import recovery
        return recovery.restore(durable, cfg=cfg, clock=clock,
                                solver=solver, **kwargs)

    # -- operator surface ----------------------------------------------

    def serve_visibility(self, port: int = 0):
        """Start the visibility HTTP server with the operator debug
        surface wired: pending-workloads views plus /metrics and the
        /debug/{cycles,breaker,router,arena} endpoints (see
        kueue_tpu/obs/OBSERVABILITY.md). Returns the started server
        (``.port`` carries the bound port); call ``.stop()`` to shut
        it down."""
        from kueue_tpu.obs import DebugEndpoints
        from kueue_tpu.visibility import VisibilityAPI, VisibilityServer
        if self.visibility_server is not None:
            # Rebinding: the old server's socket and serve-forever
            # thread would otherwise leak with no reachable handle.
            self.visibility_server.stop()
        server = VisibilityServer(
            VisibilityAPI(self.queues), port=port,
            debug=DebugEndpoints(self.scheduler, self.metrics),
            query_plane=self.query_plane, metrics=self.metrics)
        server.start()
        self.visibility_server = server
        return server

    def dumper(self, out=None):
        """A SIGUSR2-ready state Dumper covering cache/queues plus the
        solver plane (breaker, router, arena, last cycle trace)."""
        from kueue_tpu.debugger import Dumper
        return Dumper(self.cache, self.queues, out=out,
                      scheduler=self.scheduler)

    # -- deterministic drivers (tests / perf harness) -------------------

    def run_until_idle(self, max_iterations: int = 10000) -> int:
        return self.runtime.run_until_idle(max_iterations=max_iterations)

    def schedule_once(self) -> None:
        """One admission cycle + controller settling."""
        self.runtime.run_until_idle()
        self.scheduler.schedule(timeout=0)
        self.runtime.run_until_idle()

    def schedule_until_settled(self, max_cycles: int = 100) -> int:
        """Run cycles until a cycle admits nothing (queues drained or
        blocked). Returns the number of cycles run."""
        cycles = 0
        for _ in range(max_cycles):
            self.runtime.run_until_idle()
            before = self.store._rv
            self.scheduler.schedule(timeout=0)
            self.runtime.run_until_idle()
            cycles += 1
            has_active = any(cqh.active and cqh.pending_active() > 0
                             for cqh in self.queues.cluster_queues.values())
            if self.store._rv == before and not has_active:
                break
        return cycles

    def advance(self, dt: float) -> None:
        self.runtime.advance(dt)
