"""Feature gates (reference: pkg/features/kube_features.go:37-125).

Defaults match the reference's v0.8 line.
"""

from __future__ import annotations

from contextlib import contextmanager

PARTIAL_ADMISSION = "PartialAdmission"
QUEUE_VISIBILITY = "QueueVisibility"
FLAVOR_FUNGIBILITY = "FlavorFungibility"
PROVISIONING_ACC = "ProvisioningACC"
VISIBILITY_ON_DEMAND = "VisibilityOnDemand"
PRIORITY_SORTING_WITHIN_COHORT = "PrioritySortingWithinCohort"
MULTIKUEUE = "MultiKueue"
LENDING_LIMIT = "LendingLimit"
MULTIKUEUE_BATCH_JOB_WITH_MANAGED_BY = "MultiKueueBatchJobWithManagedBy"
MULTIPLE_PREEMPTIONS = "MultiplePreemptions"
TPU_SOLVER = "TPUSolver"  # kueue_tpu extension: batched JAX admission solver

_DEFAULTS = {
    PARTIAL_ADMISSION: True,
    QUEUE_VISIBILITY: False,
    FLAVOR_FUNGIBILITY: True,
    PROVISIONING_ACC: True,
    VISIBILITY_ON_DEMAND: False,
    PRIORITY_SORTING_WITHIN_COHORT: True,
    MULTIKUEUE: False,
    LENDING_LIMIT: True,
    MULTIKUEUE_BATCH_JOB_WITH_MANAGED_BY: False,
    MULTIPLE_PREEMPTIONS: True,
    TPU_SOLVER: False,
}

_gates = dict(_DEFAULTS)


def enabled(name: str) -> bool:
    return _gates.get(name, False)


def set_feature_gates(gates: dict) -> None:
    for name, value in gates.items():
        if name not in _DEFAULTS:
            raise ValueError(f"unknown feature gate {name}")
        _gates[name] = bool(value)


def reset() -> None:
    _gates.clear()
    _gates.update(_DEFAULTS)


@contextmanager
def override(**gates):
    """Test helper: temporarily flip gates."""
    saved = dict(_gates)
    try:
        set_feature_gates({k: v for k, v in gates.items()})
        yield
    finally:
        _gates.clear()
        _gates.update(saved)
