"""kueue_tpu: a TPU-native job-level queueing and admission framework.

A ground-up reimplementation of the capabilities of Kueue
(sigs.k8s.io/kueue, reference at /root/reference): ClusterQueue /
LocalQueue / Workload / ResourceFlavor APIs, StrictFIFO and
BestEffortFIFO queueing, cohort borrowing/lending with hierarchical
quotas, priority- and DRF-fair-share preemption, flavor fungibility,
partial admission, admission checks (ProvisioningRequest-style gates and
MultiKueue multi-cluster dispatch), a job-integration framework,
webhook-equivalent validation, metrics, a visibility API and CLI.

The defining difference from the reference: the per-cycle admission
computation (flavor assignment + preemption over the ClusterQueue/Cohort
snapshot; reference hot loop at pkg/scheduler/scheduler.go:197-353) is
also available as one batched tensor program, jit-compiled with JAX and
solved on TPU (`kueue_tpu.solver`), with the sequential CPU path
(`kueue_tpu.scheduler`) as the conformance oracle and fallback.
"""

__version__ = "0.1.0"
