"""Bulk importer: adopt already-running pods into the queueing system.

Equivalent of the reference's cmd/importer (pod/check.go:32,
pod/import.go:43): `check` validates that every in-scope pod's
namespace maps to an existing LocalQueue on an existing ClusterQueue
that covers the pod's resources in the target flavor; `import_pods`
then creates a Workload per pod with admission already set
(QuotaReserved + Admitted), so the cache accounts for it without
touching the running pod.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api import corev1, kueue as api
from kueue_tpu.api.meta import ObjectMeta
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.core.resources import pod_effective_requests


@dataclass
class MappingRule:
    """namespace (+ optional pod label match) -> LocalQueue name
    (reference: simple label map or advanced mapping file)."""
    namespace: str
    queue_name: str
    match_labels: dict = field(default_factory=dict)

    def matches(self, pod: corev1.Pod) -> bool:
        if pod.metadata.namespace != self.namespace:
            return False
        return all(pod.metadata.labels.get(k) == v
                   for k, v in self.match_labels.items())


@dataclass
class ImportResult:
    checked: int = 0
    imported: int = 0
    skipped: list = field(default_factory=list)   # (pod key, reason)
    errors: list = field(default_factory=list)


class Importer:
    def __init__(self, manager, rules: list, flavor: str = "default"):
        self.manager = manager
        self.store = manager.store
        self.rules = rules
        self.flavor = flavor

    def _rule_for(self, pod: corev1.Pod) -> Optional[MappingRule]:
        for rule in self.rules:
            if rule.matches(pod):
                return rule
        return None

    def _in_scope(self) -> list:
        return [p for p in self.store.list("Pod")
                if p.status.phase == corev1.POD_RUNNING
                and self._rule_for(p) is not None]

    def check(self) -> ImportResult:
        """Validate the namespace->queue mapping before importing
        (reference: pod/check.go:32)."""
        result = ImportResult()
        for pod in self._in_scope():
            result.checked += 1
            rule = self._rule_for(pod)
            key = f"{pod.metadata.namespace}/{pod.metadata.name}"
            lq = self.store.try_get("LocalQueue", pod.metadata.namespace,
                                    rule.queue_name)
            if lq is None:
                result.errors.append(
                    f"{key}: LocalQueue {rule.queue_name} not found")
                continue
            cq = self.store.try_get("ClusterQueue", "", lq.spec.cluster_queue)
            if cq is None:
                result.errors.append(
                    f"{key}: ClusterQueue {lq.spec.cluster_queue} not found")
                continue
            covered = {res for rg in cq.spec.resource_groups
                       for res in rg.covered_resources
                       if any(fq.name == self.flavor for fq in rg.flavors)}
            missing = set(pod_effective_requests(pod.spec)) - covered
            if missing:
                result.errors.append(
                    f"{key}: resources {sorted(missing)} not covered by "
                    f"flavor {self.flavor} in ClusterQueue {cq.metadata.name}")
        return result

    def import_pods(self) -> ImportResult:
        """Create Workloads with retroactive admission
        (reference: pod/import.go:43)."""
        result = self.check()
        if result.errors:
            return result
        now = self.manager.clock.now()
        for pod in self._in_scope():
            rule = self._rule_for(pod)
            lq = self.store.get("LocalQueue", pod.metadata.namespace,
                                rule.queue_name)
            name = f"pod-{pod.metadata.name}"
            if self.store.try_get("Workload", pod.metadata.namespace, name):
                result.skipped.append(
                    (f"{pod.metadata.namespace}/{pod.metadata.name}",
                     "workload exists"))
                continue
            requests = pod_effective_requests(pod.spec)
            wl = api.Workload(metadata=ObjectMeta(
                name=name, namespace=pod.metadata.namespace,
                labels={api.MANAGED_LABEL: "true"},
                owner_references=[]))
            wl.spec.queue_name = rule.queue_name
            wl.spec.pod_sets = [api.PodSet(
                name=api.DEFAULT_PODSET_NAME, count=1,
                template=corev1.PodTemplateSpec(
                    labels=dict(pod.metadata.labels),
                    spec=pod.spec))]
            admission = api.Admission(
                cluster_queue=lq.spec.cluster_queue,
                pod_set_assignments=[api.PodSetAssignment(
                    name=api.DEFAULT_PODSET_NAME,
                    flavors={res: self.flavor for res in requests},
                    resource_usage=dict(requests), count=1)])
            wlpkg.set_quota_reservation(wl, admission, now)
            wlpkg.sync_admitted_condition(wl, now)
            self.store.create(wl)
            result.imported += 1
        return result
