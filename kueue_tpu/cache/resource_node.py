"""Hierarchical quota math.

Equivalent of the reference's pkg/cache/resource_node.go:27-179:
- subtree_quota: node quota + children's lendable capacity
- guaranteed_quota: subtree quota the node will not lend out
- available(): remaining capacity walking up the cohort chain, capped by
  borrowing limits
- add_usage/remove_usage: usage bubbling past guaranteed quota

Nodes implement the protocol: `.resource_node` (ResourceNode) and
`.parent_node()` (node or None).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.core.resources import FlavorResource


@dataclass
class ResourceQuota:
    nominal: int = 0
    borrowing_limit: Optional[int] = None
    lending_limit: Optional[int] = None


@dataclass
class ResourceNode:
    quotas: dict = field(default_factory=dict)        # FlavorResource -> ResourceQuota
    subtree_quota: dict = field(default_factory=dict)  # FlavorResource -> int
    usage: dict = field(default_factory=dict)          # FlavorResource -> int

    def clone(self) -> "ResourceNode":
        # quotas/subtree_quota are replaced wholesale on update; share them.
        return ResourceNode(quotas=self.quotas, subtree_quota=self.subtree_quota,
                            usage=dict(self.usage))

    def quota_for(self, fr: FlavorResource) -> ResourceQuota:
        return self.quotas.get(fr, _ZERO_QUOTA)

    def guaranteed_quota(self, fr: FlavorResource) -> int:
        q = self.quotas.get(fr)
        if q is not None and q.lending_limit is not None:
            return max(0, self.subtree_quota.get(fr, 0) - q.lending_limit)
        return 0

    def calculate_lendable(self) -> dict:
        """Aggregate subtree quota per resource name
        (reference: calculateLendable)."""
        lendable: dict = {}
        for fr, q in self.subtree_quota.items():
            lendable[fr.resource] = lendable.get(fr.resource, 0) + q
        return lendable


_ZERO_QUOTA = ResourceQuota()


def available(node, fr: FlavorResource, enforce_borrow_limit: bool = True) -> int:
    """Remaining capacity for `node`, walking the cohort chain; may be
    negative under overadmission (reference: resource_node.go:89-104)."""
    rn: ResourceNode = node.resource_node
    parent = node.parent_node()
    if parent is None:
        return rn.subtree_quota.get(fr, 0) - rn.usage.get(fr, 0)
    guaranteed = rn.guaranteed_quota(fr)
    local_available = max(0, guaranteed - rn.usage.get(fr, 0))
    parent_available = available(parent, fr, enforce_borrow_limit)
    q = rn.quotas.get(fr)
    if enforce_borrow_limit and q is not None and q.borrowing_limit is not None:
        stored_in_parent = rn.subtree_quota.get(fr, 0) - guaranteed
        used_in_parent = max(0, rn.usage.get(fr, 0) - guaranteed)
        with_max_from_parent = stored_in_parent - used_in_parent + q.borrowing_limit
        parent_available = min(with_max_from_parent, parent_available)
    return local_available + parent_available


def potential_available(node, fr: FlavorResource) -> int:
    """Max capacity available assuming zero usage, respecting borrowing
    limits (reference: resource_node.go:108-119)."""
    rn: ResourceNode = node.resource_node
    parent = node.parent_node()
    if parent is None:
        return rn.subtree_quota.get(fr, 0)
    avail = rn.guaranteed_quota(fr) + potential_available(parent, fr)
    q = rn.quotas.get(fr)
    if q is not None and q.borrowing_limit is not None:
        avail = min(rn.subtree_quota.get(fr, 0) + q.borrowing_limit, avail)
    return avail


def add_usage(node, fr: FlavorResource, val: int) -> None:
    rn: ResourceNode = node.resource_node
    local_available = max(0, rn.guaranteed_quota(fr) - rn.usage.get(fr, 0))
    rn.usage[fr] = rn.usage.get(fr, 0) + val
    parent = node.parent_node()
    if parent is not None and val > local_available:
        add_usage(parent, fr, val - local_available)


def remove_usage(node, fr: FlavorResource, val: int) -> None:
    rn: ResourceNode = node.resource_node
    stored_in_parent = rn.usage.get(fr, 0) - rn.guaranteed_quota(fr)
    rn.usage[fr] = rn.usage.get(fr, 0) - val
    parent = node.parent_node()
    if stored_in_parent <= 0 or parent is None:
        return
    remove_usage(parent, fr, min(val, stored_in_parent))
