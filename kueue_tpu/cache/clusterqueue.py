"""Cache-side ClusterQueue and Cohort internals.

Equivalent of the reference's pkg/cache/clusterqueue.go + cohort.go:
spec ingestion into ResourceNode quotas, usage accounting for
admitted/assumed workloads, activity status, allocatable-resource
generation, per-LocalQueue usage.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu import features
from kueue_tpu.api import kueue as api
from kueue_tpu.cache import resource_node as rnode
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.core.resources import FlavorResource

# ClusterQueue status (reference: pkg/metrics ClusterQueueStatus)
PENDING = "pending"
ACTIVE = "active"
TERMINATING = "terminating"


@dataclass
class ResourceGroupInfo:
    covered_resources: set = field(default_factory=set)
    flavors: list = field(default_factory=list)  # ordered flavor names
    label_keys: set = field(default_factory=set)  # node-label keys across flavors

    def clone(self) -> "ResourceGroupInfo":
        return ResourceGroupInfo(covered_resources=set(self.covered_resources),
                                 flavors=list(self.flavors),
                                 label_keys=set(self.label_keys))


def build_quotas(spec_groups: list) -> dict:
    """Flatten API resource groups into FlavorResource -> ResourceQuota,
    honoring the LendingLimit feature gate."""
    quotas: dict = {}
    lending_enabled = features.enabled(features.LENDING_LIMIT)
    for rg in spec_groups:
        for fq in rg.flavors:
            for rq in fq.resources:
                quotas[FlavorResource(fq.name, rq.name)] = rnode.ResourceQuota(
                    nominal=rq.nominal_quota,
                    borrowing_limit=rq.borrowing_limit,
                    lending_limit=rq.lending_limit if lending_enabled else None,
                )
    return quotas


class CohortCache:
    """Cache-side cohort node (reference: pkg/cache/cohort.go). Supports
    arbitrary-depth trees via the v1alpha1 Cohort parent edge
    (cohort_types.go:26-100); quota math walks the chain
    (resource_node.go:89-146)."""

    def __init__(self, name: str):
        self.name = name
        self.resource_node = rnode.ResourceNode()
        self.manager = None  # set by Cache

    def _node(self):
        return self.manager.cohorts.get(self.name) if self.manager else None

    def parent_node(self) -> Optional["CohortCache"]:
        node = self._node()
        if node is None or node.parent is None:
            return None
        return node.parent.payload

    def root(self) -> "CohortCache":
        c = self
        while (p := c.parent_node()) is not None:
            c = p
        return c

    def child_cqs(self) -> list:
        node = self._node()
        return list(node.child_cqs.values()) if node else []

    def child_cohorts(self) -> list:
        node = self._node()
        return [n.payload for n in node.child_cohorts.values()] if node else []


class ClusterQueueCache:
    """Cache-side ClusterQueue (reference: pkg/cache/clusterqueue.go)."""

    def __init__(self, cq: api.ClusterQueue):
        self.name = cq.metadata.name
        self.resource_node = rnode.ResourceNode()
        self.workloads: dict = {}  # key -> Info
        self.workloads_not_ready: set = set()
        self.admitted_usage: dict = {}  # FlavorResource -> int (Admitted=True only)
        self.admitted_workloads_count = 0
        # monotonic: bumped on every usage-moving mutation (cheap status
        # change-detection for the CQ/LQ reconcilers at scale)
        self.usage_version = 0
        self.allocatable_resource_generation = 0
        self.cohort: Optional[CohortCache] = None
        self.missing_flavors: list = []
        self.missing_checks: list = []
        self.inactive_checks: list = []
        self.multiple_single_instance_controller_checks = False
        self.local_queues: dict = {}  # "ns/name" -> LocalQueueUsage
        self.update(cq)

    def update(self, cq: api.ClusterQueue) -> None:
        spec = cq.spec
        self.spec = spec
        self.cohort_name = spec.cohort
        self.queueing_strategy = spec.queueing_strategy
        self.namespace_selector = spec.namespace_selector
        self.preemption = spec.preemption
        self.flavor_fungibility = spec.flavor_fungibility
        self.fair_weight = spec.fair_sharing.weight if spec.fair_sharing else 1000
        self.stop_policy = spec.stop_policy
        self.admission_checks = admission_checks_map(spec)
        self.resource_groups = []
        for rg in spec.resource_groups:
            info = ResourceGroupInfo(covered_resources=set(rg.covered_resources),
                                     flavors=[fq.name for fq in rg.flavors])
            self.resource_groups.append(info)
        new_quotas = build_quotas(spec.resource_groups)
        if new_quotas != self.resource_node.quotas:
            self.allocatable_resource_generation += 1
        self.resource_node.quotas = new_quotas
        update_cluster_queue_resource_node(self)

    # --- hierarchicalResourceNode protocol ---

    def parent_node(self) -> Optional[CohortCache]:
        return self.cohort

    # --- flavor/check availability (activity gating) ---

    def update_with_flavors(self, flavors: dict) -> None:
        self.missing_flavors = [
            f for rg in self.resource_groups for f in rg.flavors if f not in flavors]
        for rg in self.resource_groups:
            rg.label_keys = set()
            for f in rg.flavors:
                rf = flavors.get(f)
                if rf is not None:
                    rg.label_keys.update(rf.spec.node_labels.keys())

    def update_with_checks(self, checks: dict) -> None:
        """checks: name -> AdmissionCheck cache entry with .active flag."""
        self.missing_checks = []
        self.inactive_checks = []
        for name in self.admission_checks:
            entry = checks.get(name)
            if entry is None:
                self.missing_checks.append(name)
            elif not entry.active:
                self.inactive_checks.append(name)

    @property
    def active(self) -> bool:
        return (self.status != TERMINATING
                and self.stop_policy == api.STOP_POLICY_NONE
                and not self.missing_flavors
                and not self.missing_checks
                and not self.inactive_checks)

    status = ACTIVE  # overridden to TERMINATING by Cache on delete

    def inactive_reason(self) -> str:
        if self.stop_policy != api.STOP_POLICY_NONE:
            return "Stopped"
        if self.missing_flavors:
            return f"FlavorNotFound: {', '.join(self.missing_flavors)}"
        if self.missing_checks:
            return f"CheckNotFoundOrInactive: {', '.join(self.missing_checks)}"
        if self.inactive_checks:
            return f"CheckNotFoundOrInactive: {', '.join(self.inactive_checks)}"
        return ""

    # --- usage accounting ---

    def add_workload(self, info: wlpkg.Info) -> None:
        self.workloads[info.key] = info
        self._update_usage(info, +1)

    def delete_workload(self, info: wlpkg.Info) -> None:
        if info.key not in self.workloads:
            return
        del self.workloads[info.key]
        self._update_usage(info, -1)
        # Freed capacity invalidates flavor-iteration resume state
        # (reference: cache.go deleteWorkload bumps the generation).
        self.allocatable_resource_generation += 1

    def _update_usage(self, info: wlpkg.Info, sign: int) -> None:
        self.usage_version += 1
        usage = info.flavor_resource_usage()
        for fr, q in usage.items():
            if sign > 0:
                rnode.add_usage(self, fr, q)
            else:
                rnode.remove_usage(self, fr, q)
        admitted = wlpkg.is_admitted(info.obj)
        if admitted:
            for fr, q in usage.items():
                self.admitted_usage[fr] = self.admitted_usage.get(fr, 0) + sign * q
            self.admitted_workloads_count += sign
        lq_key = wlpkg.queue_key(info.obj)
        lq = self.local_queues.get(lq_key)
        if lq is not None:
            lq.version += 1
            for fr, q in usage.items():
                lq.usage[fr] = lq.usage.get(fr, 0) + sign * q
                if admitted:
                    lq.admitted_usage[fr] = lq.admitted_usage.get(fr, 0) + sign * q
            lq.reserving_workloads += sign
            if admitted:
                lq.admitted_workloads += sign

    def reserving_workloads_count(self) -> int:
        return len(self.workloads)


@dataclass
class LocalQueueUsage:
    usage: dict = field(default_factory=dict)
    admitted_usage: dict = field(default_factory=dict)
    reserving_workloads: int = 0
    admitted_workloads: int = 0
    version: int = 0  # bumped on every mutation (change detection)


def admission_checks_map(spec: api.ClusterQueueSpec) -> dict:
    """Aggregate admissionChecks + admissionChecksStrategy into
    name -> set of flavors (empty set = all flavors)
    (reference: clusterqueue_snapshot.go:41-44)."""
    out: dict = {}
    for name in spec.admission_checks:
        out[name] = set()
    for rule in spec.admission_checks_strategy:
        out[rule.name] = set(rule.on_flavors)
    return out


def update_cluster_queue_resource_node(cq: ClusterQueueCache) -> None:
    """SubtreeQuota(CQ) = nominal quotas
    (reference: resource_node.go:156-161)."""
    cq.resource_node.subtree_quota = {
        fr: q.nominal for fr, q in cq.resource_node.quotas.items()}


def update_cohort_resource_node(cohort: CohortCache) -> None:
    """Recompute subtree quotas/usage for the whole tree containing
    `cohort` (reference: resource_node.go:163-179, extended recursively
    over child cohorts for hierarchical v1alpha1 cohorts)."""
    _update_cohort_subtree(cohort.root())


def _update_cohort_subtree(cohort: CohortCache) -> None:
    """Post-order: children's subtree quotas feed the parent; a child's
    lendable capacity is its subtree quota minus its guaranteed quota, and
    only over-guaranteed usage bubbles up."""
    rn = cohort.resource_node
    rn.subtree_quota = {fr: q.nominal for fr, q in rn.quotas.items()}
    rn.usage = {}

    def _fold(child_rn: rnode.ResourceNode) -> None:
        for fr, child_quota in child_rn.subtree_quota.items():
            rn.subtree_quota[fr] = (rn.subtree_quota.get(fr, 0)
                                    + child_quota - child_rn.guaranteed_quota(fr))
        for fr, child_usage in child_rn.usage.items():
            over = max(0, child_usage - child_rn.guaranteed_quota(fr))
            if over:
                rn.usage[fr] = rn.usage.get(fr, 0) + over

    for child in cohort.child_cohorts():
        _update_cohort_subtree(child)
        _fold(child.resource_node)
    for child in cohort.child_cqs():
        update_cluster_queue_resource_node(child)
        _fold(child.resource_node)
