"""State layer: authoritative in-memory mirror of admitted usage plus
lock-free scheduling snapshots (reference: pkg/cache)."""

from kueue_tpu.cache.cache import Cache  # noqa: F401
from kueue_tpu.cache.snapshot import ClusterQueueSnapshot, CohortSnapshot, Snapshot  # noqa: F401
