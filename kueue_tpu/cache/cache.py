"""The Cache: authoritative in-memory mirror of admitted usage.

Equivalent of the reference's pkg/cache/cache.go:89-595: tracks
ClusterQueues/cohorts/flavors/checks/local-queues plus assumed workloads
(optimistic admission before the API write), and produces deep-copied
Snapshots for lock-free scheduling cycles.
"""

from __future__ import annotations

import threading
import time as _time
from dataclasses import dataclass
from typing import Optional

from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import is_condition_true
from kueue_tpu.cache.clusterqueue import (
    ACTIVE,
    TERMINATING,
    ClusterQueueCache,
    CohortCache,
    LocalQueueUsage,
    build_quotas,
    update_cohort_resource_node,
)
from kueue_tpu.cache.snapshot import ClusterQueueSnapshot, CohortSnapshot, Snapshot
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.core.hierarchy import Manager as HierarchyManager


# Journal-consumer names (see the usage-journal block in Cache.__init__).
SNAPSHOT_CONSUMER = "snapshot"
SOLVER_CONSUMER = "solver"


@dataclass
class AdmissionCheckEntry:
    controller_name: str = ""
    active: bool = False
    single_instance_in_cluster_queue: bool = False


class Cache:
    def __init__(self, pods_ready_tracking: bool = False,
                 excluded_resource_prefixes: Optional[list] = None,
                 incremental_snapshots: bool = True):
        self._lock = threading.RLock()
        self._pods_ready_cond = threading.Condition(self._lock)
        self.hm: HierarchyManager = HierarchyManager(cohort_factory=self._new_cohort)
        self.resource_flavors: dict = {}  # name -> ResourceFlavor
        self.admission_checks: dict = {}  # name -> AdmissionCheckEntry
        self.assumed_workloads: dict = {}  # wl key -> cq name
        self.pods_ready_tracking = pods_ready_tracking
        self.excluded_resource_prefixes = excluded_resource_prefixes or []
        # Bumped on cohort-object changes (re-parent, cohort quotas):
        # structural edits invisible to per-CQ generations.
        self.cohort_epoch = 0
        # Monotonic capacity version: bumped on ANY capacity-affecting
        # change (CQ/cohort/flavor edits, workload removal). Snapshot
        # cohorts carry it as their allocatable generation so stored
        # flavor-resume state is invalidated by a simple `>` check — a
        # per-tree sum would shrink when a tree loses members and stall
        # invalidation forever.
        self._capacity_version = 0
        # Bumped on ResourceFlavor spec changes (taints / node labels):
        # they alter flavor eligibility without touching any CQ quota
        # generation, so topology-derived caches key on this too.
        self.flavor_spec_epoch = 0
        # Bumped on any change to the encoded solver TOPOLOGY (CQ set /
        # quotas / cohort tree / flavors / activity) — deliberately NOT on
        # workload add/remove, which only moves usage. The solver keys its
        # topology tensors on this instead of per-CQ allocatable
        # generations (those bump on every workload deletion purely to
        # invalidate flavor-resume state).
        self.topology_epoch = 0
        # Usage journal: every usage-moving workload mutation appends
        # (seq, kind, cq, key, usage, aux) so consumers can reconcile
        # derived state with tiny deltas instead of a full rebuild per
        # cycle. Two consumers share it through per-consumer cursors
        # (entries are pruned once EVERY cursor has passed them): the
        # solver's device-resident state ("solver", registered by
        # enable_usage_journal) and the incremental snapshot maintainer
        # ("snapshot", registered below). kinds: 'add'/'del' move usage;
        # 'cq'/'ready' are snapshot-replay-only records (non-structural
        # ClusterQueue updates, pods-ready flips) with usage=None.
        self.usage_journal_enabled = False
        self._journal: list = []
        self._journal_seq = 0
        self._journal_cap = 200_000
        # MultiKueue remote-cluster capacity source (ISSUE 13): a
        # callable returning (columns tuple, mk check-name frozenset) —
        # the manager wires it to MultiKueueController.capacity_columns
        # when remote clusters exist. Every snapshot handout (full AND
        # light) is stamped with the current columns, so the solver can
        # score cross-cluster placement inside the fused solve and a
        # lost cluster's columns mask to zero on the next snapshot.
        self.remote_capacity_source = None
        self._remote_columns_cache = None  # last FULL snapshot's stamp
        self._journal_cursors: dict = {}  # consumer -> consumed-up-to seq
        self._journal_overflowed: set = set()  # consumers that lost entries
        self._journal_aux_stripped = 0  # aux dropped for seqs <= this
        # Incremental snapshot maintenance (see incremental.py and
        # SNAPSHOTS.md): keep one persistent full Snapshot advanced by
        # journal replay instead of deep-cloning 2k CQ trees per cycle.
        self._maintainer = None
        if incremental_snapshots:
            from kueue_tpu.cache.incremental import SnapshotMaintainer
            self._maintainer = SnapshotMaintainer(self)
            self._journal_cursors[SNAPSHOT_CONSUMER] = 0
            self.usage_journal_enabled = True
        # Structural-dirty bookkeeping for the maintainer's per-CQ
        # partial rebuild (incremental.py): a single-CQ structural edit
        # (quota/resource-group change on ONE ClusterQueue, same cohort
        # edge) records just that CQ's name, so the next snapshot sync
        # rebuilds only that CQ's subtree instead of every master.
        # Anything wider (CQ add/delete, cohort or flavor or check
        # changes) sets the all-flag and keeps the full-rebuild path.
        # Only maintained when a maintainer exists (the set would
        # otherwise grow without a consumer).
        self._structural_dirty_cqs: set = set()
        self._structural_dirty_all = False
        # Snapshot-build accounting (perf/bench visibility): which path
        # served each full snapshot() and how long the build took.
        # "partial" = per-CQ structural rebuild + journal replay.
        self.snapshot_stats = {"full": 0, "incremental": 0, "light": 0,
                               "partial": 0}
        self.snapshot_build_s: list = []
        # Handout accounting (ISSUE 10 satellite): every FULL snapshot
        # handed out through snapshot() counts as taken, every
        # release_snapshot of one counts as released (idempotent — a
        # double release counts once). live_handouts is the leak
        # detector the crash-restart suite asserts returns to zero
        # after a shutdown that dropped an in-flight speculative cycle.
        self.handouts_taken = 0
        self.handouts_released = 0

    def _new_cohort(self, name: str) -> CohortCache:
        cohort = CohortCache(name)
        cohort.manager = self.hm
        return cohort

    # --- usage journal (device-resident solver state reconciliation) ---

    def enable_usage_journal(self) -> None:
        with self._lock:
            self.usage_journal_enabled = True
            self._journal_cursors.setdefault(SOLVER_CONSUMER,
                                             self._journal_seq)

    def _journal_usage(self, kind: str, cq_name: str, key: str,
                       usage: Optional[dict], aux=None) -> None:
        """kind: 'add' | 'del' (usage-moving, consumed by solver AND
        snapshot maintainer) | 'cq' | 'ready' (snapshot-replay-only,
        usage=None). aux: (Info, not_ready) for 'add' entries so snapshot
        replay can reconstruct workload maps. Caller holds the lock."""
        if not self.usage_journal_enabled:
            return
        if self._maintainer is None:
            aux = None  # only snapshot replay reads it
        self._journal_seq += 1
        self._journal.append((self._journal_seq, kind, cq_name, key,
                              usage, aux))
        if len(self._journal) > self._journal_cap:
            # Bound memory when a consumer stops draining: only the
            # laggards lose their backlog (and see the overflow flag on
            # their next drain, falling back to a full rebuild); an
            # actively-draining consumer keeps its pending entries.
            for name, cur in self._journal_cursors.items():
                if self._journal_seq - cur > self._journal_cap:
                    self._journal_overflowed.add(name)
                    self._journal_cursors[name] = self._journal_seq
            self._prune_journal_locked()

    def _prune_journal_locked(self) -> None:
        """Drop entries every registered consumer has consumed. Seqs are
        contiguous (+1 per append, pruned only from the front), so list
        index == seq - first_seq."""
        if not self._journal:
            return
        if not self._journal_cursors:
            self._journal.clear()
            return
        low = min(self._journal_cursors.values())
        first = self._journal[0][0]
        if low >= first:
            del self._journal[:low - first + 1]
        # Entries the snapshot maintainer has consumed can never be read
        # for replay again — drop their aux payload so a lagging solver
        # consumer doesn't pin deleted workloads' Info objects (full pod
        # sets/conditions) for up to a journal-cap of entries. Each
        # entry is stripped at most once (amortized O(1) per append).
        if not self._journal:
            return
        snap_cur = self._journal_cursors.get(SNAPSHOT_CONSUMER)
        if snap_cur is None:
            return
        first = self._journal[0][0]
        upto = min(snap_cur, self._journal[-1][0])
        for seq in range(max(self._journal_aux_stripped + 1, first),
                         upto + 1):
            entry = self._journal[seq - first]
            if entry[5] is not None:
                self._journal[seq - first] = entry[:5] + (None,)
        self._journal_aux_stripped = max(self._journal_aux_stripped, upto)

    def _mark_structural(self, cq_name: Optional[str] = None) -> None:
        """Record the scope of a structural (epoch-bumping) change for
        the snapshot maintainer: a CQ name when the change is contained
        to that ClusterQueue's subtree, None for anything wider. Caller
        holds the lock and has already bumped the epoch."""
        if self._maintainer is None:
            return
        if cq_name is None:
            self._structural_dirty_all = True
        else:
            self._structural_dirty_cqs.add(cq_name)

    def take_structural_dirty(self) -> tuple:
        """Consume the structural-dirty scope accumulated since the last
        call: (dirty CQ names, all-flag). Caller holds the lock (the
        maintainer's _sync runs under Cache.snapshot's lock)."""
        dirty, dirty_all = (self._structural_dirty_cqs,
                            self._structural_dirty_all)
        self._structural_dirty_cqs = set()
        self._structural_dirty_all = False
        return dirty, dirty_all

    def generation_token(self) -> tuple:
        """The structural generation stamp for speculative solves
        (scheduler/stages.SpeculationToken): three epoch ints, read
        under the lock. Workload churn does NOT move any of these —
        usage movement reconciles through the journal; only structural
        edits (CQ/cohort/flavor-spec changes) invalidate an in-flight
        speculative result."""
        with self._lock:
            return (self.topology_epoch, self.cohort_epoch,
                    self.flavor_spec_epoch)

    def generation_lag(self, token: tuple) -> int:
        """How many structural generations a consumer's stamped token
        lags the live cache: the sum of per-epoch deltas (each epoch is
        monotonic, so the sum is 0 iff the token is current). The query
        plane (obs/queryplane.py) and tools/visibility_probe.py price
        read-side staleness with this."""
        with self._lock:
            cur = (self.topology_epoch, self.cohort_epoch,
                   self.flavor_spec_epoch)
        return sum(abs(c - t) for c, t in zip(cur, tuple(token)))

    def snapshot_current(self, snap: Snapshot) -> bool:
        """Cheap generation-token check: True iff no structural epoch
        moved since ``snap`` was produced (see
        incremental.generations_current)."""
        from kueue_tpu.cache.incremental import generations_current
        with self._lock:
            return generations_current(snap, self)

    def journal_overflowed(self, consumer: str = SOLVER_CONSUMER) -> bool:
        """Peek (without clearing) whether ``consumer`` lost journal
        entries since its last drain — a speculative result computed on
        residency whose corrections were dropped is unsound and must
        abort (the flag itself still resets at the next drain, which
        falls back to a full rebuild)."""
        with self._lock:
            return consumer in self._journal_overflowed

    def drain_usage_journal(self, upto_seq: int,
                            consumer: str = "solver") -> tuple:
        """Return (entries with cursor < seq <= upto_seq, overflowed) for
        `consumer` and advance its cursor; the overflow flag resets once
        observed. Entries stay visible to the other registered consumers
        until everyone's cursor has passed them — draining for one
        consumer never loses entries for another."""
        with self._lock:
            cursor = self._journal_cursors.get(consumer, 0)
            upto = min(upto_seq, self._journal_seq)
            entries: list = []
            if self._journal and upto >= self._journal[0][0]:
                first = self._journal[0][0]
                lo = max(0, cursor - first + 1)
                hi = upto - first + 1
                if hi > lo:
                    entries = self._journal[lo:hi]
            self._journal_cursors[consumer] = max(cursor, upto)
            overflow = consumer in self._journal_overflowed
            self._journal_overflowed.discard(consumer)
            self._prune_journal_locked()
            return entries, overflow

    # --- ClusterQueues ---

    def add_cluster_queue(self, cq: api.ClusterQueue) -> ClusterQueueCache:
        with self._lock:
            self._capacity_version += 1
            self.topology_epoch += 1
            self._mark_structural()  # may materialize a new cohort node
            cqc = ClusterQueueCache(cq)
            self.hm.add_cluster_queue(cqc.name, cqc)
            self.hm.update_cluster_queue_edge(cqc.name, cq.spec.cohort)
            self._wire_cohort(cqc)
            cqc.update_with_flavors(self.resource_flavors)
            cqc.update_with_checks(self.admission_checks)
            self._refresh_cohort(cqc)
            return cqc

    @staticmethod
    def _topo_signature(cqc) -> tuple:
        """The CQ fields the solver topology encodes: changes here (and
        only here) invalidate the encoded tensors. Reconcilers re-push
        ClusterQueues on every STATUS write; bumping the epoch on those
        would rebuild the topology (and drop device-resident solver
        state) every admission cycle."""
        return (cqc.cohort_name,
                tuple((tuple(sorted(rg.covered_resources)), tuple(rg.flavors))
                      for rg in cqc.resource_groups),
                tuple(sorted(cqc.resource_node.quotas.items())),
                cqc.fair_weight,
                cqc.flavor_fungibility.when_can_borrow,
                cqc.active,
                tuple(sorted((k, tuple(sorted(v)))
                             for k, v in cqc.admission_checks.items())))

    def update_cluster_queue(self, cq: api.ClusterQueue) -> None:
        with self._lock:
            self._capacity_version += 1
            cqc = self.hm.cluster_queues.get(cq.metadata.name)
            if cqc is None:
                return
            old_sig = self._topo_signature(cqc)
            old_cohort = cqc.cohort
            cqc.update(cq)
            self.hm.update_cluster_queue_edge(cqc.name, cq.spec.cohort)
            self._wire_cohort(cqc)
            cqc.update_with_flavors(self.resource_flavors)
            cqc.update_with_checks(self.admission_checks)
            if old_cohort is not None and old_cohort is not cqc.cohort:
                update_cohort_resource_node(old_cohort)
            self._refresh_cohort(cqc)
            if self._topo_signature(cqc) != old_sig:
                self.topology_epoch += 1
                # Same cohort payload => the cohort graph's SHAPE is
                # unchanged (quota edits only move this CQ's node and
                # the tree's aggregates): the maintainer may rebuild
                # just this CQ's subtree. An edge move (or to/from a
                # fresh cohort) invalidates the master cohort graph.
                self._mark_structural(
                    cqc.name if old_cohort is cqc.cohort else None)
            else:
                # Non-structural update (namespace selector, preemption
                # policy, fungibility knobs): invisible to every epoch,
                # so snapshot replay must refresh this CQ explicitly.
                self._journal_usage("cq", cqc.name, "", None)

    def terminate_cluster_queue(self, name: str) -> None:
        """Stop admissions while keeping the usage accounting alive until
        the last reserving workload finishes (reference:
        cache.TerminateClusterQueue, cache.go:~300)."""
        with self._lock:
            cqc = self.hm.cluster_queues.get(name)
            if cqc is not None:
                cqc.status = TERMINATING
                self.topology_epoch += 1
                self._mark_structural(name)  # an activity flip, CQ-local

    def delete_cluster_queue(self, name: str) -> None:
        with self._lock:
            self._capacity_version += 1
            self.topology_epoch += 1
            self._mark_structural()  # cohort membership/GC changes
            cqc = self.hm.cluster_queues.get(name)
            if cqc is None:
                return
            cqc.status = TERMINATING
            old_cohort = cqc.cohort
            self.hm.delete_cluster_queue(name)
            if old_cohort is not None:
                update_cohort_resource_node(old_cohort)

    def cluster_queue(self, name: str) -> Optional[ClusterQueueCache]:
        return self.hm.cluster_queues.get(name)

    def cluster_queue_active(self, name: str) -> bool:
        cqc = self.hm.cluster_queues.get(name)
        return cqc is not None and cqc.active

    def _wire_cohort(self, cqc: ClusterQueueCache) -> None:
        node = self.hm.cohort_of(cqc.name)
        cqc.cohort = node.payload if node else None

    def _refresh_cohort(self, cqc: ClusterQueueCache) -> None:
        if cqc.cohort is not None:
            update_cohort_resource_node(cqc.cohort)

    # --- Cohorts (explicit v1alpha1 objects with quotas) ---

    def add_or_update_cohort(self, cohort: api.Cohort) -> None:
        """Raises ValueError on a cycle-inducing parent edge; the quota
        update still lands and both trees stay consistent."""
        with self._lock:
            existing = self.hm.cohorts.get(cohort.metadata.name)
            if existing is not None:
                # No-op re-push guard (reconcilers re-deliver on status
                # writes): same parent + quotas -> keep the epochs, or
                # every resync would drop the solver topology + device
                # residency.
                parent = (existing.parent.name
                          if existing.parent is not None else "")
                if parent == (cohort.spec.parent or "") \
                        and existing.payload.resource_node.quotas \
                        == build_quotas(cohort.spec.resource_groups):
                    return
            self.cohort_epoch += 1
            self._capacity_version += 1
            self.topology_epoch += 1
            self._mark_structural()
            node = self.hm.add_cohort(cohort.metadata.name)
            node.payload.resource_node.quotas = build_quotas(cohort.spec.resource_groups)
            old_root = node.payload.root()
            try:
                self.hm.update_cohort_edge(cohort.metadata.name,
                                           cohort.spec.parent or "")
            finally:
                # A re-parent detaches this subtree: refresh the old tree
                # too (and always re-aggregate the quota edit above, even
                # when the edge update raises on a cycle).
                if old_root.name != node.payload.root().name:
                    update_cohort_resource_node(old_root)
                update_cohort_resource_node(node.payload)

    def delete_cohort(self, name: str) -> None:
        with self._lock:
            self.cohort_epoch += 1
            self._capacity_version += 1
            self.topology_epoch += 1
            self._mark_structural()
            node = self.hm.cohorts.get(name)
            if node is None:
                return
            payload = node.payload
            payload.resource_node.quotas = {}
            old_root = payload.root()
            self.hm.delete_cohort(name)
            if old_root is not payload:
                update_cohort_resource_node(old_root)
            if name in self.hm.cohorts:  # still referenced by CQs/children
                update_cohort_resource_node(payload)

    # --- flavors & checks ---

    def add_or_update_resource_flavor(self, rf: api.ResourceFlavor) -> set:
        with self._lock:
            old = self.resource_flavors.get(rf.metadata.name)
            self.resource_flavors[rf.metadata.name] = rf
            if old is not None and old.spec == rf.spec:
                # No-op re-push (reconcilers re-deliver on status/metadata
                # writes): eligibility didn't change, keep the epochs —
                # bumping them drops solver topology + device residency.
                return set()
            return self._refresh_flavor_dependents()

    def delete_resource_flavor(self, name: str) -> set:
        with self._lock:
            self.resource_flavors.pop(name, None)
            return self._refresh_flavor_dependents()

    def _refresh_flavor_dependents(self) -> set:
        self._capacity_version += 1
        self.flavor_spec_epoch += 1
        self.topology_epoch += 1
        self._mark_structural()
        affected = set()
        for cqc in self.hm.cluster_queues.values():
            was = cqc.active
            cqc.update_with_flavors(self.resource_flavors)
            if cqc.active != was:
                affected.add(cqc.name)
        return affected

    def add_or_update_admission_check(self, ac: api.AdmissionCheck) -> set:
        with self._lock:
            entry = AdmissionCheckEntry(
                controller_name=ac.spec.controller_name,
                active=is_condition_true(ac.status.conditions, api.ADMISSION_CHECK_ACTIVE))
            if self.admission_checks.get(ac.metadata.name) == entry:
                # No-op re-push: CQ activity can't change, keep the epoch.
                return set()
            self.admission_checks[ac.metadata.name] = entry
            return self._refresh_check_dependents()

    def delete_admission_check(self, name: str) -> set:
        with self._lock:
            self.admission_checks.pop(name, None)
            return self._refresh_check_dependents()

    def _refresh_check_dependents(self) -> set:
        self.topology_epoch += 1
        self._mark_structural()
        affected = set()
        for cqc in self.hm.cluster_queues.values():
            was = cqc.active
            cqc.update_with_checks(self.admission_checks)
            if cqc.active != was:
                affected.add(cqc.name)
        return affected

    # --- local queues ---

    def add_local_queue(self, lq: api.LocalQueue) -> None:
        with self._lock:
            cqc = self.hm.cluster_queues.get(lq.spec.cluster_queue)
            if cqc is None:
                return
            key = f"{lq.metadata.namespace}/{lq.metadata.name}"
            usage = LocalQueueUsage()
            # Rebuild usage from workloads already in the CQ (reference:
            # clusterqueue.go:440-448).
            for info in cqc.workloads.values():
                if wlpkg.queue_key(info.obj) != key:
                    continue
                for fr, q in info.flavor_resource_usage().items():
                    usage.usage[fr] = usage.usage.get(fr, 0) + q
                    if wlpkg.is_admitted(info.obj):
                        usage.admitted_usage[fr] = usage.admitted_usage.get(fr, 0) + q
                usage.reserving_workloads += 1
                if wlpkg.is_admitted(info.obj):
                    usage.admitted_workloads += 1
            cqc.local_queues[key] = usage

    def delete_local_queue(self, lq: api.LocalQueue) -> None:
        with self._lock:
            cqc = self.hm.cluster_queues.get(lq.spec.cluster_queue)
            if cqc is not None:
                cqc.local_queues.pop(f"{lq.metadata.namespace}/{lq.metadata.name}", None)

    def local_queue_usage(self, lq: api.LocalQueue) -> Optional[LocalQueueUsage]:
        cqc = self.hm.cluster_queues.get(lq.spec.cluster_queue)
        if cqc is None:
            return None
        return cqc.local_queues.get(f"{lq.metadata.namespace}/{lq.metadata.name}")

    # --- workloads (reference: cache.go:390-595) ---

    def add_or_update_workload(self, wl: api.Workload) -> bool:
        with self._lock:
            self._delete_workload_locked(wl)
            if wl.status.admission is None:
                return False
            cqc = self.hm.cluster_queues.get(wl.status.admission.cluster_queue)
            if cqc is None:
                return False
            info = self._new_info(wl)
            cqc.add_workload(info)
            not_ready = (self.pods_ready_tracking and not is_condition_true(
                wl.status.conditions, api.WORKLOAD_PODS_READY))
            self._journal_usage("add", cqc.name, info.key,
                                info.flavor_resource_usage(),
                                (info, not_ready))
            if not_ready:
                cqc.workloads_not_ready.add(info.key)
            self._pods_ready_cond.notify_all()
            return True

    def delete_workload(self, wl: api.Workload) -> bool:
        with self._lock:
            deleted = self._delete_workload_locked(wl)
            self._pods_ready_cond.notify_all()
            return deleted

    def _delete_workload_locked(self, wl: api.Workload) -> bool:
        key = wlpkg.key(wl)
        cq_name = self.assumed_workloads.pop(key, None)
        if cq_name is None and wl.status.admission is not None:
            cq_name = wl.status.admission.cluster_queue
        if cq_name is None:
            # The admission may already be cleared on the object (eviction
            # completed); fall back to membership lookup by key.
            for candidate in self.hm.cluster_queues.values():
                if key in candidate.workloads:
                    cq_name = candidate.name
                    break
        if cq_name is None:
            return False
        cqc = self.hm.cluster_queues.get(cq_name)
        if cqc is None:
            return False
        info = cqc.workloads.get(key)
        if info is None:
            return False
        cqc.delete_workload(info)
        self._journal_usage("del", cqc.name, key,
                            info.flavor_resource_usage())
        cqc.workloads_not_ready.discard(key)
        self._capacity_version += 1  # freed capacity invalidates resume state
        return True

    def assume_workload(self, wl: api.Workload,
                        info: Optional[wlpkg.Info] = None) -> None:
        """Optimistically account for a workload before the API write
        (reference: cache.go:546). `info` (optional) skips re-parsing the
        admission when the caller just built it (scheduler admit path)."""
        with self._lock:
            key = wlpkg.key(wl)
            if key in self.assumed_workloads:
                raise KeyError(f"workload {key} already assumed")
            if wl.status.admission is None:
                raise ValueError("cannot assume workload without admission")
            cqc = self.hm.cluster_queues.get(wl.status.admission.cluster_queue)
            if cqc is None:
                raise KeyError(f"cluster queue {wl.status.admission.cluster_queue} not found")
            if info is None or info.obj is not wl:
                info = self._new_info(wl)
            cqc.add_workload(info)
            not_ready = (self.pods_ready_tracking and not is_condition_true(
                wl.status.conditions, api.WORKLOAD_PODS_READY))
            self._journal_usage("add", cqc.name, key,
                                info.flavor_resource_usage(),
                                (info, not_ready))
            if not_ready:
                cqc.workloads_not_ready.add(key)
            self.assumed_workloads[key] = cqc.name

    def forget_workload(self, wl: api.Workload) -> None:
        with self._lock:
            key = wlpkg.key(wl)
            if key not in self.assumed_workloads:
                raise KeyError(f"workload {key} not assumed")
            self._delete_workload_locked(wl)
            self._pods_ready_cond.notify_all()

    def is_assumed_or_admitted(self, info: wlpkg.Info) -> bool:
        with self._lock:
            key = info.key
            if key in self.assumed_workloads:
                return True
            cqc = self.hm.cluster_queues.get(info.cluster_queue)
            return cqc is not None and key in cqc.workloads

    def _new_info(self, wl: api.Workload) -> wlpkg.Info:
        return wlpkg.Info(wl, excluded_resource_prefixes=self.excluded_resource_prefixes)

    # --- PodsReady gating (reference: cache.go:145-192) ---

    def pods_ready_for_all_admitted_workloads(self) -> bool:
        with self._lock:
            if not self.pods_ready_tracking:
                return True
            return all(not cqc.workloads_not_ready
                       for cqc in self.hm.cluster_queues.values())

    def mark_workload_pods_ready(self, wl: api.Workload) -> None:
        with self._lock:
            key = wlpkg.key(wl)
            for cqc in self.hm.cluster_queues.values():
                if key in cqc.workloads_not_ready:
                    cqc.workloads_not_ready.discard(key)
                    self._journal_usage("ready", cqc.name, key, None)
            self._pods_ready_cond.notify_all()

    def wait_for_pods_ready(self, timeout: Optional[float] = None) -> bool:
        with self._pods_ready_cond:
            return self._pods_ready_cond.wait_for(
                lambda: all(not c.workloads_not_ready
                            for c in self.hm.cluster_queues.values()),
                timeout=timeout)

    # --- snapshot (reference: snapshot.go:79-142) ---

    def snapshot(self, light: bool = False) -> Snapshot:
        # light=True shares the cache trees instead of deep-copying (see
        # ClusterQueueSnapshot): READ-ONLY cycles only (the pipelined
        # all-fit path, whose usage truth is the device-resident state).
        # Full snapshots go through the incremental maintainer when one
        # is attached: the persistent snapshot is advanced by journal
        # replay and handed out under copy-on-write (SNAPSHOTS.md)
        # instead of deep-cloning every CQ's trees per cycle.
        with self._lock:
            if light:
                self.snapshot_stats["light"] += 1
                if self._maintainer is not None:
                    # Periodic background advance: a long pipelined
                    # all-fit stretch takes only light snapshots, so the
                    # snapshot consumer's journal backlog would hit the
                    # cursor cap and pay a surprise full rebuild on the
                    # next sync cycle. Catch up (replay, no handout)
                    # once the backlog passes half the cap.
                    backlog = self._journal_seq - self._journal_cursors.get(
                        SNAPSHOT_CONSUMER, 0)
                    if backlog > self._journal_cap // 2:
                        self._maintainer.catch_up()
                snap = self._build_snapshot(light=True)
            else:
                t0 = _time.perf_counter()
                if self._maintainer is not None:
                    snap, mode = self._maintainer.advance()
                else:
                    snap, mode = self._build_snapshot(), "full"
                self.snapshot_stats[mode] += 1
                if len(self.snapshot_build_s) >= (1 << 20):
                    # Bound the sample buffer on very long runs; late
                    # samples (steady state) are the ones the
                    # percentiles should reflect anyway.
                    del self.snapshot_build_s[:1 << 19]
                self.snapshot_build_s.append(_time.perf_counter() - t0)
                self.handouts_taken += 1
                snap._handout_live = True
        # OUTSIDE the cache lock: the capacity source reads the local
        # Store and the remote managers' caches — taking Store._lock
        # while holding Cache._lock would invert the store-watch
        # handlers' Store._lock -> Cache._lock order (AB-BA risk in
        # threaded deployments).
        return self._stamp_remote(snap, light=light)

    def _stamp_remote(self, snap: Snapshot, light: bool = False) -> Snapshot:
        """Attach the current remote-cluster capacity columns (read-only
        per handout; the source rebuilds the tuple on change). Called
        WITHOUT the cache lock held — see snapshot(). LIGHT snapshots
        (the depth-2 pipelined all-fit hot path takes one per cycle)
        reuse the last FULL snapshot's columns instead of re-walking
        every remote cache + the plan table — capacity is an advisory
        score, stale by at most one sync cycle there."""
        src = self.remote_capacity_source
        if src is None:
            return snap
        cached = self._remote_columns_cache
        if light and cached is not None:
            snap.remote_clusters, snap.mk_check_names = cached
            return snap
        try:
            cols, checks = src()
        except Exception:  # noqa: BLE001 — capacity is advisory
            # A torn read during remote churn degrades to "no columns
            # this cycle" (placement falls back to the controller's
            # mirror-to-all race), never a failed cycle.
            cols, checks = (), frozenset()
        self._remote_columns_cache = (cols, checks)
        snap.remote_clusters = cols
        snap.mk_check_names = checks
        return snap

    def release_snapshot(self, snap: Snapshot) -> None:
        """Optional hint that the caller will never read or mutate
        `snap` again: the incremental maintainer may then recycle its
        un-materialized copy-on-write shells into the NEXT handout,
        skipping the O(CQs) shell rebuild per cycle. Safe to omit —
        unreleased snapshots are simply never reused. Releasing a
        snapshot that is still read afterwards is a caller bug (its
        shells may start reflecting a newer cycle)."""
        if getattr(snap, "light", False):
            return
        with self._lock:
            if getattr(snap, "_handout_live", False):
                snap._handout_live = False
                self.handouts_released += 1
            if self._maintainer is not None:
                self._maintainer.release(snap)

    @property
    def live_handouts(self) -> int:
        """Full snapshots handed out and not yet released — the leak
        detector for abandoned cycles (ISSUE 10 satellite). Consumers
        that legitimately never release (debug oracles) keep their
        handouts counted here; the scheduler/solver paths all
        release."""
        return self.handouts_taken - self.handouts_released

    def _build_snapshot(self, light: bool = False) -> Snapshot:
        """From-scratch snapshot construction (the full deep clone, or
        the shared-tree light view). The incremental maintainer uses the
        same building blocks; this stays the equivalence oracle."""
        with self._lock:
            snap = Snapshot()
            snap.light = light
            for name, cqc in self.hm.cluster_queues.items():
                if not cqc.active:
                    snap.inactive_cluster_queue_sets.add(name)
                    continue
                snap.cluster_queues[name] = ClusterQueueSnapshot(cqc,
                                                                light=light)
            snap.resource_flavors = dict(self.resource_flavors)
            cohort_snaps: dict = {}
            for cname, node in self.hm.cohorts.items():
                cohort_snap = CohortSnapshot(
                    cname, node.payload.resource_node if light
                    else node.payload.resource_node.clone())
                # The monotonic capacity version: any capacity change
                # anywhere (including in sibling subtrees of a tree)
                # invalidates stored flavor-resume state via a `>` check.
                cohort_snap.allocatable_resource_generation = self._capacity_version
                cohort_snaps[cname] = cohort_snap
                for cqc in node.child_cqs.values():
                    if cqc.name in snap.cluster_queues:
                        cq_snap = snap.cluster_queues[cqc.name]
                        cq_snap.cohort = cohort_snap
                        cohort_snap.members.add(cq_snap)
            # Wire the cohort tree (hierarchical v1alpha1 cohorts).
            for cname, node in self.hm.cohorts.items():
                if node.parent is not None:
                    parent_snap = cohort_snaps[node.parent.name]
                    cohort_snaps[cname].parent = parent_snap
                    parent_snap.child_cohorts.add(cohort_snaps[cname])
            snap.cohort_epoch = self.cohort_epoch
            snap.flavor_spec_epoch = self.flavor_spec_epoch
            snap.topology_epoch = self.topology_epoch
            snap.journal_seq = self._journal_seq
            return snap

    # --- usage reporting (status/metrics) ---

    def usage_for_cluster_queue(self, name: str) -> tuple:
        """(reservation usage, admitted usage) as FlavorResource dicts."""
        with self._lock:
            cqc = self.hm.cluster_queues.get(name)
            if cqc is None:
                return {}, {}
            return dict(cqc.resource_node.usage), dict(cqc.admitted_usage)
