"""Incremental journal-replay snapshot maintenance.

Keeps ONE persistent full Snapshot ("master") and advances it on every
`Cache.snapshot()` call by replaying drained usage-journal entries
(workload add/del usage deltas, non-structural CQ updates, pods-ready
flips) onto the cloned ResourceNode trees and CQ workload maps, instead
of deep-cloning every ClusterQueue's resource groups, workload maps and
hierarchical usage nodes from scratch — an O(CQs x flavors x resources)
copy that was pure overhead when only a handful of workloads moved since
the last cycle.

Fallback to a from-scratch rebuild happens only when a structural epoch
moved (cohort_epoch / flavor_spec_epoch / topology_epoch — CQ and cohort
adds/deletes, quota or flavor-spec changes, activity flips) or the
journal overflowed for the snapshot consumer. Equal epochs guarantee
every journaled entry in between is non-structural, so replay is exact.

Handouts are copy-on-write (see SNAPSHOTS.md for the full contract):
each call returns a fresh Snapshot of shallow per-CQ/per-cohort shells
sharing the master's containers. A cycle that mutates its snapshot for
preemption simulation privatizes just the touched CQ (and its cohort
chain) on first write; the master likewise privatizes a CQ's containers
before replaying a delta onto it while a handout may still hold them —
so handed-out snapshots stay frozen at their journal_seq and per-cycle
cloning is bounded by the CQs actually touched on either side.

Inactive ClusterQueues are absent from snapshots but their admitted
usage still bubbles into live cohort nodes, so the maintainer keeps
"hidden" master snapshots for them: replay targets for usage bubbling
that are never handed out.
"""

from __future__ import annotations

from kueue_tpu.cache import resource_node as rnode
from kueue_tpu.cache.snapshot import ClusterQueueSnapshot, CohortSnapshot, Snapshot

# Mirrors kueue_tpu.cache.cache.SNAPSHOT_CONSUMER (importing it here
# would be circular: Cache.__init__ imports this module at runtime).
SNAPSHOT_CONSUMER = "snapshot"


def snapshot_generations(snapshot) -> tuple:
    """A snapshot's structural generation stamp, in the SAME canonical
    order as ``Cache.generation_token()`` — the one place the tuple
    layout is defined on the snapshot side, so a future fourth epoch
    has exactly two producers to touch (here and generation_token)."""
    return (snapshot.topology_epoch, snapshot.cohort_epoch,
            snapshot.flavor_spec_epoch)


def generations_current(snapshot, cache) -> bool:
    """Generation-token validation for the speculative admission
    pipeline: True iff no STRUCTURAL epoch moved since ``snapshot`` was
    produced — the cheap alternative to comparing snapshots field by
    field (three int compares instead of an O(CQs x flavors) walk).

    The epochs used are exactly the ones the maintainer's ``_sync``
    keys its full-rebuild fallback on: equal epochs guarantee every
    journaled entry since the snapshot is non-structural, so a
    speculative solve dispatched against the snapshot stays sound —
    workload churn reconciles through the usage journal and through the
    encode arena's per-slot generations, which the SpeculationToken
    checks separately. Caller holds the cache lock (or tolerates a
    torn read, as the scheduler's single-threaded cycle does).
    """
    return snapshot_generations(snapshot) == cache.generation_token()


class SnapshotMaintainer:
    def __init__(self, cache):
        self._cache = cache
        self._cqs: dict = {}      # name -> master snapshot (active CQs)
        self._hidden: dict = {}   # name -> master for inactive CQs
        self._cohorts: dict = {}  # name -> master CohortSnapshot
        self._inactive: set = set()
        self._epochs = None
        # Master containers NOT shared with any handout (privatized
        # since the last handout, or never handed out). Tracked here by
        # name — not as per-object flags — so the hot handout loop does
        # no per-CQ lease bookkeeping at all: a handout simply clears
        # these sets (everything is shared again) and _own re-privatizes
        # on demand.
        self._fresh_cqs: set = set()
        self._fresh_cohorts: set = set()
        # Shell recycling (see release()): the latest handout, returned
        # by its consumer, whose un-materialized CQ shells the next
        # handout may reuse instead of re-allocating O(CQs) objects.
        self._handout_gen = 0
        self._reusable = None  # (handout gen, its cluster_queues dict)
        # Engagement counters (perf artifacts / the smoke test assert
        # that steady-state cycles take the incremental path).
        self.full_rebuilds = 0
        self.incremental_advances = 0
        self.partial_rebuilds = 0
        self.background_advances = 0
        self.shell_reuses = 0

    def advance(self) -> tuple:
        """Bring the persistent snapshot up to the cache's current state
        and return (handout snapshot, "incremental" | "full"). Caller
        holds the cache lock."""
        mode = self._sync()
        return self._handout(self._epochs), mode

    def catch_up(self) -> None:
        """Background advance WITHOUT a handout: drains and replays the
        journal so a long light-snapshot-only stretch (pipelined all-fit
        cycles) cannot overflow the snapshot consumer's cursor cap and
        pay a surprise full rebuild at the next sync cycle. Caller holds
        the cache lock."""
        self.background_advances += 1
        self._sync()

    def _sync(self) -> str:
        cache = self._cache
        epochs = (cache.cohort_epoch, cache.flavor_spec_epoch,
                  cache.topology_epoch)
        entries, overflow = cache.drain_usage_journal(
            cache._journal_seq, consumer=SNAPSHOT_CONSUMER)
        if overflow or self._epochs != epochs:
            dirty, dirty_all = cache.take_structural_dirty()
            if (not overflow and not dirty_all and dirty
                    and self._epochs is not None
                    and self._epochs[0] == epochs[0]
                    and self._epochs[1] == epochs[1]):
                # Every structural change since the last sync was a
                # single-CQ edit with an unchanged cohort edge (quota /
                # resource-group / activity): rebuild ONLY those CQs'
                # subtrees from live state and replay the journal for
                # everyone else, instead of re-cloning 2k masters
                # because one tenant's quota moved (the flavor-churn
                # scenario's steady diet). Entries for the dirty CQs
                # are subsumed by their from-live rebuild.
                self._replay([e for e in entries if e[2] not in dirty])
                for name in dirty:
                    self._rebuild_cq(name)
                self._epochs = epochs
                self.partial_rebuilds += 1
                return "partial"
            # Structural change (or lost journal entries): the drained
            # entries are subsumed by rebuilding from live state.
            self._rebuild()
            self._epochs = epochs
            self.full_rebuilds += 1
            return "full"
        self._replay(entries)
        self.incremental_advances += 1
        return "incremental"

    # --- full rebuild (the epoch/overflow fallback) ---

    def _rebuild(self) -> None:
        cache = self._cache
        self._cqs = {}
        self._hidden = {}
        self._cohorts = {}
        self._inactive = set()
        for name, cqc in cache.hm.cluster_queues.items():
            snap_cq = ClusterQueueSnapshot(cqc)
            # Stamped once so the handout __dict__ copy hands every
            # shell _shared=True for free (see ClusterQueueSnapshot).
            snap_cq._shared = True
            if cqc.active:
                self._cqs[name] = snap_cq
            else:
                self._inactive.add(name)
                self._hidden[name] = snap_cq
        self._fresh_cqs = set(cache.hm.cluster_queues)
        self._fresh_cohorts = set(cache.hm.cohorts)
        for cname, node in cache.hm.cohorts.items():
            self._cohorts[cname] = CohortSnapshot(
                cname, node.payload.resource_node.clone())
        for cname, node in cache.hm.cohorts.items():
            cohort = self._cohorts[cname]
            if node.parent is not None:
                cohort.parent = self._cohorts[node.parent.name]
                cohort.parent.child_cohorts.add(cohort)
            for cqc in node.child_cqs.values():
                member = self._cqs.get(cqc.name) \
                    or self._hidden.get(cqc.name)
                if member is not None:
                    # Hidden CQs get the cohort pointer (usage bubbling)
                    # but are not members; handouts rebuild member sets.
                    member.cohort = cohort

    # --- per-CQ structural rebuild (single-CQ epoch bumps) ---

    def _rebuild_cq(self, name: str) -> None:
        """Rebuild ONE ClusterQueue's master from live state after a
        structural edit contained to it (quota / resource-group /
        activity change, cohort edge unchanged), then re-sync its cohort
        tree's aggregates — the live tree was already re-aggregated by
        update_cohort_resource_node, so quotas/subtree_quota/usage come
        from there. Preconditions enforced by _sync: no cohort-graph
        shape change, no flavor-spec or cohort-object epoch movement."""
        cache = self._cache
        self._cqs.pop(name, None)
        self._hidden.pop(name, None)
        self._inactive.discard(name)
        cqc = cache.hm.cluster_queues.get(name)
        if cqc is None:
            # CQ deletes are dirty-all (full rebuild); defensive only.
            return
        snap_cq = ClusterQueueSnapshot(cqc)
        snap_cq._shared = True
        if cqc.active:
            self._cqs[name] = snap_cq
        else:
            self._inactive.add(name)
            self._hidden[name] = snap_cq
        # The fresh clone shares nothing with any handout.
        self._fresh_cqs.add(name)
        node = cache.hm.cohort_of(name)
        if node is not None:
            cohort = self._cohorts.get(node.name)
            if cohort is not None:
                snap_cq.cohort = cohort
                self._sync_cohort_tree_from_live(cohort.root())

    def _sync_cohort_tree_from_live(self, cohort) -> None:
        """Re-sync a master cohort tree's resource nodes (quotas,
        subtree_quota, usage) from the live tree, privatizing shared
        nodes first (handouts keep their frozen view). Used by the
        per-CQ rebuild: a quota edit on one member re-aggregates the
        live tree wholesale, exactly like a non-structural CQ refresh
        re-syncs usage (see _sync_cohort_tree_usage)."""
        live = self._cache.hm.cohorts.get(cohort.name)
        if live is not None:
            if cohort.name not in self._fresh_cohorts:
                cohort.resource_node = cohort.resource_node.clone()
                self._fresh_cohorts.add(cohort.name)
            node = live.payload.resource_node
            # Re-share the live quota dicts exactly like a fresh clone
            # would (ResourceNode.clone shares quotas/subtree_quota).
            cohort.resource_node.quotas = node.quotas
            cohort.resource_node.subtree_quota = node.subtree_quota
            cohort.resource_node.usage = dict(node.usage)
        for child in cohort.child_cohorts:
            self._sync_cohort_tree_from_live(child)

    # --- journal replay (the steady-state path) ---

    def _replay(self, entries: list) -> None:
        cache = self._cache
        refresh: set = set()
        for entry in entries:
            kind, cq_name, key = entry[1], entry[2], entry[3]
            if kind == "cq":
                refresh.add(cq_name)
                continue
            mcq = self._cqs.get(cq_name)
            if mcq is None:
                mcq = self._hidden.get(cq_name)
                if mcq is None:
                    continue
            if kind == "add":
                usage = entry[4]
                info, not_ready = entry[5]
                self._own(mcq)
                mcq.workloads[key] = info
                if not_ready:
                    mcq.workloads_not_ready.add(key)
                for fr, q in usage.items():
                    rnode.add_usage(mcq, fr, q)
            elif kind == "del":
                usage = entry[4]
                self._own(mcq)
                mcq.workloads.pop(key, None)
                mcq.workloads_not_ready.discard(key)
                for fr, q in usage.items():
                    rnode.remove_usage(mcq, fr, q)
                # Freed capacity invalidates flavor-resume state
                # (mirrors ClusterQueueCache.delete_workload).
                mcq.allocatable_resource_generation += 1
            elif kind == "ready":
                self._own(mcq)
                mcq.workloads_not_ready.discard(key)
        for name in refresh:
            self._refresh_cq(name)

    def _refresh_cq(self, name: str) -> None:
        """Re-sync the fields a non-structural ClusterQueue update can
        move. Anything else (quotas, resource-group shape, cohort edge,
        activity) changes the topology signature and takes the
        full-rebuild path instead — usage and workload maps are
        exclusively owned by the delta entries."""
        cqc = self._cache.hm.cluster_queues.get(name)
        mcq = self._cqs.get(name) or self._hidden.get(name)
        if cqc is None or mcq is None:
            return
        self._own(mcq)
        mcq.namespace_selector = cqc.namespace_selector
        mcq.preemption = cqc.preemption
        mcq.flavor_fungibility = cqc.flavor_fungibility
        mcq.fair_weight = cqc.fair_weight
        mcq.resource_groups = [rg.clone() for rg in cqc.resource_groups]
        mcq.admission_checks = {k: set(v)
                                for k, v in cqc.admission_checks.items()}
        # Equal content by the no-topo-bump precondition; re-share the
        # live dicts exactly like a fresh clone would.
        mcq.resource_node.quotas = cqc.resource_node.quotas
        mcq.resource_node.subtree_quota = cqc.resource_node.subtree_quota
        # The update rebuilt the LIVE cohort tree's usage wholesale
        # (update_cohort_resource_node), which drops zero-valued entries
        # that incremental bubbling keeps; re-sync the tree from live
        # state so the maintained snapshot matches a fresh clone exactly.
        if mcq.cohort is not None:
            self._sync_cohort_tree_usage(mcq.cohort.root())

    def _sync_cohort_tree_usage(self, cohort) -> None:
        live = self._cache.hm.cohorts.get(cohort.name)
        if live is not None:
            if cohort.name not in self._fresh_cohorts:
                cohort.resource_node = cohort.resource_node.clone()
                self._fresh_cohorts.add(cohort.name)
            cohort.resource_node.usage = \
                dict(live.payload.resource_node.usage)
        for child in cohort.child_cohorts:
            self._sync_cohort_tree_usage(child)

    def _own(self, mcq: ClusterQueueSnapshot) -> None:
        """Master-side copy-on-write: privatize this CQ's containers (and
        the cohort chain's usage nodes) before replaying a delta, so a
        handout that still shares them keeps its frozen view."""
        fresh = self._fresh_cqs
        if mcq.name not in fresh:
            mcq.workloads = dict(mcq.workloads)
            mcq.workloads_not_ready = set(mcq.workloads_not_ready)
            mcq.resource_node = mcq.resource_node.clone()
            fresh.add(mcq.name)
        fresh = self._fresh_cohorts
        cohort = mcq.cohort
        while cohort is not None and cohort.name not in fresh:
            cohort.resource_node = cohort.resource_node.clone()
            fresh.add(cohort.name)
            cohort = cohort.parent

    # --- copy-on-write handout ---

    def release(self, snap: Snapshot) -> None:
        """The consumer is done with this handout (it will never read or
        mutate it again): its un-materialized shells become candidates
        for recycling into the NEXT handout. Only the latest handout
        qualifies — an older one would hand back shells whose master
        state has since been re-shared with a newer snapshot."""
        if getattr(snap, "_handout_gen", -1) == self._handout_gen:
            self._reusable = (self._handout_gen, snap.cluster_queues)

    def _handout(self, epochs: tuple) -> Snapshot:
        cache = self._cache
        snap = Snapshot()
        snap.cohort_epoch, snap.flavor_spec_epoch, snap.topology_epoch = \
            epochs
        snap.journal_seq = cache._journal_seq
        snap.resource_flavors = dict(cache.resource_flavors)
        snap.inactive_cluster_queue_sets = set(self._inactive)
        # Shells released back from the previous handout (release()):
        # one whose master was untouched since (not in _fresh_cqs) and
        # that its cycle never materialized (_shared still True) is
        # VALUE-identical to the fresh __dict__ copy we would build — so
        # recycle the object and skip the allocation + copy. Everything
        # else (replayed masters, materialized shells) is rebuilt.
        prev_cqs = None
        if self._reusable is not None \
                and self._reusable[0] == self._handout_gen:
            prev_cqs = self._reusable[1]
        self._reusable = None
        self._handout_gen += 1
        snap._handout_gen = self._handout_gen
        cohort_shells: dict = {}
        for cname, cohort in self._cohorts.items():
            # The monotonic capacity version (see Cache.snapshot's full
            # build): refreshed on every handout.
            cohort.allocatable_resource_generation = cache._capacity_version
            cohort_shells[cname] = cohort.clone_shell()
        for cname, cohort in self._cohorts.items():
            if cohort.parent is not None:
                shell = cohort_shells[cname]
                parent = cohort_shells[cohort.parent.name]
                shell.parent = parent
                parent.child_cohorts.add(shell)
        # Hot loop (2k CQs per cycle): a shell is a bare __dict__ copy of
        # the master — _shared=True rides along from the master's stamp —
        # with `cohort` rewired into this handout's cohort shells.
        snap_cqs = snap.cluster_queues
        new = ClusterQueueSnapshot.__new__
        cls = ClusterQueueSnapshot
        fresh_cqs = self._fresh_cqs
        for name, mcq in self._cqs.items():
            shell = prev_cqs.get(name) if prev_cqs is not None else None
            if shell is not None and shell._shared \
                    and name not in fresh_cqs:
                self.shell_reuses += 1
            else:
                shell = new(cls)
                shell.__dict__.update(mcq.__dict__)
            cohort = mcq.cohort
            if cohort is not None:
                cohort_shell = cohort_shells[cohort.name]
                shell.cohort = cohort_shell
                cohort_shell.members.add(shell)
            else:
                shell.cohort = None
            snap_cqs[name] = shell
        # Everything just handed out is shared again: master-side COW
        # re-privatizes on demand. Hidden masters never ship, so they
        # stay permanently fresh.
        self._fresh_cqs = set(self._hidden)
        self._fresh_cohorts = set()
        return snap
