"""Scheduling snapshot: deep copy of usage trees for lock-free cycles.

Equivalent of the reference's pkg/cache/snapshot.go:79-142 +
clusterqueue_snapshot.go + cohort_snapshot.go + the DRF share math
(clusterqueue.go:503-564).
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.cache import resource_node as rnode
from kueue_tpu.cache.clusterqueue import ClusterQueueCache, ResourceGroupInfo
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.core.resources import FlavorResource


class CohortSnapshot:
    # Copy-on-write flag (incremental snapshots, see incremental.py):
    # marks a handout shell sharing the maintainer's usage node until
    # first mutation. The class-level default keeps plain deep-clone
    # snapshots zero-cost; the maintainer tracks which of ITS containers
    # are shared with handouts on its own side (name sets, not flags).
    _shared = False

    def __init__(self, name: str, resource_node: rnode.ResourceNode):
        self.name = name
        self.resource_node = resource_node
        self.members: set = set()  # direct ClusterQueueSnapshot children
        self.child_cohorts: set = set()  # direct CohortSnapshot children
        self.parent: Optional["CohortSnapshot"] = None
        self.allocatable_resource_generation = 0

    def clone_shell(self) -> "CohortSnapshot":
        """Shallow copy-on-write view for an incremental-snapshot
        handout: shares the usage node until first mutation (the tree
        wiring — members/parent/child_cohorts — is rebuilt per handout
        so each snapshot's cohort graph is self-contained)."""
        shell = CohortSnapshot.__new__(CohortSnapshot)
        shell.name = self.name
        shell.resource_node = self.resource_node
        shell.members = set()
        shell.child_cohorts = set()
        shell.parent = None
        shell.allocatable_resource_generation = \
            self.allocatable_resource_generation
        shell._shared = True
        return shell

    def parent_node(self) -> Optional["CohortSnapshot"]:
        return self.parent

    def root(self) -> "CohortSnapshot":
        c = self
        while c.parent is not None:
            c = c.parent
        return c

    def subtree_cqs(self):
        """All member CQs in this cohort's subtree (the borrowing domain
        for hierarchical cohorts)."""
        yield from self.members
        for child in self.child_cohorts:
            yield from child.subtree_cqs()


class ClusterQueueSnapshot:
    # Copy-on-write flag, as on CohortSnapshot. The maintainer stamps
    # _shared=True onto its master objects once, so the hot handout loop
    # (incremental.py:_handout) propagates it through a plain __dict__
    # copy — masters are never mutated through add_usage, so the flag is
    # only ever honored on handed-out shells.
    _shared = False

    def __init__(self, cq: ClusterQueueCache, light: bool = False):
        """light=True shares the cache's structures instead of cloning
        (READ-ONLY consumers only): pipelined all-fit cycles never
        simulate on the snapshot — they read selectors, generations and
        admission checks — and cloning 2k resource trees per cycle was
        measurable. Any path that mutates snapshot state (preemption
        simulation, intra-cycle accounting) must use a full snapshot.

        Thread-safety contract: light readers may race cache mutators,
        so they may only read (a) scalar fields and (b) container fields
        the cache replaces WHOLESALE on update (resource_groups, quotas,
        admission_checks, selectors — see ClusterQueueCache.update);
        in-place-mutated containers (resource_node.usage, workloads) must
        not be iterated through a light snapshot (the solver's establish
        path re-takes a full snapshot for exactly this reason)."""
        self.name = cq.name
        self.cohort: Optional[CohortSnapshot] = None
        self.light = light
        if light:
            self.resource_groups = cq.resource_groups
            self.workloads = cq.workloads
            self.workloads_not_ready = cq.workloads_not_ready
            self.admission_checks = cq.admission_checks
            self.resource_node = cq.resource_node
        else:
            self.resource_groups = [rg.clone() for rg in cq.resource_groups]
            self.workloads = dict(cq.workloads)
            self.workloads_not_ready = set(cq.workloads_not_ready)
            self.admission_checks = {k: set(v)
                                     for k, v in cq.admission_checks.items()}
            self.resource_node = cq.resource_node.clone()
        self.namespace_selector = cq.namespace_selector
        self.preemption = cq.preemption
        self.fair_weight = cq.fair_weight
        self.flavor_fungibility = cq.flavor_fungibility
        self.allocatable_resource_generation = cq.allocatable_resource_generation

    def _materialize(self) -> None:
        """First mutation of a copy-on-write shell: privatize this CQ's
        containers and the cohort chain's usage nodes, so preemption
        simulation and intra-cycle accounting never write through to the
        maintainer's persistent snapshot. Bounds per-cycle cloning to
        the CQs a cycle actually touches. resource_groups and
        admission_checks stay shared — no cycle path mutates them."""
        self.workloads = dict(self.workloads)
        self.workloads_not_ready = set(self.workloads_not_ready)
        self.resource_node = self.resource_node.clone()
        self._shared = False
        cohort = self.cohort
        while cohort is not None and cohort._shared:
            cohort.resource_node = cohort.resource_node.clone()
            cohort._shared = False
            cohort = cohort.parent

    # --- hierarchicalResourceNode protocol ---

    def parent_node(self) -> Optional[CohortSnapshot]:
        return self.cohort

    # --- quota queries (reference: clusterqueue_snapshot.go:53-135) ---

    def rg_by_resource(self, resource: str) -> Optional[ResourceGroupInfo]:
        for rg in self.resource_groups:
            if resource in rg.covered_resources:
                return rg
        return None

    def quota_for(self, fr: FlavorResource) -> rnode.ResourceQuota:
        return self.resource_node.quota_for(fr)

    def usage_for(self, fr: FlavorResource) -> int:
        return self.resource_node.usage.get(fr, 0)

    def available(self, fr: FlavorResource) -> int:
        return rnode.available(self, fr, True)

    def potential_available(self, fr: FlavorResource) -> int:
        return rnode.potential_available(self, fr)

    def borrowing_with(self, fr: FlavorResource, val: int) -> bool:
        return self.usage_for(fr) + val > self.quota_for(fr).nominal

    def borrowing(self, fr: FlavorResource) -> bool:
        return self.borrowing_with(fr, 0)

    def fits(self, usage: dict) -> bool:
        return all(self.available(fr) >= q for fr, q in usage.items())

    def add_usage(self, usage: dict) -> None:
        if self.light:
            # writing through a light snapshot would mutate the LIVE
            # cache's trees — corruption, not simulation
            raise RuntimeError("mutating a light (shared) snapshot")
        if self._shared:
            self._materialize()
        for fr, q in usage.items():
            rnode.add_usage(self, fr, q)

    def remove_usage(self, usage: dict) -> None:
        if self.light:
            raise RuntimeError("mutating a light (shared) snapshot")
        if self._shared:
            self._materialize()
        for fr, q in usage.items():
            rnode.remove_usage(self, fr, q)

    # --- DRF fair share (reference: clusterqueue.go:503-564) ---

    def dominant_resource_share(self) -> tuple:
        return dominant_resource_share(self, None, 0)

    def dominant_resource_share_with(self, wl_req: dict) -> tuple:
        return dominant_resource_share(self, wl_req, 1)

    def dominant_resource_share_without(self, wl_req: dict) -> tuple:
        return dominant_resource_share(self, wl_req, -1)


def dominant_resource_share(cq: ClusterQueueSnapshot, wl_req: Optional[dict], m: int) -> tuple:
    """(share, resource): share in [0, 1e6] — max over resources of
    (usage above remaining nominal quota / cohort lendable) * 1000,
    divided by the fair weight. Zero weight -> maxsize."""
    if cq.cohort is None:
        return 0, ""
    if cq.fair_weight == 0:
        return sys.maxsize, ""
    borrowing: dict = {}
    for fr in _flavor_resources(cq):
        remaining = cq.quota_for(fr).nominal - cq.usage_for(fr)
        b = (m * (wl_req or {}).get(fr, 0)) - remaining
        if b > 0:
            borrowing[fr.resource] = borrowing.get(fr.resource, 0) + b
    if not borrowing:
        return 0, ""
    # The borrowing domain is the whole cohort tree: the denominator is
    # the root's lendable capacity so shares are comparable across
    # subtrees (flat cohorts: root() is the cohort itself).
    lendable = cq.cohort.root().resource_node.calculate_lendable()
    drs, d_res = -1, ""
    for r_name in sorted(borrowing):
        lr = lendable.get(r_name, 0)
        if lr > 0:
            ratio = borrowing[r_name] * 1000 // lr
            if ratio > drs:
                drs, d_res = ratio, r_name
    dws = drs * 1000 // cq.fair_weight
    return dws, d_res


def _flavor_resources(cq: ClusterQueueSnapshot):
    for rg in cq.resource_groups:
        for f in rg.flavors:
            for r in rg.covered_resources:
                yield FlavorResource(f, r)


@dataclass
class Snapshot:
    cluster_queues: dict = field(default_factory=dict)  # name -> ClusterQueueSnapshot
    resource_flavors: dict = field(default_factory=dict)  # name -> ResourceFlavor
    inactive_cluster_queue_sets: set = field(default_factory=set)
    cohort_epoch: int = 0  # cohort-object structure version (Cache.cohort_epoch)
    flavor_spec_epoch: int = 0  # ResourceFlavor spec version (taints/labels)
    topology_epoch: int = 0  # solver-topology version (Cache.topology_epoch)
    journal_seq: int = 0  # usage-journal position at snapshot time
    light: bool = False  # shared (not cloned) state; read-only consumers
    # MultiKueue remote-cluster capacity columns (ISSUE 13): an ordered
    # tuple of (cluster_name, {(flavor, resource): available}, active)
    # stamped by Cache.snapshot() from the wired capacity source.
    # Lost clusters stamp active=False — their columns mask to zero in
    # the solve, so re-placement falls out of the next cycle's scoring.
    # Immutable per handout (the source rebuilds the tuple on change).
    remote_clusters: tuple = ()
    # AdmissionCheck names controlled by the multikueue controller —
    # lets the encoder mark which CQs route through the columns.
    mk_check_names: frozenset = frozenset()

    def remove_workload(self, wl: wlpkg.Info) -> None:
        """Simulate removal (reference: snapshot.go:39)."""
        if self.light:
            raise RuntimeError("mutating a light (shared) snapshot")
        cq = self.cluster_queues[wl.cluster_queue]
        if cq._shared:
            cq._materialize()
        cq.workloads.pop(wl.key, None)
        cq.remove_usage(wl.flavor_resource_usage())

    def add_workload(self, wl: wlpkg.Info) -> None:
        if self.light:
            raise RuntimeError("mutating a light (shared) snapshot")
        cq = self.cluster_queues[wl.cluster_queue]
        if cq._shared:
            cq._materialize()
        cq.workloads[wl.key] = wl
        cq.add_usage(wl.flavor_resource_usage())
