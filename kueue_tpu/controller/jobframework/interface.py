"""GenericJob SPI and the integration registry.

Equivalent of the reference's pkg/controller/jobframework/interface.go:36-128
and integrationmanager.go:56-118. Optional capabilities (reclaimable pods,
custom stop, finalize, skip, priority class) are modeled as optional
methods probed with hasattr — the Python analogue of the reference's Go
type assertions.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

# stop reasons (reference: interface.go:76-83)
STOP_REASON_WORKLOAD_DELETED = "WorkloadDeleted"
STOP_REASON_WORKLOAD_EVICTED = "WorkloadEvicted"
STOP_REASON_NO_MATCHING_WORKLOAD = "NoMatchingWorkload"
STOP_REASON_NOT_ADMITTED = "NotAdmitted"


class GenericJob:
    """The contract every job integration implements
    (reference: interface.go:36-60).

    Optional capability methods (probed with hasattr, mirroring the
    reference's optional interfaces):
    - reclaimable_pods() -> list[api.ReclaimablePod]     (JobWithReclaimablePods)
    - stop(store, podsets_info, reason, msg) -> bool     (JobWithCustomStop)
    - finalize(store)                                    (JobWithFinalize)
    - skip() -> bool                                     (JobWithSkip)
    - priority_class() -> str                            (JobWithPriorityClass)
    """

    def object(self):
        """The underlying store object (has .metadata)."""
        raise NotImplementedError

    def is_suspended(self) -> bool:
        raise NotImplementedError

    def suspend(self) -> None:
        raise NotImplementedError

    def run_with_podsets_info(self, podsets_info: list) -> None:
        """Inject node selectors/counts and unsuspend
        (may raise podset.PermanentError)."""
        raise NotImplementedError

    def restore_podsets_info(self, podsets_info: list) -> bool:
        raise NotImplementedError

    def finished(self) -> tuple:
        """(message, success, finished)."""
        raise NotImplementedError

    def pod_sets(self) -> list:
        """list[api.PodSet] for the workload."""
        raise NotImplementedError

    def is_active(self) -> bool:
        """True if any pods are still running."""
        raise NotImplementedError

    def pods_ready(self) -> bool:
        raise NotImplementedError

    def gvk(self) -> str:
        """Group/kind string, e.g. "batch/job"."""
        raise NotImplementedError


class ComposableJob(GenericJob):
    """A job composed of multiple objects (reference: interface.go:108-128;
    implemented by the pod-group integration)."""

    def load(self, store, namespace: str, name: str) -> tuple:
        """Returns (remove_finalizers, found)."""
        raise NotImplementedError

    def run(self, store, podsets_info: list, recorder, msg: str) -> None:
        raise NotImplementedError

    def construct_composable_workload(self, store, recorder):
        raise NotImplementedError

    def list_child_workloads(self, store) -> list:
        raise NotImplementedError

    def find_matching_workloads(self, store, recorder) -> tuple:
        """Returns (match, to_delete)."""
        raise NotImplementedError

    def stop(self, store, podsets_info: list, reason: str, msg: str) -> list:
        """Returns the objects stopped now."""
        raise NotImplementedError


@dataclass
class IntegrationCallbacks:
    """Registry entry (reference: integrationmanager.go:56-82)."""
    name: str                        # framework name, e.g. "batch/job"
    kind: str                        # store kind, e.g. "Job"
    new_job: Callable                # (obj) -> GenericJob wrapper
    job_type: type                   # the store object dataclass
    add_to_scheme: Optional[Callable] = None
    is_managing_conflict: Optional[Callable] = None
    # integrations that must also be enabled (reference: DependencyList,
    # e.g. deployment -> pod)
    depends_on: list = field(default_factory=list)
    # ComposableJob integrations construct their wrapper without a loaded
    # object (new_job(None)) and load members themselves
    composable: bool = False
    # map a watched object to its reconcile key (default: ns/name); the
    # pod integration maps group members to "group/ns/groupname"
    # (reference: pod/event_handlers.go:43)
    reconcile_key: Optional[Callable] = None
    # map a child Workload (+ its controller OwnerReference) to the owner
    # job's reconcile key (default: "ns/owner.name")
    reconcile_key_for_workload: Optional[Callable] = None


_registry: dict[str, IntegrationCallbacks] = {}


def register_integration(cb: IntegrationCallbacks) -> None:
    """reference: integrationmanager.go RegisterIntegration"""
    if cb.name in _registry:
        raise ValueError(f"integration {cb.name} already registered")
    _registry[cb.name] = cb


def get_integration(name: str) -> Optional[IntegrationCallbacks]:
    return _registry.get(name)


def integration_names() -> list:
    return list(_registry)


def forget_integrations() -> None:
    """Test hook (reference: integrationmanager_test)."""
    _registry.clear()
