"""JobReconciler: the job <-> workload state machine.

Equivalent of the reference's pkg/controller/jobframework/reconciler.go:204-1000:
- ensureOneWorkload: 1:1 job->Workload with dedup + equivalence checks,
  prebuilt-workload support (:563-665)
- constructWorkload + priority resolution
  (WorkloadPriorityClass > job > pod PriorityClass, :879-962)
- startJob: inject PodSetInfo from the admission + admission-check
  podSetUpdates, unsuspend (:798-821, :964-1000)
- stopJob: suspend + restore pod templates, custom/composable stop
  (:823-866)
- eviction handling: stop, then once inactive clear the quota
  reservation and set Requeued (:435-455)
- PodsReady condition sync, reclaimable-pod propagation, finished
  propagation, parent-workload gating for owned jobs (:268-315)

One deviation, by design: the sim store has no ownerRef garbage
collector, so when a job disappears this reconciler deletes its child
workloads (the reference only strips finalizers and lets k8s GC
collect).
"""

from __future__ import annotations

from typing import Optional

from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import Condition, OwnerReference, find_condition, set_condition
from kueue_tpu.core import podset as podsetpkg
from kueue_tpu.core import priority as prioritypkg
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.controller.jobframework.interface import (
    ComposableJob,
    GenericJob,
    STOP_REASON_NOT_ADMITTED,
    STOP_REASON_NO_MATCHING_WORKLOAD,
    STOP_REASON_WORKLOAD_DELETED,
    STOP_REASON_WORKLOAD_EVICTED,
)
from kueue_tpu.controller.jobframework.workload_names import workload_name_for_owner

JOB_UID_LABEL = "kueue.x-k8s.io/job-uid"
FAILED_TO_START_REASON = "FailedToStart"
FINISHED_SUCCEEDED = "Succeeded"
FINISHED_FAILED = "Failed"


def queue_name(job: GenericJob) -> str:
    """The queue-name label on the job (reference: QueueNameForObject)."""
    return job.object().metadata.labels.get(api.QUEUE_LABEL, "")


def prebuilt_workload_for(job: GenericJob) -> Optional[str]:
    return job.object().metadata.labels.get(api.PREBUILT_WORKLOAD_LABEL)


def workload_priority_class_name(job: GenericJob) -> str:
    return job.object().metadata.labels.get(api.PRIORITY_CLASS_LABEL, "")


def is_owner_managed_by_kueue(owner: OwnerReference) -> bool:
    from kueue_tpu.controller.jobframework.interface import _registry
    return any(cb.kind == owner.kind for cb in _registry.values())


class JobReconciler:
    def __init__(self, store, recorder, clock, integration,
                 manage_jobs_without_queue_name: bool = False,
                 wait_for_pods_ready: bool = False,
                 label_keys_to_copy: Optional[list] = None):
        self.store = store
        self.recorder = recorder
        self.clock = clock
        self.integration = integration   # IntegrationCallbacks
        self.manage_jobs_without_queue_name = manage_jobs_without_queue_name
        self.wait_for_pods_ready = wait_for_pods_ready
        self.label_keys_to_copy = label_keys_to_copy or []

    # ------------------------------------------------------------------

    def reconcile(self, key: str):
        namespace, name = key.split("/", 1)
        if self.integration.composable:
            job = self.integration.new_job(None)
            drop_finalizers, found = job.load(self.store, namespace, name)
            obj = job.object() if found else None
            if obj is None:
                job_for_cleanup = job
                if drop_finalizers:
                    return self._drop_finalizers(job_for_cleanup, namespace, name)
                return None
        else:
            obj = self.store.try_get(self.integration.kind, namespace, name)
            job = self.integration.new_job(obj) if obj is not None else None
            drop_finalizers = obj is None or obj.metadata.deletion_timestamp is not None

        if job is not None and hasattr(job, "skip") and job.skip():
            return None

        if drop_finalizers:
            return self._drop_finalizers(job, namespace, name)

        # ownership: child jobs are gated on their parent's workload
        # (reference: :268-315)
        owner = next((o for o in obj.metadata.owner_references if o.controller), None)
        standalone = owner is None or not is_owner_managed_by_kueue(owner)

        if not self.manage_jobs_without_queue_name and not queue_name(job):
            if standalone:
                return None
            if not self._parent_job_managed(owner, namespace):
                return None

        if not standalone:
            _, _, finished = job.finished()
            if not finished and not job.is_suspended():
                parent_wl = self._parent_workload(owner, namespace)
                if parent_wl is None or not wlpkg.is_admitted(parent_wl):
                    job.suspend()
                    self.store.update(job.object())
                    self.recorder.event(obj, "Normal", "Suspended",
                                        "Kueue managed child job suspended")
            return None

        # 1. single-workload invariant
        wl = self._ensure_one_workload(job, obj)

        if wl is not None and wlpkg.is_finished(wl):
            self._finalize_job(job)
            self.recorder.event(obj, "Normal", "FinishedWorkload",
                                f"Workload '{wlpkg.key(wl)}' is declared finished")
            self._remove_workload_finalizer(wl)
            return None

        # 1.1 workload pending deletion
        if wl is not None and wl.metadata.deletion_timestamp is not None:
            self._stop_job(job, wl, STOP_REASON_WORKLOAD_DELETED, "Workload is deleted")
            self._remove_workload_finalizer(wl)
            return None

        # 2. job finished -> propagate Finished condition
        message, success, finished = job.finished()
        if finished:
            if wl is not None and not wlpkg.is_finished(wl):
                set_condition(wl.status.conditions, Condition(
                    type=api.WORKLOAD_FINISHED, status="True",
                    reason=FINISHED_SUCCEEDED if success else FINISHED_FAILED,
                    message=message,
                    observed_generation=wl.metadata.generation), self.clock.now())
                self.store.update(wl)
                self.recorder.event(obj, "Normal", "FinishedWorkload",
                                    f"Workload '{wlpkg.key(wl)}' is declared finished")
            self._finalize_job(job)
            return None

        # 3. no workload yet
        if wl is None:
            return self._handle_job_with_no_workload(job, obj)

        # 4. reclaimable pods
        if hasattr(job, "reclaimable_pods"):
            recl = job.reclaimable_pods()
            if _reclaimable_as_dict(recl) != _reclaimable_as_dict(wl.status.reclaimable_pods):
                wl.status.reclaimable_pods = recl
                self.store.update(wl)
                return None

        # 5. PodsReady condition
        if self.wait_for_pods_ready:
            cond = self._pods_ready_condition(job, wl)
            existing = find_condition(wl.status.conditions, api.WORKLOAD_PODS_READY)
            if existing is None or existing.status != cond.status:
                set_condition(wl.status.conditions, cond, self.clock.now())
                self.store.update(wl)

        # 6. eviction
        ev = find_condition(wl.status.conditions, api.WORKLOAD_EVICTED)
        if ev is not None and ev.status == "True":
            self._stop_job(job, wl, STOP_REASON_WORKLOAD_EVICTED, ev.message)
            if wlpkg.has_quota_reservation(wl) and not job.is_active():
                # Requeued=True immediately only for preemption/check
                # evictions; other reasons wait for their own trigger
                # (reference: :443-449)
                set_requeued = ev.reason in (api.EVICTED_BY_PREEMPTION,
                                             api.EVICTED_BY_ADMISSION_CHECK)
                wlpkg.set_requeued_condition(wl, ev.reason, ev.message,
                                             set_requeued, self.clock.now())
                wlpkg.unset_quota_reservation_with_condition(
                    wl, "Pending", ev.message, self.clock.now())
                self.store.update(wl)
            return None

        # 7. suspended
        if job.is_suspended():
            if wlpkg.is_admitted(wl):
                return self._start_job(job, obj, wl)
            q = queue_name(job)
            if wl.spec.queue_name != q:
                wl.spec.queue_name = q
                self.store.update(wl)
            return None

        # 8. unsuspended but not admitted
        if not wlpkg.is_admitted(wl):
            self._stop_job(job, wl, STOP_REASON_NOT_ADMITTED,
                           "Not admitted by cluster queue")
        return None

    # -- helpers --------------------------------------------------------

    def _drop_finalizers(self, job, namespace: str, name: str):
        """Sim plays the k8s GC role: orphaned child workloads are deleted."""
        if job is not None and isinstance(job, ComposableJob):
            children = job.list_child_workloads(self.store)
        else:
            children = self._child_workloads(namespace, name)
        for wl in children:
            if api.RESOURCE_IN_USE_FINALIZER in wl.metadata.finalizers:
                wl.metadata.finalizers.remove(api.RESOURCE_IN_USE_FINALIZER)
                self.store.update(wl)
            try:
                self.store.delete("Workload", wl.metadata.namespace, wl.metadata.name)
            except KeyError:
                pass
        if job is not None:
            self._finalize_job(job)
        return None

    def _child_workloads(self, namespace: str, owner_name: str) -> list:
        kind = self.integration.kind
        return self.store.list(
            "Workload", namespace=namespace,
            where=lambda wl: any(o.controller and o.kind == kind and o.name == owner_name
                                 for o in wl.metadata.owner_references))

    def _parent_workload(self, owner: OwnerReference, namespace: str):
        wl_name = workload_name_for_owner(owner.name, owner.uid, owner.kind.lower())
        for wl in self.store.list("Workload", namespace=namespace):
            if any(o.controller and o.name == owner.name and o.kind == owner.kind
                   for o in wl.metadata.owner_references):
                return wl
        return None

    def _parent_job_managed(self, owner: OwnerReference, namespace: str) -> bool:
        from kueue_tpu.controller.jobframework.interface import _registry
        for cb in _registry.values():
            if cb.kind == owner.kind:
                parent = self.store.try_get(cb.kind, namespace, owner.name)
                if parent is not None and parent.metadata.labels.get(api.QUEUE_LABEL):
                    return True
        return False

    # -- ensureOneWorkload (reference: :563-665) ------------------------

    def _ensure_one_workload(self, job: GenericJob, obj):
        prebuilt = prebuilt_workload_for(job)
        if prebuilt is not None:
            wl = self.store.try_get("Workload", obj.metadata.namespace, prebuilt)
            if wl is None:
                return None
            if not self._ensure_prebuilt_ownership(wl, obj):
                return None
            if not self._prebuilt_in_sync(wl, job):
                # out-of-sync prebuilt workload: stop & deactivate
                # (reference: ensurePrebuiltWorkloadInSync -> Stop)
                self._stop_job(job, wl, STOP_REASON_NO_MATCHING_WORKLOAD,
                               "The prebuilt workload is out of sync with the job")
                return None
            return wl

        if isinstance(job, ComposableJob):
            match, to_delete = job.find_matching_workloads(self.store, self.recorder)
        else:
            match, to_delete = self._find_matching_workloads(job, obj)

        to_update = None
        if (match is None and to_delete and job.is_suspended()
                and not wlpkg.has_quota_reservation(to_delete[0])):
            to_update = to_delete[0]
            to_delete = to_delete[1:]

        if match is None and not job.is_suspended():
            w = to_delete[0] if len(to_delete) == 1 else None
            _, _, finished = job.finished()
            if not finished:
                msg = ("Missing Workload; unable to restore pod templates" if w is None
                       else "No matching Workload; restoring pod templates "
                            "according to existent Workload")
                self._stop_job(job, w, STOP_REASON_NO_MATCHING_WORKLOAD, msg)

        for wl in to_delete:
            self._remove_workload_finalizer(wl)
            try:
                self.store.delete("Workload", wl.metadata.namespace, wl.metadata.name)
                self.recorder.event(obj, "Normal", "DeletedWorkload",
                                    f"Deleted not matching Workload: {wlpkg.key(wl)}")
            except KeyError:
                pass

        if to_update is not None:
            return self._update_workload_to_match(job, obj, to_update)
        return match

    def _find_matching_workloads(self, job: GenericJob, obj):
        match = None
        to_delete = []
        for wl in self._child_workloads(obj.metadata.namespace, obj.metadata.name):
            if match is None and self._equivalent_to_workload(job, wl):
                match = wl
            else:
                to_delete.append(wl)
        return match, to_delete

    def _equivalent_to_workload(self, job: GenericJob, wl: api.Workload) -> bool:
        """reference: equivalentToWorkload (:753-777)."""
        job_podsets = job.pod_sets()
        running = self._expected_running_pod_sets(wl)
        if running is not None:
            if _compare_podsets(job_podsets, running, wlpkg.is_admitted(wl)):
                return True
            return job.is_suspended() and _compare_podsets(
                job_podsets, wl.spec.pod_sets, wlpkg.is_admitted(wl))
        return _compare_podsets(job_podsets, wl.spec.pod_sets, wlpkg.is_admitted(wl))

    def _expected_running_pod_sets(self, wl: api.Workload):
        """The pod sets as they look with admission info injected
        (reference: expectedRunningPodSets :724-751)."""
        if not wlpkg.has_quota_reservation(wl):
            return None
        try:
            infos = self._podsets_info_from_status(wl)
        except podsetpkg.PermanentError:
            return None
        info_map = {i.name: i for i in infos}
        out = []
        partial_ok = any(ps.min_count is not None for ps in wl.spec.pod_sets)
        for ps in wl.spec.pod_sets:
            info = info_map.get(ps.name)
            if info is None:
                return None
            clone = api.PodSet(name=ps.name, count=ps.count, min_count=ps.min_count,
                               template=_copy_template(ps.template))
            try:
                podsetpkg.merge_into_template(clone.template, info)
            except podsetpkg.PermanentError:
                return None
            if partial_ok and ps.min_count is not None:
                clone.count = info.count
            out.append(clone)
        return out

    def _update_workload_to_match(self, job: GenericJob, obj, wl: api.Workload):
        new_wl = self._construct_workload(job, obj)
        self._prepare_workload(job, new_wl)
        wl.spec = new_wl.spec
        self.store.update(wl)
        self.recorder.event(obj, "Normal", "UpdatedWorkload",
                            f"Updated not matching Workload for suspended job: "
                            f"{wlpkg.key(wl)}")
        return wl

    def _ensure_prebuilt_ownership(self, wl: api.Workload, obj) -> bool:
        if any(o.controller and o.uid == obj.metadata.uid
               for o in wl.metadata.owner_references):
            return True
        if any(o.controller for o in wl.metadata.owner_references):
            return False  # controlled by someone else
        wl.metadata.owner_references.append(OwnerReference(
            kind=self.integration.kind, name=obj.metadata.name,
            uid=obj.metadata.uid, controller=True))
        wl.metadata.labels[JOB_UID_LABEL] = obj.metadata.uid
        self.store.update(wl)
        return True

    def _prebuilt_in_sync(self, wl: api.Workload, job: GenericJob) -> bool:
        return self._equivalent_to_workload(job, wl)

    # -- construct / start / stop ---------------------------------------

    def _handle_job_with_no_workload(self, job: GenericJob, obj):
        if prebuilt_workload_for(job) is not None:
            self._stop_job(job, None, STOP_REASON_NO_MATCHING_WORKLOAD,
                           "missing workload")
            return None
        # wait for the job's pods to terminate before re-creating
        # (reference: handleJobWithNoWorkload waits on IsActive)
        if not job.is_suspended() and job.is_active():
            return 1.0
        if isinstance(job, ComposableJob):
            wl = job.construct_composable_workload(self.store, self.recorder)
            if wl is None:
                return None
        else:
            wl = self._construct_workload(job, obj)
        self._prepare_workload(job, wl)
        from kueue_tpu.sim import AlreadyExists, Invalid
        try:
            self.store.create(wl)
        except AlreadyExists:
            return True  # lost a race -> immediate retry
        except Invalid as exc:
            # webhook rejection: retrying won't change the outcome
            # (reference: unretryable error handling, reconciler.go:384-395)
            self.recorder.event(obj, "Warning", "FailedCreateWorkload", str(exc))
            return None
        self.recorder.event(obj, "Normal", "CreatedWorkload",
                            f"Created Workload: {wlpkg.key(wl)}")
        return None

    def _construct_workload(self, job: GenericJob, obj) -> api.Workload:
        from kueue_tpu.api.meta import ObjectMeta
        wl = api.Workload(metadata=ObjectMeta(
            name=workload_name_for_owner(obj.metadata.name, obj.metadata.uid,
                                         job.gvk()),
            namespace=obj.metadata.namespace,
            labels={k: v for k, v in obj.metadata.labels.items()
                    if k in self.label_keys_to_copy},
            finalizers=[api.RESOURCE_IN_USE_FINALIZER],
            owner_references=[OwnerReference(
                kind=self.integration.kind, name=obj.metadata.name,
                uid=obj.metadata.uid, controller=True)]))
        wl.metadata.labels[JOB_UID_LABEL] = obj.metadata.uid
        wl.spec.pod_sets = job.pod_sets()
        wl.spec.queue_name = queue_name(job)
        return wl

    def _prepare_workload(self, job: GenericJob, wl: api.Workload) -> None:
        """Priority: WorkloadPriorityClass > job PriorityClass > pod
        PriorityClass (reference: :936-962)."""
        pod_pc = ""
        if hasattr(job, "priority_class"):
            pod_pc = job.priority_class()
        if not pod_pc:
            for ps in wl.spec.pod_sets:
                if ps.template.spec.priority_class_name:
                    pod_pc = ps.template.spec.priority_class_name
                    break
        wpcs = {w.metadata.name: w for w in self.store.list(
            "WorkloadPriorityClass", copy_objects=False)}
        pcs = {p.metadata.name: p for p in self.store.list(
            "PriorityClass", copy_objects=False)}
        source, name, value = prioritypkg.priority_from_classes(
            pod_pc, workload_priority_class_name(job), wpcs, pcs)
        wl.spec.priority_class_source = source
        wl.spec.priority_class_name = name
        wl.spec.priority = value

    def _podsets_info_from_status(self, wl: api.Workload) -> list:
        """reference: getPodSetsInfoFromStatus (:964-1000)."""
        if wl.status.admission is None:
            return []
        flavors = {rf.metadata.name: rf for rf in self.store.list(
            "ResourceFlavor", copy_objects=False)}
        counts = {ps.name: ps.count for ps in wl.spec.pod_sets}
        infos = []
        for psa in wl.status.admission.pod_set_assignments:
            info = podsetpkg.from_assignment(psa, flavors, counts.get(psa.name, 0))
            for check in wl.status.admission_checks:
                for update in check.pod_set_updates:
                    if update.name == info.name:
                        info = podsetpkg.merge(info, podsetpkg.from_update(update))
                        break
            infos.append(info)
        return infos

    def _start_job(self, job: GenericJob, obj, wl: api.Workload):
        try:
            infos = self._podsets_info_from_status(wl)
        except podsetpkg.PermanentError as exc:
            self._fail_workload_start(wl, str(exc))
            return None
        msg = f"Admitted by clusterQueue {wl.status.admission.cluster_queue}"
        if isinstance(job, ComposableJob):
            job.run(self.store, infos, self.recorder, msg)
            return None
        try:
            job.run_with_podsets_info(infos)
        except podsetpkg.PermanentError as exc:
            self._fail_workload_start(wl, str(exc))
            return None
        self.store.update(job.object())
        self.recorder.event(obj, "Normal", "Started", msg)
        return None

    def _fail_workload_start(self, wl: api.Workload, message: str) -> None:
        set_condition(wl.status.conditions, Condition(
            type=api.WORKLOAD_FINISHED, status="True",
            reason=FAILED_TO_START_REASON, message=message,
            observed_generation=wl.metadata.generation), self.clock.now())
        self.store.update(wl)

    def _stop_job(self, job: GenericJob, wl: Optional[api.Workload],
                  reason: str, msg: str) -> None:
        infos = _podsets_info_from_workload(wl)
        if isinstance(job, ComposableJob):
            stopped = job.stop(self.store, infos, reason, msg)
            for o in stopped:
                self.recorder.event(o, "Normal", "Stopped", msg)
            return
        if hasattr(job, "stop"):
            if job.stop(self.store, infos, reason, msg):
                self.recorder.event(job.object(), "Normal", "Stopped", msg)
            return
        if job.is_suspended():
            return
        job.suspend()
        if infos:
            job.restore_podsets_info(infos)
        self.store.update(job.object())
        self.recorder.event(job.object(), "Normal", "Stopped", msg)

    def _finalize_job(self, job: GenericJob) -> None:
        if hasattr(job, "finalize"):
            job.finalize(self.store)

    def _remove_workload_finalizer(self, wl: api.Workload) -> None:
        if api.RESOURCE_IN_USE_FINALIZER in wl.metadata.finalizers:
            wl.metadata.finalizers.remove(api.RESOURCE_IN_USE_FINALIZER)
            try:
                self.store.update(wl)
            except KeyError:
                pass

    def _pods_ready_condition(self, job: GenericJob, wl: api.Workload) -> Condition:
        if wlpkg.is_admitted(wl) and (job.is_suspended() or not job.pods_ready()):
            return Condition(type=api.WORKLOAD_PODS_READY, status="False",
                             reason="PodsReady", message="Not all pods are ready or succeeded",
                             observed_generation=wl.metadata.generation)
        return Condition(type=api.WORKLOAD_PODS_READY,
                         status="True" if wlpkg.is_admitted(wl) else "False",
                         reason="PodsReady",
                         message="All pods were ready or succeeded since the workload admission"
                         if wlpkg.is_admitted(wl) else "Not all pods are ready or succeeded",
                         observed_generation=wl.metadata.generation)


def _podsets_info_from_workload(wl: Optional[api.Workload]) -> list:
    """The restore-side info: the original pod templates recorded in the
    workload spec (reference: GetPodSetsInfoFromWorkload)."""
    if wl is None:
        return []
    return [podsetpkg.snapshot_template(ps.name, ps.count, ps.template)
            for ps in wl.spec.pod_sets]


def _reclaimable_as_dict(pods: list) -> dict:
    return {rp.name: rp.count for rp in pods}


def _compare_podsets(a: list, b: list, admitted: bool) -> bool:
    """equality.ComparePodSetSlices: counts may differ pre-admission only
    via reclaim; templates compared on the scheduling-relevant fields."""
    if len(a) != len(b):
        return False
    for ps_a, ps_b in zip(a, b):
        if ps_a.name != ps_b.name:
            return False
        if admitted:
            if ps_a.count < ps_b.count:
                return False
        elif ps_a.count != ps_b.count:
            return False
        sa, sb = ps_a.template.spec, ps_b.template.spec
        if [(_c.requests, _c.limits) for _c in sa.containers] != \
           [(_c.requests, _c.limits) for _c in sb.containers]:
            return False
    return True


def _copy_template(template):
    import copy
    return copy.deepcopy(template)
