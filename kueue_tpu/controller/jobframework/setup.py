"""Wire enabled job integrations onto the sim runtime.

Equivalent of the reference's pkg/controller/jobframework/setup.go:53-155:
one JobReconciler-backed controller per enabled framework, watching the
job kind and re-enqueuing the owner on child Workload events.
"""

from __future__ import annotations

from typing import Optional

from kueue_tpu.api import kueue as api
from kueue_tpu.controller.jobframework.interface import get_integration
from kueue_tpu.controller.jobframework.reconciler import JobReconciler
from kueue_tpu.sim import DELETED


def setup_integrations(runtime, store, recorder, cfg, frameworks: Optional[list] = None):
    """Returns {framework name -> JobReconciler}. Unknown frameworks raise
    (the reference disables integrations whose CRDs are absent; every
    registered kind exists in the sim store by construction)."""
    enabled = {}
    names = list(frameworks if frameworks is not None
                 else cfg.integrations.frameworks)
    # expand dependencies (reference: DependencyList, e.g. deployment->pod)
    for name in list(names):
        cb = get_integration(name)
        if cb is None:
            raise ValueError(f"unknown integration {name!r} "
                             f"(is its module imported?)")
        for dep in cb.depends_on:
            if dep not in names:
                names.append(dep)

    w = cfg.wait_for_pods_ready
    for name in names:
        cb = get_integration(name)
        rec = JobReconciler(
            store, recorder, runtime.clock, cb,
            manage_jobs_without_queue_name=cfg.manage_jobs_without_queue_name,
            wait_for_pods_ready=bool(w and w.enable))
        ctrl = runtime.controller(f"job:{name}", rec.reconcile)

        def on_job(event, obj, old, _ctrl=ctrl, _cb=cb):
            if _cb.reconcile_key is not None:
                _ctrl.enqueue(_cb.reconcile_key(obj))
            else:
                _ctrl.enqueue(f"{obj.metadata.namespace}/{obj.metadata.name}")

        store.watch(cb.kind, on_job)
        enabled[name] = rec

    # child Workload events re-enqueue the owning job's reconciler
    kind_to_entry = {}
    for name in enabled:
        cb = get_integration(name)
        ctrl = runtime.controllers[
            [c.name for c in runtime.controllers].index(f"job:{name}")]
        kind_to_entry[cb.kind] = (cb, ctrl)

    def on_workload(event, wl, old):
        for owner in wl.metadata.owner_references:
            if owner.controller and owner.kind in kind_to_entry:
                cb, ctrl = kind_to_entry[owner.kind]
                if cb.reconcile_key_for_workload is not None:
                    ctrl.enqueue(cb.reconcile_key_for_workload(wl, owner))
                else:
                    ctrl.enqueue(f"{wl.metadata.namespace}/{owner.name}")

    store.watch("Workload", on_workload)
    return enabled
