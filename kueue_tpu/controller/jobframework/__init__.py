"""Job-integration framework: the GenericJob SPI + shared reconciler.

Equivalent of the reference's pkg/controller/jobframework
(interface.go:36-128, reconciler.go:204-1000, integrationmanager.go,
workload_names.go, setup.go). Integrations register via
`register_integration`; `setup_integrations` wires the enabled ones onto
the sim runtime.
"""

from kueue_tpu.controller.jobframework.interface import (
    GenericJob,
    ComposableJob,
    IntegrationCallbacks,
    register_integration,
    get_integration,
    integration_names,
    forget_integrations,
    STOP_REASON_WORKLOAD_DELETED,
    STOP_REASON_WORKLOAD_EVICTED,
    STOP_REASON_NO_MATCHING_WORKLOAD,
    STOP_REASON_NOT_ADMITTED,
)
from kueue_tpu.controller.jobframework.reconciler import JobReconciler
from kueue_tpu.controller.jobframework.workload_names import workload_name_for_owner
from kueue_tpu.controller.jobframework.setup import setup_integrations

__all__ = [
    "GenericJob", "ComposableJob", "IntegrationCallbacks",
    "register_integration", "get_integration", "integration_names",
    "forget_integrations",
    "JobReconciler", "workload_name_for_owner", "setup_integrations",
    "STOP_REASON_WORKLOAD_DELETED", "STOP_REASON_WORKLOAD_EVICTED",
    "STOP_REASON_NO_MATCHING_WORKLOAD", "STOP_REASON_NOT_ADMITTED",
]
