"""Deterministic Workload names for owner jobs.

Equivalent of the reference's pkg/controller/jobframework/workload_names.go:
"<kind>-<jobname>-<hash suffix>" truncated to a DNS label.
"""

from __future__ import annotations

import hashlib

MAX_NAME_LENGTH = 63
HASH_LENGTH = 5


def workload_name_for_owner(owner_name: str, owner_uid: str, gvk: str) -> str:
    kind = gvk.rsplit("/", 1)[-1].lower()
    digest = hashlib.sha256(f"{gvk}/{owner_name}/{owner_uid}".encode()).hexdigest()
    suffix = digest[:HASH_LENGTH]
    prefix = f"{kind}-{owner_name}"
    if len(prefix) > MAX_NAME_LENGTH - HASH_LENGTH - 1:
        prefix = prefix[: MAX_NAME_LENGTH - HASH_LENGTH - 1]
    return f"{prefix}-{suffix}"
