"""LocalQueueReconciler: status + Active condition + StopPolicy.

Equivalent of the reference's pkg/controller/core/localqueue_controller.go:
status counts (pending from queue manager, reserving/admitted + flavor
usage from cache), Active condition gated on the target ClusterQueue's
existence/active state and the LQ's own StopPolicy.
"""

from __future__ import annotations

import copy as _copy

from typing import Optional

from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import Condition, set_condition
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.sim import ADDED, DELETED, Store
from kueue_tpu.sim.runtime import EventRecorder


class LocalQueueReconciler:
    def __init__(self, store: Store, queues, cache, recorder: EventRecorder,
                 clock, metrics=None):
        self.store = store
        self.queues = queues
        self.cache = cache
        self.recorder = recorder
        self.clock = clock
        self.metrics = metrics
        self._last_sig: dict = {}  # lq key -> last written status inputs
        from kueue_tpu.controller.core.status_usage import FlavorUsageCache
        self._usage_cache = FlavorUsageCache()

    def reconcile(self, key: str):
        namespace, name = key.split("/", 1)
        lq = self.store.try_get("LocalQueue", namespace, name,
                                copy_object=False)
        if lq is None:
            return None
        cq = self.store.try_get("ClusterQueue", "", lq.spec.cluster_queue,
                                copy_object=False)
        # Cheap change signature: most LQ reconciles at scale are fan-out
        # echoes of unrelated events — skip the full status rebuild (and
        # its no-op update_status compare) when the inputs are unchanged.
        # The CQ's resourceVersion covers spec changes (the flavor usage
        # rows are built from cq.spec).
        usage0 = self.cache.local_queue_usage(lq)
        sig = (lq.metadata.resource_version,
               cq.metadata.resource_version if cq is not None else None,
               self.queues.pending_workloads_in_local_queue(key),
               self.cache.cluster_queue_active(lq.spec.cluster_queue),
               usage0.version if usage0 is not None else None)
        if self._last_sig.get(key) == sig:
            return None
        self._last_sig[key] = sig
        status_obj = _copy.copy(lq)
        status_obj.status = api.LocalQueueStatus(
            conditions=[_copy.copy(c) for c in lq.status.conditions])
        lq = status_obj
        now = self.clock.now()
        if lq.spec.stop_policy != api.STOP_POLICY_NONE:
            cond = Condition(type=api.LOCAL_QUEUE_ACTIVE, status="False",
                             reason="Stopped", message="LocalQueue is stopped",
                             observed_generation=lq.metadata.generation)
        else:
            if cq is None:
                cond = Condition(
                    type=api.LOCAL_QUEUE_ACTIVE, status="False",
                    reason="ClusterQueueDoesNotExist",
                    message="Can't submit new workloads to clusterQueue",
                    observed_generation=lq.metadata.generation)
            elif not self.cache.cluster_queue_active(lq.spec.cluster_queue):
                cond = Condition(
                    type=api.LOCAL_QUEUE_ACTIVE, status="False",
                    reason="ClusterQueueIsInactive",
                    message="Can't submit new workloads to clusterQueue",
                    observed_generation=lq.metadata.generation)
            else:
                cond = Condition(type=api.LOCAL_QUEUE_ACTIVE, status="True",
                                 reason="Ready", message="Can submit new workloads to clusterQueue",
                                 observed_generation=lq.metadata.generation)
        set_condition(lq.status.conditions, cond, now)

        lq.status.pending_workloads = self.queues.pending_workloads_in_local_queue(key)
        usage = self.cache.local_queue_usage(lq)
        if usage is not None:
            lq.status.reserving_workloads = usage.reserving_workloads
            lq.status.admitted_workloads = usage.admitted_workloads
            if cq is not None:
                lq.status.flavors_reservation = self._usage_cache.build(
                    key, "resv", cq.spec, usage.usage, borrowed=False)
                lq.status.flavors_usage = self._usage_cache.build(
                    key, "adm", cq.spec, usage.admitted_usage, borrowed=False)
        else:
            lq.status.reserving_workloads = 0
            lq.status.admitted_workloads = 0
        self.store.update_status(lq, owned_status=True)
        return None

    # -- watch handlers -------------------------------------------------

    def handle_event(self, event: str, lq: api.LocalQueue,
                     old: Optional[api.LocalQueue], enqueue) -> None:
        key = f"{lq.metadata.namespace}/{lq.metadata.name}"
        if event == ADDED:
            workloads = self.store.list(
                "Workload", namespace=lq.metadata.namespace,
                where=lambda wl: wl.spec.queue_name == lq.metadata.name
                and not wlpkg.is_finished(wl))
            self.queues.add_local_queue(lq, workloads)
            self.cache.add_local_queue(lq)
        elif event == DELETED:
            self.queues.delete_local_queue(lq)
            self.cache.delete_local_queue(lq)
            self._last_sig.pop(key, None)
            self._usage_cache.forget(key)
            return
        else:
            if old is not None and old.spec.cluster_queue != lq.spec.cluster_queue:
                self.cache.delete_local_queue(old)
                self.cache.add_local_queue(lq)
            self.queues.update_local_queue(lq)
        enqueue(key)



