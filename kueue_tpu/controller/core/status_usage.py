"""Cached FlavorUsage status construction.

The CQ and LQ reconcilers rebuild their status flavor-usage lists
(spec-ordered, every flavor × resource — reference:
clusterqueue_controller.go:372-418) on every usage change. At the
north-star scale that is 2k queues × 32 flavors × 2 resources × 2
lists per cycle — millions of dataclass allocations per run, and the
profile's top control-plane cost.

Per cycle, though, only the few flavors a wave actually landed in
change; the rest of the list is bit-identical to the previous build.
This cache reuses the previous FlavorUsage object whenever a flavor's
(usage, quota) signature is unchanged. Status objects are read-only by
convention once written (the same informer-style contract the store's
watch fan-out relies on), so sharing children across successive status
objects is safe — and makes the store's no-op status compare faster,
since list equality short-circuits on element identity.
"""

from __future__ import annotations

from kueue_tpu.api import kueue as api


class FlavorUsageCache:
    def __init__(self):
        # owner key -> {(flavor, tag): (signature, FlavorUsage)}
        self._by_owner: dict = {}

    def forget(self, owner: str) -> None:
        self._by_owner.pop(owner, None)

    def build(self, owner: str, tag: str, spec: api.ClusterQueueSpec,
              usage: dict, borrowed: bool) -> list:
        """FlavorResource dict -> status FlavorUsage list in spec order;
        borrowed=True also reports usage above nominal (the CQ status
        form; LQ statuses report totals only).

        The change signature per flavor is (the FlavorQuotas object
        identity, that flavor's nonzero usage): quota values can only
        change through a spec write, which replaces the spec subtree and
        so the FlavorQuotas objects (the cache entry holds a strong ref,
        so the identity can't be recycled) — and grouping only the
        NONZERO usage entries first makes the common case (a wave lands
        in a few flavors; the other 30 are untouched) cost one dict hit
        per flavor instead of a quota-by-quota tuple build."""
        cache = self._by_owner.setdefault(owner, {})
        by_flavor: dict = {}
        for (fname, rname), v in usage.items():
            if v:
                by_flavor.setdefault(fname, {})[rname] = v
        # Whole-list fast path: in steady state (a finish returns what
        # the next admission takes), usage at reconcile time is often
        # bit-identical to the previous build even though the outer
        # change signature moved (pending counts, interleaved writes).
        whole = cache.get(("", tag))
        if whole is not None and whole[0] is spec and whole[1] == by_flavor:
            return whole[2]
        out = []
        for rg in spec.resource_groups:
            for fq in rg.flavors:
                nz = by_flavor.get(fq.name)
                k = (fq.name, tag)
                hit = cache.get(k)
                if hit is not None and hit[0] is fq and hit[1] == nz:
                    out.append(hit[2])
                    continue
                resources = []
                for q in fq.resources:
                    used = nz.get(q.name, 0) if nz else 0
                    resources.append(api.ResourceUsage(
                        name=q.name, total=used,
                        borrowed=(max(0, used - q.nominal_quota)
                                  if borrowed else 0)))
                fu = api.FlavorUsage(name=fq.name, resources=resources)
                cache[k] = (fq, nz, fu)
                out.append(fu)
        cache[("", tag)] = (spec, by_flavor, out)
        return out
