"""WorkloadReconciler: the Workload lifecycle state machine.

Equivalent of the reference's
pkg/controller/core/workload_controller.go:136-552 plus its watch event
handlers (:554-757):
- orphan finalizer GC
- deactivation (spec.active=false) -> eviction; DeactivationTarget handling
- Requeued condition management (reactivation, backoff-finished,
  LocalQueue/ClusterQueue restart)
- admission-check state seeding per CQ strategy + check-based eviction
  (Retry -> evict, Rejected -> deactivate)
- SyncAdmittedCondition once QuotaReserved and all checks Ready
- LQ/CQ existence + stop-policy gating (Inadmissible condition, drain
  evictions under HoldAndDrain)
- PodsReady timeout eviction with exponential requeue backoff and
  backoffLimitCount deactivation (:486-552)
- watch handlers feeding queue.Manager / cache.Cache exactly per the
  status-transition matrix (:560-757)
"""

from __future__ import annotations

import random
import time as _time
from typing import Optional

from kueue_tpu import config as cfgpkg
from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import find_condition, is_condition_true, remove_condition
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.sim import ADDED, DELETED, MODIFIED, NotFound, Store
from kueue_tpu.sim.runtime import EventRecorder


class WorkloadReconciler:
    def __init__(self, store: Store, queues, cache, recorder: EventRecorder,
                 clock, cfg: Optional[cfgpkg.Configuration] = None, metrics=None,
                 watchers: Optional[list] = None,
                 rng: Optional[random.Random] = None, obs_recorder=None,
                 journeys=None):
        self.store = store
        self.queues = queues
        self.cache = cache
        self.recorder = recorder
        self.clock = clock
        self.cfg = cfg or cfgpkg.Configuration()
        self.metrics = metrics
        # Optional obs FlightRecorder: the per-event spans inside a
        # reconcile (reconcile.workload.{event}) land in whatever cycle
        # trace is open (no-op otherwise — same disabled contract as
        # every recorder hook).
        self.obs_recorder = obs_recorder
        # Optional obs JourneyLedger: check-gated admissions and
        # evictions stamp the workload's journey, and the admission
        # wait-time histograms are fed FROM the ledger's seal (one
        # emission site — ISSUE 14). None = direct metrics calls.
        self.journeys = journeys
        # seeded for reproducible backoff jitter in the deterministic sim
        self.rng = rng or random.Random(0)
        # MultiKueue et al. observe workload transitions (reference:
        # workload_controller.go notifyWatchers).
        self.watchers = watchers if watchers is not None else []

    # ------------------------------------------------------------------
    # reconcile
    # ------------------------------------------------------------------

    def reconcile(self, key: str):
        namespace, name = key.split("/", 1)
        # Shared read for the no-write early exits (most reconciles are
        # fan-out echoes of finished/stable workloads); clone only once
        # a mutating path is possible.
        shared = self.store.try_get("Workload", namespace, name,
                                    copy_object=False)
        if shared is None:
            return None
        if wlpkg.is_finished(shared) \
                and shared.metadata.deletion_timestamp is None:
            return None
        wl = api.clone_workload(shared)
        now = self.clock.now()

        # orphan GC (reference: :146-148)
        if not wl.metadata.owner_references and wl.metadata.deletion_timestamp is not None:
            if api.RESOURCE_IN_USE_FINALIZER in wl.metadata.finalizers:
                wl.metadata.finalizers.remove(api.RESOURCE_IN_USE_FINALIZER)
                self.store.update(wl)
            return None

        if wlpkg.is_finished(wl):
            return None

        if wlpkg.is_active(wl):
            if is_condition_true(wl.status.conditions, api.WORKLOAD_DEACTIVATION_TARGET):
                wl.spec.active = False
                self.store.update(wl)
                return None
            requeued = find_condition(wl.status.conditions, api.WORKLOAD_REQUEUED)
            if requeued is not None and requeued.status == "False":
                if requeued.reason == api.EVICTED_BY_DEACTIVATION:
                    wlpkg.set_requeued_condition(
                        wl, api.WORKLOAD_REACTIVATED,
                        "The workload was reactivated", True, now)
                    self.store.update(wl)
                    return None
                if requeued.reason == api.EVICTED_BY_PODS_READY_TIMEOUT:
                    rs = wl.status.requeue_state
                    if rs is not None and rs.requeue_at is not None:
                        remaining = rs.requeue_at - now
                        if remaining > 0:
                            return remaining
                        rs.requeue_at = None
                    wlpkg.set_requeued_condition(
                        wl, api.WORKLOAD_BACKOFF_FINISHED,
                        "The workload backoff was finished", True, now)
                    self.store.update(wl)
                    return None
        else:
            # deactivated -> evict (reference: :186-215)
            if self._event_span("deactivation",
                                self._reconcile_deactivation, wl, now):
                return None

        lq = self.store.try_get("LocalQueue", wl.metadata.namespace,
                                wl.spec.queue_name, copy_object=False)
        lq_exists = lq is not None
        lq_active = lq_exists and lq.spec.stop_policy == api.STOP_POLICY_NONE
        if lq_exists and lq_active and _requeued_disabled_by(wl, api.EVICTED_BY_LOCAL_QUEUE_STOPPED):
            wlpkg.set_requeued_condition(
                wl, api.WORKLOAD_LOCAL_QUEUE_RESTARTED,
                "The LocalQueue was restarted after being stopped", True, now)
            self.store.update(wl)
            return None

        cq_name = self.queues.cluster_queue_for_workload(wl)
        if cq_name is not None:
            cq = self.store.try_get("ClusterQueue", "", cq_name,
                                    copy_object=False)
            if cq is not None:
                if (_requeued_disabled_by(wl, api.EVICTED_BY_CLUSTER_QUEUE_STOPPED)
                        and cq.spec.stop_policy == api.STOP_POLICY_NONE):
                    wlpkg.set_requeued_condition(
                        wl, api.WORKLOAD_CLUSTER_QUEUE_RESTARTED,
                        "The ClusterQueue was restarted after being stopped", True, now)
                    self.store.update(wl)
                    return None
                if self._event_span("admission-checks",
                                    self._sync_admission_checks,
                                    wl, cq, now):
                    return None

        # Admitted flips to True only here, once all checks are Ready
        # (reference: :252-268)
        if not wlpkg.is_admitted(wl) and self._event_span(
                "sync-admitted", wlpkg.sync_admitted_condition, wl, now):
            self.store.update(wl)
            if wlpkg.is_admitted(wl):
                qr = find_condition(wl.status.conditions, api.WORKLOAD_QUOTA_RESERVED)
                checks_wait = now - qr.last_transition_time if qr else 0.0
                self.recorder.event(
                    wl, "Normal", "Admitted",
                    f"Admitted by ClusterQueue {wl.status.admission.cluster_queue}, "
                    f"wait time since reservation was {checks_wait:.0f}s")
                if self.journeys is not None:
                    # THE emission site for check-gated admission SLIs
                    # (ISSUE 14 reconcile-by-construction): the ledger
                    # observes admission_wait_time +
                    # admission_checks_wait_time and seals the journey.
                    self.journeys.admitted_after_checks(
                        wl, cq_name or "",
                        wlpkg.queued_wait_time(wl, now), checks_wait)
                elif self.metrics and cq_name:
                    self.metrics.admitted_workload(cq_name, wlpkg.queued_wait_time(wl, now))
                    self.metrics.admission_checks_wait_time.observe(
                        checks_wait, cluster_queue=cq_name)
            return None

        if wlpkg.has_quota_reservation(wl):
            if self._event_span("check-eviction",
                                self._reconcile_check_based_eviction,
                                wl, cq_name, now):
                return None
            if self._event_span("lq-active", self._reconcile_lq_active_state,
                                wl, lq, lq_exists, now):
                return None
            if cq_name is not None and self._event_span(
                    "cq-active", self._reconcile_cq_active_state,
                    wl, cq_name, now):
                return None
            return self._event_span("pods-ready-timeout",
                                    self._reconcile_not_ready_timeout,
                                    wl, cq_name, now)

        # Eviction completed (no reservation): retryable/stale check
        # states return to Pending so the next admission re-runs them
        # (reference: ResetChecksOnEviction). Without this a MultiKueue
        # worker-lost Retry would livelock evict/requeue, and a stale
        # Ready could admit a re-reserved workload no worker holds.
        if wl.status.admission_checks and self._event_span(
                "reset-checks", wlpkg.reset_checks_after_eviction, wl, now):
            self.store.update(wl)
            return None

        # pending: surface why the workload can't queue (reference: :285-330)
        msg = None
        if not lq_exists:
            msg = f"LocalQueue {wl.spec.queue_name} doesn't exist"
        elif not lq_active:
            msg = f"LocalQueue {wl.spec.queue_name} is inactive"
        elif cq_name is None:
            msg = f"ClusterQueue {lq.spec.cluster_queue} doesn't exist"
        elif not self.cache.cluster_queue_active(cq_name):
            msg = f"ClusterQueue {cq_name} is inactive"
        if msg is not None:
            if wlpkg.unset_quota_reservation_with_condition(
                    wl, api.WORKLOAD_INADMISSIBLE, msg, now):
                self.store.update(wl)
        return None


    # -- per-event observability (PR-5 follow-up) -----------------------

    def _event_span(self, name: str, fn, *args):
        """Time one event handler inside the reconcile: feeds the
        reconcile_event_seconds{controller,event} histogram (the
        per-event split of the coarse reconcile_seconds series) and
        emits a nested flight-recorder span
        (``reconcile.workload.{event}`` — dotted, so cycle phase sums
        never double-count it) when a cycle trace is open. Without
        metrics/recorder this is the plain call."""
        if self.metrics is None and self.obs_recorder is None:
            return fn(*args)
        t0 = _time.perf_counter()
        out = fn(*args)
        dt = _time.perf_counter() - t0
        if self.metrics is not None:
            self.metrics.reconcile_event("workload", name, dt)
        if self.obs_recorder is not None:
            self.obs_recorder.span(f"reconcile.workload.{name}", t0, dt)
        return out

    # -- pieces ---------------------------------------------------------

    def _reconcile_deactivation(self, wl: api.Workload, now: float) -> bool:
        updated = evicted = False
        reason = api.EVICTED_BY_DEACTIVATION
        message = "The workload is deactivated"
        dt = find_condition(wl.status.conditions, api.WORKLOAD_DEACTIVATION_TARGET)
        if not wlpkg.is_evicted(wl):
            if dt is not None:
                reason += dt.reason
                message = f"{message} due to {dt.message}"
            wlpkg.set_evicted_condition(wl, reason, message, now)
            updated = evicted = True
        if dt is not None:
            remove_condition(wl.status.conditions, api.WORKLOAD_DEACTIVATION_TARGET)
            updated = True
        if wl.status.requeue_state is not None:
            wl.status.requeue_state = None
            updated = True
        if updated:
            self.store.update(wl)
            if evicted and wl.status.admission is not None:
                self._report_evicted(wl, wl.status.admission.cluster_queue, reason, message)
            return True
        return False

    def _sync_admission_checks(self, wl: api.Workload, cq: api.ClusterQueue,
                               now: float) -> bool:
        from kueue_tpu.cache.clusterqueue import admission_checks_map
        checks = wlpkg.admission_checks_for_workload(wl, admission_checks_map(cq.spec))
        if wlpkg.sync_admission_check_conditions(wl, checks, now):
            self.store.update(wl)
            return True
        return False

    def _reconcile_check_based_eviction(self, wl: api.Workload,
                                        cq_name: Optional[str], now: float) -> bool:
        if wlpkg.is_evicted(wl):
            return False
        if not wlpkg.has_retry_checks(wl) and not wlpkg.has_rejected_checks(wl):
            return False
        if wlpkg.has_rejected_checks(wl):
            rejected = [c for c in wl.status.admission_checks
                        if c.state == api.CHECK_STATE_REJECTED][0]
            wl.spec.active = False
            self.store.update(wl)
            self.recorder.event(
                wl, "Warning", "AdmissionCheckRejected",
                f"Deactivating workload because AdmissionCheck for {rejected.name} "
                f"was Rejected: {rejected.message}")
            return True
        message = "At least one admission check is false"
        wlpkg.set_evicted_condition(wl, api.EVICTED_BY_ADMISSION_CHECK, message, now)
        self.store.update(wl)
        self._report_evicted(wl, cq_name or "", api.EVICTED_BY_ADMISSION_CHECK, message)
        return True

    def _reconcile_lq_active_state(self, wl: api.Workload, lq, lq_exists: bool,
                                   now: float) -> bool:
        stop = lq.spec.stop_policy if lq_exists else api.STOP_POLICY_NONE
        if wlpkg.is_admitted(wl):
            if stop != api.HOLD_AND_DRAIN or wlpkg.is_evicted(wl):
                return False
            wlpkg.set_evicted_condition(
                wl, api.EVICTED_BY_LOCAL_QUEUE_STOPPED, "The LocalQueue is stopped", now)
            self.store.update(wl)
            self._report_evicted(wl, lq.spec.cluster_queue,
                                 api.EVICTED_BY_LOCAL_QUEUE_STOPPED,
                                 "The LocalQueue is stopped")
            return True
        if not lq_exists or lq.metadata.deletion_timestamp is not None:
            wlpkg.unset_quota_reservation_with_condition(
                wl, api.WORKLOAD_INADMISSIBLE,
                f"LocalQueue {wl.spec.queue_name} is terminating or missing", now)
            self.store.update(wl)
            return True
        if stop != api.STOP_POLICY_NONE:
            wlpkg.unset_quota_reservation_with_condition(
                wl, api.WORKLOAD_INADMISSIBLE,
                f"LocalQueue {wl.spec.queue_name} is stopped", now)
            self.store.update(wl)
            return True
        return False

    def _reconcile_cq_active_state(self, wl: api.Workload, cq_name: str,
                                   now: float) -> bool:
        cq = self.store.try_get("ClusterQueue", "", cq_name,
                                copy_object=False)
        stop = cq.spec.stop_policy if cq is not None else api.STOP_POLICY_NONE
        if wlpkg.is_admitted(wl):
            if cq is None or stop != api.HOLD_AND_DRAIN or wlpkg.is_evicted(wl):
                return False
            wlpkg.set_evicted_condition(
                wl, api.EVICTED_BY_CLUSTER_QUEUE_STOPPED, "The ClusterQueue is stopped", now)
            self.store.update(wl)
            self._report_evicted(wl, cq_name, api.EVICTED_BY_CLUSTER_QUEUE_STOPPED,
                                 "The ClusterQueue is stopped")
            return True
        if cq is None or cq.metadata.deletion_timestamp is not None:
            wlpkg.unset_quota_reservation_with_condition(
                wl, api.WORKLOAD_INADMISSIBLE,
                f"ClusterQueue {cq_name} is terminating or missing", now)
            self.store.update(wl)
            return True
        if stop != api.STOP_POLICY_NONE:
            wlpkg.unset_quota_reservation_with_condition(
                wl, api.WORKLOAD_INADMISSIBLE, f"ClusterQueue {cq_name} is stopped", now)
            self.store.update(wl)
            return True
        return False

    # -- PodsReady timeout (reference: :486-552, :778-802) --------------

    def _reconcile_not_ready_timeout(self, wl: api.Workload,
                                     cq_name: Optional[str], now: float):
        if not wlpkg.is_active(wl) or wlpkg.is_evicted(wl):
            return None
        counting, recheck_after = self._admitted_not_ready(wl, now)
        if not counting:
            return None
        if recheck_after > 0:
            return recheck_after
        if self._trigger_deactivation_or_backoff(wl, now):
            return None
        message = f"Exceeded the PodsReady timeout {wl.metadata.namespace}/{wl.metadata.name}"
        wlpkg.set_evicted_condition(wl, api.EVICTED_BY_PODS_READY_TIMEOUT, message, now)
        self.store.update(wl)
        self._report_evicted(wl, cq_name or "", api.EVICTED_BY_PODS_READY_TIMEOUT, message)
        return None

    def _admitted_not_ready(self, wl: api.Workload, now: float):
        w = self.cfg.wait_for_pods_ready
        if w is None or not w.enable:
            return False, 0.0
        if not wlpkg.is_admitted(wl):
            return False, 0.0
        pods_ready = find_condition(wl.status.conditions, api.WORKLOAD_PODS_READY)
        if pods_ready is not None and pods_ready.status == "True":
            return False, 0.0
        admitted = find_condition(wl.status.conditions, api.WORKLOAD_ADMITTED)
        elapsed = now - admitted.last_transition_time
        if (pods_ready is not None and pods_ready.status == "False"
                and pods_ready.last_transition_time > admitted.last_transition_time):
            elapsed = now - pods_ready.last_transition_time
        return True, max(0.0, w.timeout_seconds - elapsed)

    def _trigger_deactivation_or_backoff(self, wl: api.Workload, now: float) -> bool:
        w = self.cfg.wait_for_pods_ready
        rs = wl.status.requeue_state or api.RequeueState()
        count = rs.count + 1
        strategy = w.requeuing_strategy
        if (strategy.backoff_limit_count is not None
                and count > strategy.backoff_limit_count):
            wlpkg.set_deactivation_target(
                wl, api.WORKLOAD_REQUEUING_LIMIT_EXCEEDED,
                "exceeding the maximum number of re-queuing retries", now)
            self.store.update(wl)
            return True
        # 60s * 2^(n-1) + jitter, capped (reference: :530-548)
        backoff = min(strategy.backoff_base_seconds * 2 ** (count - 1),
                      strategy.backoff_max_seconds)
        backoff *= 1.0 + strategy.backoff_jitter * self.rng.random()
        rs.requeue_at = now + backoff
        rs.count = count
        wl.status.requeue_state = rs
        return False

    def _report_evicted(self, wl: api.Workload, cq_name: str, reason: str,
                        message: str) -> None:
        self.recorder.event(wl, "Normal", "EvictedDueTo" + reason, message)
        if self.metrics and cq_name:
            self.metrics.report_evicted_workload(cq_name, reason)
        if self.journeys is not None:
            # Eviction re-opens the journey: the requeue/re-admission
            # loop it starts is part of the workload's admission story.
            self.journeys.evicted(wlpkg.key(wl), cq_name, reason)

    # ------------------------------------------------------------------
    # watch handlers feeding queues + cache (reference: :554-757)
    # ------------------------------------------------------------------

    def handle_event(self, event: str, wl: api.Workload,
                     old: Optional[api.Workload], enqueue) -> None:
        if event == ADDED:
            self._on_create(wl)
        elif event == DELETED:
            self._on_delete(wl)
        else:
            self._on_update(old, wl, enqueue)
        for watcher in self.watchers:
            watcher(old if event != ADDED else None,
                    wl if event != DELETED else None)
        enqueue(wlpkg.key(wl))

    def _on_create(self, wl: api.Workload) -> None:
        if wlpkg.status(wl) == wlpkg.STATUS_FINISHED:
            return
        if not wlpkg.has_quota_reservation(wl):
            self.queues.add_or_update_workload(wl)
        else:
            self.cache.add_or_update_workload(wl)

    def _on_delete(self, wl: api.Workload) -> None:
        if wlpkg.has_quota_reservation(wl):
            self.queues.queue_associated_inadmissible_workloads_after(
                wl, lambda: self.cache.delete_workload(wl))
        self.queues.delete_workload(wl)

    def _on_update(self, old: api.Workload, wl: api.Workload, enqueue) -> None:
        prev_status = wlpkg.status(old)
        status = wlpkg.status(wl)
        active = wlpkg.is_active(wl)
        if status == wlpkg.STATUS_FINISHED or not active:
            self.queues.delete_workload(wl)
            self.queues.queue_associated_inadmissible_workloads_after(
                old, lambda: self.cache.delete_workload(old))
        elif prev_status == wlpkg.STATUS_PENDING and status == wlpkg.STATUS_PENDING:
            self.queues.update_workload(old, wl)
        elif prev_status == wlpkg.STATUS_PENDING:
            self.queues.delete_workload(old)
            self.cache.add_or_update_workload(wl)
        elif status == wlpkg.STATUS_PENDING:
            rs = wl.status.requeue_state
            backoff = (rs.requeue_at - self.clock.now()) if rs and rs.requeue_at else 0.0
            # pass `old` — the new object's admission is already cleared,
            # and the cohort flush needs the releasing CQ from it
            self.queues.queue_associated_inadmissible_workloads_after(
                old, lambda: self.cache.delete_workload(wl))
            if backoff <= 0:
                self.queues.add_or_update_workload(wl)
            # else: the reconcile loop re-queues after the backoff expires
            # (Requeued=BackoffFinished), replacing the reference's
            # time.AfterFunc (:700-713).
        elif (prev_status == wlpkg.STATUS_ADMITTED and status == wlpkg.STATUS_ADMITTED
              and old.status.reclaimable_pods != wl.status.reclaimable_pods):
            self.queues.queue_associated_inadmissible_workloads_after(
                wl, lambda: self.cache.add_or_update_workload(wl))
        else:
            self.cache.add_or_update_workload(wl)


def _requeued_disabled_by(wl: api.Workload, reason: str) -> bool:
    cond = find_condition(wl.status.conditions, api.WORKLOAD_REQUEUED)
    return cond is not None and cond.status == "False" and cond.reason == reason
