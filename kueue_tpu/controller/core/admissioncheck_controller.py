"""AdmissionCheckReconciler + ResourceFlavorReconciler.

Equivalents of the reference's
pkg/controller/core/admissioncheck_controller.go (Active condition per
registered check controller, cache sync, CQ re-activation fan-out) and
pkg/controller/core/resourceflavor_controller.go (in-use finalizer while
any ClusterQueue references the flavor, cache sync).
"""

from __future__ import annotations

from typing import Optional

from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import Condition, set_condition
from kueue_tpu.sim import ADDED, DELETED, Store
from kueue_tpu.sim.runtime import EventRecorder


class AdmissionCheckReconciler:
    """An AdmissionCheck is Active iff a controller is registered for its
    spec.controller_name (the reference checks this lazily via the
    controllers' own status updates; here registration is explicit)."""

    def __init__(self, store: Store, queues, cache, recorder: EventRecorder,
                 clock, registered_controllers: Optional[set] = None):
        self.store = store
        self.queues = queues
        self.cache = cache
        self.recorder = recorder
        self.clock = clock
        self.registered_controllers = registered_controllers if \
            registered_controllers is not None else set()

    def reconcile(self, key: str):
        ac = self.store.try_get("AdmissionCheck", "", key)
        if ac is None:
            return None
        now = self.clock.now()
        if ac.spec.controller_name in self.registered_controllers:
            cond = Condition(type=api.ADMISSION_CHECK_ACTIVE, status="True",
                             reason="Active",
                             message="The admission check is active",
                             observed_generation=ac.metadata.generation)
        else:
            cond = Condition(type=api.ADMISSION_CHECK_ACTIVE, status="False",
                             reason="ControllerNotRegistered",
                             message=f"No controller registered for "
                                     f"{ac.spec.controller_name!r}",
                             observed_generation=ac.metadata.generation)
        if set_condition(ac.status.conditions, cond, now):
            self.store.update(ac)
        return None

    def handle_event(self, event: str, ac: api.AdmissionCheck,
                     old: Optional[api.AdmissionCheck], enqueue) -> None:
        if event == DELETED:
            affected = self.cache.delete_admission_check(ac.metadata.name)
        else:
            affected = self.cache.add_or_update_admission_check(ac)
            enqueue(ac.metadata.name)
        # CQs whose active state flipped need re-queueing of parked work
        if affected:
            self.queues.queue_inadmissible_workloads(affected)


class ResourceFlavorReconciler:
    """Finalizer lifecycle: the flavor keeps the in-use finalizer while any
    ClusterQueue references it (reference: resourceflavor_controller.go)."""

    def __init__(self, store: Store, queues, cache, recorder: EventRecorder, clock):
        self.store = store
        self.queues = queues
        self.cache = cache
        self.recorder = recorder
        self.clock = clock

    def reconcile(self, key: str):
        rf = self.store.try_get("ResourceFlavor", "", key)
        if rf is None:
            return None
        in_use = self._flavor_in_use(key)
        if rf.metadata.deletion_timestamp is not None:
            if not in_use and api.RESOURCE_IN_USE_FINALIZER in rf.metadata.finalizers:
                rf.metadata.finalizers.remove(api.RESOURCE_IN_USE_FINALIZER)
                self.store.update(rf)
            return None
        if api.RESOURCE_IN_USE_FINALIZER not in rf.metadata.finalizers:
            rf.metadata.finalizers.append(api.RESOURCE_IN_USE_FINALIZER)
            self.store.update(rf)
        return None

    def _flavor_in_use(self, name: str) -> bool:
        for cq in self.store.list("ClusterQueue", copy_objects=False):
            for rg in cq.spec.resource_groups:
                if any(fq.name == name for fq in rg.flavors):
                    return True
        return False

    def handle_event(self, event: str, rf: api.ResourceFlavor,
                     old: Optional[api.ResourceFlavor], enqueue) -> None:
        if event == DELETED:
            affected = self.cache.delete_resource_flavor(rf.metadata.name)
        else:
            affected = self.cache.add_or_update_resource_flavor(rf)
            enqueue(rf.metadata.name)
        if affected:
            self.queues.queue_inadmissible_workloads(affected)
