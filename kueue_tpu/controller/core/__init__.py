"""Core controller wiring.

Equivalent of the reference's pkg/controller/core/core.go:36-112
(SetupControllers) plus the watch registrations each reconciler's
SetupWithManager performs: store watch events feed the queue manager and
cache (the informer event-handler role) and enqueue reconcile keys,
including the cross-kind fan-outs (CQ events re-enqueue that queue's
workloads and LQs; AC/RF events re-enqueue referencing CQs).
"""

from __future__ import annotations

from typing import Optional

from kueue_tpu.api import kueue as api
from kueue_tpu.controller.core.admissioncheck_controller import (
    AdmissionCheckReconciler,
    ResourceFlavorReconciler,
)
from kueue_tpu.controller.core.clusterqueue_controller import ClusterQueueReconciler
from kueue_tpu.controller.core.localqueue_controller import LocalQueueReconciler
from kueue_tpu.controller.core.workload_controller import WorkloadReconciler
from kueue_tpu.sim import DELETED, Store
from kueue_tpu.sim.runtime import EventRecorder, Runtime


class CoreControllers:
    def __init__(self, wl, cq, lq, ac, rf):
        self.workload = wl
        self.cluster_queue = cq
        self.local_queue = lq
        self.admission_check = ac
        self.resource_flavor = rf


def setup_core_controllers(runtime: Runtime, store: Store, queues, cache,
                           recorder: EventRecorder, cfg=None, metrics=None,
                           registered_check_controllers: Optional[set] = None,
                           obs_recorder=None, journeys=None) -> CoreControllers:
    clock = runtime.clock
    wl_r = WorkloadReconciler(store, queues, cache, recorder, clock, cfg,
                              metrics, obs_recorder=obs_recorder,
                              journeys=journeys)
    cq_r = ClusterQueueReconciler(store, queues, cache, recorder, clock, metrics)
    lq_r = LocalQueueReconciler(store, queues, cache, recorder, clock, metrics)
    ac_r = AdmissionCheckReconciler(store, queues, cache, recorder, clock,
                                    registered_check_controllers)
    rf_r = ResourceFlavorReconciler(store, queues, cache, recorder, clock)

    wl_ctrl = runtime.controller("workload", wl_r.reconcile)
    cq_ctrl = runtime.controller("clusterqueue", cq_r.reconcile)
    lq_ctrl = runtime.controller("localqueue", lq_r.reconcile)
    ac_ctrl = runtime.controller("admissioncheck", ac_r.reconcile)
    rf_ctrl = runtime.controller("resourceflavor", rf_r.reconcile)

    def on_workload(event, wl, old):
        wl_r.handle_event(event, wl, old, wl_ctrl.enqueue)
        # keep LQ/CQ status counts fresh (reference: per-CRD watches on
        # Workload in clusterqueue/localqueue controllers)
        lq_ctrl.enqueue(f"{wl.metadata.namespace}/{wl.spec.queue_name}")
        cq_name = queues.cluster_queue_for_workload(wl)
        if cq_name:
            cq_ctrl.enqueue(cq_name)
        elif wl.status.admission is not None:
            cq_ctrl.enqueue(wl.status.admission.cluster_queue)

    def on_cluster_queue(event, cq, old):
        cq_r.handle_event(event, cq, old, cq_ctrl.enqueue)
        # Fan out to the queue's LQs/workloads only on spec changes or
        # deletion — status-only writes (the CQ reconciler's own) would
        # otherwise cost O(N^2) reconciles per cycle (reference:
        # workloadQueueHandler, workload_controller.go:757+).
        if event != DELETED and old is not None and old.spec == cq.spec:
            return
        name = cq.metadata.name
        for lq in store.list("LocalQueue", where=lambda q: q.spec.cluster_queue == name):
            lq_ctrl.enqueue(f"{lq.metadata.namespace}/{lq.metadata.name}")
            for wl in store.list("Workload", namespace=lq.metadata.namespace,
                                 where=lambda w: w.spec.queue_name == lq.metadata.name):
                wl_ctrl.enqueue(f"{wl.metadata.namespace}/{wl.metadata.name}")
        # flavors referenced by a deleted CQ may now be finalizable
        if event == DELETED:
            for rg in cq.spec.resource_groups:
                for fq in rg.flavors:
                    rf_ctrl.enqueue(fq.name)

    def on_local_queue(event, lq, old):
        lq_r.handle_event(event, lq, old, lq_ctrl.enqueue)
        # status-only writes (pending counts) don't re-enqueue the
        # queue's workloads — that would cost O(N^2) per admission cycle
        if event != DELETED and old is not None and old.spec == lq.spec:
            return
        if lq.spec.cluster_queue:
            cq_ctrl.enqueue(lq.spec.cluster_queue)
        for wl in store.list("Workload", namespace=lq.metadata.namespace,
                             where=lambda w: w.spec.queue_name == lq.metadata.name):
            wl_ctrl.enqueue(f"{wl.metadata.namespace}/{wl.metadata.name}")

    def on_admission_check(event, ac, old):
        ac_r.handle_event(event, ac, old, ac_ctrl.enqueue)
        name = ac.metadata.name
        for cq in store.list("ClusterQueue", copy_objects=False):
            checks = set(cq.spec.admission_checks) | {
                r.name for r in cq.spec.admission_checks_strategy}
            if name in checks:
                cq_ctrl.enqueue(cq.metadata.name)

    def on_resource_flavor(event, rf, old):
        rf_r.handle_event(event, rf, old, rf_ctrl.enqueue)
        name = rf.metadata.name
        for cq in store.list("ClusterQueue", copy_objects=False):
            if any(fq.name == name for rg in cq.spec.resource_groups
                   for fq in rg.flavors):
                cq_ctrl.enqueue(cq.metadata.name)

    def on_cohort(event, cohort, old):
        # v1alpha1 Cohort objects: parent edges + own quotas feed the
        # cache's cohort tree (reference: the cohort controller wiring in
        # cache.AddOrUpdateCohort, pkg/cache/cache.go:418). A topology
        # change can unblock any parked workload whose CQ sits in a
        # cohort, so flush those queues (cohort events are rare).
        if event == DELETED:
            cache.delete_cohort(cohort.metadata.name)
        else:
            try:
                cache.add_or_update_cohort(cohort)
            except ValueError as exc:  # cycle-inducing parent edge
                recorder.event(cohort, "Warning", "CohortCycle", str(exc))
        names = {name for name, cqc in cache.hm.cluster_queues.items()
                 if cqc.cohort is not None}
        queues.queue_inadmissible_workloads(names)
        for n in names:
            cq_ctrl.enqueue(n)

    store.watch("Workload", on_workload)
    store.watch("Cohort", on_cohort)
    store.watch("ClusterQueue", on_cluster_queue)
    store.watch("LocalQueue", on_local_queue)
    store.watch("AdmissionCheck", on_admission_check)
    store.watch("ResourceFlavor", on_resource_flavor)

    return CoreControllers(wl_r, cq_r, lq_r, ac_r, rf_r)
