"""ClusterQueueReconciler: status, Active condition, terminating finalization.

Equivalent of the reference's
pkg/controller/core/clusterqueue_controller.go:159-203 (+ status update
:334-449, QueueVisibility snapshot cron :553+):
- mirrors spec into cache + queue manager (watch handlers)
- status: pending/reserving/admitted counts, flavorsReservation/Usage,
  Active condition with the cache's inactive reason
- finalizer removed only once no workload reserves quota
- per-CQ metrics incl. optional resource quotas/usage
"""

from __future__ import annotations

import copy as _copy

from typing import Optional

from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import Condition, set_condition
from kueue_tpu.sim import ADDED, DELETED, Store
from kueue_tpu.sim.runtime import EventRecorder

REQUEUE_TERMINATING_SECONDS = 1.0


class ClusterQueueReconciler:
    def __init__(self, store: Store, queues, cache, recorder: EventRecorder,
                 clock, metrics=None, report_resource_metrics: bool = False):
        self.store = store
        self.queues = queues
        self.cache = cache
        self.recorder = recorder
        self.clock = clock
        self.metrics = metrics
        self.report_resource_metrics = report_resource_metrics
        self._last_sig: dict = {}  # cq name -> last written status inputs
        from kueue_tpu.controller.core.status_usage import FlavorUsageCache
        self._usage_cache = FlavorUsageCache()

    def reconcile(self, key: str):
        cq = self.store.try_get("ClusterQueue", "", key, copy_object=False)
        if cq is None:
            return None
        now = self.clock.now()

        if cq.metadata.deletion_timestamp is not None:
            # finalize only when nothing reserves quota anymore
            # (reference: :173-190)
            cqc = self.cache.cluster_queue(key)
            if cqc is not None and cqc.reserving_workloads_count() > 0:
                if self.metrics:
                    self.metrics.report_cluster_queue_status(key, "terminating")
                return REQUEUE_TERMINATING_SECONDS
            if api.RESOURCE_IN_USE_FINALIZER in cq.metadata.finalizers:
                cq = _copy.deepcopy(cq)
                cq.metadata.finalizers.remove(api.RESOURCE_IN_USE_FINALIZER)
                self.store.update(cq)
            return None

        cqc = self.cache.cluster_queue(key)
        if cqc is None:
            return None

        # Cheap change signature: skip rebuilding the full 32-flavor
        # status object (and its no-op update_status compare) when the
        # inputs are unchanged — at scale most CQ reconciles are fan-out
        # echoes of unrelated admissions.
        act = self.queues.cluster_queues.get(key)
        sig = (cq.metadata.resource_version,
               (act.pending_active(), act.pending_inadmissible())
               if act is not None else self.queues.pending(key),
               cqc.usage_version,
               cqc.active,
               # Cohort-level inputs: a sibling CQ's or cohort's quota
               # change alters this CQ's weighted share / lendable math,
               # and the inactive message can change (different missing
               # flavor) while `active` stays False. topology_epoch moves
               # on spec-level changes only — not workload churn — so the
               # fan-out-echo skip stays effective.
               self.cache.topology_epoch,
               cqc.inactive_reason() if not cqc.active else "")
        if self._last_sig.get(key) == sig:
            return None
        self._last_sig[key] = sig
        # status (reference: :334-449)
        reservation_usage, admitted_usage = self.cache.usage_for_cluster_queue(key)
        status_obj = _copy.copy(cq)
        status_obj.status = api.ClusterQueueStatus(
            conditions=[_copy.copy(c) for c in cq.status.conditions],
            fair_sharing_weighted_share=cq.status.fair_sharing_weighted_share,
            pending_workloads=self.queues.pending(key),
            reserving_workloads=cqc.reserving_workloads_count(),
            admitted_workloads=cqc.admitted_workloads_count,
            flavors_reservation=self._usage_cache.build(
                key, "resv", cq.spec, reservation_usage, borrowed=True),
            flavors_usage=self._usage_cache.build(
                key, "adm", cq.spec, admitted_usage, borrowed=True))
        cq = status_obj

        active = cqc.active
        if active:
            cond = Condition(type=api.CLUSTER_QUEUE_ACTIVE, status="True",
                             reason="Ready", message="Can admit new workloads",
                             observed_generation=cq.metadata.generation)
        else:
            cond = Condition(type=api.CLUSTER_QUEUE_ACTIVE, status="False",
                             reason=_reason_token(cqc.inactive_reason()),
                             message=f"Can't admit new workloads: {cqc.inactive_reason()}",
                             observed_generation=cq.metadata.generation)
        set_condition(cq.status.conditions, cond, now)
        self.store.update_status(cq, owned_status=True)
        self.queues.set_cluster_queue_active(key, active)

        if self.metrics:
            self.metrics.report_cluster_queue_status(
                key, "active" if active else "pending")
            self.metrics.reserving_active_workloads.set(
                cq.status.reserving_workloads, cluster_queue=key)
            self.metrics.admitted_active_workloads.set(
                cq.status.admitted_workloads, cluster_queue=key)
            act = self.queues.cluster_queues.get(key)
            if act is not None:
                self.metrics.report_pending_workloads(
                    key, act.pending_active(), act.pending_inadmissible())
            if self.report_resource_metrics:
                self._report_resource_metrics(cq, reservation_usage, admitted_usage)

        # QueueVisibility top-N snapshots refresh on the manager's timed
        # task (reference: :553+ runs them on the QueueVisibility
        # interval, not per reconcile — a full backlog sort per status
        # echo was a top control-plane cost at the 2k-CQ scale).
        return None

    def _report_resource_metrics(self, cq, reservation_usage, admitted_usage):
        cohort = cq.spec.cohort
        for rg in cq.spec.resource_groups:
            for fq in rg.flavors:
                for quota in fq.resources:
                    fr = (fq.name, quota.name)
                    self.metrics.report_cluster_queue_quotas(
                        cohort, cq.metadata.name, fq.name, quota.name,
                        quota.nominal_quota,
                        quota.borrowing_limit if quota.borrowing_limit is not None else -1,
                        quota.lending_limit if quota.lending_limit is not None else -1)
                    lbl = dict(cohort=cohort, cluster_queue=cq.metadata.name,
                               flavor=fq.name, resource=quota.name)
                    self.metrics.cluster_queue_resource_reservation.set(
                        reservation_usage.get(fr, 0), **lbl)
                    self.metrics.cluster_queue_resource_usage.set(
                        admitted_usage.get(fr, 0), **lbl)

    # -- watch handlers (reference: clusterqueue_controller.go event side) --

    def handle_event(self, event: str, cq: api.ClusterQueue,
                     old: Optional[api.ClusterQueue], enqueue) -> None:
        name = cq.metadata.name
        if event == ADDED:
            self.cache.add_cluster_queue(cq)
            self.queues.add_cluster_queue(cq)
        elif event == DELETED:
            self.cache.delete_cluster_queue(name)
            self.queues.delete_cluster_queue(name)
            self._last_sig.pop(name, None)
            self._usage_cache.forget(name)
            if self.metrics:
                self.metrics.clear_cluster_queue_metrics(name)
            return
        else:
            if cq.metadata.deletion_timestamp is not None:
                # terminating: cache flips status so no new admissions
                self.cache.terminate_cluster_queue(name)
            # Status-subresource writes share the stored spec object
            # (store.update_status copies only status), so an identity
            # check skips the cache/queue spec re-ingest for the CQ
            # reconciler's own counter refreshes — the dominant CQ event
            # class at scale.
            if old is None or old.spec is not cq.spec:
                self.cache.update_cluster_queue(cq)
                self.queues.update_cluster_queue(
                    cq, spec_updated=old is None or old.spec != cq.spec)
        enqueue(name)


def _reason_token(reason: str) -> str:
    return reason.split(":", 1)[0] if reason else "Unknown"



