"""Controller layer: core CRD reconcilers, the job-integration framework
(GenericJob SPI), per-job integrations and admission-check controllers.

Mirrors the reference's pkg/controller tree (SURVEY.md §2.5), running on
the sim runtime instead of controller-runtime.
"""
