"""ProvisioningRequest admission-check controller.

Equivalent of the reference's
pkg/controller/admissionchecks/provisioning/controller.go:139-608:
- for every workload with QuotaReserved and a check handled by this
  controller, create one ProvisioningRequest (+ PodTemplates from the
  assigned pod sets) per relevant check, configured by the check's
  ProvisioningRequestConfig
- map ProvReq conditions to check state: Provisioned=True -> Ready with
  podSetUpdates binding pods to the request (consume annotation);
  Failed -> Retry with capped exponential backoff on a fresh
  "-attemptN" request (attempt <= maxRetries), then Rejected
  (:246-335, :484-608)
- BookingExpired/CapacityRevoked after admission -> no-op here; the
  workload controller evicts on check state changes
"""

from __future__ import annotations

import hashlib
import struct
from typing import Optional

from kueue_tpu.api import autoscaling as asapi
from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import Condition, ObjectMeta, find_condition, is_condition_true, set_condition
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.sim import ADDED, DELETED, Store

CONTROLLER_NAME = "kueue.x-k8s.io/provisioning-request"
CONSUME_ANNOTATION = "autoscaling.x-k8s.io/consume-provisioning-request"
CLASS_NAME_ANNOTATION = "autoscaling.x-k8s.io/provisioning-class-name"
DEFAULT_MAX_RETRIES = 3
DEFAULT_MIN_BACKOFF_SECONDS = 60.0
DEFAULT_BACKOFF_JITTER = 0.2


def request_name(wl_name: str, check_name: str, attempt: int) -> str:
    base = f"{wl_name}-{check_name}"
    return base if attempt <= 1 else f"{base}-attempt{attempt}"


def _jitter_fraction(seed: int, key: str) -> float:
    """Deterministic per-key jitter in [0, 1): a keyed hash, NOT a
    shared RNG stream — the backoff is recomputed on every reconcile,
    so the fraction must be stable for a given (workload, check,
    attempt) while differing across workloads. Python's builtin hash is
    salted per process; blake2b is stable across runs, so fake-clock
    tests stay reproducible."""
    salt = (seed & (2**64 - 1)).to_bytes(8, "little")  # any int seed
    digest = hashlib.blake2b(key.encode(), digest_size=8,
                             salt=salt).digest()
    return struct.unpack("<Q", digest)[0] / 2**64


class ProvisioningController:
    def __init__(self, store: Store, recorder, clock,
                 max_retries: int = DEFAULT_MAX_RETRIES,
                 min_backoff_seconds: float = DEFAULT_MIN_BACKOFF_SECONDS,
                 backoff_jitter: float = DEFAULT_BACKOFF_JITTER,
                 jitter_seed: int = 0):
        self.store = store
        self.recorder = recorder
        self.clock = clock
        self.max_retries = max_retries
        self.min_backoff_seconds = min_backoff_seconds
        # Retry-storm de-synchronization: workloads that failed together
        # (one capacity outage fails a whole wave of ProvReqs at the
        # same transition time) must not all retry at the same instant.
        # Each (workload, check, attempt) gets a stable multiplicative
        # jitter in [1, 1 + backoff_jitter); 0 restores the pure
        # base * 2^(attempt-1) schedule.
        self.backoff_jitter = backoff_jitter
        self.jitter_seed = jitter_seed

    def _backoff_seconds(self, wl_name: str, check_name: str,
                         attempt: int) -> float:
        backoff = self.min_backoff_seconds * 2 ** (attempt - 1)
        if self.backoff_jitter > 0:
            frac = _jitter_fraction(self.jitter_seed,
                                    f"{wl_name}/{check_name}/{attempt}")
            backoff *= 1.0 + self.backoff_jitter * frac
        return backoff

    # -- discovery ------------------------------------------------------

    def _relevant_checks(self, wl: api.Workload) -> list:
        """Names of this controller's checks on the workload."""
        out = []
        for state in wl.status.admission_checks:
            ac = self.store.try_get("AdmissionCheck", "", state.name)
            if ac is not None and ac.spec.controller_name == CONTROLLER_NAME:
                out.append(state.name)
        return out

    def _config_for(self, check_name: str) -> Optional[asapi.ProvisioningRequestConfig]:
        ac = self.store.try_get("AdmissionCheck", "", check_name)
        if ac is None or ac.spec.parameters is None:
            return None
        return self.store.try_get("ProvisioningRequestConfig", "",
                                  ac.spec.parameters.name)

    # -- reconcile ------------------------------------------------------

    def reconcile(self, key: str):
        namespace, name = key.split("/", 1)
        wl = self.store.try_get("Workload", namespace, name)
        if wl is None or wlpkg.is_finished(wl):
            return None
        if not wlpkg.has_quota_reservation(wl) or not wlpkg.is_active(wl):
            return None
        checks = self._relevant_checks(wl)
        if not checks:
            return None
        requeue_after = None
        updated = False
        for check_name in checks:
            result = self._sync_check(wl, check_name)
            if isinstance(result, float):
                requeue_after = result if requeue_after is None \
                    else min(requeue_after, result)
            elif result:
                updated = True
        if updated:
            self.store.update(wl)
        return requeue_after

    def _sync_check(self, wl: api.Workload, check_name: str):
        """Returns True if the workload's check state changed, or a float
        requeue delay while backing off."""
        now = self.clock.now()
        state = wlpkg.find_admission_check(wl, check_name)
        if state is None or state.state in (api.CHECK_STATE_READY,
                                            api.CHECK_STATE_REJECTED):
            return False

        # find the latest attempt's request
        attempt = 1
        pr = None
        for a in range(self.max_retries + 1, 0, -1):
            candidate = self.store.try_get(
                "ProvisioningRequest", wl.metadata.namespace,
                request_name(wl.metadata.name, check_name, a))
            if candidate is not None:
                pr = candidate
                attempt = a
                break

        if pr is None:
            self._create_request(wl, check_name, 1)
            return False

        if is_condition_true(pr.status.conditions, asapi.PROVISIONED):
            # Ready + podSetUpdates binding pods to the request
            # (reference: :593-608)
            updates = [api.PodSetUpdate(
                name=psa.name,
                annotations={CONSUME_ANNOTATION: pr.metadata.name,
                             CLASS_NAME_ANNOTATION:
                                 pr.spec.provisioning_class_name})
                for psa in wl.status.admission.pod_set_assignments]
            wlpkg.set_admission_check_state(
                wl.status.admission_checks,
                api.AdmissionCheckState(name=check_name,
                                        state=api.CHECK_STATE_READY,
                                        message="Provisioning completed",
                                        pod_set_updates=updates), now)
            return True

        failed = find_condition(pr.status.conditions, asapi.FAILED)
        if failed is not None and failed.status == "True":
            if attempt <= self.max_retries:
                # exponential backoff before the next attempt
                # (reference: remainingTimeToRetry :317-335), with
                # seeded per-workload jitter so a wave that failed
                # together doesn't retry in lockstep
                backoff = self._backoff_seconds(wl.metadata.name,
                                                check_name, attempt)
                elapsed = now - failed.last_transition_time
                remaining = backoff - elapsed
                if remaining > 0:
                    return float(remaining)
                self._create_request(wl, check_name, attempt + 1)
                wlpkg.set_admission_check_state(
                    wl.status.admission_checks,
                    api.AdmissionCheckState(
                        name=check_name, state=api.CHECK_STATE_PENDING,
                        message=f"Retrying after failure: {failed.message}"), now)
                return True
            wlpkg.set_admission_check_state(
                wl.status.admission_checks,
                api.AdmissionCheckState(name=check_name,
                                        state=api.CHECK_STATE_REJECTED,
                                        message=failed.message), now)
            return True

        if state.message != "Provisioning in progress":
            wlpkg.set_admission_check_state(
                wl.status.admission_checks,
                api.AdmissionCheckState(name=check_name,
                                        state=api.CHECK_STATE_PENDING,
                                        message="Provisioning in progress"), now)
            return True
        return False

    def _create_request(self, wl: api.Workload, check_name: str,
                        attempt: int) -> None:
        config = self._config_for(check_name)
        name = request_name(wl.metadata.name, check_name, attempt)
        managed = set(config.spec.managed_resources) if config else set()
        pod_sets = []
        for psa in wl.status.admission.pod_set_assignments:
            ps = next(p for p in wl.spec.pod_sets if p.name == psa.name)
            if managed and not (managed & set(psa.resource_usage)):
                continue  # podset doesn't use any managed resource
            template_name = f"ppt-{name}-{psa.name}"
            self._ensure(asapi.PodTemplate(
                metadata=ObjectMeta(name=template_name,
                                    namespace=wl.metadata.namespace),
                template=ps.template))
            count = psa.count if psa.count is not None else ps.count
            pod_sets.append(asapi.ProvisioningRequestPodSet(
                pod_template_ref=template_name, count=count))
        pr = asapi.ProvisioningRequest(
            metadata=ObjectMeta(name=name, namespace=wl.metadata.namespace,
                                owner_references=[]))
        pr.spec.provisioning_class_name = \
            config.spec.provisioning_class_name if config else ""
        pr.spec.parameters = dict(config.spec.parameters) if config else {}
        pr.spec.pod_sets = pod_sets
        self._ensure(pr)
        self.recorder.event(wl, "Normal", "ProvisioningRequestCreated",
                            f"Created ProvisioningRequest: {name}")

    def _ensure(self, obj) -> None:
        from kueue_tpu.sim import AlreadyExists
        try:
            self.store.create(obj)
        except AlreadyExists:
            pass


def setup_provisioning_controller(runtime, store: Store, recorder,
                                  **kwargs) -> ProvisioningController:
    """Wire the controller: reconcile on Workload and ProvisioningRequest
    events (reference: SetupWithManager + indexes, indexer.go:83)."""
    controller = ProvisioningController(store, recorder, runtime.clock, **kwargs)
    ctrl = runtime.controller("provisioning", controller.reconcile)

    def on_workload(event, wl, old):
        if event != DELETED:
            ctrl.enqueue(wlpkg.key(wl))

    def on_provreq(event, pr, old):
        # requests are named "<wl>-<check>[-attemptN]" — find owners by
        # listing workloads in the namespace (the reference uses an index)
        for wl in store.list("Workload", namespace=pr.metadata.namespace):
            if pr.metadata.name.startswith(wl.metadata.name + "-"):
                ctrl.enqueue(wlpkg.key(wl))

    store.watch("Workload", on_workload)
    store.watch("ProvisioningRequest", on_provreq)
    return controller
