"""AdmissionCheck controllers (reference: pkg/controller/admissionchecks):
provisioning (cluster-autoscaler ProvisioningRequest gate) and multikueue
(multi-cluster dispatch). The TPU batch solver also plugs in through the
same mechanism (kueue_tpu.solver.service)."""
