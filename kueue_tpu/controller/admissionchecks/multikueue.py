"""MultiKueue: multi-cluster dispatch as an AdmissionCheck.

Equivalent of the reference's pkg/controller/admissionchecks/multikueue
(multikueuecluster.go:67-307, workload.go:137-420):
- each MultiKueueCluster names a worker cluster; the reference dials it
  via a kubeconfig secret with fsnotify-driven reconnect — the sim
  resolves the name through an injected registry of remote stores
  (worker clusters are full KueueManagers in tests, the analogue of the
  reference's two envtest instances in one process)
- for every local workload with QuotaReserved and a multikueue check,
  mirror the workload (and its batch Job, via the adapter) into every
  cluster of the check's MultiKueueConfig
- the FIRST cluster to reserve quota wins: the mirrors on the other
  clusters are deleted; the check turns Ready and records the cluster
- the remote Finished condition is copied back, then remotes are GC'd
- if the reserving cluster disappears, the check flips to Retry after
  worker_lost_timeout (config multiKueue.workerLostTimeout)
"""

from __future__ import annotations

import copy
from typing import Optional

from kueue_tpu.api import autoscaling as asapi
from kueue_tpu.api import kueue as api
from kueue_tpu.api.corev1 import RESOURCE_PODS
from kueue_tpu.api.meta import Condition, find_condition, is_condition_true, set_condition
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.sim import DELETED, Store

CONTROLLER_NAME = "kueue.x-k8s.io/multikueue"
ORIGIN_LABEL = "kueue.x-k8s.io/multikueue-origin"


class MultiKueueAdapter:
    """Per-job-kind remote sync (reference: jobframework.MultiKueueAdapter,
    interface.go:160-196)."""

    KIND = ""

    def sync_job(self, local_store: Store, remote_store: Store,
                 wl: api.Workload, origin: str) -> None:
        """Create/refresh the remote job object and copy its status back."""

    def delete_remote(self, remote_store: Store, namespace: str, name: str) -> None:
        pass

    def keep_admission_check_pending(self) -> bool:
        """reference: KeepAdmissionCheckPending — batch Jobs run remotely
        while the local check stays Pending (managedBy gate absent)."""
        return False


class BatchJobAdapter(MultiKueueAdapter):
    KIND = "Job"

    def sync_job(self, local_store, remote_store, wl, origin):
        owner = next((o for o in wl.metadata.owner_references
                      if o.controller and o.kind == "Job"), None)
        if owner is None:
            return
        local_job = local_store.try_get("Job", wl.metadata.namespace, owner.name)
        if local_job is None:
            return
        remote_job = remote_store.try_get("Job", wl.metadata.namespace, owner.name)
        if remote_job is None:
            clone = copy.deepcopy(local_job)
            clone.metadata.resource_version = 0
            clone.metadata.uid = ""
            clone.metadata.labels[ORIGIN_LABEL] = origin
            # bind the remote job to the mirrored Workload so the worker's
            # jobframework doesn't construct a duplicate (reference:
            # job_multikueue_adapter.go sets the prebuilt-workload label)
            clone.metadata.labels[api.PREBUILT_WORKLOAD_LABEL] = wl.metadata.name
            remote_store.create(clone)
            return
        # copy remote status back to the local job (reference:
        # job_multikueue_adapter.go SyncJob)
        if remote_job.status != local_job.status:
            local_job.status = remote_job.status
            local_store.update(local_job)

    def delete_remote(self, remote_store, namespace, name):
        try:
            remote_store.delete("Job", namespace, name)
        except KeyError:
            pass


ADAPTERS = {"Job": BatchJobAdapter()}


def _remote_available(cache) -> dict:
    """{(flavor, resource): available} across a worker cluster's CQs:
    nominal minus usage, clamped at zero, summed per flavor-resource —
    the capacity envelope one column of the batched placement solve
    offers. Reads under the remote cache's lock (worker managers never
    lock back into the local one, so the order is acyclic)."""
    caps: dict = {}
    with cache._lock:
        for cqc in cache.hm.cluster_queues.values():
            rn = cqc.resource_node
            for fr, quota in rn.quotas.items():
                avail = quota.nominal - rn.usage.get(fr, 0)
                if avail > 0:
                    key = (fr.flavor, fr.resource)
                    caps[key] = caps.get(key, 0) + avail
    return caps


class MultiKueueController:
    def __init__(self, store: Store, recorder, clock,
                 remote_clusters: Optional[dict] = None,
                 origin: str = "multikueue",
                 worker_lost_timeout: float = 15 * 60.0):
        self.store = store
        self.recorder = recorder
        self.clock = clock
        # cluster name -> remote Store (or KueueManager, resolved below)
        self.remote_clusters = remote_clusters if remote_clusters is not None else {}
        self.origin = origin
        self.worker_lost_timeout = worker_lost_timeout
        self._lost_since: dict = {}  # wl key -> first-noticed-lost time
        # Activity probe (reference: multikueuecluster.go connection
        # monitor): clusters marked lost are unreachable — excluded from
        # placement, mirror deletion and orphan GC — until rejoined.
        # Scenario drivers and (eventually) a real connection prober
        # flip these; the sim's "worker cluster loss" failure mode
        # (SURVEY.md §5) is exercised through exactly this surface.
        self.lost_clusters: set = set()
        # wl key -> cluster the Ready check recorded. Placement is
        # sticky: on reconcile the recorded cluster is probed FIRST, so
        # a lost cluster rejoining with a stale reserved mirror cannot
        # steal the workload back from its re-placement (the stale
        # mirror is deleted by the first-wins branch instead) — the
        # no-double-dispatch invariant under cluster loss/rejoin.
        self._reserving: dict = {}
        # wl key -> cluster the BATCHED solve chose (ISSUE 13): the
        # admission cycle scores remote clusters as capacity columns
        # (kernel.score_cluster_columns_impl / the scheduler's host
        # oracle) and forwards decisions here via note_placement. A
        # planned workload mirrors ONLY to its chosen cluster — this
        # controller becomes the executor of device-made decisions
        # instead of racing mirrors across the fleet per workload.
        # Un-planned workloads keep the reference's mirror-to-all race.
        self.planned: dict = {}
        self._planned_at: dict = {}    # wl key -> decision time (staleness)
        self.placements_planned = 0    # decisions received
        self.placements_executed = 0   # single-cluster mirrors performed
        self.placements_expired = 0    # plans dropped to the mirror race
        # Optional obs JourneyLedger (manager wiring): the planned-
        # mirror lifecycle stamps mk-planned/executed/expired spans —
        # with the cluster name — onto the workload's journey, so
        # cross-cluster placement stays causal in the timeline
        # (ISSUE 14; post-PR-13 mesh context).
        self.journeys = None
        self._ctrl = None  # workqueue handle, set by setup_*

    def _remote_store(self, cluster_name: str) -> Optional[Store]:
        if cluster_name in self.lost_clusters:
            return None  # unreachable: no reads, writes, or deletes
        remote = self.remote_clusters.get(cluster_name)
        if remote is None:
            return None
        return remote.store if hasattr(remote, "store") else remote

    def cluster_active(self, cluster_name: str) -> bool:
        return self._remote_store(cluster_name) is not None \
            and self.store.try_get("MultiKueueCluster", "", cluster_name) is not None

    # -- activity probe (cluster loss / rejoin) -------------------------

    def mark_cluster_lost(self, cluster_name: str) -> None:
        """The worker became unreachable (connection probe failure):
        exclude it everywhere and re-reconcile so workloads reserved
        there start their worker-lost timeout."""
        if cluster_name in self.lost_clusters:
            return
        self.lost_clusters.add(cluster_name)
        self.recorder.system_event(
            "Warning", "MultiKueueClusterLost",
            f'worker cluster "{cluster_name}" is unreachable')
        self._requeue_all()

    def mark_cluster_rejoined(self, cluster_name: str) -> None:
        """The worker is reachable again: re-reconcile so stale mirrors
        left from before the loss are cleaned up (sticky placement keeps
        re-placed workloads where they landed) and the cluster returns
        to the placement set."""
        if cluster_name not in self.lost_clusters:
            return
        self.lost_clusters.discard(cluster_name)
        self.recorder.system_event(
            "Normal", "MultiKueueClusterRejoined",
            f'worker cluster "{cluster_name}" rejoined')
        self._requeue_all()

    def _requeue_all(self) -> None:
        """Re-enqueue every local workload (reference: the cluster
        connection watcher queues all workloads on connect/disconnect,
        multikueuecluster.go:187-253). Non-multikueue workloads no-op
        in reconcile."""
        if self._ctrl is None:
            return
        for wl in self.store.list("Workload", copy_objects=False):
            self._ctrl.enqueue(wlpkg.key(wl))

    # -- batched placement (capacity columns of the solve) ---------------

    def note_placement(self, wl_key: str, cluster_name: str) -> None:
        """Record a solve-made placement decision (scheduler hook). The
        next reconcile of this workload mirrors only to the chosen
        cluster. Idempotent; later decisions overwrite earlier ones
        (a re-placed workload after cluster loss gets a fresh choice)."""
        self.planned[wl_key] = cluster_name
        self._planned_at[wl_key] = self.clock.now()
        self.placements_planned += 1
        if self.journeys is not None:
            self.journeys.mk_event(wl_key, "planned", cluster_name)
        if self._ctrl is not None:
            self._ctrl.enqueue(wl_key)

    def capacity_columns(self) -> tuple:
        """(columns, mk_check_names) for Cache snapshot stamping:
        columns is an ordered tuple of
        (cluster_name, {(flavor, resource): available}, active) in
        sorted-name order — the scoring order the batched solve, the
        host oracle and the planned-mirror path all share. Lost or
        unregistered clusters stamp active=False with NO capacity: the
        column masks to zero on the next snapshot, so re-placement of
        their workloads falls out of the next cycle's scoring.

        In-flight debit: a plan the remote has not RESERVED yet is
        capacity the remote usage read can't see (the mirror is still
        queueing there), so its request is consumed from the columns
        via the shared placement rule — without this, consecutive
        cycles would pile every head onto the same already-chosen
        cluster while its siblings sit idle."""
        cols = []
        for name in sorted(self.remote_clusters):
            active = self.cluster_active(name)
            caps: dict = {}
            remote = self.remote_clusters.get(name)
            cache = getattr(remote, "cache", None)
            if active and cache is not None:
                caps = _remote_available(cache)
            cols.append((name, caps, active))
        cols = tuple(cols)
        reqs, pinned = [], []
        covers_pods_memo: dict = {}
        # list(): reconcile pops plans concurrently in threaded
        # deployments — a mid-iteration mutation must not tear the
        # whole stamp down to "no columns this cycle".
        for key, cluster in list(self.planned.items()):
            if self._reserving.get(key) is not None:
                # Reserved ANYWHERE: the remote usage read covers it
                # (and if it reserved off-plan, debiting the planned
                # column would be wrong — reconcile drops such plans).
                continue
            namespace, wname = key.split("/", 1)
            wl = self.store.try_get("Workload", namespace, wname)
            if wl is None or not wlpkg.has_quota_reservation(wl):
                continue
            info = wlpkg.Info(wl)
            # the debit must consume the SAME request vector the
            # placement scored (wlpkg.mk_request_vector is the one
            # shared fold): pods included when the local CQ covers it
            covers = covers_pods_memo.get(info.cluster_queue)
            if covers is None:
                cq = self.store.try_get("ClusterQueue", "",
                                        info.cluster_queue)
                covers = cq is not None and any(
                    RESOURCE_PODS in rg.covered_resources
                    for rg in cq.spec.resource_groups)
                covers_pods_memo[info.cluster_queue] = covers
            reqs.append(wlpkg.mk_request_vector(info, covers))
            pinned.append(cluster)
        if reqs:
            from kueue_tpu.solver.encode import consume_remote_dicts
            cols = consume_remote_dicts(cols, reqs, pinned)
        checks = frozenset(
            ac.metadata.name
            for ac in self.store.list("AdmissionCheck", copy_objects=False)
            if ac.spec.controller_name == CONTROLLER_NAME)
        return cols, checks

    # -- check/config resolution ----------------------------------------

    def _check_for(self, wl: api.Workload) -> Optional[str]:
        for state in wl.status.admission_checks:
            ac = self.store.try_get("AdmissionCheck", "", state.name)
            if ac is not None and ac.spec.controller_name == CONTROLLER_NAME:
                return state.name
        return None

    def _clusters_for_check(self, check_name: str) -> list:
        ac = self.store.try_get("AdmissionCheck", "", check_name)
        if ac is None or ac.spec.parameters is None:
            return []
        config = self.store.try_get("MultiKueueConfig", "", ac.spec.parameters.name)
        if config is None:
            return []
        return [c for c in config.spec.clusters if self.cluster_active(c)]

    # -- reconcile ------------------------------------------------------

    def reconcile(self, key: str):
        namespace, name = key.split("/", 1)
        wl = self.store.try_get("Workload", namespace, name)
        if wl is None:
            self._gc_remotes(namespace, name)
            return None
        check_name = self._check_for(wl)
        if check_name is None:
            return None
        now = self.clock.now()
        state = wlpkg.find_admission_check(wl, check_name)

        if wlpkg.is_finished(wl):
            self._gc_remotes(namespace, name)
            return None
        if not wlpkg.has_quota_reservation(wl):
            self._gc_remotes(namespace, name)
            return None

        clusters = self._clusters_for_check(check_name)
        reserving = None
        # Sticky placement: probe the recorded reserving cluster first,
        # so a rejoined cluster holding a stale reserved mirror cannot
        # out-rank the workload's current placement (no double
        # dispatch; the stale mirror is GC'd below instead). The
        # solve-planned cluster probes next — with a planned single
        # mirror it is the only cluster that can be reserving anyway.
        recorded = self._reserving.get(wlpkg.key(wl))
        planned = self.planned.get(wlpkg.key(wl))
        head = [c for c in (recorded, planned) if c in clusters]
        ordered = head + [c for c in clusters if c not in head] \
            if head else clusters
        for cluster in ordered:
            remote = self._remote_store(cluster)
            if remote is None:
                continue  # lost: unreachable, cannot be observed reserving
            remote_wl = remote.try_get("Workload", namespace, name)
            if remote_wl is not None and wlpkg.has_quota_reservation(remote_wl):
                reserving = cluster
                break

        if reserving is None and state is not None \
                and state.state == api.CHECK_STATE_READY:
            # the reserving worker vanished (reference: wlReconciler
            # workerLostTimeout, workload.go:380-420)
            first = self._lost_since.setdefault(wlpkg.key(wl), now)
            remaining = self.worker_lost_timeout - (now - first)
            if remaining > 0:
                return float(remaining)
            self._lost_since.pop(wlpkg.key(wl), None)
            self._reserving.pop(wlpkg.key(wl), None)
            # the plan died with the worker: the next admission cycle
            # re-scores the workload against the masked columns
            self.planned.pop(wlpkg.key(wl), None)
            self._planned_at.pop(wlpkg.key(wl), None)
            wlpkg.set_admission_check_state(
                wl.status.admission_checks,
                api.AdmissionCheckState(
                    name=check_name, state=api.CHECK_STATE_RETRY,
                    message="Reserving remote lost"), now)
            self.store.update(wl)
            return None
        self._lost_since.pop(wlpkg.key(wl), None)

        if reserving is not None:
            self._reserving[wlpkg.key(wl)] = reserving
            if self.planned.get(wlpkg.key(wl)) not in (None, reserving):
                # Reality disagrees with the plan (the planned cluster
                # was lost and the mirror race placed elsewhere): drop
                # the stale plan, or capacity_columns would debit the
                # planned cluster's column for this workload's whole
                # lifetime.
                self.planned.pop(wlpkg.key(wl), None)
                self._planned_at.pop(wlpkg.key(wl), None)
            # first reservation wins: drop the other mirrors and their jobs
            adapter = self._adapter_for(wl)
            owner = next((o for o in wl.metadata.owner_references
                          if o.controller), None)
            for cluster in clusters:
                if cluster != reserving:
                    self._delete_mirror(cluster, namespace, name)
                    other = self._remote_store(cluster)
                    if adapter is not None and owner is not None \
                            and other is not None:
                        adapter.delete_remote(other, namespace, owner.name)
            remote = self._remote_store(reserving)
            remote_wl = remote.try_get("Workload", namespace, name)
            # copy the remote Finished condition back
            if remote_wl is not None and wlpkg.is_finished(remote_wl):
                fin = find_condition(remote_wl.status.conditions,
                                     api.WORKLOAD_FINISHED)
                set_condition(wl.status.conditions, copy.deepcopy(fin), now)
                self.store.update(wl)
                return None
            if adapter is not None:
                adapter.sync_job(self.store, remote, wl, self.origin)
            if state is not None and state.state != api.CHECK_STATE_READY:
                wlpkg.set_admission_check_state(
                    wl.status.admission_checks,
                    api.AdmissionCheckState(
                        name=check_name, state=api.CHECK_STATE_READY,
                        message=f'The workload got reservation on "{reserving}"'),
                    now)
                self.store.update(wl)
            return None

        # No remote reservation yet: with a solve-planned placement,
        # mirror ONLY to the chosen cluster — the per-workload
        # mirror-everywhere race (and its K-1 mirror deletions on the
        # win) leaves the admission hot path. A plan naming a cluster
        # that is currently lost/inactive falls back to the reference's
        # mirror-to-all race until the next cycle re-scores the
        # workload against the masked columns. Starvation bound: a plan
        # whose cluster never reserves within the worker-lost timeout
        # (wedged remote, capacity the scoring over-estimated) EXPIRES
        # back to the race — the planned path can delay cross-cluster
        # placement, never strand it.
        targets = clusters
        single_mirror = False
        requeue_after = None
        if planned is not None and planned in clusters:
            age = now - self._planned_at.get(wlpkg.key(wl), now)
            if age > self.worker_lost_timeout:
                self.planned.pop(wlpkg.key(wl), None)
                self._planned_at.pop(wlpkg.key(wl), None)
                self.placements_expired += 1
                if self.journeys is not None:
                    self.journeys.mk_event(wlpkg.key(wl), "expired",
                                           planned)
            else:
                targets = [planned]
                single_mirror = True
                # Schedule the expiry check: a planned cluster that
                # never reserves produces NO watch events, so without a
                # timed requeue the age gate above could never fire and
                # the workload would strand on one pending mirror —
                # the bounded-starvation contract needs the timer.
                requeue_after = float(self.worker_lost_timeout - age) + 1.0
        for cluster in targets:
            remote = self._remote_store(cluster)
            if remote is None:
                continue  # lost: mirrored on rejoin via _requeue_all
            if remote.try_get("Workload", namespace, name) is None:
                from kueue_tpu.sim import AlreadyExists
                clone = self._clone_for_remote(wl)
                try:
                    remote.create(clone)
                    if single_mirror:
                        # counted per mirror actually CREATED on the
                        # planned cluster — re-reconciles of an
                        # existing mirror don't inflate the surface
                        self.placements_executed += 1
                        if self.journeys is not None:
                            self.journeys.mk_event(wlpkg.key(wl),
                                                   "executed", cluster)
                except AlreadyExists:
                    pass
            adapter = self._adapter_for(wl)
            if adapter is not None:
                adapter.sync_job(self.store, remote, wl, self.origin)
        return requeue_after

    def _adapter_for(self, wl: api.Workload) -> Optional[MultiKueueAdapter]:
        owner = next((o for o in wl.metadata.owner_references if o.controller), None)
        if owner is None:
            return None
        return ADAPTERS.get(owner.kind)

    def _clone_for_remote(self, wl: api.Workload) -> api.Workload:
        clone = copy.deepcopy(wl)
        clone.metadata.resource_version = 0
        clone.metadata.uid = ""
        clone.metadata.labels[ORIGIN_LABEL] = self.origin
        clone.metadata.owner_references = []
        clone.metadata.finalizers = []
        clone.status = api.WorkloadStatus()
        return clone

    def _delete_mirror(self, cluster: str, namespace: str, name: str) -> None:
        remote = self._remote_store(cluster)
        if remote is None:
            return
        remote_wl = remote.try_get("Workload", namespace, name)
        if remote_wl is None:
            return
        if remote_wl.metadata.labels.get(ORIGIN_LABEL) != self.origin:
            return  # not ours
        if remote_wl.metadata.finalizers:
            remote_wl.metadata.finalizers = []
            remote.update(remote_wl)
        try:
            remote.delete("Workload", namespace, name)
        except KeyError:
            pass

    def _gc_remotes(self, namespace: str, name: str) -> None:
        """Remote orphan GC (reference: multikueuecluster.go:255-305).
        Lost clusters are skipped (unreachable); their stale mirrors
        are collected by the periodic gc_orphans pass after rejoin."""
        self._reserving.pop(f"{namespace}/{name}", None)
        self.planned.pop(f"{namespace}/{name}", None)
        self._planned_at.pop(f"{namespace}/{name}", None)
        for cluster in list(self.remote_clusters):
            self._delete_mirror(cluster, namespace, name)

    def gc_orphans(self) -> int:
        """Periodic GC: remote workloads whose local original is gone
        (reference: GC interval, config multiKueue.gcInterval). Runs on
        the manager's runtime timer every multiKueue.gcInterval seconds;
        lost clusters are skipped until they rejoin."""
        removed = 0
        for cluster in list(self.remote_clusters):
            remote = self._remote_store(cluster)
            if remote is None:
                continue
            for remote_wl in remote.list(
                    "Workload", copy_objects=False,
                    where=lambda w: w.metadata.labels.get(ORIGIN_LABEL) == self.origin):
                local = self.store.try_get(
                    "Workload", remote_wl.metadata.namespace, remote_wl.metadata.name)
                if local is None:
                    self._delete_mirror(cluster, remote_wl.metadata.namespace,
                                        remote_wl.metadata.name)
                    removed += 1
        return removed


def setup_multikueue_controller(runtime, store: Store, recorder,
                                remote_clusters: Optional[dict] = None,
                                **kwargs) -> MultiKueueController:
    controller = MultiKueueController(store, recorder, runtime.clock,
                                      remote_clusters=remote_clusters, **kwargs)
    ctrl = runtime.controller("multikueue", controller.reconcile)
    controller._ctrl = ctrl

    def on_workload(event, wl, old):
        ctrl.enqueue(wlpkg.key(wl))

    store.watch("Workload", on_workload)

    # remote workload/job transitions re-trigger the local reconcile
    # (reference: watch fan-in channels, multikueuecluster.go:187-253)
    def watch_remote(cluster_name: str) -> None:
        remote = controller._remote_store(cluster_name)
        if remote is None:
            return
        def on_remote(event, obj, old):
            ctrl.enqueue(f"{obj.metadata.namespace}/{obj.metadata.name}")
        remote.watch("Workload", on_remote)

    controller.watch_remote = watch_remote
    for cluster_name in (remote_clusters or {}):
        watch_remote(cluster_name)
    return controller
