"""kubeflow.org training-job integrations: TFJob, PyTorchJob, PaddleJob,
XGBoostJob, MXJob, MPIJob.

Equivalent of the reference's shared wrapper
pkg/controller/jobs/kubeflow/kubeflowjob/kubeflowjob_controller.go
instantiated per kind (pkg/controller/jobs/{tfjob,pytorchjob,paddlejob,
xgboostjob,mxjob}) and pkg/controller/jobs/mpijob (same shape on
v2beta1): one PodSet per replica type in canonical order, RunPolicy
suspend, Finished from Succeeded/Failed conditions.
"""

from __future__ import annotations

import copy

from kueue_tpu.api import kubeflow as kf
from kueue_tpu.api import kueue as api
from kueue_tpu.core import podset as podsetpkg
from kueue_tpu.controller.jobframework.interface import (
    GenericJob,
    IntegrationCallbacks,
    register_integration,
)


class KubeflowJob(GenericJob):
    """Shared GenericJob over ReplicaSpecs (reference:
    kubeflowjob_controller.go:50-173)."""

    def __init__(self, obj, framework: str):
        self.kj = obj
        self.framework = framework
        self.kind = type(obj).__name__

    def object(self):
        return self.kj

    def gvk(self) -> str:
        return self.framework

    def is_suspended(self) -> bool:
        return self.kj.spec.run_policy.suspend

    def suspend(self) -> None:
        self.kj.spec.run_policy.suspend = True

    def is_active(self) -> bool:
        return any(s.active > 0 for s in self.kj.status.replica_statuses.values())

    def _ordered_types(self) -> list:
        order = kf.REPLICA_ORDER.get(self.kind, [])
        present = [t for t in order if t in self.kj.spec.replica_specs]
        extra = [t for t in self.kj.spec.replica_specs if t not in present]
        return present + sorted(extra)

    def pod_sets(self) -> list:
        return [api.PodSet(name=rtype.lower(),
                           template=copy.deepcopy(self.kj.spec.replica_specs[rtype].template),
                           count=self.kj.spec.replica_specs[rtype].replicas)
                for rtype in self._ordered_types()]

    def run_with_podsets_info(self, podsets_info: list) -> None:
        self.kj.spec.run_policy.suspend = False
        types = self._ordered_types()
        if len(podsets_info) != len(types):
            raise podsetpkg.PermanentError(
                f"expected {len(types)} podset infos, got {len(podsets_info)}")
        by_name = {i.name: i for i in podsets_info}
        for rtype in types:
            info = by_name.get(rtype.lower())
            if info is None:
                raise podsetpkg.PermanentError(f"no podset info for {rtype}")
            podsetpkg.merge_into_template(
                self.kj.spec.replica_specs[rtype].template, info)

    def restore_podsets_info(self, podsets_info: list) -> bool:
        changed = False
        by_name = {i.name: i for i in podsets_info}
        for rtype in self._ordered_types():
            info = by_name.get(rtype.lower())
            if info is not None:
                changed = podsetpkg.restore_template(
                    self.kj.spec.replica_specs[rtype].template, info) or changed
        return changed

    def finished(self) -> tuple:
        for c in self.kj.status.conditions:
            if c.type in (kf.JOB_SUCCEEDED, kf.JOB_FAILED) and c.status == "True":
                return c.message, c.type == kf.JOB_SUCCEEDED, True
        return "", True, False

    def pods_ready(self) -> bool:
        for rtype in self._ordered_types():
            expected = self.kj.spec.replica_specs[rtype].replicas
            s = self.kj.status.replica_statuses.get(rtype)
            if s is None or s.active + s.succeeded < expected:
                return False
        return True


_KINDS = [
    ("kubeflow.org/tfjob", kf.TFJob),
    ("kubeflow.org/pytorchjob", kf.PyTorchJob),
    ("kubeflow.org/paddlejob", kf.PaddleJob),
    ("kubeflow.org/xgboostjob", kf.XGBoostJob),
    ("kubeflow.org/mxjob", kf.MXJob),
    ("kubeflow.org/mpijob", kf.MPIJob),
]

for _framework, _type in _KINDS:
    register_integration(IntegrationCallbacks(
        name=_framework, kind=_type.KIND,
        new_job=(lambda obj, _fw=_framework: KubeflowJob(obj, _fw)),
        job_type=_type))
