"""Plain Pod / pod-group integration — a ComposableJob.

Equivalent of the reference's pkg/controller/jobs/pod/pod_controller.go
(:148,253,560-700,958) and event_handlers.go:43:
- single pods: the webhook gates them with the kueue.x-k8s.io/admission
  scheduling gate + managed label; admission removes the gate and
  injects flavor node selectors; "suspend" for an ungated pod means
  deletion (gates are immutable once scheduled)
- pod groups via labels/annotations pod-group-name /
  pod-group-total-count / role-hash / retriable-in-group: one Workload
  per group with one PodSet per distinct pod shape (role hash); the
  workload is created once all expected pods exist (or immediately with
  the fast-admission annotation); excess pods are deleted
- group reconcile requests use the "group/<ns>/<name>" key prefix so
  every member pod fans into one reconcile
"""

from __future__ import annotations

import copy
import hashlib
import json

from kueue_tpu.api import corev1
from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import ObjectMeta, OwnerReference
from kueue_tpu.core import podset as podsetpkg
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.controller.jobframework.interface import (
    ComposableJob,
    IntegrationCallbacks,
    register_integration,
)

FRAMEWORK_NAME = "pod"
GROUP_NAME_LABEL = "kueue.x-k8s.io/pod-group-name"
GROUP_TOTAL_COUNT_ANNOTATION = "kueue.x-k8s.io/pod-group-total-count"
ROLE_HASH_ANNOTATION = "kueue.x-k8s.io/role-hash"
RETRIABLE_IN_GROUP_ANNOTATION = "kueue.x-k8s.io/retriable-in-group"
GROUP_FAST_ADMISSION_ANNOTATION = "kueue.x-k8s.io/pod-group-fast-admission"
GROUP_SERVING_ANNOTATION = "kueue.x-k8s.io/pod-group-serving"


def pod_group_name(pod: corev1.Pod) -> str:
    return pod.metadata.labels.get(GROUP_NAME_LABEL, "")


def reconcile_key_for_pod(pod: corev1.Pod) -> str:
    group = pod_group_name(pod)
    if group:
        return f"group/{pod.metadata.namespace}/{group}"
    return f"{pod.metadata.namespace}/{pod.metadata.name}"


def is_gated(pod: corev1.Pod) -> bool:
    return api.ADMISSION_GATE in pod.spec.scheduling_gates


def is_terminated(pod: corev1.Pod) -> bool:
    return pod.status.phase in (corev1.POD_SUCCEEDED, corev1.POD_FAILED)


def is_runnable_or_succeeded(pod: corev1.Pod) -> bool:
    if pod.metadata.deletion_timestamp is not None:
        return pod.status.phase == corev1.POD_SUCCEEDED
    return pod.status.phase != corev1.POD_FAILED


def role_hash(pod: corev1.Pod) -> str:
    """Shape checksum grouping pods into PodSets
    (reference: getRoleHash :593-622)."""
    if ROLE_HASH_ANNOTATION in pod.metadata.annotations:
        return pod.metadata.annotations[ROLE_HASH_ANNOTATION]
    shape = {
        "containers": [(c.name, sorted(c.requests.items()), sorted(c.limits.items()))
                       for c in pod.spec.containers],
        "initContainers": [(c.name, sorted(c.requests.items()), sorted(c.limits.items()))
                           for c in pod.spec.init_containers],
        "nodeSelector": sorted(pod.spec.node_selector.items()),
        "tolerations": [(t.key, t.operator, t.value, t.effect)
                        for t in pod.spec.tolerations],
        "priority": pod.spec.priority,
    }
    digest = hashlib.sha256(json.dumps(shape, sort_keys=True).encode()).hexdigest()
    return digest[:8]


def _template_from_pod(pod: corev1.Pod) -> corev1.PodTemplateSpec:
    return corev1.PodTemplateSpec(labels=dict(pod.metadata.labels),
                                  annotations=dict(pod.metadata.annotations),
                                  spec=copy.deepcopy(pod.spec))


class PodJob(ComposableJob):
    def __init__(self, _obj=None):
        self.pod: corev1.Pod = None
        self.pods: list = []
        self.is_group = False
        self.namespace = ""
        self.group = ""
        self.name = ""  # single-pod name, kept even when the pod is gone

    # -- load (reference: Load :624-668) --------------------------------

    def load(self, store, namespace: str, name: str) -> tuple:
        if namespace == "group":
            self.is_group = True
            self.namespace, self.group = name.split("/", 1)
            self.pods = sorted(
                store.list("Pod", namespace=self.namespace,
                           labels={GROUP_NAME_LABEL: self.group}),
                key=lambda p: ((p.metadata.creation_timestamp or 0.0),
                               p.metadata.name))
            if not self.pods:
                return True, False
            self.pod = self.pods[0]
            return False, True
        self.namespace = namespace
        self.name = name
        pod = store.try_get("Pod", namespace, name)
        if pod is None:
            return True, False
        self.pod = pod
        self.pods = [pod]
        return pod.metadata.deletion_timestamp is not None, True

    def object(self):
        return self.pod

    def gvk(self) -> str:
        return FRAMEWORK_NAME

    def is_suspended(self) -> bool:
        return is_terminated(self.pod) or is_gated(self.pod)

    def suspend(self) -> None:
        pass  # gates can't be re-added; stop() deletes instead

    def is_active(self) -> bool:
        return any(not is_terminated(p) and not is_gated(p) for p in self.pods)

    def _total_count(self) -> int:
        raw = self.pod.metadata.annotations.get(GROUP_TOTAL_COUNT_ANNOTATION)
        return int(raw) if raw is not None else len(self.pods)

    def pod_sets(self) -> list:
        if not self.is_group:
            return [api.PodSet(name=api.DEFAULT_PODSET_NAME,
                               template=_template_from_pod(self.pod), count=1)]
        out = []
        for pod in self.pods:
            if not is_runnable_or_succeeded(pod):
                continue
            rh = role_hash(pod)
            existing = next((ps for ps in out if ps.name == rh), None)
            if existing is not None:
                existing.count += 1
            else:
                out.append(api.PodSet(name=rh, template=_template_from_pod(pod),
                                      count=1))
        return out

    def finished(self) -> tuple:
        if not self.is_group:
            if self.pod.status.phase == corev1.POD_SUCCEEDED:
                return "Pod succeeded", True, True
            if self.pod.status.phase == corev1.POD_FAILED:
                return "Pod failed", False, True
            return "", True, False
        # group semantics (reference: Finished :253-330): an unretriable
        # failed pod fails the whole group; all-succeeded completes it
        succeeded = 0
        for pod in self.pods:
            if pod.status.phase == corev1.POD_FAILED:
                if pod.metadata.annotations.get(RETRIABLE_IN_GROUP_ANNOTATION) == "false":
                    return "Pod in group failed and is not retriable", False, True
            elif pod.status.phase == corev1.POD_SUCCEEDED:
                succeeded += 1
        if succeeded >= self._total_count():
            return "Pods succeeded", True, True
        return "", True, False

    def pods_ready(self) -> bool:
        ready = sum(1 for p in self.pods
                    if p.status.phase in (corev1.POD_RUNNING, corev1.POD_SUCCEEDED))
        return ready >= (self._total_count() if self.is_group else 1)

    def run_with_podsets_info(self, podsets_info: list) -> None:
        raise NotImplementedError  # ComposableJob uses run()

    def restore_podsets_info(self, podsets_info: list) -> bool:
        return False

    # -- composable operations ------------------------------------------

    def run(self, store, podsets_info: list, recorder, msg: str) -> None:
        """Ungate + inject selectors (reference: Run :282-330)."""
        by_name = {i.name: i for i in podsets_info}
        for pod in self.pods:
            if not is_gated(pod):
                continue
            name = api.DEFAULT_PODSET_NAME if not self.is_group else role_hash(pod)
            info = by_name.get(name)
            if info is None:
                continue
            # pin the role hash before injection mutates the shape fields
            # (the reference's webhook stamps RoleHashAnnotation up front)
            if self.is_group:
                pod.metadata.annotations.setdefault(ROLE_HASH_ANNOTATION, name)
            pod.spec.scheduling_gates = [g for g in pod.spec.scheduling_gates
                                         if g != api.ADMISSION_GATE]
            for k, v in info.node_selector.items():
                pod.spec.node_selector.setdefault(k, v)
            pod.spec.tolerations.extend(info.tolerations)
            for k, v in info.labels.items():
                pod.metadata.labels.setdefault(k, v)
            for k, v in info.annotations.items():
                pod.metadata.annotations.setdefault(k, v)
            store.update(pod)
            recorder.event(pod, "Normal", "Started", msg)

    def stop(self, store, podsets_info: list, reason: str, msg: str) -> list:
        """Delete non-terminated pods (reference: Stop :170-206 — ungated
        pods can't be re-suspended)."""
        stopped = []
        for pod in self.pods:
            if is_terminated(pod):
                continue
            try:
                if api.RESOURCE_IN_USE_FINALIZER in pod.metadata.finalizers:
                    pod.metadata.finalizers.remove(api.RESOURCE_IN_USE_FINALIZER)
                    store.update(pod)
                store.delete("Pod", pod.metadata.namespace, pod.metadata.name)
                stopped.append(pod)
            except KeyError:
                pass
        return stopped

    def construct_composable_workload(self, store, recorder):
        """reference: ConstructComposableWorkload — wait for the whole
        group unless fast admission is requested."""
        if not self.is_group:
            wl = api.Workload(metadata=ObjectMeta(
                name=self.pod.metadata.name,
                namespace=self.pod.metadata.namespace,
                finalizers=[api.RESOURCE_IN_USE_FINALIZER],
                owner_references=[OwnerReference(
                    kind="Pod", name=self.pod.metadata.name,
                    uid=self.pod.metadata.uid, controller=True)]))
            wl.spec.pod_sets = self.pod_sets()
            wl.spec.queue_name = self.pod.metadata.labels.get(api.QUEUE_LABEL, "")
            return wl
        total = self._total_count()
        runnable = [p for p in self.pods if is_runnable_or_succeeded(p)]
        fast = self.pod.metadata.annotations.get(
            GROUP_FAST_ADMISSION_ANNOTATION) == "true"
        if len(runnable) < total and not fast:
            return None  # wait for the rest of the group
        pod_sets = self.pod_sets()
        if fast and sum(ps.count for ps in pod_sets) < total and pod_sets:
            pod_sets[0].count += total - sum(ps.count for ps in pod_sets)
        wl = api.Workload(metadata=ObjectMeta(
            name=self.group, namespace=self.namespace,
            annotations={"kueue.x-k8s.io/is-group-workload": "true"},
            finalizers=[api.RESOURCE_IN_USE_FINALIZER],
            owner_references=[OwnerReference(
                kind="Pod", name=self.group, uid=f"group-{self.group}",
                controller=True)]))
        wl.spec.pod_sets = pod_sets
        wl.spec.queue_name = self.pod.metadata.labels.get(api.QUEUE_LABEL, "")
        return wl

    def list_child_workloads(self, store) -> list:
        name = self.group if self.is_group else self.name
        return store.list(
            "Workload", namespace=self.namespace,
            where=lambda wl: any(o.controller and o.kind == "Pod" and o.name == name
                                 for o in wl.metadata.owner_references))

    def find_matching_workloads(self, store, recorder) -> tuple:
        match = None
        to_delete = []
        job_podsets = {ps.name: ps.count for ps in self.pod_sets()}
        for wl in self.list_child_workloads(store):
            wl_podsets = {ps.name: ps.count for ps in wl.spec.pod_sets}
            if match is None and self._podsets_compatible(job_podsets, wl_podsets):
                match = wl
            else:
                to_delete.append(wl)
        return match, to_delete

    def _podsets_compatible(self, job_podsets: dict, wl_podsets: dict) -> bool:
        if not self.is_group:
            return set(job_podsets) == set(wl_podsets)
        # group pods may still be arriving or already cleaned up; the
        # workload matches while every observed role exists in it
        return all(name in wl_podsets and count <= wl_podsets[name]
                   for name, count in job_podsets.items()) or not job_podsets


def reconcile_key_for_workload(wl, owner) -> str:
    if wl.metadata.annotations.get("kueue.x-k8s.io/is-group-workload") == "true":
        return f"group/{wl.metadata.namespace}/{owner.name}"
    return f"{wl.metadata.namespace}/{owner.name}"


register_integration(IntegrationCallbacks(
    name=FRAMEWORK_NAME, kind="Pod", new_job=PodJob, job_type=corev1.Pod,
    composable=True, reconcile_key=reconcile_key_for_pod,
    reconcile_key_for_workload=reconcile_key_for_workload))
