"""Per-framework job integrations (reference: pkg/controller/jobs/*).

Importing a submodule registers its integration; `register_all()` loads
every built-in one (the reference's side-effect imports in
cmd/kueue/main.go).
"""

import importlib

_MODULES = ["job", "jobset", "kubeflow", "ray", "pod", "deployment"]


def register_all():
    for mod in _MODULES:
        try:
            importlib.import_module(f"kueue_tpu.controller.jobs.{mod}")
        except ImportError:
            pass  # integration not built yet; its framework name won't resolve
