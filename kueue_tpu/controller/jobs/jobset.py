"""JobSet integration.

Equivalent of the reference's pkg/controller/jobs/jobset/jobset_controller.go:
one PodSet per ReplicatedJob (count = replicas x per-job pod count),
suspend at the JobSet level, Finished from Completed/Failed conditions,
PodsReady from per-replicated-job ready+succeeded counts.
"""

from __future__ import annotations

import copy

from kueue_tpu.api import jobset as jobsetapi
from kueue_tpu.api import kueue as api
from kueue_tpu.core import podset as podsetpkg
from kueue_tpu.controller.jobframework.interface import (
    GenericJob,
    IntegrationCallbacks,
    register_integration,
)

FRAMEWORK_NAME = "jobset.x-k8s.io/jobset"


def _job_pods(job_spec) -> int:
    count = job_spec.parallelism
    if job_spec.completions is not None:
        count = min(count, job_spec.completions)
    return count


class JobSetJob(GenericJob):
    def __init__(self, obj: jobsetapi.JobSet):
        self.js = obj

    def object(self):
        return self.js

    def gvk(self) -> str:
        return FRAMEWORK_NAME

    def is_suspended(self) -> bool:
        return self.js.spec.suspend

    def suspend(self) -> None:
        self.js.spec.suspend = True

    def is_active(self) -> bool:
        return any(s.active > 0 for s in self.js.status.replicated_jobs_status)

    def pod_sets(self) -> list:
        return [api.PodSet(name=rj.name,
                           template=copy.deepcopy(rj.template.template),
                           count=rj.replicas * _job_pods(rj.template))
                for rj in self.js.spec.replicated_jobs]

    def run_with_podsets_info(self, podsets_info: list) -> None:
        self.js.spec.suspend = False
        if len(podsets_info) != len(self.js.spec.replicated_jobs):
            raise podsetpkg.PermanentError(
                f"expected {len(self.js.spec.replicated_jobs)} podset infos, "
                f"got {len(podsets_info)}")
        by_name = {i.name: i for i in podsets_info}
        for rj in self.js.spec.replicated_jobs:
            info = by_name.get(rj.name)
            if info is None:
                raise podsetpkg.PermanentError(f"no podset info for {rj.name}")
            podsetpkg.merge_into_template(rj.template.template, info)

    def restore_podsets_info(self, podsets_info: list) -> bool:
        changed = False
        by_name = {i.name: i for i in podsets_info}
        for rj in self.js.spec.replicated_jobs:
            info = by_name.get(rj.name)
            if info is not None:
                changed = podsetpkg.restore_template(rj.template.template, info) or changed
        return changed

    def finished(self) -> tuple:
        for c in self.js.status.conditions:
            if c.type in (jobsetapi.JOBSET_COMPLETED, jobsetapi.JOBSET_FAILED) \
                    and c.status == "True":
                return c.message, c.type == jobsetapi.JOBSET_COMPLETED, True
        return "", True, False

    def pods_ready(self) -> bool:
        by_name = {s.name: s for s in self.js.status.replicated_jobs_status}
        for rj in self.js.spec.replicated_jobs:
            s = by_name.get(rj.name)
            expected = rj.replicas * _job_pods(rj.template)
            if s is None or s.ready + s.succeeded < expected:
                return False
        return True


register_integration(IntegrationCallbacks(
    name=FRAMEWORK_NAME, kind="JobSet", new_job=JobSetJob,
    job_type=jobsetapi.JobSet))
