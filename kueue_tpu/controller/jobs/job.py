"""batch/v1 Job integration.

Equivalent of the reference's pkg/controller/jobs/job/job_controller.go:
- suspend semantics; one "main" PodSet of min(parallelism, completions)
- partial admission: parallelism scaled to the admitted count, original
  kept in an annotation; optional completions sync (:260-299)
- reclaimable pods from succeeded counts (:216-231)
- Finished from the Complete/Failed conditions (:301-308)
"""

from __future__ import annotations

import copy
from typing import Optional

from kueue_tpu.api import batchv1
from kueue_tpu.api import kueue as api
from kueue_tpu.core import podset as podsetpkg
from kueue_tpu.controller.jobframework.interface import (
    GenericJob,
    IntegrationCallbacks,
    register_integration,
)

FRAMEWORK_NAME = "batch/job"
MIN_PARALLELISM_ANNOTATION = "kueue.x-k8s.io/job-min-parallelism"
COMPLETIONS_EQUAL_PARALLELISM_ANNOTATION = \
    "kueue.x-k8s.io/job-completions-equal-parallelism"
ORIGINAL_PARALLELISM_ANNOTATION = "kueue.x-k8s.io/original-parallelism"


class BatchJob(GenericJob):
    def __init__(self, obj: batchv1.Job):
        self.job = obj

    def object(self):
        return self.job

    def gvk(self) -> str:
        return FRAMEWORK_NAME

    def is_suspended(self) -> bool:
        return self.job.spec.suspend

    def suspend(self) -> None:
        self.job.spec.suspend = True

    def is_active(self) -> bool:
        return self.job.status.active != 0

    def _pods_count(self) -> int:
        count = self.job.spec.parallelism
        if self.job.spec.completions is not None:
            count = min(count, self.job.spec.completions)
        return count

    def _min_pods_count(self) -> Optional[int]:
        raw = self.job.metadata.annotations.get(MIN_PARALLELISM_ANNOTATION)
        try:
            return int(raw) if raw is not None else None
        except ValueError:
            return None

    def _sync_completions(self) -> bool:
        return self.job.metadata.annotations.get(
            COMPLETIONS_EQUAL_PARALLELISM_ANNOTATION, "") == "true"

    def pod_sets(self) -> list:
        return [api.PodSet(name=api.DEFAULT_PODSET_NAME,
                           template=copy.deepcopy(self.job.spec.template),
                           count=self._pods_count(),
                           min_count=self._min_pods_count())]

    def run_with_podsets_info(self, podsets_info: list) -> None:
        self.job.spec.suspend = False
        if len(podsets_info) != 1:
            raise podsetpkg.PermanentError(
                f"expected 1 podset info, got {len(podsets_info)}")
        info = podsets_info[0]
        if self._min_pods_count() is not None and info.count != self.job.spec.parallelism:
            self.job.metadata.annotations[ORIGINAL_PARALLELISM_ANNOTATION] = \
                str(self.job.spec.parallelism)
            self.job.spec.parallelism = info.count
            if self._sync_completions():
                self.job.spec.completions = info.count
        podsetpkg.merge_into_template(self.job.spec.template, info)

    def restore_podsets_info(self, podsets_info: list) -> bool:
        if not podsets_info:
            return False
        changed = False
        original = self.job.metadata.annotations.pop(
            ORIGINAL_PARALLELISM_ANNOTATION, None)
        if original is not None and int(original) != self.job.spec.parallelism:
            self.job.spec.parallelism = int(original)
            if self._sync_completions():
                self.job.spec.completions = int(original)
            changed = True
        return podsetpkg.restore_template(
            self.job.spec.template, podsets_info[0]) or changed

    def finished(self) -> tuple:
        for c in self.job.status.conditions:
            if c.type in (batchv1.JOB_COMPLETE, batchv1.JOB_FAILED) and c.status == "True":
                return c.message, c.type != batchv1.JOB_FAILED, True
        return "", True, False

    def pods_ready(self) -> bool:
        return self.job.status.succeeded + self.job.status.ready >= self._pods_count()

    # optional: JobWithReclaimablePods (reference: :216-231)
    def reclaimable_pods(self) -> list:
        parallelism = self.job.spec.parallelism
        if parallelism == 1 or self.job.status.succeeded == 0:
            return []
        completions = (self.job.spec.completions
                       if self.job.spec.completions is not None else parallelism)
        remaining = completions - self.job.status.succeeded
        if remaining >= parallelism:
            return []
        return [api.ReclaimablePod(name=api.DEFAULT_PODSET_NAME,
                                   count=parallelism - remaining)]


register_integration(IntegrationCallbacks(
    name=FRAMEWORK_NAME, kind="Job", new_job=BatchJob, job_type=batchv1.Job))
