"""KubeRay integrations: RayJob and RayCluster.

Equivalent of the reference's pkg/controller/jobs/rayjob/rayjob_controller.go
and raycluster/raycluster_controller.go: PodSets = head (count 1) + one
per worker group (count = replicas); suspend at the CR level; RayJob
finishes from jobStatus SUCCEEDED/FAILED, RayCluster never finishes on
its own (serving-style).
"""

from __future__ import annotations

import copy

from kueue_tpu.api import kueue as api
from kueue_tpu.api import ray as rayapi
from kueue_tpu.core import podset as podsetpkg
from kueue_tpu.controller.jobframework.interface import (
    GenericJob,
    IntegrationCallbacks,
    register_integration,
)

RAYJOB_FRAMEWORK = "ray.io/rayjob"
RAYCLUSTER_FRAMEWORK = "ray.io/raycluster"
HEAD_PODSET = "head"


class _RayBase(GenericJob):
    def _cluster_spec(self) -> rayapi.RayClusterSpec:
        raise NotImplementedError

    def pod_sets(self) -> list:
        spec = self._cluster_spec()
        out = [api.PodSet(name=HEAD_PODSET,
                          template=copy.deepcopy(spec.head_group_spec.template),
                          count=1)]
        for wg in spec.worker_group_specs:
            out.append(api.PodSet(name=wg.group_name,
                                  template=copy.deepcopy(wg.template),
                                  count=wg.replicas,
                                  min_count=wg.min_replicas))
        return out

    def run_with_podsets_info(self, podsets_info: list) -> None:
        spec = self._cluster_spec()
        expected = 1 + len(spec.worker_group_specs)
        if len(podsets_info) != expected:
            raise podsetpkg.PermanentError(
                f"expected {expected} podset infos, got {len(podsets_info)}")
        by_name = {i.name: i for i in podsets_info}
        head = by_name.get(HEAD_PODSET)
        if head is None:
            raise podsetpkg.PermanentError("no podset info for head")
        podsetpkg.merge_into_template(spec.head_group_spec.template, head)
        for wg in spec.worker_group_specs:
            info = by_name.get(wg.group_name)
            if info is None:
                raise podsetpkg.PermanentError(f"no podset info for {wg.group_name}")
            if wg.min_replicas is not None:
                wg.replicas = info.count
            podsetpkg.merge_into_template(wg.template, info)
        self._unsuspend()

    def restore_podsets_info(self, podsets_info: list) -> bool:
        spec = self._cluster_spec()
        changed = False
        by_name = {i.name: i for i in podsets_info}
        head = by_name.get(HEAD_PODSET)
        if head is not None:
            changed = podsetpkg.restore_template(spec.head_group_spec.template, head)
        for wg in spec.worker_group_specs:
            info = by_name.get(wg.group_name)
            if info is not None:
                if wg.min_replicas is not None and wg.replicas != info.count:
                    wg.replicas = info.count
                    changed = True
                changed = podsetpkg.restore_template(wg.template, info) or changed
        return changed

    def _unsuspend(self) -> None:
        raise NotImplementedError


class RayJobJob(_RayBase):
    def __init__(self, obj: rayapi.RayJob):
        self.rj = obj

    def object(self):
        return self.rj

    def gvk(self) -> str:
        return RAYJOB_FRAMEWORK

    def _cluster_spec(self):
        return self.rj.spec.ray_cluster_spec

    def is_suspended(self) -> bool:
        return self.rj.spec.suspend

    def suspend(self) -> None:
        self.rj.spec.suspend = True

    def _unsuspend(self) -> None:
        self.rj.spec.suspend = False

    def is_active(self) -> bool:
        return self.rj.status.job_deployment_status != ""

    def finished(self) -> tuple:
        if self.rj.status.job_status in ("SUCCEEDED", "FAILED"):
            return (self.rj.status.message,
                    self.rj.status.job_status == "SUCCEEDED", True)
        return "", True, False

    def pods_ready(self) -> bool:
        expected = sum(wg.replicas for wg in self._cluster_spec().worker_group_specs)
        return self.rj.status.ready_worker_replicas >= expected


class RayClusterJob(_RayBase):
    def __init__(self, obj: rayapi.RayCluster):
        self.rc = obj

    def object(self):
        return self.rc

    def gvk(self) -> str:
        return RAYCLUSTER_FRAMEWORK

    def _cluster_spec(self):
        return self.rc.spec

    def is_suspended(self) -> bool:
        return self.rc.spec.suspend

    def suspend(self) -> None:
        self.rc.spec.suspend = True

    def _unsuspend(self) -> None:
        self.rc.spec.suspend = False

    def is_active(self) -> bool:
        return self.rc.status.ready_worker_replicas > 0

    def finished(self) -> tuple:
        # a RayCluster is a long-running service; it only stops via
        # deletion or eviction (reference: raycluster_controller.go)
        return "", True, False

    def pods_ready(self) -> bool:
        expected = sum(wg.replicas for wg in self.rc.spec.worker_group_specs)
        return self.rc.status.ready_worker_replicas >= expected


register_integration(IntegrationCallbacks(
    name=RAYJOB_FRAMEWORK, kind="RayJob", new_job=RayJobJob,
    job_type=rayapi.RayJob))
register_integration(IntegrationCallbacks(
    name=RAYCLUSTER_FRAMEWORK, kind="RayCluster", new_job=RayClusterJob,
    job_type=rayapi.RayCluster))
