"""Deployment integration (serving workloads).

Equivalent of the reference's pkg/controller/jobs/deployment
(deployment_webhook.go:112, deployment_controller.go:66,
DependencyList: ["pod"]): a Deployment is NOT queued as one unit — its
webhook propagates the queue-name label into the pod template so each
replica pod is queued individually through the pod integration. The
jobframework never manages the Deployment object itself (skip()).
"""

from __future__ import annotations

from kueue_tpu.api import appsv1
from kueue_tpu.api import kueue as api
from kueue_tpu.controller.jobframework.interface import (
    GenericJob,
    IntegrationCallbacks,
    register_integration,
)

FRAMEWORK_NAME = "deployment"


def propagate_queue_label(deployment: appsv1.Deployment) -> bool:
    """Webhook defaulting: copy the queue-name label to the pod template
    (reference: deployment_webhook.go:112). Returns True if changed."""
    q = deployment.metadata.labels.get(api.QUEUE_LABEL)
    if not q:
        return False
    if deployment.spec.template.labels.get(api.QUEUE_LABEL) == q:
        return False
    deployment.spec.template.labels[api.QUEUE_LABEL] = q
    return True


class DeploymentJob(GenericJob):
    """Never managed by the jobframework directly — pods are the unit."""

    def __init__(self, obj):
        self.deployment = obj

    def object(self):
        return self.deployment

    def gvk(self) -> str:
        return FRAMEWORK_NAME

    def skip(self) -> bool:
        return True


register_integration(IntegrationCallbacks(
    name=FRAMEWORK_NAME, kind="Deployment", new_job=DeploymentJob,
    job_type=appsv1.Deployment, depends_on=["pod"]))
