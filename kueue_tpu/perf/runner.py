"""Perf harness runner + recorder.

Equivalent of the reference's test/performance/scheduler/{runner,recorder}
(runner/main.go): drive a full KueueManager on a virtual clock through
the generated arrival schedule, fake workload execution (a workload
"runs" for its class runtime, then finishes), and record per-class
time-to-admission stats plus time-weighted ClusterQueue usage.

The virtual clock reproduces the reference's queueing dynamics exactly
(arrival intervals, runtimes, quotas), so per-class time-to-admission is
directly comparable to the reference's wall-clock numbers in
default_rangespec.yaml as long as the scheduler keeps up; real compute
time is reported separately as the throughput signal.
"""

from __future__ import annotations

import heapq
import time
from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import Condition, FakeClock, ObjectMeta, set_condition
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.manager import KueueManager
from kueue_tpu.perf.generator import FLAVOR, GeneratedLoad, RESOURCE


def _percentile(sorted_samples: list, q: float) -> float:
    return sorted_samples[min(len(sorted_samples) - 1,
                              int(q * len(sorted_samples)))]


@dataclass
class ClassStats:
    times_to_admission: list = field(default_factory=list)

    def _q(self, q: float) -> float:
        if not self.times_to_admission:
            return 0.0
        data = sorted(self.times_to_admission)
        idx = min(len(data) - 1, int(q * len(data)))
        return data[idx]

    @property
    def avg(self) -> float:
        return (sum(self.times_to_admission) / len(self.times_to_admission)
                if self.times_to_admission else 0.0)

    @property
    def p50(self) -> float:
        return self._q(0.50)

    @property
    def p99(self) -> float:
        return self._q(0.99)


@dataclass
class RunResult:
    total: int = 0
    admitted: int = 0
    finished: int = 0
    cycles: int = 0
    wall_s: float = 0.0            # real compute time of the simulation
    virtual_makespan_s: float = 0.0
    class_stats: dict = field(default_factory=dict)   # class -> ClassStats
    cq_class_avg_usage_pct: dict = field(default_factory=dict)
    admissions_per_wall_second: float = 0.0
    cycle_p50_ms: float = 0.0      # admission-cycle wall latency
    cycle_p99_ms: float = 0.0
    # Total scheduler-cycle time vs wall: wall - cycle_time_total is the
    # control plane's share, making the full-stack-vs-cycle-rate gap
    # (VERDICT r4 ask #5) checkable from the artifact itself.
    cycle_time_total_s: float = 0.0
    # Solver-path attribution (VERDICT r4 missing #4): which engine ran
    # each cycle, whether residency/pipelining engaged, and where the
    # solver cycle time went.
    engine_cycles: dict = field(default_factory=dict)
    pipelined_hit_rate: Optional[float] = None
    # Speculative-pipeline outcomes (scheduler/PIPELINE.md): validated
    # commits vs mis-speculation aborts by validation reason.
    speculation: dict = field(default_factory=dict)
    # Phase attribution note: solver_phase_s mirrors the flight
    # recorder's span tree exactly — dotted keys ("dispatch.scatter")
    # are sub-spans already included inside their prefix phase, so
    # summing the TOP-LEVEL keys gives total solver time and the
    # artifact agrees with /debug/cycles by construction.
    solver_phase_s: dict = field(default_factory=dict)
    solver_counters: dict = field(default_factory=dict)
    # Per-cycle transport (the device round-trip story): average bytes
    # on the wire per dispatch/collect across the run. None for
    # solver-less runs or runs that never round-tripped. The
    # decision-only fetch rangespec bounds these.
    upload_bytes_per_cycle: Optional[float] = None
    fetch_bytes_per_cycle: Optional[float] = None
    # Snapshot-build attribution (incremental journal-replay snapshots):
    # per-snapshot build latency and which path served each call
    # (incremental advance vs full rebuild vs light view).
    snapshot_build_p50_ms: float = 0.0
    snapshot_build_p99_ms: float = 0.0
    snapshot_counts: dict = field(default_factory=dict)
    # Encode-phase attribution (workload encode arena): per-prepare()
    # batch-assembly latency.
    encode_p50_ms: float = 0.0
    encode_p99_ms: float = 0.0
    # Per-cycle phase latency (cycle flight recorder): p50/p99 of the
    # cycle_phase_seconds histograms, merged across routes. Only phases
    # that actually observed samples appear (a CPU-only run has no
    # encode/dispatch series).
    phase_p50_ms: dict = field(default_factory=dict)
    phase_p99_ms: dict = field(default_factory=dict)
    # Compile-storm accounting (solver/COMPILE.md): program variants
    # that first executed INSIDE a measured cycle — i.e. potential jit
    # compiles on the hot path. The north-star rangespec pins this at 0
    # after warmup. None for solver-less runs.
    mid_traffic_compiles: Optional[int] = None
    # Compile-governor summary (state, per-bucket provenance counts,
    # warmup faults, cycles the route gate diverted to cpu-warmup).
    warmup: dict = field(default_factory=dict)


class Runner:
    def __init__(self, load: GeneratedLoad, solver=None, cfg=None):
        self.load = load
        self.clock = FakeClock(0.0)
        self.mgr = KueueManager(cfg=cfg, clock=self.clock, solver=solver)
        self.solver = solver

    def run(self, max_virtual_s: float = 10 ** 6) -> RunResult:
        mgr, clock, load = self.mgr, self.clock, self.load
        result = RunResult(total=len(load.arrivals))
        cycle_times: list = []

        for rf in load.flavors:
            mgr.store.create(rf)
        for cq in load.cluster_queues:
            mgr.store.create(cq)
        for lq in load.local_queues:
            mgr.store.create(lq)
        mgr.run_until_idle(max_iterations=10_000_000)

        if self.mgr.warm_governor is not None:
            # Pre-clock shape-bucket warmup (VERDICT r4 ask #3), now
            # delegated to the compile governor (solver/warmgov.py): ONE
            # copy of the geometric bucket ladder, walked synchronously
            # before the measured clock starts so no measured cycle or
            # router sample carries a compile. expected_pending
            # pre-sizes the encode arena (no mid-run growth -> stable
            # gather shapes) and warms the arena-resident variants.
            # Failures are no longer silently swallowed: every faulted
            # bucket lands in vlog, warmup_faults_total, and the
            # governor's /debug/warmup status — the walk itself is
            # fault-contained (a failed bucket degrades that bucket to
            # the cpu-warmup route, never the run).
            self.mgr.warm_governor.run_sync(
                expected_pending=len(load.arrivals))

        # The measured clock starts AFTER environment setup + shape
        # warmup (the reference's harness also measures from scheduler
        # start, recorder.go) — compiles must not land in wall_s.
        start_wall = time.monotonic()

        arrival_by_key = {f"{a.namespace}/{a.name}": a for a in load.arrivals}
        admitted_at: dict = {}

        # record admissions through the watch, like the reference's
        # recorder consumes workload events
        events: list = []  # heap of (virtual time, seq, kind, payload)
        seq = [0]

        def push(at, kind, payload):
            seq[0] += 1
            heapq.heappush(events, (at, seq[0], kind, payload))

        def on_workload(event, wl, old):
            key = wlpkg.key(wl)
            if key in admitted_at or key not in arrival_by_key:
                return
            if not wlpkg.has_quota_reservation(wl):
                return
            arrival = arrival_by_key[key]
            now = clock.now()
            admitted_at[key] = now
            result.admitted += 1
            stats = result.class_stats.setdefault(arrival.class_name, ClassStats())
            stats.times_to_admission.append(now - arrival.at_s)
            push(now + arrival.runtime_s, "finish", key)

        mgr.store.watch("Workload", on_workload)

        for arrival in load.arrivals:
            push(arrival.at_s, "arrive", arrival)

        # time-weighted usage sampling per CQ class
        usage_acc: dict = {}   # cq class -> accumulated pct*dt
        last_sample_t = 0.0

        def sample_usage(now):
            nonlocal last_sample_t
            dt = now - last_sample_t
            if dt <= 0:
                return
            per_class: dict = {}
            for cq in load.cluster_queues:
                cqc = mgr.cache.cluster_queue(cq.metadata.name)
                if cqc is None:
                    continue
                # total across the CQ's flavor window (the 32-flavor
                # north-star shape spreads quota over many flavors)
                rg = cq.spec.resource_groups[0]
                nominal = sum(rq.nominal_quota for fq in rg.flavors
                              for rq in fq.resources
                              if rq.name == RESOURCE)
                used = sum(v for fr, v in cqc.resource_node.usage.items()
                           if fr.resource == RESOURCE)
                cls = load.cq_class[cq.metadata.name]
                per_class.setdefault(cls, []).append(
                    100.0 * min(used, nominal) / nominal if nominal else 0.0)
            for cls, pcts in per_class.items():
                usage_acc[cls] = usage_acc.get(cls, 0.0) + dt * (sum(pcts) / len(pcts))
            last_sample_t = now

        while events:
            at, _, _, _ = events[0]
            if at > max_virtual_s:
                break
            sample_usage(at)
            clock.t = max(clock.t, at)
            # apply every event due at this instant
            while events and events[0][0] <= clock.t:
                _, _, kind, payload = heapq.heappop(events)
                if kind == "arrive":
                    wl = api.Workload(metadata=ObjectMeta(
                        name=payload.name, namespace=payload.namespace))
                    wl.spec.queue_name = payload.queue_name
                    wl.spec.priority = payload.priority
                    wl.spec.pod_sets = [api.PodSet(
                        name=api.DEFAULT_PODSET_NAME, count=1)]
                    wl.spec.pod_sets[0].template.spec.containers = [
                        _container(payload.request)]
                    mgr.store.create(wl)
                else:
                    namespace, name = payload.split("/", 1)
                    wl = mgr.store.try_get("Workload", namespace, name)
                    if wl is not None and not wlpkg.is_finished(wl):
                        set_condition(wl.status.conditions, Condition(
                            type=api.WORKLOAD_FINISHED, status="True",
                            reason="Succeeded", message="simulated completion"),
                            clock.now())
                        mgr.store.update(wl)
                        result.finished += 1
            mgr.run_until_idle(max_iterations=10_000_000)
            # schedule until this instant's admissions are exhausted; a
            # pipelined dispatch admits one cycle late, so keep going
            # while a cycle is still in flight
            for _ in range(1000):
                before = result.admitted
                c0 = time.perf_counter()
                mgr.scheduler.schedule(timeout=0)
                cycle_times.append(time.perf_counter() - c0)
                mgr.run_until_idle(max_iterations=10_000_000)
                result.cycles += 1
                if result.admitted == before \
                        and mgr.scheduler._inflight is None:
                    break

        result.virtual_makespan_s = clock.now()
        sample_usage(clock.now())
        for cls, acc in usage_acc.items():
            result.cq_class_avg_usage_pct[cls] = (
                acc / result.virtual_makespan_s if result.virtual_makespan_s else 0.0)
        result.wall_s = time.monotonic() - start_wall
        result.admissions_per_wall_second = (
            result.admitted / result.wall_s if result.wall_s else 0.0)
        result.engine_cycles = dict(mgr.scheduler.cycle_counts)
        dev = (result.engine_cycles.get("device", 0)
               + result.engine_cycles.get("device-pipelined", 0))
        if dev:
            result.pipelined_hit_rate = (
                result.engine_cycles.get("device-pipelined", 0) / dev)
        sched = mgr.scheduler
        if sched.speculation_hits or sched.speculation_aborts:
            result.speculation = {
                "hits": sched.speculation_hits,
                "aborts": sched.speculation_aborts,
                "abort_reasons": dict(sched.speculation_abort_reasons),
            }
        if self.solver is not None:
            result.solver_phase_s = {
                k: round(v, 2)
                for k, v in getattr(self.solver, "phase_s", {}).items()}
            result.solver_counters = dict(
                getattr(self.solver, "counters", {}))
            result.mid_traffic_compiles = result.solver_counters.get(
                "mid_traffic_compiles")
            c = result.solver_counters
            if c.get("dispatches"):
                result.upload_bytes_per_cycle = (
                    c.get("upload_bytes", 0) / c["dispatches"])
            if c.get("collects"):
                result.fetch_bytes_per_cycle = (
                    c.get("fetch_bytes", 0) / c["collects"])
        gov = self.mgr.warm_governor
        if gov is not None:
            st = gov.status()
            sources: dict = {}
            for b in st["buckets"]:
                key = b["source"] if b["state"] == "warm" else b["state"]
                sources[key] = sources.get(key, 0) + 1
            result.warmup = {
                "state": st["state"],
                "programs_warmed": st["programs_warmed"],
                "warmup_faults": st["warmup_faults"],
                "unwarm_routed_cycles": st["unwarm_routed_cycles"],
                "bucket_sources": sources,
            }
        if cycle_times:
            result.cycle_time_total_s = sum(cycle_times)
            cycle_times.sort()
            result.cycle_p50_ms = _percentile(cycle_times, 0.50) * 1e3
            result.cycle_p99_ms = _percentile(cycle_times, 0.99) * 1e3
        builds = sorted(mgr.cache.snapshot_build_s)
        if builds:
            result.snapshot_build_p50_ms = _percentile(builds, 0.50) * 1e3
            result.snapshot_build_p99_ms = _percentile(builds, 0.99) * 1e3
        result.snapshot_counts = dict(mgr.cache.snapshot_stats)
        encodes = sorted(getattr(self.solver, "encode_samples", None) or [])
        if encodes:
            result.encode_p50_ms = _percentile(encodes, 0.50) * 1e3
            result.encode_p99_ms = _percentile(encodes, 0.99) * 1e3
        # Phase p50/p99 from the flight-recorder-fed histograms
        # (cycle_phase_seconds, merged across routes): the rangespec's
        # per-phase regression bounds read these.
        import math as _math
        for phase in ("snapshot", "nominate", "encode", "route",
                      "dispatch", "fetch", "decode", "preempt-plan",
                      "apply", "requeue"):
            v50 = mgr.metrics.phase_percentile(phase, 0.50)
            if _math.isnan(v50):
                continue
            result.phase_p50_ms[phase] = v50 * 1e3
            result.phase_p99_ms[phase] = \
                mgr.metrics.phase_percentile(phase, 0.99) * 1e3
        return result


def _container(request: int):
    from kueue_tpu.api.corev1 import Container
    return Container(name="c", requests={RESOURCE: request})
